"""Parallel execution plane: meshes, shardings, host→device feeding.

SURVEY §2.4 — the reference's worker-per-core/work-stealing parallelism
maps to batch-parallel device meshes here; §7 hard part #2 — the
host-side read pipeline that keeps the device fed.
"""

from . import autotune, procpool
from .feeder import PipelineStats, WindowPipeline, pipeline_depth
from .mesh import (
    AXES,
    accelerator_count,
    batch_sharding,
    dispatch_devices,
    factor3,
    flat_mesh,
    make_mesh,
    multihost_init,
    pad_to_multiple,
    replicated,
)

__all__ = [
    "AXES",
    "PipelineStats",
    "autotune",
    "procpool",
    "WindowPipeline",
    "accelerator_count",
    "batch_sharding",
    "dispatch_devices",
    "factor3",
    "flat_mesh",
    "make_mesh",
    "multihost_init",
    "pad_to_multiple",
    "pipeline_depth",
    "replicated",
]
