"""Closed-loop autotuner — telemetry-driven batch/ladder/depth control.

BENCH_E2E pins e2e throughput at ~490 files/s against a host→device
link that swings 0.01–0.06 GB/s run to run while
``sd_device_dispatch_occupancy`` shows the chips idling — yet every
batch size, pad-ladder rung, feeder depth, and pipeline depth was a
static constant tuned for an uncongested link. PRs 1–6 built the
measurement plane (link probes, occupancy, feeder depth/wait,
event-loop lag, health verdicts); this module spends it.

Two pieces:

- :class:`PipelinePolicy` — the ONE home for the previously scattered
  tuning constants (``batch_ladder`` rungs from ``ops/cas.py``, the
  thumbnailer's ``DEVICE_BATCH`` chunk sizing, the identifier's window
  size, the feeder's ``pipeline_depth``), one policy object per
  workload (``identify`` / ``thumbnail``). Every consumer reads its
  sizing through :func:`policy` — sdlint SD013 flags hard-coded
  batch/depth constants that bypass this seam.

- :class:`Controller` — periodically samples the existing telemetry
  (``sd_bench_link_probe_gbps``, ``sd_device_dispatch_occupancy``,
  feeder wait/fetch deltas, event-loop lag, the ``DeviceLadder``
  demotion level) and adjusts each policy with AIMD-style damped
  steps: a knob only moves after ``STEP_STREAK`` consecutive ticks
  agree on the direction, so alternating congested/clear samples hold
  instead of thrashing. Decisions land on the ``autotune`` flight
  ring (with the active trace id, like every ring emit) and update the
  ``sd_autotune_*`` gauges/counters.

Decision rules (docs/performance.md "Closed-loop autotuner"):

- **starved** (mean consumer wait per feeder take over the tick is
  high): the per-window cost — congested-link transfer latency, slow
  reads, an injected ``feeder.fetch`` stall — dominates, so AMORTIZE:
  widen the host window (multiplicative, ×2 up to ``SCALE_MAX``) and
  deepen the in-flight pipeline (+1 up to the feeder cap). This is the
  adaptive-batching shape inference servers use to ride varying load.
- **overbuffered** (waits are instant while the knobs sit above
  static): decay back toward the static defaults (halve the scale,
  −1 depth) — no reason to hold memory and latency hostage.
- **congested link** (the latest ``sd_bench_link_probe_gbps`` probe is
  positive but under ``CONGESTED_GBPS``): cap the per-device dispatch
  rung one step down — smaller batches pad less, so fewer junk bytes
  ride the scarce link and the flow stays steady; also shed any extra
  pipeline depth (in-flight windows are in-flight bytes).
- **full batches** (mean dispatch occupancy ≥ ``OCC_HIGH``, link not
  congested — an absent probe counts as not congested, since only
  bench rigs set one): promote the rung back toward saturating.
- **low occupancy** (chips mostly hauling pad rows): demote the rung —
  real batches aren't filling it anyway, so demotion costs nothing and
  stops shipping padding.
- **event-loop lag** past ``health.LOOP_LAG_DEGRADED``: stop deepening
  the pipeline and shed any depth boost — more in-flight windows are
  more loop work. The WINDOW deliberately does not shed on lag: a
  batch pass drags a small host's loop regardless, and wider windows
  mean fewer steps and DB commits per file (shrinking them under lag
  measurably slowed both arms of the A/B).
- the rung may NEVER exceed what the ``DeviceLadder`` demotion level
  allows (full mesh → top rung, surviving subset → middle, host path →
  bottom): a controller must not promote batches onto chips the
  resilience plane just demoted away from.

``SD_AUTOTUNE=0`` disables the controller AND makes every policy read
return the pre-autotuner static value bit-for-bit (golden-tested).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any

#: per-device cas dispatch pad rungs — at most 3 compiled programs per
#: bucket, and a 5-file tail pads to 32 rows, not 1024. Moved here from
#: ops/cas.py (which re-exports) so the autotuner owns the one copy.
BATCH_LADDER = (32, 256, 1024)

#: identifier host-window rows per device (was DEVICE_CHUNK_SIZE in
#: object/file_identifier/job.py) — device batches amortize dispatch
#: overhead, so the device window is the top ladder rung per chip
IDENTIFY_DEVICE_WINDOW = BATCH_LADDER[-1]
#: the reference's CPU parity chunk (ref:file_identifier/mod.rs:34)
IDENTIFY_CPU_WINDOW = 100

#: thumbnail images per device dispatch per accelerator (was
#: DEVICE_BATCH in object/media/thumbnail/actor.py)
THUMB_DEVICE_BATCH = 32

#: embedding images per device dispatch per accelerator (the semantic
#: search forward pass, ops/embed_jax.py — same quantum shape as the
#: thumbnailer's)
EMBED_DEVICE_BATCH = 32

#: feeder read-ahead: base depth and hard cap (parallel/feeder.py's
#: pipeline_depth shape function still derives the device scaling)
FEEDER_BASE_DEPTH = 3
FEEDER_DEPTH_CAP = 8

#: rows per multi-process-pool batch (parallel/procpool.py): one
#: round-trip's serialize+frame tax amortized over this many entries.
#: Small enough that a 128-entry shard still fans out across workers,
#: large enough that msgpack+pipe overhead stays a rounding error.
PROCPOOL_BATCH_ROWS = 32

#: window-scale bounds: the static base is the floor (shrinking the
#: host window below it just multiplies per-window overhead — the
#: congestion response lives in the dispatch RUNG, which controls how
#: much padding rides the link); ≥8× static stops amortizing anything
#: real and only adds latency + host memory
SCALE_MIN = 1.0
SCALE_MAX = 8.0

#: link probe below this is a congested tunnel (bench_e2e's threshold)
CONGESTED_GBPS = 0.5
#: mean consumer wait per take that counts as starved (a warm handoff
#: is <2 ms; 50 ms of blocking per window means the producer lost)
STARVED_WAIT_S = 0.05
#: an EXTREME wait: the consumer sat blocked for half a second on one
#: window — widening steps immediately (fast-start), damping would just
#: burn more half-second windows collecting confirmations
URGENT_WAIT_S = 0.5
#: mean wait under which the pipeline is comfortably ahead
OVERBUFFERED_WAIT_S = 0.002
#: dispatch-occupancy bands for rung control
OCC_LOW = 0.5
OCC_HIGH = 0.9

#: damping: a knob steps only after this many consecutive ticks agree
#: on the direction; ticks with no new samples hold the streak (an
#: idle pipeline is not evidence of anything)
STEP_STREAK = 2

#: pool-quantum control bands: when dispatch (serialize + queue put)
#: eats this share of the batch roundtrip, the IPC tax dominates and a
#: wider quantum amortizes it; a roundtrip past POOL_RT_SLOW_S says the
#: quantum is hurting latency (and lease margins) and should shrink
POOL_DISPATCH_SHARE = 0.15
POOL_RT_SLOW_S = 2.0
#: pool-scale bounds mirror the window-scale rationale: the static
#: quantum is the floor, ≥8× stops amortizing anything real
POOL_SCALE_MIN = 1.0
POOL_SCALE_MAX = 8.0

#: per-stage lease-target hysteresis: the Controller republishes a
#: stage's lease target only when it moved ≥25% — lease sizing is a
#: fallback path, not a hot loop, and jittery targets would spam the
#: decision ring
STAGE_LEASE_HYSTERESIS = 0.25

WORKLOADS = ("identify", "thumbnail", "embed")


def enabled() -> bool:
    """SD_AUTOTUNE=0 → static config bit-for-bit (no controller, no
    policy deviation)."""
    return os.environ.get("SD_AUTOTUNE", "1") != "0"


def _ladder_rung_cap() -> int:
    """Max rung index the DeviceLadder's demotion level allows: the
    autotuner may never promote batches past the rung the resilience
    plane demoted to."""
    from . import mesh as _mesh

    level = _mesh.LADDER.level
    return max(0, len(BATCH_LADDER) - 1 - int(level))


@dataclass
class PipelinePolicy:
    """Per-workload tuning state. Static defaults ARE the pre-autotune
    constants; the controller nudges the knobs, consumers read the
    derived sizes through the methods below (the one seam)."""

    workload: str
    #: index into BATCH_LADDER — per-device rows per device dispatch
    rung: int = len(BATCH_LADDER) - 1
    #: multiplier on the static host window / chunk rows
    window_scale: float = 1.0
    #: additive adjustment to the feeder read-ahead depth
    depth_extra: int = 0
    #: multiplier on the static procpool batch quantum (its own knob:
    #: the pool's IPC tax and the host window amortize different costs)
    pool_scale: float = 1.0

    def reset(self) -> None:
        self.rung = len(BATCH_LADDER) - 1
        self.window_scale = 1.0
        self.depth_extra = 0
        self.pool_scale = 1.0

    # ---- derived sizes (the seam every consumer reads) ---------------

    def dispatch_rows_per_device(self) -> int:
        """Per-device rows per device dispatch (ops/cas.cas_ids_begin's
        step = this × device count). Clamped to the DeviceLadder's
        demotion rung while autotuning."""
        if not enabled():
            return BATCH_LADDER[-1]
        return BATCH_LADDER[min(self.rung, _ladder_rung_cap())]

    def identify_window_rows(self, n_devices: int = 1) -> int:
        """Identifier cursor-window rows (device backends); the host
        window that becomes one feeder fetch."""
        base = IDENTIFY_DEVICE_WINDOW * max(1, n_devices)
        if not enabled():
            return base
        return max(BATCH_LADDER[0], int(base * self.window_scale))

    def thumb_chunk_rows(self, n_accel: int = 1) -> int:
        """Thumbnailer images per device chunk (the 3-deep software
        pipeline's quantum)."""
        base = THUMB_DEVICE_BATCH * max(1, n_accel)
        if not enabled():
            return base
        return max(1, int(base * self.window_scale))

    def embed_chunk_rows(self, n_accel: int = 1) -> int:
        """Embedding images per device chunk (the semantic-search
        forward pass quantum)."""
        base = EMBED_DEVICE_BATCH * max(1, n_accel)
        if not enabled():
            return base
        return max(1, int(base * self.window_scale))

    def procpool_batch_rows(self) -> int:
        """Entries per multi-process-pool round-trip (the execute leg's
        per-stage shipping quantum — parallel/procpool.py). An explicit
        ``SD_PROCS_BATCH`` pins it; otherwise the controller's
        ``pool_scale`` knob sizes it from observed per-batch dispatch /
        roundtrip deltas (``_tick_pool``) — growing when the IPC tax
        dominates, shrinking on slow or underfilled batches."""
        explicit = os.environ.get("SD_PROCS_BATCH")
        if explicit:
            try:
                return max(1, int(explicit))
            except ValueError:
                pass
        if not enabled():
            return PROCPOOL_BATCH_ROWS
        return max(8, int(PROCPOOL_BATCH_ROWS * self.pool_scale))

    def feeder_depth(self, n_devices: int = 1) -> int:
        """In-flight feeder windows (read live by WindowPipeline, so a
        mid-job adjustment takes effect on the next fetch)."""
        from .feeder import pipeline_depth

        base = pipeline_depth(
            max(1, n_devices), base=FEEDER_BASE_DEPTH, cap=FEEDER_DEPTH_CAP
        )
        if not enabled():
            return base
        return max(2, min(FEEDER_DEPTH_CAP, base + self.depth_extra))

    def snapshot(self) -> dict[str, Any]:
        return {
            "rung": self.rung,
            "rows_per_device": self.dispatch_rows_per_device(),
            "window_scale": round(self.window_scale, 3),
            "depth_extra": self.depth_extra,
            "pool_scale": round(self.pool_scale, 3),
            "pool_quantum": self.procpool_batch_rows(),
        }


@dataclass
class Sample:
    """One tick's telemetry deltas (cumulative reads diffed by the
    controller; tests may hand-build one and feed it to tick())."""

    wait_mean_s: float | None = None   # mean feeder wait per take
    wait_n: int = 0
    fetch_s: float = 0.0               # producer fetch time this tick
    fetch_n: int = 0
    h2d_bytes: float = 0.0
    occ_mean: dict[str, float | None] = field(default_factory=dict)
    occ_n: dict[str, int] = field(default_factory=dict)
    link_gbps: float = 0.0             # latest probe; 0 = no probe yet
    loop_lag_s: float = 0.0
    demotion_level: int = 0
    # procpool per-batch deltas this tick (owner-side series)
    pool_batches: int = 0
    pool_dispatch_s: float = 0.0
    pool_roundtrip_s: float = 0.0
    pool_rows: float = 0.0


#: which occupancy `op` label feeds each workload's rung control
_OCC_OP = {"identify": "blake3", "thumbnail": "thumbnail", "embed": "embed"}


class Controller:
    """Samples the registry on an interval and nudges the policies.

    ``tick()`` is synchronous and side-effect-complete, so tests and
    the bench drive it directly; ``start()``/``stop()`` run it on a
    supervised asyncio task (Node lifecycle), interval from
    ``SD_AUTOTUNE_INTERVAL_S`` (default 1.0)."""

    def __init__(self, interval: float | None = None):
        self.interval = interval if interval is not None else float(
            os.environ.get("SD_AUTOTUNE_INTERVAL_S", "1.0")
        )
        self.policies: dict[str, PipelinePolicy] = {
            w: PipelinePolicy(w) for w in WORKLOADS
        }
        self._lock = threading.Lock()
        self._prev: dict[str, Any] | None = None
        # (workload, knob) -> signed streak of same-direction wishes
        self._streaks: dict[tuple[str, str], int] = {}
        # execution-continuum outputs: per-stage observed rate (folded
        # from scheduler.RATES each tick) and the derived lease target
        # the WORK board falls back to when a claimer reports no rate
        self.stage_rates: dict[str, float] = {}
        self.stage_lease: dict[str, float] = {}
        self._task: Any = None
        self._tasks: set = set()
        self._stopped = False
        # CONTROLLER is process-global while Nodes start/stop it:
        # refcount so the first of two in-process nodes to shut down
        # doesn't kill the survivor's tuning
        self._starts = 0
        self.ticks = 0

    # ---- lifecycle (mirrors telemetry.events.LoopLagMonitor) ---------

    def start(self) -> None:
        import asyncio
        import logging

        from ..utils.tasks import supervise

        if not enabled():
            return
        self._starts += 1
        if self._task is not None and not self._task.done():
            # a never-done task on a CLOSED loop (a node torn down
            # without shutdown) would otherwise wedge start() forever —
            # drop it and adopt the tick loop onto the current loop; a
            # task on any still-open loop keeps ticking for everyone
            if not self._task.get_loop().is_closed():
                return
            self._task = None
        # surface the knob gauges immediately: a quiet controller that
        # never steps is invisible on /metrics otherwise
        for w, p in self.policies.items():
            self._export_gauges(w, p)
        self._stopped = False
        self._task = supervise(
            asyncio.get_running_loop().create_task(self._run()),
            self._tasks, logging.getLogger(__name__), "autotune controller",
        )

    async def stop(self) -> None:
        self._starts = max(0, self._starts - 1)
        if self._starts > 0:
            return  # another in-process node still depends on the loop
        self._stopped = True
        task = self._task
        self._task = None
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except BaseException:  # noqa: BLE001 - cancellation cleanup
                pass

    async def _run(self) -> None:
        import asyncio

        while not self._stopped:
            await asyncio.sleep(self.interval)
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - a bad tick must not kill the loop
                import logging

                logging.getLogger(__name__).exception("autotune tick failed")

    def reset(self) -> None:
        with self._lock:
            for p in self.policies.values():
                p.reset()
            self._prev = None
            self._streaks.clear()
            self.ticks = 0
            self.stage_rates.clear()
            self.stage_lease.clear()
        for w, p in self.policies.items():
            self._export_gauges(w, p)

    def stage_rate(self, stage_id: str) -> float:
        """The Controller's per-stage rate output (files/s) — 0.0 until
        the stage has executed shards here. The WORK board's lease
        fallback when a claimer self-reports no rate for a stage."""
        return self.stage_rates.get(stage_id, 0.0)

    def reset_stage_targets(self) -> None:
        """Clears the continuum state (scheduler.reset() fans out here;
        telemetry.reset() zeroes the gauges themselves)."""
        self.stage_rates.clear()
        self.stage_lease.clear()

    # ---- sampling ----------------------------------------------------

    def _cumulative(self) -> dict[str, Any]:
        from ..telemetry import metrics as _tm
        from ..telemetry.snapshot import gauge_value

        occ = {
            op: _tm.DEVICE_DISPATCH_OCCUPANCY.stats(op=op)
            for op in _OCC_OP.values()
        }
        return {
            "wait": _tm.FEEDER_WAIT_SECONDS.stats(),
            "fetch": _tm.FEEDER_FETCH_SECONDS.stats(),
            "h2d": _tm.FEEDER_H2D_BYTES.value(),
            "occ": occ,
            "link": gauge_value("sd_bench_link_probe_gbps"),
            "lag": gauge_value("sd_event_loop_lag_seconds"),
            "pool_dispatch": _tm.PROCPOOL_DISPATCH_SECONDS.stats(),
            "pool_rt": _tm.PROCPOOL_ROUNDTRIP_SECONDS.stats(),
            "pool_rows": _tm.PROCPOOL_BATCH_ROWS.stats(),
        }

    def sample(self) -> Sample:
        """Diff the registry against the previous tick's cumulative
        snapshot. The first call primes the baseline and returns an
        empty sample (cold start ⇒ static defaults hold)."""
        from . import mesh as _mesh

        cur = self._cumulative()
        prev, self._prev = self._prev, cur
        s = Sample(
            link_gbps=cur["link"],
            loop_lag_s=cur["lag"],
            demotion_level=int(_mesh.LADDER.level),
        )
        if prev is None:
            return s
        dwait_n = int(cur["wait"]["count"] - prev["wait"]["count"])
        dwait_s = cur["wait"]["sum"] - prev["wait"]["sum"]
        if dwait_n > 0:
            s.wait_mean_s = dwait_s / dwait_n
            s.wait_n = dwait_n
        s.fetch_n = int(cur["fetch"]["count"] - prev["fetch"]["count"])
        s.fetch_s = cur["fetch"]["sum"] - prev["fetch"]["sum"]
        s.h2d_bytes = cur["h2d"] - prev["h2d"]
        for op in _OCC_OP.values():
            dn = int(cur["occ"][op]["count"] - prev["occ"][op]["count"])
            ds = cur["occ"][op]["sum"] - prev["occ"][op]["sum"]
            s.occ_n[op] = dn
            s.occ_mean[op] = (ds / dn) if dn > 0 else None
        s.pool_batches = int(
            cur["pool_rt"]["count"] - prev["pool_rt"]["count"])
        s.pool_dispatch_s = (
            cur["pool_dispatch"]["sum"] - prev["pool_dispatch"]["sum"])
        s.pool_roundtrip_s = cur["pool_rt"]["sum"] - prev["pool_rt"]["sum"]
        s.pool_rows = cur["pool_rows"]["sum"] - prev["pool_rows"]["sum"]
        return s

    # ---- the control law ---------------------------------------------

    def tick(self, sample: Sample | None = None) -> list[dict[str, Any]]:
        """One sampling + adjustment pass; returns the decisions made
        (also recorded on the ``autotune`` ring + metrics)."""
        if not enabled():
            return []
        with self._lock:
            if sample is None:
                sample = self.sample()
            self.ticks += 1
            decisions: list[dict[str, Any]] = []
            for workload, pol in self.policies.items():
                decisions.extend(self._tick_workload(workload, pol, sample))
            decisions.extend(self._tick_stages(sample))
        return decisions

    def _tick_workload(
        self, workload: str, pol: PipelinePolicy, s: Sample
    ) -> list[dict[str, Any]]:
        """Per-knob wishes are three-valued: ±1 asks for a damped step,
        0 is CONTRARY/neutral evidence (resets the streak — alternating
        congested/clear samples therefore never step), None is NO
        evidence (an idle tick holds the streak — silence is not a
        counter-argument)."""
        out: list[dict[str, Any]] = []
        congested = 0 < s.link_gbps < CONGESTED_GBPS
        clear = s.link_gbps >= CONGESTED_GBPS
        lagging = self._loop_lagging(s)
        occ = s.occ_mean.get(_OCC_OP[workload])

        # --- window scale (host window / chunk rows) ---
        # NOTE: event-loop lag deliberately does NOT shed the window: a
        # batch pass on a small host drags the loop regardless (the
        # work, not the window, is the cause), and a WIDER window means
        # fewer steps and fewer DB commits per file — shrinking it
        # under lag measurably made both arms of the A/B slower.
        want: int | None
        urgent = False
        reason = ""
        if congested:
            # scarce link: decay any amortization back to the static
            # base (the rung below handles the padding-vs-link tradeoff)
            want = -1 if pol.window_scale > SCALE_MIN else 0
            reason = "congested"
        elif workload == "identify":
            if s.wait_mean_s is None:
                # a clear link with an idle feeder argues against a
                # congestion-driven shrink; an unknown link says nothing
                want = 0 if clear else None
            elif s.wait_mean_s >= STARVED_WAIT_S:
                want = +1  # amortize the per-window cost
                urgent = s.wait_mean_s >= URGENT_WAIT_S
                reason = "starved"
            elif s.wait_mean_s <= OVERBUFFERED_WAIT_S \
                    and pol.window_scale > 1.0:
                want = -1  # decay toward static
                reason = "overbuffered"
            else:
                want = 0
        else:
            # no feeder on the thumbnail path: chunk sizing tracks how
            # full the device chunks actually run
            if occ is None:
                want = 0 if clear else None
            elif occ >= OCC_HIGH and not congested:
                # full chunks justify growth on their own: the link
                # probe only exists on bench rigs (production nodes
                # never set it), so requiring a positive probe here
                # would make this knob demote-only in production
                want = +1
                reason = "saturate"
            elif occ < OCC_LOW and pol.window_scale > 1.0:
                want = -1
                reason = "pad-waste"
            else:
                want = 0
        if self._step(workload, "window", want, urgent=urgent):
            new = pol.window_scale * (2.0 if want > 0 else 0.5)
            new = min(SCALE_MAX, max(SCALE_MIN, new))
            if new != pol.window_scale:
                out.append(self._apply(
                    workload, pol, "window_scale", pol.window_scale, new, s,
                    reason,
                ))
                pol.window_scale = new

        # --- feeder depth (identify only: the thumbnailer's software
        # pipeline is structurally 3-deep) ---
        if workload == "identify":
            if lagging or congested:
                # in-flight windows are in-flight bytes AND loop work:
                # shed any boost (never below the static base — lag on
                # a small host is the workload's fault, not the depth's)
                want = -1 if pol.depth_extra > 0 else 0
            elif s.wait_mean_s is None:
                # a clear link with an idle feeder is contrary evidence
                # against congestion-driven shedding, but says nothing
                # about starvation
                want = 0 if clear else None
            elif s.wait_mean_s >= STARVED_WAIT_S:
                want = +1
            elif s.wait_mean_s <= OVERBUFFERED_WAIT_S \
                    and pol.depth_extra > 0:
                want = -1
            else:
                want = 0
            if self._step(workload, "depth", want):
                new_extra = pol.depth_extra + (1 if want > 0 else -1)
                new_extra = max(0, min(FEEDER_DEPTH_CAP, new_extra))
                if new_extra != pol.depth_extra:
                    out.append(self._apply(
                        workload, pol, "depth_extra",
                        pol.depth_extra, new_extra, s,
                        "starved" if want > 0 else
                        ("loop-lag" if lagging else
                         "congested" if congested else "overbuffered"),
                    ))
                    pol.depth_extra = new_extra

        # --- dispatch rung (identify only: the thumbnail resize pads
        # pow2 per size bucket, not the cas ladder) ---
        if workload == "identify":
            cap = _ladder_rung_cap()
            if pol.rung > cap:
                # demotion clamp applies immediately, undamped: the
                # resilience plane already proved those chips are gone
                out.append(self._apply(
                    workload, pol, "rung", pol.rung, cap, s,
                    "device-ladder-demotion",
                ))
                pol.rung = cap
                self._streaks.pop((workload, "rung"), None)
            if congested:
                # small batches pad less: fewer junk bytes on the
                # scarce link, steadier flow
                want = -1 if pol.rung > 0 else 0
            elif occ is not None:
                if occ < OCC_LOW:
                    want = -1 if pol.rung > 0 else 0
                elif occ >= OCC_HIGH:
                    # full batches justify promotion whether or not a
                    # probe exists (only bench rigs set one) — a
                    # probe-gated promote would be a demote-only
                    # ratchet in production. Congestion is excluded by
                    # the branch above.
                    want = +1  # saturate (a no-op step at the cap)
                else:
                    want = 0 if clear else None
            elif clear:
                # link demonstrably clear and nothing argues against
                # saturating — drift back toward the top rung
                want = +1 if pol.rung < cap else 0
            else:
                want = None
            if self._step(workload, "rung", want):
                new_rung = max(0, min(cap, pol.rung + (1 if want > 0 else -1)))
                if new_rung != pol.rung:
                    out.append(self._apply(
                        workload, pol, "rung", pol.rung, new_rung, s,
                        "congested" if (congested and want < 0) else
                        ("pad-waste" if want < 0 else "saturate"),
                    ))
                    pol.rung = new_rung

        out.extend(self._tick_pool(workload, pol, s))
        return out

    def _tick_pool(
        self, workload: str, pol: PipelinePolicy, s: Sample
    ) -> list[dict[str, Any]]:
        """Procpool batch-quantum control (the execution continuum's
        IPC leg). Evidence is the owner-side per-batch deltas — shared
        across workloads because the pool is, so each workload's knob
        sees the same signal but keeps its own damped streak:

        - **slow roundtrips** (mean submit→result past
          ``POOL_RT_SLOW_S``): the quantum is hurting latency — and a
          stolen shard's lease margin — so shrink toward static;
        - **underfilled** (mean rows under half the current quantum
          while scaled up): call sites aren't producing batches that
          size, so the scale buys nothing — decay;
        - **IPC tax** (dispatch time ≥ ``POOL_DISPATCH_SHARE`` of the
          roundtrip while roundtrips are fast): serialization + queue
          overhead dominates — widen the quantum to amortize it."""
        if s.pool_batches <= 0:
            want: int | None = None  # idle pool: silence, not evidence
            reason = ""
        else:
            rt_mean = s.pool_roundtrip_s / s.pool_batches
            rows_mean = s.pool_rows / s.pool_batches
            share = (s.pool_dispatch_s / s.pool_roundtrip_s
                     if s.pool_roundtrip_s > 0 else 0.0)
            if rt_mean >= POOL_RT_SLOW_S and pol.pool_scale > POOL_SCALE_MIN:
                want, reason = -1, "slow-roundtrip"
            elif (rows_mean < 0.5 * pol.procpool_batch_rows()
                    and pol.pool_scale > POOL_SCALE_MIN):
                want, reason = -1, "underfilled"
            elif share >= POOL_DISPATCH_SHARE and rt_mean < POOL_RT_SLOW_S:
                want, reason = +1, "ipc-tax"
            else:
                want, reason = 0, ""
        if not self._step(workload, "pool", want):
            return []
        new = pol.pool_scale * (2.0 if want > 0 else 0.5)
        new = min(POOL_SCALE_MAX, max(POOL_SCALE_MIN, new))
        if new == pol.pool_scale:
            return []
        decision = self._apply(
            workload, pol, "pool_scale", pol.pool_scale, new, s, reason)
        pol.pool_scale = new
        return [decision]

    def _tick_stages(self, s: Sample) -> list[dict[str, Any]]:
        """Per-stage lease targets (the continuum's WORK-board output):
        fold the scheduler's per-stage throughput EWMAs into the lease
        a default-sized shard would need at that rate, clamped to the
        board's lease law bounds. Republished only past the hysteresis
        band — lease sizing is a fallback path, not a hot loop."""
        from ..p2p import work as _work
        from . import scheduler as _scheduler

        out: list[dict[str, Any]] = []
        try:
            from ..location.indexer.mesh import shard_files_default

            files = shard_files_default()
        except Exception:  # noqa: BLE001 - sizing default is fine
            files = 128
        for stage_id in _scheduler.STAGES:
            rate = _scheduler.RATES.rate(stage_id)
            if rate <= 0:
                continue
            self.stage_rates[stage_id] = rate
            target = min(
                _work.LEASE_MAX_S,
                max(_work.LEASE_MIN_S, files / rate * _work.LEASE_SLACK),
            )
            old = self.stage_lease.get(stage_id)
            if old is not None and old > 0 \
                    and abs(target - old) <= STAGE_LEASE_HYSTERESIS * old:
                continue
            self.stage_lease[stage_id] = target
            from ..telemetry import metrics as _tm
            from ..telemetry.events import AUTOTUNE_EVENTS

            AUTOTUNE_EVENTS.emit(
                "stage-lease",
                stage=stage_id,
                rate_files_per_s=round(rate, 3),
                old=None if old is None else round(old, 3),
                new=round(target, 3),
            )
            # inline bounded conditional pins the label domain at the
            # emit site (SD007): the stage registry is the vocabulary
            _tm.WORK_STAGE_LEASE_TARGET.set(
                target,
                stage="identify.hash" if stage_id == "identify.hash" else (
                    "thumb" if stage_id == "thumb" else (
                        "media.extract" if stage_id == "media.extract" else (
                            "phash" if stage_id == "phash" else (
                                "embed" if stage_id == "embed"
                                else "other")))),
            )
            out.append({
                "knob": "stage_lease", "stage": stage_id,
                "from": old, "to": target,
                "rate_files_per_s": round(rate, 3),
            })
        return out

    @staticmethod
    def _loop_lagging(s: Sample) -> bool:
        from ..telemetry.health import LOOP_LAG_DEGRADED

        return s.loop_lag_s >= LOOP_LAG_DEGRADED

    def _step(self, workload: str, knob: str, want: int | None,
              urgent: bool = False) -> bool:
        """Damping: return True when `want` (±1) has persisted for
        STEP_STREAK consecutive deciding ticks. None (no evidence)
        holds the streak; 0 (contrary/neutral evidence) resets it; an
        opposite wish restarts it in the new direction. ``urgent``
        promotions (extreme waits) step immediately — the next
        confirmation would cost another extreme wait to collect."""
        key = (workload, knob)
        if want is None:
            return False
        if want == 0:
            self._streaks.pop(key, None)
            return False
        if urgent and want > 0:
            self._streaks[key] = 0
            return True
        streak = self._streaks.get(key, 0)
        streak = streak + want if (streak > 0) == (want > 0) or streak == 0 \
            else want
        if abs(streak) >= STEP_STREAK:
            self._streaks[key] = 0
            return True
        self._streaks[key] = streak
        return False

    def _apply(
        self, workload: str, pol: PipelinePolicy, knob: str,
        old: Any, new: Any, s: Sample, reason: str,
    ) -> dict[str, Any]:
        from ..telemetry import metrics as _tm
        from ..telemetry.events import AUTOTUNE_EVENTS

        action = "promote" if (new > old) else "demote"
        decision = {
            "workload": workload, "knob": knob, "action": action,
            "from": old, "to": new, "reason": reason,
        }
        AUTOTUNE_EVENTS.emit(
            "decision",
            workload=workload,
            knob=knob,
            action=action,
            old=old,
            new=new,
            reason=reason,
            wait_mean_s=None if s.wait_mean_s is None
            else round(s.wait_mean_s, 4),
            link_gbps=round(s.link_gbps, 3),
            loop_lag_s=round(s.loop_lag_s, 4),
            demotion_level=s.demotion_level,
        )
        # inline bounded conditionals pin the label domains at the
        # emit site (SD007): WORKLOADS and the action verbs are the
        # entire vocabulary
        _tm.AUTOTUNE_DECISIONS.inc(
            workload="identify" if workload == "identify"
            else ("thumbnail" if workload == "thumbnail" else "embed"),
            action="promote" if action == "promote" else "demote",
        )
        self._export_gauges(workload, pol, knob, new)
        return decision

    def _export_gauges(
        self, workload: str, pol: PipelinePolicy,
        knob: str | None = None, new: Any = None,
    ) -> None:
        from ..telemetry import metrics as _tm

        scale = new if knob == "window_scale" else pol.window_scale
        rung = new if knob == "rung" else pol.rung
        extra = new if knob == "depth_extra" else pol.depth_extra
        pscale = new if knob == "pool_scale" else pol.pool_scale
        # inline bounded conditionals pin the label domain at each
        # emit site (SD007): WORKLOADS is the entire vocabulary
        _tm.AUTOTUNE_WINDOW_SCALE.set(
            float(scale),
            workload="identify" if workload == "identify"
            else ("thumbnail" if workload == "thumbnail" else "embed"))
        _tm.AUTOTUNE_RUNG.set(
            float(rung),
            workload="identify" if workload == "identify"
            else ("thumbnail" if workload == "thumbnail" else "embed"))
        _tm.AUTOTUNE_DEPTH_EXTRA.set(
            float(extra),
            workload="identify" if workload == "identify"
            else ("thumbnail" if workload == "thumbnail" else "embed"))
        _tm.AUTOTUNE_POOL_SCALE.set(
            float(pscale),
            workload="identify" if workload == "identify"
            else ("thumbnail" if workload == "thumbnail" else "embed"))

    def snapshot(self) -> dict[str, Any]:
        """Current knob state — embedded in health.evaluate() so the
        federation snapshot carries autotune state onto GET /mesh,
        including the execution continuum's per-stage rates and lease
        targets (the Controller's WORK-board outputs)."""
        from . import scheduler as _scheduler

        return {
            "enabled": enabled(),
            "ticks": self.ticks,
            "policies": {
                w: p.snapshot() for w, p in self.policies.items()
            },
            "stages": {
                **_scheduler.snapshot(),
                "lease_targets": {
                    st: round(v, 3) for st, v in self.stage_lease.items()
                },
            },
        }


#: the process-wide controller + policies every consumer reads
CONTROLLER = Controller()


def policy(workload: str) -> PipelinePolicy:
    """The live policy object for a workload — THE seam. Unknown
    workloads fail loudly (a typo must not mint an untuned policy)."""
    return CONTROLLER.policies[workload]


def snapshot() -> dict[str, Any]:
    return CONTROLLER.snapshot()


def observed_files_per_s(workload: str = "identify") -> float | None:
    """Telemetry-derived throughput for a workload — the same registry
    series the controller ticks on, folded to one number. Used by the
    mesh work plane: a claiming peer self-reports this rate so the
    coordinator can size its lease (p2p/work.py), before the worker has
    any shard-measured rate of its own. None until the workload has
    processed anything here."""
    from ..telemetry import metrics as _tm

    if workload != "identify":
        return None
    files = _tm.IDENTIFIER_FILES.value()
    secs = (
        _tm.IDENTIFIER_STAGE_SECONDS.stats(stage="hash")["sum"]
        + _tm.IDENTIFIER_STAGE_SECONDS.stats(stage="db")["sum"]
    )
    if not files or secs <= 0:
        return None
    return files / secs


def reset() -> None:
    """Test/bench isolation: static knobs, cleared streaks/baselines."""
    CONTROLLER.reset()


__all__ = [
    "BATCH_LADDER",
    "CONTROLLER",
    "Controller",
    "FEEDER_BASE_DEPTH",
    "FEEDER_DEPTH_CAP",
    "IDENTIFY_CPU_WINDOW",
    "IDENTIFY_DEVICE_WINDOW",
    "EMBED_DEVICE_BATCH",
    "PipelinePolicy",
    "Sample",
    "THUMB_DEVICE_BATCH",
    "enabled",
    "policy",
    "reset",
    "snapshot",
]
