"""Device-mesh construction + canonical shardings.

SURVEY §2.4: the reference's parallelism (worker-per-core task system,
NCCL-free QUIC mesh) maps onto TPU primitives as batch-parallel
`shard_map`/`pjit` over a `jax.sharding.Mesh`. This module owns the
canonical axis vocabulary — `dp` (batch), `fsdp` (param shards), `tp`
(tensor) — and the helpers every call site shares, so meshes are built
one way everywhere (`__graft_entry__.dryrun_multichip` exercises the
same factoring on the driver's virtual device count).

Multi-host: `multihost_init()` wraps `jax.distributed.initialize` —
inside a pod/slice collectives ride ICI; across hosts, DCN. Library
metadata sync stays on the host-side CRDT/P2P plane (§5), never on
device collectives.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Any, Sequence

import numpy as np

AXES = ("dp", "fsdp", "tp")


def factor3(n: int) -> tuple[int, int, int]:
    """n devices → (dp, fsdp, tp), preferring tp=2 then fsdp=2 (the
    same factoring the driver dry-runs)."""
    tp = 2 if n % 2 == 0 else 1
    rem = n // tp
    fsdp = 2 if rem % 2 == 0 else 1
    return rem // fsdp, fsdp, tp


def make_mesh(
    devices: Sequence[Any] | None = None,
    shape: tuple[int, int, int] | None = None,
):
    """Standard 3-axis mesh over the available devices."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    dp, fsdp, tp = shape or factor3(len(devices))
    count = dp * fsdp * tp
    return Mesh(np.array(devices[:count]).reshape(dp, fsdp, tp), AXES)


def flat_mesh(devices: Sequence[Any] | None = None):
    """One-axis `dp` mesh — batch-parallel work (hashing, pHash rows)."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), ("dp",))


def batch_sharding(mesh: Any, *, all_axes: bool = False):
    """NamedSharding splitting dim 0 over dp (or every axis)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(tuple(mesh.axis_names)) if all_axes else P(mesh.axis_names[0])
    return NamedSharding(mesh, spec)


def replicated(mesh: Any):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def pad_to_multiple(arr: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Pad dim 0 so sharded batches divide evenly; returns (arr, pad)."""
    pad = (-arr.shape[0]) % multiple
    if pad:
        arr = np.concatenate(
            [arr, np.zeros((pad, *arr.shape[1:]), arr.dtype)]
        )
    return arr, pad


_ACCEL_COUNT: list[int] | None = None


def accelerator_count() -> int:
    """Local non-CPU device count, 1 when only CPU (or no jax) is live.

    The batch/depth scale factor for dp dispatch: the identifier's
    chunk size, the thumbnailer's device chunk, and the feeder depth
    all multiply by this so one host window feeds the whole mesh.
    Virtual host-platform devices deliberately do NOT count — they
    share the same cores, so scaling host batches by them only makes
    batches slower."""
    global _ACCEL_COUNT
    if _ACCEL_COUNT is None:
        try:
            import jax

            devs = jax.devices()
            _ACCEL_COUNT = [
                len(devs) if devs and devs[0].platform != "cpu" else 1
            ]
        except Exception:  # noqa: BLE001 - no usable accelerator
            _ACCEL_COUNT = [1]
    return _ACCEL_COUNT[0]


def dispatch_devices() -> list:
    """All local JAX devices for dp-sharded dispatch ([] when jax is
    unusable). Unlike `accelerator_count`, virtual CPU devices DO
    appear here — sharding is a correctness surface the test suite
    exercises on the forced host platform."""
    try:
        import jax

        return list(jax.devices())
    except Exception:  # noqa: BLE001
        return []


# --- graceful degradation ladder (utils/resilience + utils/faults) ---------

LEVEL_MESH = 0      # full dp mesh — every local device
LEVEL_SUBSET = 1    # surviving chip subset (per-device probe survivors)
LEVEL_HOST = 2      # host reference path — no device dispatch at all


class DeviceLadder:
    """Demotion ladder for device dispatch: all chips → surviving chip
    subset → host reference path — a failed batch degrades instead of
    failing the job.

    Callers take ``(devices, level)`` from :meth:`filter` and report
    the dispatch outcome back via :meth:`record_success` /
    :meth:`record_failure`. Demotion probes each device individually
    (one tiny transfer+readback, routed through the ``device.probe``
    fault point so chaos tests pick which chips "die") and keeps the
    survivors. After ``reset_timeout`` the ladder hands out ONE
    half-open probe dispatch at the next level up; its success re-arms
    (promotes), its failure restarts the clock — the same breaker
    discipline as ``utils.resilience.CircuitBreaker``, but over ladder
    rungs instead of a binary gate.

    Every transition updates ``sd_device_demotion_level`` and lands on
    the ``resilience`` flight ring, so a node quietly hashing on one
    chip (or on the CPU) is visible from /metrics, /health, and /mesh.
    """

    def __init__(self, reset_timeout: float = 30.0):
        self.reset_timeout = reset_timeout
        self._level = LEVEL_MESH
        self._subset_ids: frozenset | None = None
        self._demoted_at = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0
        self._lock = threading.Lock()

    def reset(self) -> None:
        with self._lock:
            self._level = LEVEL_MESH
            self._subset_ids = None
            self._probe_inflight = False
        self._set_gauge(LEVEL_MESH)

    @property
    def level(self) -> int:
        return self._level

    @staticmethod
    def _set_gauge(level: int) -> None:
        from ..telemetry import metrics as _tm

        _tm.DEVICE_DEMOTION.set(float(level))

    def _probe_device(self, index: int, dev: Any) -> bool:
        from ..utils import faults as _faults

        if _faults.hit("device.probe", arg=str(index)) is not None:
            return False
        try:
            import jax

            back = np.asarray(jax.device_put(np.arange(4, dtype=np.int32), dev))
            return bool((back == np.arange(4)).all())
        except Exception:  # noqa: BLE001 - a dead chip raises anything
            return False

    def _survivors(self, devices: Sequence[Any]) -> list[Any]:
        return [
            d for i, d in enumerate(devices) if self._probe_device(i, d)
        ]

    def filter(self, devices: Sequence[Any]) -> tuple[list[Any], int]:
        """The device set + ladder level for the next dispatch. An
        empty list means the host path. When a demoted ladder's reset
        timeout has elapsed, ONE caller gets the promoted level as a
        half-open probe (it must report the outcome)."""
        devices = list(devices)
        now = time.monotonic()
        with self._lock:
            level = self._level
            if (
                level > LEVEL_MESH
                # an in-flight probe older than the reset window was
                # abandoned (its dispatch died without reporting) —
                # don't let it wedge re-arming forever
                and (not self._probe_inflight
                     or now - self._probe_started >= self.reset_timeout)
                and now - self._demoted_at >= self.reset_timeout
            ):
                level -= 1
                self._probe_inflight = True
                self._probe_started = now
            subset_ids = self._subset_ids
        if level == LEVEL_MESH:
            return devices, level
        if level == LEVEL_HOST:
            return [], level
        if subset_ids:
            subset = [d for d in devices if d.id in subset_ids]
        else:
            subset = self._survivors(devices)
            if subset:
                # cache the sweep (e.g. after a HOST→SUBSET re-arm left
                # no subset) — probing every device is a blocking
                # round-trip per chip and must not run per dispatch
                with self._lock:
                    if self._subset_ids is None:
                        self._subset_ids = frozenset(d.id for d in subset)
        return (subset or devices[:1]), level

    def record_success(self, level: int) -> None:
        """A dispatch at ``level`` completed — a half-open probe's
        success promotes (re-arms) the ladder to that level. Only the
        probe holder (level below current) touches probe bookkeeping:
        a concurrent same-level dispatch reporting in must not clear an
        in-flight probe it does not own."""
        from ..telemetry.events import RESILIENCE_EVENTS

        with self._lock:
            if level >= self._level:
                return
            self._probe_inflight = False
            self._level = level
            if level == LEVEL_MESH:
                self._subset_ids = None
        self._set_gauge(level)
        RESILIENCE_EVENTS.emit("device_promote", level=level)

    def probe_inconclusive(self, level: int) -> None:
        """A dispatch holding the half-open probe finished WITHOUT
        actually exercising the rung's devices (e.g. a tail batch too
        small to shard ran on the single default device) — release the
        probe slot without promoting, so the next real dispatch gets
        the probe instead of a false re-arm."""
        with self._lock:
            if level < self._level:
                self._probe_inflight = False

    def record_failure(self, level: int, devices: Sequence[Any]) -> int:
        """A dispatch at ``level`` failed — demote one rung (probing
        for survivors when leaving the full mesh) and return the new
        level."""
        from ..telemetry.events import RESILIENCE_EVENTS

        devices = list(devices)
        if level == LEVEL_MESH and len(devices) > 1:
            survivors = self._survivors(devices)
            next_level = LEVEL_SUBSET if survivors else LEVEL_HOST
            subset = frozenset(d.id for d in survivors)
        else:
            next_level = LEVEL_HOST
            subset = None
        with self._lock:
            if level < self._level:
                self._probe_inflight = False  # the probe itself failed
            if next_level <= self._level:
                # another dispatch already demoted at least this far;
                # just restart the re-arm clock
                self._demoted_at = time.monotonic()
                return self._level
            self._level = next_level
            self._subset_ids = subset
            self._demoted_at = time.monotonic()
        self._set_gauge(next_level)
        RESILIENCE_EVENTS.emit(
            "device_demote",
            level=next_level,
            survivors=len(subset) if subset is not None else 0,
            failed_level=level,
        )
        return next_level


#: the process-wide ladder every auto-policy dispatch consults
LADDER = DeviceLadder()


def ladder_devices() -> tuple[list[Any], int]:
    """``dispatch_devices()`` filtered through the degradation ladder:
    (devices, level) — an empty list means use the host reference
    path. Callers MUST report the dispatch outcome back to ``LADDER``
    so demotion/re-arm bookkeeping stays truthful."""
    devs = dispatch_devices()
    if not devs:
        return [], LEVEL_HOST
    return LADDER.filter(devs)


def multihost_init(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join a multi-host JAX cluster (ref role: the NCCL/MPI backend of
    a conventional stack). No-ops when the env provides no cluster —
    single-host keeps working untouched."""
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "SD_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None and num_processes is None:
        env = os.environ
        if not any(k in env for k in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS")):
            return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except Exception:
        return False
