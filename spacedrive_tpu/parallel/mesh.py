"""Device-mesh construction + canonical shardings.

SURVEY §2.4: the reference's parallelism (worker-per-core task system,
NCCL-free QUIC mesh) maps onto TPU primitives as batch-parallel
`shard_map`/`pjit` over a `jax.sharding.Mesh`. This module owns the
canonical axis vocabulary — `dp` (batch), `fsdp` (param shards), `tp`
(tensor) — and the helpers every call site shares, so meshes are built
one way everywhere (`__graft_entry__.dryrun_multichip` exercises the
same factoring on the driver's virtual device count).

Multi-host: `multihost_init()` wraps `jax.distributed.initialize` —
inside a pod/slice collectives ride ICI; across hosts, DCN. Library
metadata sync stays on the host-side CRDT/P2P plane (§5), never on
device collectives.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Sequence

import numpy as np

AXES = ("dp", "fsdp", "tp")


def factor3(n: int) -> tuple[int, int, int]:
    """n devices → (dp, fsdp, tp), preferring tp=2 then fsdp=2 (the
    same factoring the driver dry-runs)."""
    tp = 2 if n % 2 == 0 else 1
    rem = n // tp
    fsdp = 2 if rem % 2 == 0 else 1
    return rem // fsdp, fsdp, tp


def make_mesh(
    devices: Sequence[Any] | None = None,
    shape: tuple[int, int, int] | None = None,
):
    """Standard 3-axis mesh over the available devices."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    dp, fsdp, tp = shape or factor3(len(devices))
    count = dp * fsdp * tp
    return Mesh(np.array(devices[:count]).reshape(dp, fsdp, tp), AXES)


def flat_mesh(devices: Sequence[Any] | None = None):
    """One-axis `dp` mesh — batch-parallel work (hashing, pHash rows)."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), ("dp",))


def batch_sharding(mesh: Any, *, all_axes: bool = False):
    """NamedSharding splitting dim 0 over dp (or every axis)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(tuple(mesh.axis_names)) if all_axes else P(mesh.axis_names[0])
    return NamedSharding(mesh, spec)


def replicated(mesh: Any):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def pad_to_multiple(arr: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Pad dim 0 so sharded batches divide evenly; returns (arr, pad)."""
    pad = (-arr.shape[0]) % multiple
    if pad:
        arr = np.concatenate(
            [arr, np.zeros((pad, *arr.shape[1:]), arr.dtype)]
        )
    return arr, pad


_ACCEL_COUNT: list[int] | None = None


def accelerator_count() -> int:
    """Local non-CPU device count, 1 when only CPU (or no jax) is live.

    The batch/depth scale factor for dp dispatch: the identifier's
    chunk size, the thumbnailer's device chunk, and the feeder depth
    all multiply by this so one host window feeds the whole mesh.
    Virtual host-platform devices deliberately do NOT count — they
    share the same cores, so scaling host batches by them only makes
    batches slower."""
    global _ACCEL_COUNT
    if _ACCEL_COUNT is None:
        try:
            import jax

            devs = jax.devices()
            _ACCEL_COUNT = [
                len(devs) if devs and devs[0].platform != "cpu" else 1
            ]
        except Exception:  # noqa: BLE001 - no usable accelerator
            _ACCEL_COUNT = [1]
    return _ACCEL_COUNT[0]


def dispatch_devices() -> list:
    """All local JAX devices for dp-sharded dispatch ([] when jax is
    unusable). Unlike `accelerator_count`, virtual CPU devices DO
    appear here — sharding is a correctness surface the test suite
    exercises on the forced host platform."""
    try:
        import jax

        return list(jax.devices())
    except Exception:  # noqa: BLE001
        return []


def multihost_init(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join a multi-host JAX cluster (ref role: the NCCL/MPI backend of
    a conventional stack). No-ops when the env provides no cluster —
    single-host keeps working untouched."""
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "SD_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None and num_processes is None:
        env = os.environ
        if not any(k in env for k in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS")):
            return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except Exception:
        return False
