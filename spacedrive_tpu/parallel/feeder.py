"""Host→device feeding — overlap disk reads with device compute.

SURVEY §7 hard part #2 ("feeding the beast"): on a 1M-file library the
sampled reads (~56 KiB/file) dominate wall-clock, so the host must be
reading batch N+1 while the device hashes batch N.

`WindowPipeline` is the mechanism: a producer thread walks a
cursor-chained fetch function back-to-back (window N+1's reads start
the moment N's reads finish, not when the consumer takes N) into a
bounded queue of `depth` windows. Because each window's fetch also
*dispatches* its device batch asynchronously, up to `depth` transfers
ride the host→device link while earlier compute completes.

`PipelineStats` records overlap so jobs can report read vs compute time
honestly (the reference's RunMetadata timing discipline,
ref:indexer/indexer_job.rs:76-88).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Generic, TypeVar

from ..telemetry import metrics as _tm
from ..telemetry import span as _span
from ..telemetry import trace as _trace

T = TypeVar("T")


def pipeline_depth(n_devices: int, base: int = 3, cap: int = 8) -> int:
    """Prefetch depth that keeps an n-device dp dispatch fed: one extra
    in-flight window per doubling of the chip count (each window drains
    n× faster, so the producer needs more read-ahead to hide the same
    disk latency), capped so host memory stays bounded. 1→3, 2→4,
    4→5, 8→6."""
    return min(cap, base + max(0, int(n_devices).bit_length() - 1))


@dataclass
class PipelineStats:
    prefetch_hits: int = 0
    prefetch_misses: int = 0
    read_time: float = 0.0  # time the consumer WAITED on reads
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)


class WindowPipeline(Generic[T]):
    """Bounded multi-window producer pipeline.

    `fetch(key)` returns `(next_key, window)` — or `None` when the
    cursor is exhausted. A daemon producer thread chains fetches
    back-to-back and parks up to `depth` ready windows; `take()` hands
    them to the consumer in order (`None` = end of stream). `close()`
    stops the producer promptly (it also aborts any blocked put), so
    pause/cancel paths can't leak the thread; re-reading the in-flight
    windows after a resume is the caller's contract (fetches must be
    side-effect-free)."""

    def __init__(
        self,
        fetch: Callable[[Any], "tuple[Any, T] | None"],
        start_key: Any,
        depth: "int | Callable[[], int]" = 3,
        measure: Callable[[T], int] | None = None,
    ):
        # `measure(window) -> bytes` attributes each fetched window's
        # host→device payload to sd_feeder_h2d_bytes_total — the
        # counter BENCH_r05 was missing when the congested link had to
        # be diagnosed from print lines
        self._measure = measure
        self.stats = PipelineStats()
        # unbounded deque + condition (NOT a bounded Queue): close()
        # must wake a blocked consumer IMMEDIATELY. A bounded queue
        # could be full when close() tried to enqueue its wake-up
        # sentinel, leaving take() to discover shutdown only via a
        # 0.1 s poll; here `depth` only throttles the producer, and
        # close() just flips the flag under the condition and notifies.
        self._buf: collections.deque = collections.deque()
        self._cond = threading.Condition()
        # `depth` may be a callable (the autotuner's live policy read):
        # _put re-evaluates it per parked window, so a mid-job depth
        # adjustment takes effect on the very next fetch
        self._depth = depth if callable(depth) else None
        self._static_depth = 1 if callable(depth) else max(1, depth)
        self._stop = threading.Event()
        self._done = False
        self._fetch = fetch
        self._error: BaseException | None = None
        # one restart is cheap insurance against a transient producer
        # crash (fetches are side-effect-free, so re-reading the failed
        # window is safe); a second crash surfaces to the consumer
        self._restarts_left = 1
        # the producer thread starts with empty contextvars — carry the
        # constructing task's trace across so feeder.fetch spans join it
        self._trace_ctx = _trace.current()
        self._thread = threading.Thread(
            target=self._run, args=(start_key,), name="sd-window-pipeline",
            daemon=True,
        )
        self._thread.start()

    def _run(self, key: Any) -> None:
        from ..utils import faults as _faults

        if self._trace_ctx is not None:
            _trace.set_current(self._trace_ctx)
        try:
            while not self._stop.is_set():
                spec = _faults.hit("feeder.fetch")
                if spec is not None:
                    if spec.mode == "stall":
                        time.sleep(spec.delay_s)
                    elif spec.mode == "crash":
                        raise _faults.InjectedFault(
                            "injected feeder producer crash"
                        )
                t0 = time.perf_counter()
                with _span("feeder.fetch"):
                    item = self._fetch(key)
                fetch_s = time.perf_counter() - t0
                with self.stats._lock:
                    self.stats.read_time += fetch_s
                _tm.FEEDER_FETCH_SECONDS.observe(fetch_s)
                if item is None:
                    self._put(None)
                    return
                key, window = item
                if self._measure is not None:
                    try:
                        _tm.FEEDER_H2D_BYTES.inc(self._measure(window))
                    except Exception:  # measurement must never kill reads
                        pass
                if not self._put(window):
                    return
        except BaseException as e:
            if self._restart(key, e):
                return
            # restart budget spent: surfaced to the consumer on take().
            # Published under the condition BEFORE the sentinel is
            # parked, so the consumer that pops the sentinel (under the
            # same condition) always observes the error with it.
            with self._cond:
                self._error = e
            self._put(None)

    def _restart(self, key: Any, exc: BaseException) -> bool:
        """Re-spawn the producer once after a crash, resuming at the
        window whose fetch failed (fetches are side-effect-free per the
        class contract). Returns False when the budget is spent — the
        caller then surfaces the error."""
        from ..telemetry.events import RESILIENCE_EVENTS

        if self._stop.is_set() or self._restarts_left <= 0:
            return False
        self._restarts_left -= 1
        _tm.FEEDER_RESTARTS.inc()
        RESILIENCE_EVENTS.emit(
            "feeder_restart", error=str(exc)[:200],
        )
        replacement = threading.Thread(
            target=self._run, args=(key,), name="sd-window-pipeline",
            daemon=True,
        )
        # the handle swap races close()'s join of the old thread: both
        # sides go through the pipeline condition so close() always
        # joins the replacement, never a corpse
        with self._cond:
            self._thread = replacement
        replacement.start()
        return True

    def _depth_now(self) -> int:
        """Current read-ahead bound; a broken policy callable degrades
        to depth 1 (throttled, never wedged or unbounded)."""
        if self._depth is None:
            return self._static_depth
        try:
            return max(1, int(self._depth()))
        except Exception:  # noqa: BLE001 - policy reads must never kill reads
            return 1

    def _put(self, item) -> bool:
        """Park one window (or the end-of-stream sentinel) for the
        consumer; blocks while `depth` windows are already parked and
        aborts promptly when close() is called. The sentinel never
        blocks — the deque is unbounded, depth only throttles real
        windows, so end-of-stream (and a producer error) reaches the
        consumer even when the buffer is full."""
        with self._cond:
            while (
                item is not None
                and len(self._buf) >= self._depth_now()
                and not self._stop.is_set()
            ):
                self._cond.wait()
            if self._stop.is_set():
                return False
            self._buf.append(item)
            _tm.FEEDER_INFLIGHT.set(len(self._buf))
            self._cond.notify_all()
            return True

    def take(self) -> T | None:
        """Next window in order; None at end of stream (raises if the
        producer died) or after close(). The time the consumer spent
        blocked is recorded as a prefetch miss; instant handoffs count
        as hits. Once the end-of-stream sentinel has been consumed every
        further take() returns None immediately — the producer thread
        has exited and there is only one sentinel, so without this latch
        an extra take() (steps outnumbering windows, e.g. the orphan set
        shrank mid-run) would spin forever."""
        if self._done:
            with self._cond:
                err = self._error
            if err is not None:
                raise err
            return None
        t0 = time.perf_counter()
        with _span("feeder.wait"):
            with self._cond:
                while not self._buf and not self._stop.is_set():
                    self._cond.wait()
                if self._buf:
                    window = self._buf.popleft()
                    self._cond.notify_all()  # free the producer's slot
                else:  # closed: wake immediately, no sentinel needed
                    window = None
                inflight = len(self._buf)
                # producer publishes _error under this condition before
                # parking the sentinel — capture it under the same lock
                err = self._error
        waited = time.perf_counter() - t0
        hit = waited < 0.002
        with self.stats._lock:
            if hit:
                self.stats.prefetch_hits += 1
            else:
                self.stats.prefetch_misses += 1
        _tm.FEEDER_WAIT_SECONDS.observe(waited)
        _tm.FEEDER_PREFETCH.inc(result="hit" if hit else "miss")
        _tm.FEEDER_INFLIGHT.set(inflight)
        if window is None:
            self._done = True
            if err is not None:
                raise err
        return window

    def close(self) -> None:
        with self._cond:
            self._stop.set()
            # one notify wakes BOTH sides instantly: a producer blocked
            # on a full buffer and a consumer blocked on an empty one
            self._cond.notify_all()
            # snapshot under the condition: _restart() swaps the handle
            # under the same lock, so this is the live producer
            producer = self._thread
        producer.join(timeout=5)
