"""Host→device feeding — overlap disk reads with device compute.

SURVEY §7 hard part #2 ("feeding the beast"): on a 1M-file library the
sampled reads (~56 KiB/file) dominate wall-clock, so the host must be
reading batch N+1 while the device hashes batch N. `Prefetcher` is the
double-buffer: a bounded thread pool runs the read stage for the next
window while the caller consumes the current one; `PipelineStats`
records overlap so jobs can report read vs compute time honestly
(the reference's RunMetadata timing discipline,
ref:indexer/indexer_job.rs:76-88).
"""

from __future__ import annotations

import concurrent.futures
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Generic, TypeVar

T = TypeVar("T")


@dataclass
class PipelineStats:
    prefetch_hits: int = 0
    prefetch_misses: int = 0
    read_time: float = 0.0  # time the consumer WAITED on reads
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)


class Prefetcher(Generic[T]):
    """One-slot lookahead keyed by an opaque token (a cursor value):
    `submit(key, fn)` schedules the next window's read stage;
    `take(key)` returns it — immediately when the device outran the
    disk, or after the residual wait otherwise."""

    def __init__(self, max_workers: int = 2):
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="sd-prefetch"
        )
        self._slot: tuple[Any, concurrent.futures.Future] | None = None
        self.stats = PipelineStats()

    def submit(self, key: Any, fn: Callable[[], T]) -> None:
        self.cancel()  # one slot: a superseded prefetch is dropped
        self._slot = (key, self._pool.submit(fn))

    def take(self, key: Any, fallback: Callable[[], T]) -> T:
        """The window for `key`, from the prefetch slot when it matches,
        else computed inline via `fallback` (counted as a miss)."""
        t0 = time.perf_counter()
        slot = self._slot
        if slot is not None and slot[0] == key:
            self._slot = None
            result = slot[1].result()
            with self.stats._lock:
                self.stats.prefetch_hits += 1
                self.stats.read_time += time.perf_counter() - t0
            return result
        result = fallback()
        with self.stats._lock:
            self.stats.prefetch_misses += 1
            self.stats.read_time += time.perf_counter() - t0
        return result

    def cancel(self) -> None:
        if self._slot is not None:
            self._slot[1].cancel()
            self._slot = None

    def shutdown(self) -> None:
        self.cancel()
        self._pool.shutdown(wait=False, cancel_futures=True)
