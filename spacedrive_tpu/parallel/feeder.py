"""Host→device feeding — overlap disk reads with device compute.

SURVEY §7 hard part #2 ("feeding the beast"): on a 1M-file library the
sampled reads (~56 KiB/file) dominate wall-clock, so the host must be
reading batch N+1 while the device hashes batch N.

`WindowPipeline` is the mechanism: a producer thread walks a
cursor-chained fetch function back-to-back (window N+1's reads start
the moment N's reads finish, not when the consumer takes N) into a
bounded queue of `depth` windows. Because each window's fetch also
*dispatches* its device batch asynchronously, up to `depth` transfers
ride the host→device link while earlier compute completes.

`PipelineStats` records overlap so jobs can report read vs compute time
honestly (the reference's RunMetadata timing discipline,
ref:indexer/indexer_job.rs:76-88).
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Generic, TypeVar

from ..telemetry import metrics as _tm
from ..telemetry import span as _span
from ..telemetry import trace as _trace

T = TypeVar("T")


@dataclass
class PipelineStats:
    prefetch_hits: int = 0
    prefetch_misses: int = 0
    read_time: float = 0.0  # time the consumer WAITED on reads
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)


class WindowPipeline(Generic[T]):
    """Bounded multi-window producer pipeline.

    `fetch(key)` returns `(next_key, window)` — or `None` when the
    cursor is exhausted. A daemon producer thread chains fetches
    back-to-back and parks up to `depth` ready windows; `take()` hands
    them to the consumer in order (`None` = end of stream). `close()`
    stops the producer promptly (it also aborts any blocked put), so
    pause/cancel paths can't leak the thread; re-reading the in-flight
    windows after a resume is the caller's contract (fetches must be
    side-effect-free)."""

    def __init__(
        self,
        fetch: Callable[[Any], "tuple[Any, T] | None"],
        start_key: Any,
        depth: int = 3,
        measure: Callable[[T], int] | None = None,
    ):
        # `measure(window) -> bytes` attributes each fetched window's
        # host→device payload to sd_feeder_h2d_bytes_total — the
        # counter BENCH_r05 was missing when the congested link had to
        # be diagnosed from print lines
        self._measure = measure
        self.stats = PipelineStats()
        self._queue: _queue.Queue = _queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._done = False
        self._fetch = fetch
        self._error: BaseException | None = None
        # the producer thread starts with empty contextvars — carry the
        # constructing task's trace across so feeder.fetch spans join it
        self._trace_ctx = _trace.current()
        self._thread = threading.Thread(
            target=self._run, args=(start_key,), name="sd-window-pipeline",
            daemon=True,
        )
        self._thread.start()

    def _run(self, key: Any) -> None:
        if self._trace_ctx is not None:
            _trace.set_current(self._trace_ctx)
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                with _span("feeder.fetch"):
                    item = self._fetch(key)
                fetch_s = time.perf_counter() - t0
                with self.stats._lock:
                    self.stats.read_time += fetch_s
                _tm.FEEDER_FETCH_SECONDS.observe(fetch_s)
                if item is None:
                    self._put(None)
                    return
                key, window = item
                if self._measure is not None:
                    try:
                        _tm.FEEDER_H2D_BYTES.inc(self._measure(window))
                    except Exception:  # measurement must never kill reads
                        pass
                if not self._put(window):
                    return
                _tm.FEEDER_INFLIGHT.set(self._queue.qsize())
        except BaseException as e:  # surfaced to the consumer on take()
            self._error = e
            self._put(None)

    def _put(self, item) -> bool:
        """Queue.put that aborts promptly when close() is called."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def take(self) -> T | None:
        """Next window in order; None at end of stream (raises if the
        producer died) or after close(). The time the consumer spent
        blocked is recorded as a prefetch miss; instant handoffs count
        as hits. Once the end-of-stream sentinel has been consumed every
        further take() returns None immediately — the producer thread
        has exited and there is only one sentinel, so without this latch
        an extra take() (steps outnumbering windows, e.g. the orphan set
        shrank mid-run) would spin forever."""
        if self._done:
            if self._error is not None:
                raise self._error
            return None
        t0 = time.perf_counter()
        with _span("feeder.wait"):
            while True:
                try:
                    window = self._queue.get(timeout=0.1)
                    break
                except _queue.Empty:
                    # close() may race a full queue (its sentinel is
                    # dropped on Full); poll the stop flag so a drained
                    # consumer can't block forever on a dead producer
                    if self._stop.is_set():
                        window = None
                        break
        waited = time.perf_counter() - t0
        hit = waited < 0.002
        with self.stats._lock:
            if hit:
                self.stats.prefetch_hits += 1
            else:
                self.stats.prefetch_misses += 1
        _tm.FEEDER_WAIT_SECONDS.observe(waited)
        _tm.FEEDER_PREFETCH.inc(result="hit" if hit else "miss")
        _tm.FEEDER_INFLIGHT.set(self._queue.qsize())
        if window is None:
            self._done = True
            if self._error is not None:
                raise self._error
        return window

    def close(self) -> None:
        self._stop.set()
        # unblock a consumer waiting in take()
        try:
            self._queue.put_nowait(None)
        except _queue.Full:
            pass
        self._thread.join(timeout=5)
