"""Slim worker-process runtime for the multi-process execution plane.

One of these runs inside every ``parallel/procpool.py`` worker process,
launched with a ``python -c`` one-liner calling :func:`main` (a fresh
interpreter — no forked locks, no inherited JAX runtime, no re-imported
``__main__``). Requests and responses are length-prefixed msgpack
frames over the worker's own stdio pipe; the worker re-points fd 1 at
stderr immediately so a stray ``print`` anywhere below can never
corrupt the framing. The contract that keeps the plane safe:

- **import-light**: no Node, no event loop, no jax. The module-level
  imports here are stdlib; each stage lazily imports exactly the
  CPU-side modules it needs (``ops/cas.py`` is importable without jax
  for exactly this reason). ``JAX_PLATFORMS`` is pinned to ``cpu`` in
  the worker env as a belt-and-braces guard — a worker must never
  contend for the owner's accelerator;
- **shared-nothing**: stage payloads arrive as msgpack blobs (plain
  dicts/lists/str/bytes/ints — sdlint SD022 enforces the same purity
  at the submit call sites) and results leave the same way. No DB
  connection, no sockets, no library objects ever cross the boundary;
  SQLite commits stay on the owning process;
- **single-writer telemetry**: workers feed their OWN registry (the
  same families — both sides import ``telemetry.metrics``) and ship an
  additive delta blob with each result; the owner merges it
  (``registry.merge_delta``), so metrics, spans, and rings keep
  exactly one writer per process. A batch that dies with its worker
  never shipped its delta, so a retried batch counts exactly once.

Stages mirror the in-process implementations bit-for-bit (same
functions where possible), so ``SD_PROCS=0`` vs pool output is
identical — the golden contract tests/test_procpool.py holds.

Wire frames (owner → worker): ``[job_id, stage, payload_blob,
stall_s]``; (worker → owner): ``[job_id, ok, body_blob, delta_blob]``.
"""

from __future__ import annotations

import os
import struct
import sys
import time
from typing import Any

#: frame header: little-endian u32 byte length
_HDR = struct.Struct("<I")
#: a single frame is bounded — a runaway payload fails loudly instead
#: of OOMing the worker (64 MiB covers any sane batch quantum)
MAX_FRAME = 64 << 20


def read_frame(fp: Any) -> bytes | None:
    """One length-prefixed frame; None on clean EOF."""
    hdr = fp.read(_HDR.size)
    if not hdr:
        return None
    if len(hdr) < _HDR.size:
        raise EOFError("torn frame header")
    (n,) = _HDR.unpack(hdr)
    if n > MAX_FRAME:
        raise ValueError(f"frame of {n} bytes exceeds {MAX_FRAME}")
    body = fp.read(n)
    if len(body) < n:
        raise EOFError("torn frame body")
    return body


def write_frame(fp: Any, blob: bytes) -> None:
    fp.write(_HDR.pack(len(blob)))
    fp.write(blob)
    fp.flush()


# --- stages ----------------------------------------------------------------
#
# Every stage is a pure function payload(dict) -> result(dict), both
# msgpack-plain. Heavy imports happen inside the stage (first call per
# worker pays them once; the pool is persistent).


def _stage_echo(payload: dict) -> dict:
    """Round-trip probe (tests + pool warmup)."""
    return payload


def _stage_hash_entries(payload: dict) -> dict:
    """The shard plane's CPU half: stat → sampled read → chunk-cache
    digests → host BLAKE3 cas_ids for journal-keyed entries
    (location/indexer/mesh.py:_execute_shard_sync's read/hash leg).

    payload: {"loc_path": str, "entries": [{"pub_id", "mat", "name",
    "ext"}, ...]}
    result:  {"results": [{"pub_id", "cas_id" | None, "identity" |
    None, "chunks" | None, "error" | None}, ...]}
    """
    from ..files.isolated_path import full_path_from_db_row
    from ..location.indexer.journal import stat_identity
    from ..ops import cas
    from ..telemetry import metrics as _tm

    loc_path = payload["loc_path"]
    out: list[dict] = []
    messages: list[bytes] = []
    msg_idx: list[int] = []
    for e in payload["entries"]:
        row = {"materialized_path": e["mat"], "name": e["name"],
               "extension": e["ext"], "is_dir": False}
        full = full_path_from_db_row(loc_path, row)
        ident = stat_identity(full)
        rec: dict[str, Any] = {
            "pub_id": e["pub_id"],
            "identity": (
                [ident.inode, ident.dev, ident.mtime_ns, ident.size]
                if ident is not None else None
            ),
            "cas_id": None, "chunks": None, "error": None,
        }
        out.append(rec)
        if ident is None:
            continue  # vanished/unreadable: the next walk removes it
        if ident.size == 0:
            rec["cas_id"] = ""  # vouched-empty sentinel
            continue
        try:
            msg = cas.read_message(full, ident.size)
        except OSError:
            rec["identity"] = None  # no vouch for an unreadable file
            rec["error"] = "unreadable"
            continue
        rec["chunks"] = cas.build_chunk_cache(msg).to_payload()
        messages.append(msg)
        msg_idx.append(len(out) - 1)
    if messages:
        for i, cas_hex in zip(msg_idx, cas.cas_ids(messages, "cpu")):
            out[i]["cas_id"] = cas_hex
        # bytes merge additively across workers; the hash-stage WALL is
        # observed once by the owner (mesh._pool_hash) — concurrent
        # workers' per-batch times would sum to CPU-seconds and skew
        # autotune.observed_files_per_s low on pool-accelerated nodes
        _tm.INDEX_BYTES_HASHED.inc(sum(len(m) for m in messages))
    return {"results": out}


def _stage_journal_match(payload: dict) -> dict:
    """consult_many's CPU half: payload decode + strict validation +
    identity compare per pre-fetched journal row (the SQL stays on the
    owner). Mirrors IndexJournal verdict semantics exactly; the owner
    does all verdict counting.

    payload: {"items": [[[mat, name, ext], identity-or-None], ...],
              "rows": [row-dict-or-None aligned with items]}
    result:  {"verdicts": [[verdict, entry-or-None, corrupt], ...]}
    """
    from ..location.indexer import journal as _journal

    verdicts: list[list] = []
    for (key, ident_raw), row in zip(payload["items"], payload["rows"]):
        if row is None:
            verdicts.append([_journal.MISS, None, False])
            continue
        entry = _journal.entry_of_row(row)
        if entry is None:
            # corrupt row: the owner drops it (DB write stays there)
            verdicts.append([_journal.BYPASSED, None, True])
            continue
        ident = (
            _journal.Identity(*(int(x) for x in ident_raw))
            if ident_raw is not None else None
        )
        plain = {
            "identity": (
                [entry.identity.inode, entry.identity.dev,
                 entry.identity.mtime_ns, entry.identity.size]
                if entry.identity is not None else None
            ),
            "stale": entry.stale,
            "cas_id": entry.cas_id,
            "thumb": entry.thumb,
            "media": entry.media_digest,
            "phash": entry.phash,
            "embed": entry.embed,
            # already strictly validated by entry_of_row — the owner
            # reconstructs without re-validating
            "chunks": entry.chunks.to_payload()
            if entry.chunks is not None else None,
        }
        if not entry.stale and ident is not None \
                and entry.identity == ident:
            verdicts.append([_journal.HIT, plain, False])
        else:
            verdicts.append([_journal.INVALIDATED, plain, False])
    return {"verdicts": verdicts}


def _stage_link_prep(payload: dict) -> dict:
    """apply_cas_results' pure prep: per-result pub_id validation and
    the deterministic (library, cas) object pub_id (uuid5). Row reads
    and the sync-write commit stay on the owning process.

    payload: {"library_id": str, "results": [{"pub_id", "cas_id",
    "ext"}, ...]}
    result:  {"usable": [[idx, fp_pub, cas, obj_pub], ...]}
    """
    from ..object.file_identifier.link import object_pub_for

    lib_id = payload["library_id"]
    usable: list[list] = []
    for i, res in enumerate(payload["results"]):
        cas = res.get("cas_id")
        if not cas or not isinstance(cas, str):
            continue  # empty/unreadable files carry no cas to link
        try:
            fp_pub = bytes.fromhex(str(res["pub_id"]))
        except (KeyError, ValueError):
            continue
        usable.append([i, fp_pub, cas, object_pub_for(lib_id, cas)])
    return {"usable": usable}


def _stage_thumb_cpu(payload: dict) -> dict:
    """The thumbnail software pipeline for one image: decode → CPU
    resize → orientation/overlay → webp encode, bit-identical to the
    actor's host fallback path (process.generate_one_cpu).

    A deterministic image failure (undecodable/oversized/vanished)
    returns ``{"webp": None, "error": ...}`` rather than raising: the
    actor then counts the error directly instead of paying a second
    full inline decode that is guaranteed to fail the same way — only
    pool-infrastructure failures surface as job errors.

    payload: {"path": str, "ext": str}
    result:  {"webp": bytes | None, "error": str | None}
    """
    from ..object.media.thumbnail.process import ThumbError, generate_one_cpu
    from ..telemetry import metrics as _tm

    t0 = time.perf_counter()
    try:
        webp = generate_one_cpu(payload["path"], payload["ext"])
    except (ThumbError, OSError) as exc:
        return {"webp": None, "error": f"{type(exc).__name__}: {exc}"}
    _tm.THUMB_STAGE_SECONDS.observe(
        time.perf_counter() - t0, stage="encode")
    return {"webp": webp, "error": None}


def _stage_phash_gray(payload: dict) -> dict:
    """The duplicate detector's decode leg: original-first JPEG draft
    decode (thumbnail fallback) to the 32×32 grayscale pHash plane
    (object/duplicates.py:_decode_gray, minus the DB lookups).

    payload: {"path": str | None, "thumb_path": str | None}
    result:  {"gray": bytes | None}  (float32 DCT_SIZE² plane)
    """
    import numpy as np

    from ..ops import phash_jax

    def _decode(path: str, draft: bool):
        from PIL import Image

        with Image.open(path) as img:
            if draft and img.format == "JPEG":
                img.draft("RGB", (phash_jax.DCT_SIZE, phash_jax.DCT_SIZE))
            return phash_jax.to_gray32(np.asarray(img.convert("RGBA")))

    for path, draft in ((payload.get("path"), True),
                        (payload.get("thumb_path"), False)):
        if not path or not os.path.exists(path):
            continue
        try:
            return {"gray": _decode(path, draft).astype(np.float32).tobytes()}
        except Exception:  # noqa: BLE001 - undecodable → next source
            continue
    return {"gray": None}


def _stage_embed_decode(payload: dict) -> dict:
    """The embedding stage's decode leg: image file → the embedder's
    fixed input plane (models/embedder.decode_image — the EXACT code
    path the inline fallback runs, so pooled and single-process decodes
    are bit-identical). Undecodable files return None slots; the owner
    skips them without paying a second guaranteed-to-fail decode.

    payload: {"paths": [str, ...]}
    result:  {"planes": [bytes | None, ...]}  (f32 S·S·3 planes)
    """
    from ..models.embedder import decode_image

    planes: list[bytes | None] = []
    for path in payload["paths"]:
        img = decode_image(path)
        planes.append(None if img is None else img.tobytes())
    return {"planes": planes}


STAGES = {
    "echo": _stage_echo,
    "identify.hash_entries": _stage_hash_entries,
    "journal.match": _stage_journal_match,
    "link.prep": _stage_link_prep,
    "thumb.cpu": _stage_thumb_cpu,
    "phash.gray": _stage_phash_gray,
    "embed.decode": _stage_embed_decode,
}


# --- the worker main loop --------------------------------------------------


def main() -> None:
    """Serve stage requests over stdio until EOF (the owner closing our
    stdin is the clean shutdown signal)."""
    # claim the framing pipe privately, then point fd 1 at stderr so
    # library prints can never interleave with frames
    out = os.fdopen(os.dup(1), "wb", buffering=0)
    os.dup2(2, 1)
    inp = os.fdopen(os.dup(0), "rb", buffering=0)
    # guards, not configuration: a worker must never grab an
    # accelerator or re-arm the owner's fault plan in its own process
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("SD_FAULTS", None)

    import msgpack

    from ..telemetry import metrics as _tm  # mint families for deltas
    from ..telemetry.registry import REGISTRY

    del _tm
    while True:
        frame = read_frame(inp)
        if frame is None:
            return
        job_id, stage, blob, stall_s = msgpack.unpackb(frame, raw=False)
        before = REGISTRY.delta_capture()
        try:
            if stall_s:
                # armed by the owner when the procpool.worker `stall`
                # fault fires — the batch is delayed inside the worker
                time.sleep(stall_s)
            fn = STAGES.get(stage)
            if fn is None:
                raise KeyError(f"unknown procpool stage {stage!r}")
            payload = msgpack.unpackb(blob, raw=False)
            body = msgpack.packb(fn(payload), use_bin_type=True)
            ok = True
        except BaseException as exc:  # noqa: BLE001 - errors are data
            body = msgpack.packb(
                {"error": f"{type(exc).__name__}: {exc}"},
                use_bin_type=True,
            )
            ok = False
        delta = REGISTRY.delta_diff(before, REGISTRY.delta_capture())
        write_frame(out, msgpack.packb(
            [job_id, ok, body, msgpack.packb(delta, use_bin_type=True)],
            use_bin_type=True,
        ))


if __name__ == "__main__":
    main()
