"""Multi-process execution plane — escape the GIL for CPU-bound stages.

BENCH_E2E ``config_mesh`` records 0.122 scaling efficiency for two
in-process nodes, and the PR 13 host profiler names why: one shared
GIL serializes every per-entry Python between the spans — journal
payload decode, chunk-cache digesting, linking SQL prep, image
decode/webp encode, pHash planes. The reference's execution layer is a
work-stealing multi-threaded Rust task system (``crates/task-system``)
that simply uses the cores; this Python mirror needs **processes**.

This module is the owner-side half: a persistent pool of worker
processes (each a fresh ``python -m spacedrive_tpu.parallel.procworker``
interpreter — the slim in-worker runtime; length-prefixed msgpack
frames over its own stdio pipe, no fork, no pickled state, no
re-imported ``__main__``) that the task system's execute leg
dispatches CPU-bound stages onto:

- **lifecycle**: spawn-started with the Node and refcounted like the
  host profiler (two in-process nodes share one pool; the first stop
  must not kill the survivor's workers). ``SD_PROCS`` sizes the pool;
  ``SD_PROCS=0`` (the default) is the golden single-process path —
  every call site falls through to its inline implementation,
  bit-identical to the pre-pool tree;
- **shared-nothing batches**: ``submit()`` msgpack-serializes the
  payload *before* it crosses the boundary — a non-plain object
  (Database, connection, loop, Node, policy) fails loudly at the call
  site, and sdlint SD022 (``process-boundary-purity``) rejects it at
  review time. The shard plane already defines the serializable unit
  (journal-keyed entries + stat identity);
- **single-writer telemetry**: each result carries the worker's
  additive counter/histogram delta; the per-worker reader merges it
  into the owner registry (``registry.merge_delta``) so metrics,
  spans, and flight rings keep exactly one writer per process. A
  batch whose worker died never shipped a delta — the retry counts
  once;
- **crash recovery**: a worker that dies mid-batch is restarted once
  and its in-flight batches are re-dispatched (each batch retries at
  most once — a twice-fatal batch fails its future, and every call
  site degrades to its inline path on pool failure, so a broken pool
  can slow a pass but never wrong it). The ``procpool.worker`` fault
  point (modes ``crash``/``stall``) drives this path deterministically
  in the chaos tier;
- **IPC amortization**: callers size batches through the per-workload
  ``PipelinePolicy.procpool_batch_rows()`` seam (parallel/autotune.py)
  so the serialize+frame tax is paid per quantum, not per row.

Evidence plane: ``sd_procpool_*`` (workers alive, dispatch/roundtrip
seconds, batch rows, restarts, job outcomes), the bench_e2e
``config_procs`` A/B, and the attribution report's ``gap``/``gil_wait``
shares shrinking (docs/performance.md "Multi-process execution plane").
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from typing import Any

from ..telemetry import metrics as _tm
from ..telemetry.registry import REGISTRY
from ..utils import faults as _faults
from . import procworker as _wire

logger = logging.getLogger(__name__)

#: hard cap on SD_PROCS — a fat-fingered value must not fork-bomb a host
MAX_PROCS = 64

#: per-batch result timeout floor for sync waiters (seconds); generous —
#: a stalled worker is recovered by the watchdog, not by waiters
REQUEST_TIMEOUT_S = 120.0

#: a worker holding any batch older than this is WEDGED (hung C call —
#: e.g. a decompression bomb inside PIL), not slow: the watchdog kills
#: it so the normal death path (restart + re-dispatch-once) reclaims
#: the capacity. Far above every sane batch (callers' own timeouts
#: give up long before), so it can only fire on a genuine hang.
WEDGE_TIMEOUT_S = 300.0
#: watchdog poll cadence
_WATCHDOG_INTERVAL_S = 5.0


def procs() -> int:
    """``SD_PROCS`` worker count. 0 (default) disables the plane —
    the golden bit-identical single-process path."""
    raw = os.environ.get("SD_PROCS", "0")
    try:
        n = int(raw)
    except ValueError:
        return 0
    return max(0, min(MAX_PROCS, n))


def enabled() -> bool:
    return procs() > 0


def rig_stamp() -> dict:
    """Host execution-rig facts stamped into every BENCH_*.json so a
    comparator can tell an honest-floor single-core run from a real
    scaling regression before gating any parallelism ratio."""
    return {
        "cpu_count": os.cpu_count() or 1,
        "procpool_procs": procs(),
    }


class ProcPoolError(RuntimeError):
    """A pool-side failure (worker error, death past the retry budget,
    pool stopped). Call sites catch this and fall back inline — the
    pool may only ever make a pass FASTER, never wrong."""


class _Job:
    __slots__ = ("id", "stage", "blob", "rows", "stall_s", "future",
                 "t_submit", "retried")

    def __init__(self, job_id: int, stage: str, blob: bytes, rows: int):
        self.id = job_id
        self.stage = stage
        self.blob = blob
        self.rows = rows
        self.stall_s = 0.0
        self.future: Future = Future()
        self.t_submit = time.monotonic()
        self.retried = False


class _Worker:
    """One subprocess + its reader thread + its write lock."""

    __slots__ = ("index", "proc", "reader", "wlock", "inflight", "gen")

    def __init__(self, index: int):
        self.index = index
        self.proc: subprocess.Popen | None = None
        self.reader: threading.Thread | None = None
        self.wlock = threading.Lock()
        self.inflight: set[int] = set()
        self.gen = 0  # bumped per restart so stale readers exit


class ProcPool:
    """The process-wide pool (:data:`POOL`); ``start``/``stop`` are
    refcounted because two in-process nodes (the loopback test mesh)
    share one interpreter."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._refs = 0
        self._running = False
        self._workers: list[_Worker] = []
        self._jobs: dict[int, _Job] = {}
        self._job_seq = itertools.count(1)
        self._size = 0
        self._watchdog: threading.Thread | None = None
        self._stop_event = threading.Event()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> bool:
        """Add one hold; the first hold spawns the workers. Returns
        True when the pool is running after the call (False under
        ``SD_PROCS=0`` — a true no-op)."""
        n = procs()
        if n <= 0:
            return False
        with self._lock:
            self._refs += 1
            if self._running:
                return True
            self._size = n
            self._workers = [_Worker(i) for i in range(n)]
            self._running = True
            for w in self._workers:
                self._spawn_locked(w)
            self._stop_event.clear()
            self._watchdog = threading.Thread(
                target=self._watch, name="sd-procpool-watchdog",
                daemon=True,
            )
            self._watchdog.start()
            _tm.PROCPOOL_WORKERS.set(n)
            return True

    def _spawn_locked(self, w: _Worker) -> None:
        """(Re)launch one worker subprocess and its reader thread."""
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"  # workers never own an accelerator
        env.pop("SD_FAULTS", None)  # the owner drives worker faults
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        w.proc = subprocess.Popen(
            # -c (not -m): the parallel package imports procworker for
            # the frame helpers, and runpy would re-execute an already-
            # imported module with a noisy RuntimeWarning
            [sys.executable, "-c",
             "from spacedrive_tpu.parallel import procworker; "
             "procworker.main()"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=None,  # worker logs/tracebacks pass through
            env=env,
        )
        w.gen += 1
        w.reader = threading.Thread(
            target=self._read_loop, args=(w, w.proc, w.gen),
            name=f"sd-procpool-r{w.index}", daemon=True,
        )
        w.reader.start()

    def stop(self) -> None:
        """Release one hold; the last release stops workers and fails
        any still-outstanding futures (call sites fall back inline)."""
        with self._lock:
            self._refs = max(0, self._refs - 1)
            if self._refs > 0:
                return
            self._running = False
            self._stop_event.set()
            workers, self._workers = self._workers, []
            jobs, self._jobs = dict(self._jobs), {}
        for w in workers:
            proc = w.proc
            if proc is None:
                continue
            try:
                proc.stdin.close()  # EOF = clean worker shutdown
            except OSError:
                pass
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=2.0)
        for w in workers:
            if w.reader is not None and w.reader.is_alive():
                w.reader.join(timeout=2.0)
        watchdog, self._watchdog = self._watchdog, None
        if watchdog is not None and watchdog.is_alive():
            watchdog.join(timeout=2.0)
        for job in jobs.values():
            if not job.future.done():
                job.future.set_exception(ProcPoolError("pool stopped"))
        _tm.PROCPOOL_WORKERS.set(0)

    def running(self) -> bool:
        # start()/stop() flip this under _lock from the loop; readers
        # include the watchdog thread — read under the same lock
        with self._lock:
            return self._running

    def worker_count(self) -> int:
        with self._lock:
            return sum(
                1 for w in self._workers
                if w.proc is not None and w.proc.poll() is None
            )

    # -- dispatch ---------------------------------------------------------

    def submit(self, stage: str, payload: Any, rows: int = 1) -> Future:
        """Ship one shared-nothing batch; returns a concurrent Future
        resolving to the stage result dict. The payload is serialized
        HERE (msgpack-plain or it fails loudly, matching sdlint SD022);
        raises :class:`ProcPoolError` when the pool is not running."""
        import msgpack

        t0 = time.perf_counter()
        try:
            blob = msgpack.packb(payload, use_bin_type=True)
        except (TypeError, ValueError) as exc:
            raise ProcPoolError(
                f"procpool payload for {stage!r} is not msgpack-plain: {exc}"
            ) from exc
        with self._lock:
            if not self._running:
                raise ProcPoolError("pool not running")
            job = _Job(next(self._job_seq), stage, blob, rows)
            w = self._pick_locked()
            spec = _faults.hit("procpool.worker")
            if spec is not None and spec.mode == "stall":
                job.stall_s = spec.delay_s
            self._jobs[job.id] = job
            w.inflight.add(job.id)
            kill = w.proc if spec is not None and spec.mode == "crash" \
                else None
        self._send(w, job)
        if kill is not None:
            # simulated process death mid-batch: the reader sees EOF,
            # restarts the worker once and re-dispatches its batches
            kill.kill()
        _tm.PROCPOOL_DISPATCH_SECONDS.observe(time.perf_counter() - t0)
        _tm.PROCPOOL_BATCH_ROWS.observe(rows)
        return job.future

    def _pick_locked(self) -> _Worker:
        return min(self._workers, key=lambda w: len(w.inflight))

    def _send(self, w: _Worker, job: _Job) -> None:
        """Frame one job onto a worker's stdin. A write failure means
        the worker is dead or dying — its reader owns the recovery, so
        the job just stays in-flight until the reaper re-dispatches."""
        import msgpack

        frame = msgpack.packb(
            [job.id, job.stage, job.blob, job.stall_s], use_bin_type=True,
        )
        try:
            with w.wlock:
                if w.proc is not None and w.proc.stdin is not None:
                    _wire.write_frame(w.proc.stdin, frame)
        except (OSError, ValueError):
            pass  # reader-side reaper re-dispatches this job

    def request(self, stage: str, payload: Any, rows: int = 1,
                timeout: float | None = None) -> Any:
        """Synchronous round-trip (worker-thread call sites — shard
        execution runs in ``to_thread``). Raises ProcPoolError on any
        pool-side failure so callers can fall back inline."""
        fut = self.submit(stage, payload, rows)
        try:
            return fut.result(timeout or REQUEST_TIMEOUT_S)
        except ProcPoolError:
            raise
        except Exception as exc:  # noqa: BLE001 - timeout/cancel → pool error
            raise ProcPoolError(f"procpool {stage} failed: {exc}") from exc

    async def run(self, stage: str, payload: Any, rows: int = 1) -> Any:
        """Event-loop-side round-trip (thumbnail actor, duplicates)."""
        fut = self.submit(stage, payload, rows)
        try:
            return await asyncio.wrap_future(fut)
        except ProcPoolError:
            raise
        except Exception as exc:  # noqa: BLE001 - normalize for callers
            raise ProcPoolError(f"procpool {stage} failed: {exc}") from exc

    # -- per-worker reader (results + recovery) ---------------------------

    def _read_loop(self, w: _Worker, proc: subprocess.Popen,
                   gen: int) -> None:
        import msgpack

        def _decode(frame: bytes) -> list | None:
            try:
                parsed = msgpack.unpackb(frame, raw=False)
            except (TypeError, ValueError):
                return None
            return parsed if isinstance(parsed, list) \
                and len(parsed) == 4 else None

        try:
            while True:
                frame = _wire.read_frame(proc.stdout)
                if frame is None:
                    break  # EOF: worker exited (or was killed)
                parsed = _decode(frame)
                if parsed is None:
                    # a torn frame means the stream is unframed from
                    # here on — treat as death, don't spin on garbage
                    break
                job_id, ok, body, delta_blob = parsed
                self._finish(w, job_id, ok, body, delta_blob)
        except (EOFError, OSError, ValueError):
            pass
        self._reap(w, proc, gen)

    def _finish(self, w: _Worker, job_id: int, ok: bool, body: bytes,
                delta_blob: bytes) -> None:
        import msgpack

        with self._lock:
            job = self._jobs.pop(job_id, None)
            w.inflight.discard(job_id)
        if job is None:
            return  # late duplicate of a re-dispatched batch
        try:
            REGISTRY.merge_delta(msgpack.unpackb(delta_blob, raw=False))
        except Exception:  # noqa: BLE001 - delta drift must not kill results
            logger.exception("procpool telemetry delta merge failed")
        _tm.PROCPOOL_ROUNDTRIP_SECONDS.observe(
            time.monotonic() - job.t_submit)
        try:
            result = msgpack.unpackb(body, raw=False)
        except Exception:  # noqa: BLE001 - torn body → job error
            result, ok = {"error": "undecodable result"}, False
        if ok:
            _tm.PROCPOOL_JOBS.inc(result="ok")
            if not job.future.done():
                job.future.set_result(result)
        else:
            _tm.PROCPOOL_JOBS.inc(result="error")
            if not job.future.done():
                job.future.set_exception(ProcPoolError(
                    f"worker {w.index} failed {job.stage}: "
                    f"{result.get('error')}"
                ))

    def _reap(self, w: _Worker, proc: subprocess.Popen, gen: int) -> None:
        """The worker behind ``gen`` is gone: restart it (if the pool
        is still running) and re-dispatch its in-flight batches, once
        per batch."""
        try:
            proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            proc.kill()
        redispatch: list[_Job] = []
        failed: list[_Job] = []
        with self._lock:
            if not self._running or w.gen != gen:
                return  # pool stopping, or a newer generation owns `w`
            for job_id in sorted(w.inflight):
                job = self._jobs.get(job_id)
                if job is None:
                    continue
                if job.retried:
                    self._jobs.pop(job_id, None)
                    failed.append(job)
                else:
                    job.retried = True
                    redispatch.append(job)
            w.inflight.clear()
            self._spawn_locked(w)
            _tm.PROCPOOL_RESTARTS.inc()
            _tm.PROCPOOL_WORKERS.set(self._size)
            targets: list[tuple[_Worker, _Job]] = []
            for job in redispatch:
                tgt = self._pick_locked()
                tgt.inflight.add(job.id)
                targets.append((tgt, job))
                _tm.PROCPOOL_JOBS.inc(result="retried")
        logger.warning(
            "procpool worker %d died; restarted (re-dispatching %d, "
            "failing %d)", w.index, len(redispatch), len(failed),
        )
        for tgt, job in targets:
            self._send(tgt, job)
        for job in failed:
            if not job.future.done():
                job.future.set_exception(ProcPoolError(
                    f"batch {job.stage} died twice; giving up"
                ))

    # -- watchdog (wedged-worker recovery) --------------------------------

    def _watch(self) -> None:
        """Kill any worker that has held a batch past WEDGE_TIMEOUT_S —
        a hung C call (decompression bomb in PIL, a pathological read)
        never returns to the frame loop, so the reader's EOF-driven
        reap can't see it. Killing converts the wedge into an ordinary
        death: restart + re-dispatch-once, and a batch that wedges its
        retry worker too fails its future (callers fall back inline)."""
        while not self._stop_event.wait(_WATCHDOG_INTERVAL_S):
            now = time.monotonic()
            wedged: list[Any] = []
            with self._lock:
                if not self._running:
                    return
                for w in self._workers:
                    if w.proc is None or w.proc.poll() is not None:
                        continue  # dead already: the reader owns it
                    oldest = min(
                        (self._jobs[jid].t_submit
                         for jid in w.inflight if jid in self._jobs),
                        default=None,
                    )
                    if oldest is not None \
                            and now - oldest > WEDGE_TIMEOUT_S:
                        wedged.append(w.proc)
            for proc in wedged:
                logger.warning(
                    "procpool worker wedged past %.0fs; killing",
                    WEDGE_TIMEOUT_S,
                )
                proc.kill()

    # -- warmup -----------------------------------------------------------

    def warm(self, timeout: float = 30.0) -> None:
        """Block until every worker answered one echo — bench arms call
        this so spawn/import cost never lands inside a timed window."""
        futs = [self.submit("echo", {"i": i}) for i in range(self._size)]
        for f in futs:
            try:
                f.result(timeout)
            except Exception:  # noqa: BLE001 - a dead worker reaps later
                pass


#: the process-wide pool — Node.start() takes a refcounted hold
#: (parallel to telemetry.sampler.SAMPLER), tests may hold it directly
POOL = ProcPool()


def get() -> ProcPool | None:
    """The running pool, or None — the one call-site gate: every
    consumer does ``pool = procpool.get()`` and falls through to its
    inline implementation when this is None (SD_PROCS=0, pool not
    started, or already stopped)."""
    return POOL if POOL.running() else None
