"""Unified stage-typed execution continuum — ONE scheduler vocabulary
for the three planes that used to schedule work separately:

- the task system's local threads (``tasks/system.py``),
- the multi-process execution plane (``parallel/procpool.py``, whose
  stage functions are the per-stage CPU legs),
- the mesh WORK shard plane (``p2p/work.py``), previously identify-only.

This module is the registry that fuses them: every distributable unit
of pipeline work is a **stage** with a stable id, and a mesh
:class:`~spacedrive_tpu.p2p.work.WorkShard` now carries its stage id so
any executor — local self-steal, remote peer — can route the shard to
the right execution leg (``location/indexer/stages.py``), consult its
own index journal first, and push the CPU-bound middle through its own
local procpool. The registry also owns the **per-stage throughput
EWMAs** the control loop runs on: executors report
``(files, seconds)`` per shard here, the PR 8 ``Controller`` folds the
rates into per-stage lease targets every tick
(``parallel/autotune.py:_tick_stages``), and the WORK board sizes
leases per stage from the claimer's self-reported per-stage rates with
the Controller targets as the fallback — heterogeneous-fleet
scheduling: a peer with idle chips bids for device-heavy shards, a
CPU-rich peer takes the decode/encode stages.

Like the telemetry registry (the precedent for process-global state
shared by in-process test nodes), ``RATES`` is process-wide;
``telemetry.reset()`` clears it alongside every metric series.

sdlint scope: this module and the stage executors it routes to are
fully inside SD014 (P2P requests must ride a ResiliencePolicy — the
scheduler is NOT a defining module) and SD022 (pool payloads must be
msgpack-plain; ``pool_for`` is a recognized pool-handle accessor).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any

#: stage ids — the bounded vocabulary every `stage` metric label and
#: wire shard carries. Adding a stage means adding it HERE (and to the
#: inline label chains at the emit sites, per SD007).
STAGE_IDENTIFY = "identify.hash"
STAGE_THUMB = "thumb"
STAGE_MEDIA = "media.extract"
STAGE_PHASH = "phash"
STAGE_EMBED = "embed"

#: EWMA blend for per-stage throughput (same constant the mesh worker
#: has always used for its claim-sizing self-report)
EWMA_KEEP = 0.7


@dataclass(frozen=True)
class StageSpec:
    """One distributable pipeline stage.

    ``workload`` names the autotune :class:`PipelinePolicy` whose
    quanta size this stage's legs; ``pool_stage`` is the procpool
    stage function that is its CPU-bound middle (None = the stage has
    no pool leg and always runs inline on the executor);
    ``journal_field`` documents which index-journal vouch the executor
    consults before touching a byte."""

    id: str
    workload: str
    pool_stage: str | None
    journal_field: str


#: the stage registry — insertion order is the grant tie-break order
STAGES: dict[str, StageSpec] = {
    STAGE_IDENTIFY: StageSpec(
        STAGE_IDENTIFY, "identify", "identify.hash_entries", "cas_id"),
    STAGE_THUMB: StageSpec(STAGE_THUMB, "thumbnail", "thumb.cpu", "thumb"),
    STAGE_MEDIA: StageSpec(STAGE_MEDIA, "identify", None, "media_digest"),
    STAGE_PHASH: StageSpec(STAGE_PHASH, "thumbnail", "phash.gray", "phash"),
    STAGE_EMBED: StageSpec(STAGE_EMBED, "embed", "embed.decode", "embed"),
}


def spec(stage_id: str) -> StageSpec:
    """The registry entry for a stage id — unknown stages fail loudly
    (a typo'd wire shard must not execute as the wrong stage)."""
    return STAGES[stage_id]


def pool_for(stage_id: str) -> Any:
    """The running process pool for a stage's CPU leg — None when the
    pool is down, SD_PROCS=0, or the stage has no pool leg. sdlint
    SD022 recognizes locals bound from this accessor as pool handles,
    so payloads shipped through them stay review-time checked."""
    if STAGES[stage_id].pool_stage is None:
        return None
    from . import procpool as _procpool

    return _procpool.get()


# --- per-stage throughput EWMAs (the control loop's input) -----------------


class StageRates:
    """Process-wide per-stage files/s EWMAs. Executors call
    :meth:`observe` once per executed shard (any stage, any origin —
    self-steal or remote claim); the Controller reads :meth:`rate` each
    tick to derive per-stage lease targets, and ``/mesh`` snapshots the
    whole table. Thread-safe: shard execution legs run in worker
    threads while the Controller ticks on the event loop."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ewma: dict[str, float] = {}
        self._observed: dict[str, int] = {}

    def observe(self, stage_id: str, files: int, seconds: float) -> None:
        if files <= 0 or seconds <= 0:
            return
        rate = files / seconds
        with self._lock:
            prev = self._ewma.get(stage_id, 0.0)
            self._ewma[stage_id] = (
                rate if prev == 0.0
                else EWMA_KEEP * prev + (1.0 - EWMA_KEEP) * rate
            )
            self._observed[stage_id] = self._observed.get(stage_id, 0) + files
        from ..telemetry import metrics as _tm

        # inline bounded conditional pins the label domain at the emit
        # site (SD007): the stage registry is the entire vocabulary
        _tm.WORK_STAGE_RATE.set(
            self._ewma[stage_id],
            stage="identify.hash" if stage_id == "identify.hash" else (
                "thumb" if stage_id == "thumb" else (
                    "media.extract" if stage_id == "media.extract" else (
                        "phash" if stage_id == "phash" else (
                            "embed" if stage_id == "embed" else "other")))),
        )

    def rate(self, stage_id: str) -> float:
        """Observed files/s EWMA for a stage — 0.0 until the stage has
        executed anything in this process."""
        with self._lock:
            return self._ewma.get(stage_id, 0.0)

    def snapshot(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {
                s: {
                    "files_per_s": round(self._ewma[s], 3),
                    "files_observed": self._observed.get(s, 0),
                }
                for s in self._ewma
            }

    def reset(self) -> None:
        with self._lock:
            self._ewma.clear()
            self._observed.clear()


#: the process-wide rate table (telemetry.reset() clears it)
RATES = StageRates()


def observed_files_per_s(stage_id: str) -> float:
    """Best available throughput estimate for a stage: the shard-
    measured EWMA when one exists, else the telemetry-derived workload
    rate (identify only — the other stages have no pre-shard series
    that reads as files/s), else 0.0."""
    rate = RATES.rate(stage_id)
    if rate > 0:
        return rate
    if stage_id == STAGE_IDENTIFY:
        from . import autotune as _autotune

        return _autotune.observed_files_per_s("identify") or 0.0
    return 0.0


def lease_seconds_for(stage_id: str, n_files: int, rate: float,
                      lease_max_s: float) -> float:
    """Per-stage lease sizing — the WORK board's one seam. ``rate`` is
    the claimer's self-reported files/s for this stage; with none, the
    Controller's per-stage target rate (its lease-sizing output,
    derived from the EWMAs each tick) stands in, and before ANY
    evidence the static default holds — restoring the pre-continuum
    lease law bit-for-bit."""
    from ..p2p import work as _work

    if rate <= 0:
        from . import autotune as _autotune

        rate = _autotune.CONTROLLER.stage_rate(stage_id)
    if rate <= 0:
        rate = _work.DEFAULT_FILES_PER_S
    lease = max(_work.LEASE_MIN_S, n_files / rate * _work.LEASE_SLACK)
    return min(lease, lease_max_s)


def snapshot() -> dict[str, Any]:
    """The continuum's state for ``/mesh`` (rides autotune.snapshot):
    per-stage rates + the registry vocabulary."""
    return {
        "stages": list(STAGES),
        "rates": RATES.snapshot(),
    }


def reset() -> None:
    """Test/bench isolation — clears the per-stage EWMAs AND the
    Controller's derived per-stage lease targets (telemetry.reset()
    calls this; the scheduler's state is registry-like)."""
    RATES.reset()
    from . import autotune as _autotune

    _autotune.CONTROLLER.reset_stage_targets()


__all__ = [
    "RATES",
    "STAGES",
    "STAGE_EMBED",
    "STAGE_IDENTIFY",
    "STAGE_MEDIA",
    "STAGE_PHASH",
    "STAGE_THUMB",
    "StageRates",
    "StageSpec",
    "lease_seconds_for",
    "observed_files_per_s",
    "pool_for",
    "reset",
    "snapshot",
    "spec",
]
