"""Error taxonomy (parity: ref:crates/utils/src/error.rs)."""

from __future__ import annotations


class SpacedriveError(Exception):
    """Base class for all framework errors."""


class FileIOError(SpacedriveError):
    """An IO error tagged with the path it happened on
    (parity: ref:crates/utils/src/error.rs FileIOError)."""

    def __init__(self, path, cause: BaseException | str):
        self.path = str(path)
        self.cause = cause
        super().__init__(f"{self.path}: {cause}")


class VersionManagerError(SpacedriveError):
    """Config migration failure (parity: ref:core/src/util/version_manager.rs)."""


class MissingFieldError(SpacedriveError):
    """A DB field expected to be present was NULL
    (parity: ref:crates/utils/src/db.rs maybe_missing)."""


def maybe_missing(value, field: str):
    """Guard against NULL DB fields (parity: ref:crates/utils/src/db.rs:12)."""
    if value is None:
        raise MissingFieldError(field)
    return value
