"""VersionManager — generic versioned-JSON config migration.

Parity: ref:core/src/util/version_manager.rs:62-143. Every on-disk
config (node, library, thumbnailer dir, …) carries a `version` field;
loading walks registered migrations from the stored version to current,
one step at a time, persisting after each step so a crash mid-migration
resumes cleanly.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

from .errors import VersionManagerError

Migration = Callable[[dict[str, Any]], dict[str, Any]]


class VersionManager:
    """Migrates dict-shaped configs `from_version -> from_version + 1`."""

    def __init__(self, current_version: int, version_field: str = "version"):
        self.current_version = current_version
        self.version_field = version_field
        self._migrations: dict[int, Migration] = {}

    def register(self, from_version: int) -> Callable[[Migration], Migration]:
        def deco(fn: Migration) -> Migration:
            self._migrations[from_version] = fn
            return fn
        return deco

    def migrate(self, data: dict[str, Any], save: Callable[[dict[str, Any]], None] | None = None) -> dict[str, Any]:
        version = int(data.get(self.version_field, 0))
        if version > self.current_version:
            raise VersionManagerError(
                f"config version {version} is newer than supported {self.current_version}"
            )
        while version < self.current_version:
            step = self._migrations.get(version)
            if step is None:
                raise VersionManagerError(f"no migration registered from version {version}")
            data = step(dict(data))
            version += 1
            data[self.version_field] = version
            if save is not None:
                save(data)
        return data

    def load(self, path: str | os.PathLike, default: dict[str, Any] | None = None) -> dict[str, Any]:
        """Load + migrate + persist a JSON config file."""
        path = os.fspath(path)
        if not os.path.exists(path):
            if default is None:
                raise VersionManagerError(f"missing config {path!r} and no default")
            data = dict(default)
            data[self.version_field] = self.current_version
            self.save(path, data)
            return data
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        return self.migrate(data, save=lambda d: self.save(path, d))

    @staticmethod
    def save(path: str | os.PathLike, data: dict[str, Any]) -> None:
        """Atomic write (tmp + rename), the crash-safety the reference
        gets from its write-then-rename config store."""
        path = os.fspath(path)
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
