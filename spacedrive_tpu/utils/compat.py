"""Cross-version asyncio shims.

``asyncio.timeout`` landed in Python 3.11; this repo (and its CI
containers) must also run on 3.10. ``timeout(delay)`` here is the
3.11 context manager when available, otherwise a small backport built
on the same cancel-then-translate mechanism ``asyncio.timeout`` uses
internally: arm a ``call_later`` that cancels the current task, and
translate that specific cancellation into ``TimeoutError`` on exit.

The backport covers the common shape (`async with timeout(s):` around
awaits in the current task). It does not implement 3.11's
reschedule/expired introspection API, and — without 3.11's
``uncancel()`` counting — an EXTERNAL ``task.cancel()`` that lands in
the same window the timer fired is indistinguishable from the timeout
and surfaces as ``TimeoutError`` (the same limitation the pre-3.11
``async_timeout`` package had; it is exactly why the uncancel
machinery was added to the stdlib). Callers that both cancel tasks
and time them out must treat a ``TimeoutError`` near shutdown as a
possible cancellation on 3.10.
"""

from __future__ import annotations

import asyncio
import sys

__all__ = ["timeout"]


if sys.version_info >= (3, 11):
    timeout = asyncio.timeout
else:

    class _Timeout:
        def __init__(self, delay: float | None):
            self._delay = delay
            self._handle: asyncio.TimerHandle | None = None
            self._task: asyncio.Task | None = None
            self._timed_out = False

        async def __aenter__(self) -> "_Timeout":
            self._task = asyncio.current_task()
            if self._task is None:
                raise RuntimeError("timeout() must be used inside a task")
            if self._delay is not None:
                self._handle = asyncio.get_running_loop().call_later(
                    self._delay, self._on_timeout
                )
            return self

        def _on_timeout(self) -> None:
            self._timed_out = True
            assert self._task is not None
            self._task.cancel()

        async def __aexit__(self, exc_type, exc, tb) -> bool:
            if self._handle is not None:
                self._handle.cancel()
                self._handle = None
            if self._timed_out and exc_type is asyncio.CancelledError:
                # our own cancellation: surface as TimeoutError, and
                # clear the pending-cancel state the cancel() left on
                # the task so callers can keep awaiting afterwards
                if hasattr(self._task, "uncancel"):
                    self._task.uncancel()  # pragma: no cover (3.11+)
                raise TimeoutError from exc
            return False

    def timeout(delay: float | None) -> "_Timeout":
        """Backport of ``asyncio.timeout`` for Python < 3.11."""
        return _Timeout(delay)
