"""Unified resilience layer — retries, circuit breakers, deadlines.

One policy object replaces the ad-hoc per-call-site handling of dead
relays, flapping peers, and wedged streams:

- **bounded retries** with decorrelated-jitter backoff (the AWS
  architecture-blog scheme: each sleep is ``uniform(base, prev * 3)``
  capped, so synchronized clients de-correlate instead of thundering
  together);
- a **per-target circuit breaker** (CLOSED → OPEN after
  ``failure_threshold`` consecutive failures; after ``reset_timeout`` a
  single HALF_OPEN probe is admitted — success closes, failure re-opens
  and restarts the clock), so a dead relay or peer costs one fast
  ``BreakerOpen`` per cycle instead of a full retry ladder;
- **deadline propagation** over a contextvar: ``deadline_scope(s)``
  bounds everything underneath — attempt timeouts and backoff sleeps
  are clipped to the remaining budget and ``DeadlineExceeded`` fires
  instead of overshooting.

Adopters: the cloud relay client (``cloud/api.py``), telemetry
federation pulls, P2P sync notify/request, and spacedrop connects.
Breaker state is exported as ``sd_breaker_open`` /
``sd_breaker_transitions_total`` and per-target detail lands on the
``resilience`` flight ring, feeding the PR 5 health verdicts (and the
federation snapshot) — the observe→act loop closed from both sides.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Iterable

# --- outcome classification -------------------------------------------------

#: retry the attempt (counts as a breaker failure)
RETRY = "retry"
#: give up now, but still count a breaker failure (the target is sick)
FAIL = "fail"
#: give up now WITHOUT counting a failure (the target answered; the
#: request itself was bad — a 4xx must never open a breaker)
PASS = "pass"

Classifier = Callable[[BaseException], str]


class BreakerOpen(ConnectionError):
    """Fast-failed: the target's circuit breaker is open."""


class DeadlineExceeded(asyncio.TimeoutError):
    """The ambient deadline expired before the call succeeded."""


# --- deadline propagation ---------------------------------------------------

_deadline: contextvars.ContextVar[float | None] = contextvars.ContextVar(
    "sd_resilience_deadline", default=None
)


@contextlib.contextmanager
def deadline_scope(seconds: float):
    """Bound everything under this block to ``seconds`` from now. Nested
    scopes only ever tighten — an inner scope cannot outlive an outer
    one."""
    now = time.monotonic()
    new = now + max(0.0, seconds)
    prev = _deadline.get()
    token = _deadline.set(new if prev is None else min(prev, new))
    try:
        yield
    finally:
        _deadline.reset(token)


def deadline_remaining() -> float | None:
    """Seconds left in the ambient deadline, or None when unbounded."""
    d = _deadline.get()
    if d is None:
        return None
    return max(0.0, d - time.monotonic())


# --- circuit breaker --------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-target failure gate. Thread-safe; cheap enough per call that
    the hot paths can consult it unconditionally."""

    def __init__(self, target: str, *, failure_threshold: int = 5,
                 reset_timeout: float = 30.0, policy: str = ""):
        self.target = str(target)
        self.policy = policy
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout = reset_timeout
        self.state = CLOSED
        self.failures = 0
        self.opened_at = 0.0
        self.half_open_since = 0.0
        self._lock = threading.Lock()

    def allow(self) -> bool:
        """May a call proceed? An OPEN breaker past its reset timeout
        admits exactly one half-open probe. A probe that never reports
        back (cancelled mid-flight) must not wedge the breaker: after
        another reset window, HALF_OPEN re-admits a fresh probe."""
        now = time.monotonic()
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if now - self.opened_at >= self.reset_timeout:
                    self._transition(HALF_OPEN)
                    self.half_open_since = now
                    return True
                return False
            # HALF_OPEN: the single probe is in flight — unless it was
            # abandoned a full reset window ago
            if now - self.half_open_since >= self.reset_timeout:
                self.half_open_since = now
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            if self.state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self.failures += 1
            if self.state == HALF_OPEN or (
                self.state == CLOSED
                and self.failures >= self.failure_threshold
            ):
                self.opened_at = time.monotonic()
                self._transition(OPEN)
            elif self.state == OPEN:
                # a failure while open (raced probe) restarts the clock
                self.opened_at = time.monotonic()

    def _transition(self, state: str) -> None:
        # caller holds self._lock
        from ..telemetry import metrics as _tm
        from ..telemetry.events import RESILIENCE_EVENTS
        from ..telemetry.peers import peer_label

        prev, self.state = self.state, state
        if state == OPEN:
            _tm.BREAKER_TRANSITIONS.inc(state="open")
        elif state == HALF_OPEN:
            _tm.BREAKER_TRANSITIONS.inc(state="half_open")
        else:
            _tm.BREAKER_TRANSITIONS.inc(state="closed")
        _tm.BREAKER_OPEN.set(float(_count_open()))
        RESILIENCE_EVENTS.emit(
            "breaker",
            policy=self.policy,
            target=peer_label(self.target),
            state=state,
            prev=prev,
            failures=self.failures,
        )


# every live breaker, for the open-count gauge + health/mesh snapshots
_breakers: "dict[tuple[str, str], CircuitBreaker]" = {}
_breakers_lock = threading.Lock()


def _count_open() -> int:
    with _breakers_lock:
        return sum(1 for b in _breakers.values() if b.state == OPEN)


def breaker_snapshot() -> dict[str, Any]:
    """Per-breaker state for /health signals and debugging. Targets are
    peer_label short-hashes — raw peer ids never leave the node."""
    from ..telemetry.peers import peer_label

    with _breakers_lock:
        items = list(_breakers.values())
    return {
        f"{b.policy}:{peer_label(b.target)}": {
            "state": b.state, "failures": b.failures,
        }
        for b in items
    }


def reset_breakers() -> None:
    """Test hook: drop every registered breaker."""
    from ..telemetry import metrics as _tm

    with _breakers_lock:
        _breakers.clear()
    _tm.BREAKER_OPEN.set(0.0)


# --- retry policy -----------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retries with decorrelated jitter.

    ``attempt_timeout`` bounds each try (clipped to the ambient
    deadline); ``max_attempts`` bounds the ladder. The expected worst
    case is therefore ``max_attempts × attempt_timeout + Σ sleeps`` —
    finite by construction, which is what sdlint SD011 cannot prove
    about a hand-rolled loop."""

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    attempt_timeout: float | None = 30.0

    def sleeps(self, rng: random.Random) -> Iterable[float]:
        prev = self.base_delay
        for _ in range(self.max_attempts - 1):
            prev = min(self.max_delay, rng.uniform(self.base_delay, prev * 3))
            yield prev


def default_classifier(exc: BaseException) -> str:
    if isinstance(exc, asyncio.CancelledError):
        return PASS
    return RETRY


class ResiliencePolicy:
    """Retry + breaker + deadline in one adoptable object.

    ``call(target, fn)`` runs ``fn`` (an async thunk) under the
    target's breaker with bounded, jittered retries. ``classify`` maps
    an exception to RETRY / FAIL / PASS (default: everything but
    cancellation retries)."""

    def __init__(self, name: str, retry: RetryPolicy | None = None, *,
                 failure_threshold: int = 5, reset_timeout: float = 30.0,
                 classify: Classifier | None = None, seed: int | None = None):
        self.name = name
        self.retry = retry or RetryPolicy()
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.classify = classify or default_classifier
        self._rng = random.Random(seed)

    def breaker(self, target: str) -> CircuitBreaker:
        key = (self.name, str(target))
        with _breakers_lock:
            b = _breakers.get(key)
            if b is None:
                b = _breakers[key] = CircuitBreaker(
                    target,
                    failure_threshold=self.failure_threshold,
                    reset_timeout=self.reset_timeout,
                    policy=self.name,
                )
        return b

    def allow(self, target: str) -> bool:
        return self.breaker(target).allow()

    async def call(self, target: str, fn: Callable[[], Awaitable[Any]], *,
                   classify: Classifier | None = None) -> Any:
        """Run ``fn`` with retries/breaker/deadline. Raises
        :class:`BreakerOpen` without calling ``fn`` when the target's
        breaker rejects, :class:`DeadlineExceeded` when the ambient
        deadline runs out, else the final attempt's exception."""
        from ..telemetry import metrics as _tm
        from ..telemetry.events import RESILIENCE_EVENTS
        from ..telemetry.peers import peer_label

        classify = classify or self.classify
        breaker = self.breaker(target)
        if not breaker.allow():
            raise BreakerOpen(
                f"{self.name}: breaker open for {peer_label(target)}"
            )
        sleeps = iter(self.retry.sleeps(self._rng))
        attempt = 0
        while True:
            attempt += 1
            remaining = deadline_remaining()
            if remaining is not None and remaining <= 0.0:
                raise DeadlineExceeded(f"{self.name}: deadline exhausted")
            budget = self.retry.attempt_timeout
            if remaining is not None:
                budget = remaining if budget is None else min(budget, remaining)
            try:
                if budget is None:
                    result = await fn()
                else:
                    from .compat import timeout

                    async with timeout(budget):
                        result = await fn()
            except (asyncio.CancelledError, KeyboardInterrupt, SystemExit):
                # cancellation/exit is never an attempt failure: it must
                # propagate immediately — not feed the breaker, not be
                # slept on, and not depend on a custom classifier
                # remembering to pass it through
                raise
            except BaseException as exc:  # noqa: BLE001 - classified below
                outcome = classify(exc)
                if outcome == PASS:
                    # the target ANSWERED (a 4xx, a refusal): proof of
                    # liveness — settle a half-open probe so the breaker
                    # can't wedge. While CLOSED, though, leave the
                    # failure streak alone: interleaved 4xx answers must
                    # not keep a half-dead target's breaker from opening
                    if breaker.state != CLOSED:
                        breaker.record_success()
                    raise
                breaker.record_failure()
                delay = next(sleeps, None)
                if outcome == FAIL or delay is None or not breaker.allow():
                    raise
                remaining = deadline_remaining()
                if remaining is not None:
                    if remaining <= 0.0:
                        raise DeadlineExceeded(
                            f"{self.name}: deadline exhausted"
                        ) from exc
                    delay = min(delay, remaining)
                _tm.RESILIENCE_RETRIES.inc()
                RESILIENCE_EVENTS.emit(
                    "retry",
                    policy=self.name,
                    target=peer_label(target),
                    attempt=attempt,
                    sleep_s=round(delay, 4),
                    error=str(exc)[:200],
                )
                await asyncio.sleep(delay)
                continue
            breaker.record_success()
            return result
