"""Retained-task supervision — the canonical remediation for sdlint
SD003 (orphaned ``create_task``).

A spawned task whose handle is dropped is GC-cancellable at any moment,
and an exception it raises surfaces only as an unraisable warning at
collection time (which pytest.ini escalates to a failure). The fix is
always the same three moves: retain the handle in a set, discard it on
completion, and RETRIEVE the exception so it gets logged instead of
lost. This helper is that pattern, once.
"""

from __future__ import annotations

import asyncio
import logging


def supervise(
    task: asyncio.Task,
    tasks: set,
    logger: logging.Logger,
    what: str,
) -> asyncio.Task:
    """Retain ``task`` in ``tasks`` until it completes; on completion,
    discard it and log any exception (cancellation is not an error).
    Returns the task for further chaining."""
    tasks.add(task)

    def _done(t: asyncio.Task) -> None:
        tasks.discard(t)
        if not t.cancelled() and t.exception() is not None:
            logger.error("%s failed: %r", what, t.exception())

    task.add_done_callback(_done)
    return task
