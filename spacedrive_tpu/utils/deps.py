"""Dependency + license inventory (the reference's deps-generator).

Parity: ref:crates/deps-generator/src/main.rs — a build tool that runs
cargo-about over the workspace and emits a JSON of every dependency
with its license for the interface's credits screen. The TPU-native
equivalent inventories BOTH dependency planes this framework actually
has:

- **Python packages**: everything importable that the package's
  runtime touches, resolved live via importlib.metadata (name,
  version, license from metadata or trove classifiers);
- **native libraries**: the ctypes-loaded C libraries (cairo,
  freetype, libheif, librsvg, libsecret, FFmpeg's libav*, sqlite),
  resolved to the actual .so on this host, with their upstream
  licenses from a curated table (these ship no queryable metadata).

`sdx licenses` prints the JSON; callers can write it to a file the
way the reference commits its generated artifact.
"""

from __future__ import annotations

import ctypes.util
import importlib.metadata as md
from typing import Any

# the packages the framework imports at runtime (stdlib excluded);
# keep in sync with the import surface — the test cross-checks a core
# subset actually resolves
PYTHON_DEPS = [
    "jax", "jaxlib", "flax", "optax", "numpy", "aiohttp", "cryptography",
    "msgpack", "Pillow", "scikit-learn", "fonttools", "zstandard",
]

# ctypes-loaded C libraries; license strings per the upstream projects
NATIVE_DEPS = [
    ("cairo", "LGPL-2.1 OR MPL-1.1", "PDF/SVG rasterization"),
    ("freetype", "FTL OR GPL-2.0", "embedded PDF font glyphs"),
    ("heif", "LGPL-3.0", "HEIF/HEIC decode"),
    ("rsvg-2", "LGPL-2.1", "SVG rendering"),
    ("secret-1", "LGPL-2.1", "OS keyring"),
    ("avformat", "LGPL-2.1", "video demux (FFmpeg)"),
    ("avcodec", "LGPL-2.1", "video decode (FFmpeg)"),
    ("avutil", "LGPL-2.1", "FFmpeg utilities"),
    ("swscale", "LGPL-2.1", "frame scaling (FFmpeg)"),
    ("sqlite3", "Public Domain", "library database"),
]


def _license_of(dist: md.Distribution) -> str:
    meta = dist.metadata
    lic = (meta.get("License-Expression") or meta.get("License") or "").strip()
    if lic and lic.upper() != "UNKNOWN" and len(lic) < 120:
        return lic
    for classifier in meta.get_all("Classifier") or []:
        if classifier.startswith("License ::"):
            return classifier.split("::")[-1].strip()
    return "unknown"


def collect() -> dict[str, Any]:
    python: list[dict[str, str]] = []
    for name in PYTHON_DEPS:
        try:
            dist = md.distribution(name)
        except md.PackageNotFoundError:
            continue
        python.append({
            "name": dist.metadata["Name"] or name,
            "version": dist.version,
            "license": _license_of(dist),
        })
    native: list[dict[str, str]] = []
    for lib, license_, role in NATIVE_DEPS:
        path = ctypes.util.find_library(lib)
        native.append({
            "name": lib,
            "resolved": path or "not present (feature degrades)",
            "license": license_,
            "role": role,
        })
    return {"python": python, "native": native}
