"""In-process event bus.

Parity: the reference's `event_bus` broadcast channel on Node
(ref:core/src/lib.rs:113 `event_bus: broadcast::channel(256)`) carrying
`CoreEvent` (ref:core/src/api/mod.rs:54-58). Here: a synchronous
fan-out bus with bounded per-subscriber queues; async consumers drain
via `subscribe()` queues.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable


class Subscription:
    def __init__(self, bus: "EventBus", maxlen: int):
        self._bus = bus
        self.queue: collections.deque[Any] = collections.deque(maxlen=maxlen)
        self._cond = threading.Condition()

    def push(self, event: Any) -> None:
        with self._cond:
            self.queue.append(event)
            self._cond.notify_all()

    def poll(self) -> list[Any]:
        with self._cond:
            items = list(self.queue)
            self.queue.clear()
            return items

    def wait(self, timeout: float | None = None) -> list[Any]:
        with self._cond:
            if not self.queue:
                self._cond.wait_for(lambda: bool(self.queue), timeout)
            items = list(self.queue)
            self.queue.clear()
            return items

    def close(self) -> None:
        self._bus.unsubscribe(self)


class EventBus:
    """Broadcast bus: every subscriber sees every event (lossy on overflow,
    like the reference's tokio broadcast channel)."""

    def __init__(self, capacity: int = 256):
        self._capacity = capacity
        self._subs: list[Subscription] = []
        self._callbacks: list[Callable[[Any], None]] = []
        self._lock = threading.Lock()

    def emit(self, event: Any) -> None:
        with self._lock:
            subs = list(self._subs)
            cbs = list(self._callbacks)
        for sub in subs:
            sub.push(event)
        for cb in cbs:
            cb(event)

    def subscribe(self) -> Subscription:
        sub = Subscription(self, self._capacity)
        with self._lock:
            self._subs.append(sub)
        return sub

    def on(self, callback: Callable[[Any], None]) -> Callable[[], None]:
        with self._lock:
            self._callbacks.append(callback)

        def off():
            with self._lock:
                if callback in self._callbacks:
                    self._callbacks.remove(callback)

        return off

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)
