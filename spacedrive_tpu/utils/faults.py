"""Fault-injection plane — deterministic, seed-controlled chaos hooks.

The resilience layer (``utils/resilience.py``) only earns trust if the
failure paths it guards actually run, so the real seams carry named
injection points: device dispatch, the H2D feeder's producer, the P2P
stream plane, the cloud relay, sync ingest, and the thumbnailer's
store→journal persistence window. Each point is a single
``faults.hit("<point>")`` call that is a no-op (one ``is None`` check
against a module global) unless a :class:`FaultPlan` is installed —
production pays nothing for the plane's existence.

A plan is a list of :class:`FaultSpec` entries — point, mode, and
activation bookkeeping (``prob``/``times``/``after``/``delay_s``) —
seeded so the same plan + seed fires the same faults in the same order
(the chaos soak's determinism contract). Plans come from the
``SD_FAULTS`` env var, the ``sdx --faults`` CLI flag, or a test
fixture via :func:`active`.

Every activation lands on the ``faults`` flight ring with the active
trace_id, so an injected fault is visible in the same PR 3 trace as
the retry/demotion it provoked, and bumps
``sd_faults_injected_total``.

Spec syntax (env/CLI)::

    point:mode[:key=value[,key=value...]][;point:mode[:...]]...
    SD_FAULTS="device.blake3:raise:times=1;relay.http:500:prob=0.5"
    SD_FAULT_SEED=7

Registered points and their modes are cataloged in :data:`FAULT_POINTS`
(and docs/robustness.md).
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
from dataclasses import dataclass, field
from typing import Any, Iterable

#: point name -> (docstring, modes) — the catalog the docs and the
#: chaos suite enumerate; hit() refuses unknown points so a typo'd
#: plan fails loudly instead of silently never firing.
FAULT_POINTS: dict[str, tuple[str, tuple[str, ...]]] = {
    "device.blake3": (
        "cas_id device dispatch (ops/blake3_jax.hash_batch)",
        ("raise", "xla", "wrong_shape"),
    ),
    "device.thumbnail": (
        "thumbnail device resize (ops/thumbnail_jax.resize_batch)",
        ("raise", "xla", "wrong_shape"),
    ),
    "embed.forward": (
        "semantic embedding forward pass (ops/embed_jax.embed_batch)",
        ("raise", "xla", "wrong_shape"),
    ),
    "search.query": (
        "vector-index device scoring (object/search/index.query) — the "
        "device leg fails, scoring must fall back to the host path with "
        "an identical ranking",
        ("raise", "xla"),
    ),
    "device.probe": (
        "per-device health probe (parallel/mesh.DeviceLadder) — arg "
        "selects the device index that reads as dead",
        ("dead",),
    ),
    "feeder.fetch": (
        "H2D window producer (parallel/feeder.WindowPipeline)",
        ("stall", "crash"),
    ),
    "p2p.connect": (
        "outbound stream open (p2p/p2p.P2P.new_stream)",
        ("reset",),
    ),
    "p2p.write": (
        "udp stream write path (p2p/udpstream.UdpStream.write)",
        ("reset", "partial"),
    ),
    "p2p.sync_serve": (
        "inbound SYNC/SYNC_REQUEST responder (p2p/manager) — the peer "
        "vanishes mid-exchange",
        ("vanish",),
    ),
    "p2p.trace_pull": (
        "inbound TELEMETRY trace_pull responder (p2p/manager) — the "
        "peer vanishes before serving its spans; distributed trace "
        "assembly must degrade to a partial report, never block",
        ("vanish",),
    ),
    "p2p.profile_pull": (
        "inbound TELEMETRY profile_pull responder (p2p/manager) — the "
        "peer vanishes before serving its host profile; the mesh "
        "profile view must degrade to a partial answer, never block",
        ("vanish",),
    ),
    "p2p.steal": (
        "work-stealing shard plane (p2p/work.py): `vanish` at arg "
        "'lease' kills the claiming worker after the lease is granted "
        "(peer dies mid-lease; the shard must expire and be re-stolen); "
        "`race` at arg 'claim' double-leases an already-leased shard "
        "(claim race; the twice-executed shard must merge idempotently)",
        ("vanish", "race"),
    ),
    "procpool.worker": (
        "multi-process execution plane (parallel/procpool.py): `crash` "
        "kills the chosen worker process right after its batch ships "
        "(death mid-batch; the pool must restart the worker once and "
        "re-dispatch, and the pass must converge bit-identical); "
        "`stall` delays the batch inside the worker by delay_s",
        ("crash", "stall"),
    ),
    "relay.http": (
        "cloud relay HTTP surface (cloud/relay middleware)",
        ("500", "timeout", "truncate"),
    ),
    "db.slow": (
        "library SQLite read path (db/database.LibraryDb.query/"
        "query_one) — `stall` sleeps delay_s per read, simulating a "
        "slow/contended disk under the whole serve surface",
        ("stall",),
    ),
    "sync.ingest": (
        "remote op ingest (sync/ingest.receive_crdt_operation)",
        ("poison",),
    ),
    "thumbnail.persist": (
        "crash window between chunk store and journal write "
        "(object/media/thumbnail/actor)",
        ("crash",),
    ),
}


class InjectedFault(RuntimeError):
    """An injected failure that production error handling must absorb."""


class InjectedCrash(BaseException):
    """Simulated process death — derives from BaseException so generic
    ``except Exception`` recovery can NOT absorb it; only the chaos
    harness (standing in for a fresh process) catches it."""


def device_error(point: str) -> Exception:
    """An XlaRuntimeError-shaped exception (the real class when jaxlib
    is importable, RuntimeError otherwise) for ``xla`` fault modes."""
    try:
        from jax._src.lib import xla_client

        return xla_client.XlaRuntimeError(f"injected XLA failure at {point}")
    except Exception:  # noqa: BLE001 - jaxlib layout varies
        return RuntimeError(f"injected XLA failure at {point}")


@dataclass
class FaultSpec:
    """One armed fault: fire ``mode`` at ``point``.

    ``after`` hits are skipped before arming, then each hit fires with
    probability ``prob`` until ``times`` activations (None = forever).
    ``arg`` narrows the spec to hits carrying the same discriminator
    (e.g. a device index for ``device.probe``). ``delay_s`` parametrizes
    stall/timeout modes.
    """

    point: str
    mode: str
    prob: float = 1.0
    times: int | None = 1
    after: int = 0
    delay_s: float = 0.2
    arg: str | None = None
    # runtime counters (owned by the plan's lock)
    hits: int = field(default=0, repr=False)
    fired: int = field(default=0, repr=False)

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        parts = text.strip().split(":", 2)
        if len(parts) < 2:
            raise ValueError(f"fault spec {text!r} is not point:mode[:k=v,...]")
        point, mode = parts[0].strip(), parts[1].strip()
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r}")
        if mode not in FAULT_POINTS[point][1]:
            raise ValueError(
                f"fault point {point!r} has no mode {mode!r} "
                f"(modes: {', '.join(FAULT_POINTS[point][1])})"
            )
        spec = cls(point=point, mode=mode)
        if len(parts) == 3 and parts[2]:
            for kv in parts[2].split(","):
                k, _, v = kv.partition("=")
                k = k.strip()
                if k == "prob":
                    spec.prob = float(v)
                elif k == "times":
                    spec.times = None if v in ("inf", "") else int(v)
                elif k == "after":
                    spec.after = int(v)
                elif k == "delay_s":
                    spec.delay_s = float(v)
                elif k == "arg":
                    spec.arg = v
                else:
                    raise ValueError(f"unknown fault spec key {k!r} in {text!r}")
        return spec


class FaultPlan:
    """A set of armed specs + the deterministic per-spec RNGs.

    Thread-safe: hits arrive from the event loop, feeder producer
    threads, and ``to_thread`` workers alike.
    """

    def __init__(self, specs: Iterable[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._rngs = [
            random.Random(f"{self.seed}:{i}:{s.point}:{s.mode}")
            for i, s in enumerate(self.specs)
        ]

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        specs = [
            FaultSpec.parse(part)
            for part in text.split(";")
            if part.strip()
        ]
        return cls(specs, seed=seed)

    def hit(self, point: str, arg: str | None = None) -> FaultSpec | None:
        """One pass through an injection point: returns the fired spec
        (recorded on the flight ring) or None. The first matching armed
        spec wins."""
        if point not in FAULT_POINTS:
            raise ValueError(f"unregistered fault point {point!r}")
        with self._lock:
            for i, spec in enumerate(self.specs):
                if spec.point != point:
                    continue
                if spec.arg is not None and spec.arg != (
                    None if arg is None else str(arg)
                ):
                    continue
                spec.hits += 1
                if spec.hits <= spec.after:
                    continue
                if spec.times is not None and spec.fired >= spec.times:
                    continue
                if spec.prob < 1.0 and self._rngs[i].random() >= spec.prob:
                    continue
                spec.fired += 1
                break
            else:
                return None
        _record_activation(spec, arg)
        return spec

    def activations(self) -> dict[str, int]:
        """Fired count per point (for soak assertions)."""
        with self._lock:
            out: dict[str, int] = {}
            for s in self.specs:
                out[s.point] = out.get(s.point, 0) + s.fired
            return out


def _record_activation(spec: FaultSpec, arg: str | None) -> None:
    # imported lazily: utils must stay importable before telemetry
    from ..telemetry import metrics as _tm
    from ..telemetry.events import FAULT_EVENTS

    _tm.FAULTS_INJECTED.inc()
    FAULT_EVENTS.emit(
        "injected",
        point=spec.point,
        mode=spec.mode,
        fired=spec.fired,
        arg=None if arg is None else str(arg),
    )


# --- the process-wide active plan ----------------------------------------

_active: list[FaultPlan | None] = [None]


def install(plan: FaultPlan | None) -> None:
    _active[0] = plan


def clear() -> None:
    _active[0] = None


def active_plan() -> FaultPlan | None:
    return _active[0]


@contextlib.contextmanager
def active(plan: FaultPlan):
    """Test-fixture activation: install for the block, restore after."""
    prev = _active[0]
    _active[0] = plan
    try:
        yield plan
    finally:
        _active[0] = prev


def install_from_env(environ=os.environ) -> FaultPlan | None:
    """Arm SD_FAULTS (seeded by SD_FAULT_SEED) if set; returns the plan."""
    text = environ.get("SD_FAULTS")
    if not text:
        return None
    plan = FaultPlan.parse(text, seed=int(environ.get("SD_FAULT_SEED", "0")))
    install(plan)
    return plan


def hit(point: str, arg: str | None = None) -> FaultSpec | None:
    """The injection-point call sites' entry: None when no plan is
    active (the common case — one list indexing and an ``is None``)."""
    plan = _active[0]
    if plan is None:
        return None
    return plan.hit(point, arg)
