from .errors import FileIOError, SpacedriveError, VersionManagerError
from .events import EventBus
