"""Tracing/logging setup.

Parity: the reference's `Node::init_logger` (ref:core/src/lib.rs:183-238)
— rolling file appender + stdout layer + env-filtered levels + a panic
hook recording file/line. Here: stdlib logging with a size-rotating file
handler, `SD_LOG`/`RUST_LOG`-style per-target filters, and an excepthook
that logs uncaught exceptions before the process dies.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys


DEFAULT_FILTER = "info,spacedrive_tpu=debug"


def _parse_filter(spec: str) -> tuple[int, dict[str, int]]:
    base = logging.INFO
    per_target: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            target, _, lvl = part.partition("=")
            per_target[target] = logging.getLevelName(lvl.strip().upper())
        else:
            base = logging.getLevelName(part.upper())
    return base, per_target


def init_logger(data_dir: str | os.PathLike | None = None, spec: str | None = None) -> None:
    """Set up stdout + rolling-file logging (4 files × 8 MiB, matching
    the reference's 4 rolled daily files)."""
    spec = spec or os.environ.get("SD_LOG") or DEFAULT_FILTER
    base, per_target = _parse_filter(spec)

    root = logging.getLogger()
    root.setLevel(logging.DEBUG)
    for h in list(root.handlers):
        root.removeHandler(h)

    fmt = logging.Formatter(
        "%(asctime)s %(levelname).1s %(name)s %(message)s", "%H:%M:%S"
    )
    out = logging.StreamHandler(sys.stderr)
    out.setFormatter(fmt)
    out.setLevel(base)
    root.addHandler(out)

    if data_dir is not None:
        log_dir = os.path.join(os.fspath(data_dir), "logs")
        os.makedirs(log_dir, exist_ok=True)
        fileh = logging.handlers.RotatingFileHandler(
            os.path.join(log_dir, "sd.log"), maxBytes=8 << 20, backupCount=4
        )
        fileh.setFormatter(fmt)
        fileh.setLevel(logging.DEBUG)
        root.addHandler(fileh)

    for target, lvl in per_target.items():
        logging.getLogger(target).setLevel(lvl)

    def hook(exc_type, exc, tb):
        logging.getLogger("panic").critical(
            "uncaught exception", exc_info=(exc_type, exc, tb)
        )
        sys.__excepthook__(exc_type, exc, tb)

    sys.excepthook = hook
