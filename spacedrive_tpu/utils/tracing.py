"""Tracing/logging setup.

Parity: the reference's `Node::init_logger` (ref:core/src/lib.rs:183-238)
— rolling file appender + stdout layer + env-filtered levels + a panic
hook recording file/line. Here: stdlib logging with a size-rotating file
handler, `SD_LOG`/`RUST_LOG`-style per-target filters, and an excepthook
that logs uncaught exceptions before the process dies.
"""

from __future__ import annotations

import logging
import logging.handlers
import os
import sys
import threading


DEFAULT_FILTER = "info,spacedrive_tpu=debug"


def _parse_filter(spec: str) -> tuple[int, dict[str, int]]:
    base = logging.INFO
    per_target: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            target, _, lvl = part.partition("=")
            per_target[target] = logging.getLevelName(lvl.strip().upper())
        else:
            base = logging.getLevelName(part.upper())
    return base, per_target


def init_logger(data_dir: str | os.PathLike | None = None, spec: str | None = None) -> None:
    """Set up stdout + rolling-file logging (4 files × 8 MiB, matching
    the reference's 4 rolled daily files)."""
    spec = spec or os.environ.get("SD_LOG") or DEFAULT_FILTER
    base, per_target = _parse_filter(spec)

    root = logging.getLogger()
    root.setLevel(logging.DEBUG)
    for h in list(root.handlers):
        root.removeHandler(h)

    fmt = logging.Formatter(
        "%(asctime)s %(levelname).1s %(name)s %(message)s", "%H:%M:%S"
    )
    out = logging.StreamHandler(sys.stderr)
    out.setFormatter(fmt)
    out.setLevel(base)
    root.addHandler(out)

    if data_dir is not None:
        log_dir = os.path.join(os.fspath(data_dir), "logs")
        os.makedirs(log_dir, exist_ok=True)
        fileh = logging.handlers.RotatingFileHandler(
            os.path.join(log_dir, "sd.log"), maxBytes=8 << 20, backupCount=4
        )
        fileh.setFormatter(fmt)
        fileh.setLevel(logging.DEBUG)
        root.addHandler(fileh)

    for target, lvl in per_target.items():
        logging.getLogger(target).setLevel(lvl)

    install_excepthooks()


def _record_error_ring(source: str, exc_info) -> None:
    """Mirror an uncaught exception into the flight recorder's error
    ring (lazy import: logging setup must work even if telemetry is
    mid-import)."""
    try:
        from ..telemetry.events import record_error

        record_error(source, None, exc_info=exc_info)
    except Exception:  # noqa: BLE001 - recording must never mask the crash
        pass


def install_excepthooks() -> None:
    """Route every crash surface into the rolling log + error ring:

    - ``sys.excepthook``: main-thread crashes (as before);
    - ``threading.excepthook``: a worker thread (window-pipeline
      producer, to_thread hasher) dying must not vanish into a silent
      default stderr print that rotates away with the terminal;
    - the asyncio side is per-loop — see ``install_loop_excepthook``,
      called by ``Node.start`` on its running loop.
    """

    def hook(exc_type, exc, tb):
        logging.getLogger("panic").critical(
            "uncaught exception", exc_info=(exc_type, exc, tb)
        )
        _record_error_ring("excepthook", (exc_type, exc, tb))
        sys.__excepthook__(exc_type, exc, tb)

    sys.excepthook = hook

    def thread_hook(args: "threading.ExceptHookArgs") -> None:
        if args.exc_type is SystemExit:
            return
        info = (args.exc_type, args.exc_value, args.exc_traceback)
        logging.getLogger("panic").critical(
            "uncaught exception in thread %s",
            getattr(args.thread, "name", "?"), exc_info=info,
        )
        _record_error_ring("thread", info)

    threading.excepthook = thread_hook


def install_loop_excepthook(loop=None) -> None:
    """Asyncio's 'exception was never retrieved' reports go to the
    loop's exception handler, not ``sys.excepthook`` — orphaned-task
    crashes would never reach the rolling log or the error ring without
    this. Installed by ``Node.start`` on its own loop."""
    import asyncio

    if loop is None:
        loop = asyncio.get_event_loop()

    def handler(loop_, context: dict) -> None:
        exc = context.get("exception")
        if exc is not None:
            info = (type(exc), exc, exc.__traceback__)
            logging.getLogger("panic").critical(
                "uncaught asyncio exception: %s",
                context.get("message", ""), exc_info=info,
            )
            _record_error_ring("loop", info)
        else:
            logging.getLogger("panic").critical(
                "asyncio loop error: %s", context.get("message", "")
            )
        loop_.default_exception_handler(context)

    loop.set_exception_handler(handler)
