"""Pure-Python ed25519 (RFC 8032) — the no-`cryptography` fallback.

Containers without the `cryptography` wheel must still boot a node:
the node config serializes an ed25519 identity keypair at first start,
so a missing AEAD stack would otherwise take the whole API layer down
with it. This module implements exactly the RFC 8032 Ed25519 operations
`p2p.identity` needs (keygen, public-key derivation, sign, verify) with
the same class surface as `cryptography`'s Ed25519PrivateKey/PublicKey.

NOT constant-time and orders of magnitude slower than the C
implementation — correctness parity only. The real `cryptography`
package is preferred whenever importable (identity.py gates on it),
and the encrypted-channel stack (Noise XX, XChaCha) stays hard-gated:
it refuses to run on this fallback rather than degrade security.
"""

from __future__ import annotations

import hashlib
import secrets

_P = 2**255 - 19
_Q = 2**252 + 27742317777372353535851937790883648493
_D = (-121665 * pow(121666, _P - 2, _P)) % _P
_I = pow(2, (_P - 1) // 4, _P)  # sqrt(-1)


def _sha512(*parts: bytes) -> bytes:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return h.digest()


def _inv(x: int) -> int:
    return pow(x, _P - 2, _P)


def _edwards_add(pt1, pt2):
    # extended homogeneous coordinates (X, Y, Z, T), RFC 8032 §5.1.4
    x1, y1, z1, t1 = pt1
    x2, y2, z2, t2 = pt2
    a = (y1 - x1) * (y2 - x2) % _P
    b = (y1 + x1) * (y2 + x2) % _P
    c = 2 * t1 * t2 * _D % _P
    dd = 2 * z1 * z2 % _P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _scalar_mult(pt, n: int):
    acc = (0, 1, 1, 0)  # neutral
    while n > 0:
        if n & 1:
            acc = _edwards_add(acc, pt)
        pt = _edwards_add(pt, pt)
        n >>= 1
    return acc


def _recover_x(y: int, sign: int) -> int | None:
    if y >= _P:
        return None
    x2 = (y * y - 1) * _inv(_D * y * y + 1) % _P
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (_P + 3) // 8, _P)
    if (x * x - x2) % _P != 0:
        x = x * _I % _P
    if (x * x - x2) % _P != 0:
        return None
    if (x & 1) != sign:
        x = _P - x
    return x


_BASE_Y = 4 * _inv(5) % _P
_BASE_X = _recover_x(_BASE_Y, 0)
_BASE = (_BASE_X, _BASE_Y, 1, _BASE_X * _BASE_Y % _P)


def _compress(pt) -> bytes:
    x, y, z, _t = pt
    zi = _inv(z)
    x, y = x * zi % _P, y * zi % _P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def _decompress(raw: bytes):
    if len(raw) != 32:
        return None
    enc = int.from_bytes(raw, "little")
    y = enc & ((1 << 255) - 1)
    x = _recover_x(y, enc >> 255)
    if x is None:
        return None
    return (x, y, 1, x * y % _P)


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    return (a & ((1 << 254) - 8)) | (1 << 254)


class InvalidSignature(Exception):
    pass


class Ed25519PublicKey:
    __slots__ = ("_raw", "_point")

    def __init__(self, raw: bytes, point):
        self._raw = raw
        self._point = point

    @classmethod
    def from_public_bytes(cls, raw: bytes) -> "Ed25519PublicKey":
        pt = _decompress(bytes(raw))
        if pt is None:
            raise ValueError("invalid ed25519 public key")
        return cls(bytes(raw), pt)

    def public_bytes(self, *_a, **_k) -> bytes:
        return self._raw

    def verify(self, signature: bytes, message: bytes) -> None:
        if len(signature) != 64:
            raise InvalidSignature("bad length")
        r_pt = _decompress(signature[:32])
        s = int.from_bytes(signature[32:], "little")
        if r_pt is None or s >= _Q:
            raise InvalidSignature("malformed")
        k = int.from_bytes(
            _sha512(signature[:32], self._raw, message), "little") % _Q
        left = _scalar_mult(_BASE, s)
        right = _edwards_add(r_pt, _scalar_mult(self._point, k))
        # compare affine coordinates
        zl, zr = _inv(left[2]), _inv(right[2])
        if (left[0] * zl - right[0] * zr) % _P != 0 or \
                (left[1] * zl - right[1] * zr) % _P != 0:
            raise InvalidSignature("verification failed")


class Ed25519PrivateKey:
    __slots__ = ("_seed", "_scalar", "_prefix", "_pub")

    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self._seed = bytes(seed)
        h = _sha512(self._seed)
        self._scalar = _clamp(h)
        self._prefix = h[32:]
        pub_pt = _scalar_mult(_BASE, self._scalar)
        self._pub = Ed25519PublicKey(_compress(pub_pt), pub_pt)

    @classmethod
    def generate(cls) -> "Ed25519PrivateKey":
        return cls(secrets.token_bytes(32))

    @classmethod
    def from_private_bytes(cls, seed: bytes) -> "Ed25519PrivateKey":
        return cls(bytes(seed))

    def private_bytes(self, *_a, **_k) -> bytes:
        return self._seed

    def public_key(self) -> Ed25519PublicKey:
        return self._pub

    def sign(self, message: bytes) -> bytes:
        r = int.from_bytes(_sha512(self._prefix, message), "little") % _Q
        r_enc = _compress(_scalar_mult(_BASE, r))
        k = int.from_bytes(
            _sha512(r_enc, self._pub.public_bytes(), message), "little") % _Q
        s = (r + k * self._scalar) % _Q
        return r_enc + int.to_bytes(s, 32, "little")
