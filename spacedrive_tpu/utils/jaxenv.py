"""Force a virtual n-device CPU JAX platform in this process.

Shared by tests/conftest.py and __graft_entry__'s multichip dryrun so
the version-sensitive scrub of private jax internals lives in exactly
one place. The scrub exists because a sitecustomize hook may register a
TPU-tunnel PJRT plugin (platform "axon") whose device query can block
even under JAX_PLATFORMS=cpu, and because the hook imports jax early —
before env vars set here would be read — so the config must also be
forced directly.
"""

from __future__ import annotations

import os


def force_cpu_devices(n: int) -> None:
    """Make jax.devices() return n virtual CPU devices, nothing else.

    Safe to call whether or not jax was already imported; must run
    before the first device query (backend instantiation) to take
    effect.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)

    try:
        import jax
        import jax._src.xla_bridge as _xb

        # chex (via optax/flax) registers TPU lowering rules at import
        # time, which needs "tpu" still present in known_platforms —
        # import them BEFORE deregistering the accelerator backends.
        try:
            import optax  # noqa: F401
            import flax  # noqa: F401
            from jax.experimental import pallas  # noqa: F401
            from jax.experimental.pallas import tpu as _pltpu  # noqa: F401
        except Exception:
            pass

        for _name in list(getattr(_xb, "_backend_factories", {})):
            if _name not in ("cpu", "interpreter"):
                _xb._backend_factories.pop(_name, None)
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", n)
        except Exception:
            pass  # older jax: the XLA_FLAGS path above applies
    except Exception:
        pass
