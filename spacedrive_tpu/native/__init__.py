"""Native (C) runtime components, loaded via ctypes.

The reference's runtime is native Rust/C (blake3 crate, libwebp, ffmpeg,
…); this package holds the new framework's native equivalents, compiled
on first use with the system toolchain and cached next to the sources.
Every consumer has a pure-Python fallback, so the framework degrades
gracefully on hosts without a C compiler.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_LOAD_FAILED = False


def _build(src: str, out: str, extra_args: tuple[str, ...] = ()) -> bool:
    """Compile one source into a shared object, caching failure in a
    sentinel file so fresh processes don't retry a known-bad build."""
    sentinel = out + ".build_failed"
    try:
        src_mtime = os.path.getmtime(src)
        if os.path.exists(sentinel) and \
                os.path.getmtime(sentinel) >= src_mtime:
            return False
    except OSError:
        return False
    for cc in ("cc", "gcc", "g++", "clang"):
        try:
            r = subprocess.run(
                [cc, "-O3", "-fPIC", "-shared", "-pthread", src, "-o", out]
                + list(extra_args),
                capture_output=True, timeout=120,
            )
            if r.returncode == 0:
                if os.path.exists(sentinel):
                    os.remove(sentinel)
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    try:
        with open(sentinel, "w") as f:
            f.write("build failed; delete this file to retry\n")
    except OSError:
        pass
    return False


def load() -> ctypes.CDLL | None:
    """The native library, building it if needed; None if unavailable."""
    global _LIB, _LOAD_FAILED
    if _LIB is not None or _LOAD_FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _LOAD_FAILED:
            return _LIB
        so = os.path.join(_DIR, "_sdnative.so")
        src = os.path.join(_DIR, "blake3.c")
        try:
            if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
                if not _build(src, so):
                    _LOAD_FAILED = True
                    return None
            lib = ctypes.CDLL(so)
            lib.b3_hash.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint32,
            ]
            lib.b3_hash_many.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32,
            ]
            lib.b3_state_size.restype = ctypes.c_uint32
            lib.b3_init.argtypes = [ctypes.c_void_p]
            lib.b3_update.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
            lib.b3_finalize.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32]
            _LIB = lib
        except OSError:
            _LOAD_FAILED = True
    return _LIB


def available() -> bool:
    return load() is not None


def blake3_digest(data: bytes, out_len: int = 32) -> bytes | None:
    """One-shot native BLAKE3; None if the native lib is unavailable."""
    lib = load()
    if lib is None:
        return None
    out = (ctypes.c_uint8 * 64)()
    lib.b3_hash(data, len(data), out, min(out_len, 64))
    return bytes(out[:out_len])


class StreamingHasher:
    """Incremental native BLAKE3 — bounded memory over unbounded input
    (the validator's full-file hash, ref:core/src/object/validation/hash.rs:9-25)."""

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._state = ctypes.create_string_buffer(lib.b3_state_size())
        lib.b3_init(self._state)

    def update(self, data: bytes | memoryview) -> "StreamingHasher":
        data = bytes(data) if isinstance(data, memoryview) else data
        self._lib.b3_update(self._state, data, len(data))
        return self

    def digest(self, out_len: int = 32) -> bytes:
        out = (ctypes.c_uint8 * 64)()
        self._lib.b3_finalize(self._state, out, min(out_len, 64))
        return bytes(out[:out_len])


def blake3_many(messages: list[bytes], nthreads: int | None = None) -> list[bytes] | None:
    """32-byte digests for a batch of messages using the threaded C path.

    This is the multi-core CPU baseline the TPU path is benchmarked
    against (the reference hashes on all cores via tokio `join_all`,
    ref:core/src/object/file_identifier/mod.rs:105-147).
    """
    lib = load()
    if lib is None:
        return None
    if nthreads is None:
        nthreads = os.cpu_count() or 1
    n = len(messages)
    lens = np.fromiter((len(m) for m in messages), np.uint32, n)
    offsets = np.zeros(n, np.uint64)
    np.cumsum(lens[:-1], out=offsets[1:])
    base = np.frombuffer(b"".join(messages), np.uint8)
    out = np.empty(n * 32, np.uint8)
    lib.b3_hash_many(
        base.ctypes.data, offsets.ctypes.data, lens.ctypes.data,
        n, out.ctypes.data, nthreads,
    )
    raw = out.tobytes()
    return [raw[i * 32:(i + 1) * 32] for i in range(n)]


# --- video decode frontend (FFmpeg FFI, ref:crates/ffmpeg) ----------------

_VIDEO_LIB: ctypes.CDLL | None = None
_VIDEO_FAILED = False
_AV_LIBS = ("-lavformat", "-lavcodec", "-lavutil", "-lswscale", "-lm")


def load_video() -> ctypes.CDLL | None:
    """The native FFmpeg frontend (movie_decoder.c), building on first
    use; None when libav headers/libraries are absent (callers fall
    back to cv2)."""
    global _VIDEO_LIB, _VIDEO_FAILED
    if _VIDEO_LIB is not None or _VIDEO_FAILED:
        return _VIDEO_LIB
    with _LOCK:
        if _VIDEO_LIB is not None or _VIDEO_FAILED:
            return _VIDEO_LIB
        so = os.path.join(_DIR, "_sdvideo.so")
        src = os.path.join(_DIR, "movie_decoder.c")
        try:
            if not os.path.exists(so) or \
                    os.path.getmtime(so) < os.path.getmtime(src):
                if not _build(src, so, _AV_LIBS):
                    _VIDEO_FAILED = True
                    return None
            lib = ctypes.CDLL(so)
            lib.sd_video_frame.restype = ctypes.c_int
            lib.sd_video_frame.argtypes = [
                ctypes.c_char_p, ctypes.c_double,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.c_char_p, ctypes.c_int,
            ]
            lib.sd_video_meta.restype = ctypes.c_int
            lib.sd_video_meta.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int64),
                ctypes.c_char_p, ctypes.c_int,
            ]
            lib.sd_video_free.argtypes = [ctypes.c_void_p]
            _VIDEO_LIB = lib
        except OSError:
            _VIDEO_FAILED = True
    return _VIDEO_LIB


def video_available() -> bool:
    return load_video() is not None


def video_frame(path: str, seek_fraction: float = 0.1):
    """(rgba HxWx4 uint8, rotation_degrees, is_cover) or None.

    Preferred-stream selection with embedded-cover preference, ~10%
    seek, display-matrix rotation (ref:movie_decoder.rs:32-629, cover
    check :352)."""
    lib = load_video()
    if lib is None:
        return None
    buf = ctypes.c_void_p()
    w = ctypes.c_int()
    h = ctypes.c_int()
    rot = ctypes.c_int()
    cover = ctypes.c_int()
    err = ctypes.create_string_buffer(256)
    rc = lib.sd_video_frame(
        os.fsencode(path), seek_fraction, ctypes.byref(buf),
        ctypes.byref(w), ctypes.byref(h), ctypes.byref(rot),
        ctypes.byref(cover), err, len(err),
    )
    if rc != 0:
        raise ValueError(
            f"video decode failed: {err.value.decode(errors='replace')}"
        )
    try:
        n = w.value * h.value * 4
        arr = np.frombuffer(
            ctypes.string_at(buf.value, n), np.uint8
        ).reshape(h.value, w.value, 4).copy()
    finally:
        lib.sd_video_free(buf)
    return arr, rot.value, bool(cover.value)


def video_meta(path: str):
    """{duration_seconds, fps, width, height, frame_count, codec} or
    None when the native frontend is unavailable; raises on bad files."""
    lib = load_video()
    if lib is None:
        return None
    dur = ctypes.c_double()
    fps = ctypes.c_double()
    w = ctypes.c_int()
    h = ctypes.c_int()
    frames = ctypes.c_int64()
    codec = ctypes.create_string_buffer(64)
    rc = lib.sd_video_meta(
        os.fsencode(path), ctypes.byref(dur), ctypes.byref(fps),
        ctypes.byref(w), ctypes.byref(h), ctypes.byref(frames),
        codec, len(codec),
    )
    if rc != 0:
        raise ValueError(f"video probe failed: {path}")
    return {
        "duration_seconds": dur.value, "fps": fps.value,
        "width": w.value, "height": h.value,
        "frame_count": int(frames.value),
        "codec": codec.value.decode(errors="replace"),
    }
