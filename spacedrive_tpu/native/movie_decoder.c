/* Native video decode frontend over FFmpeg (libavformat/-codec/-util +
 * libswscale).
 *
 * Role parity with the reference's sd-ffmpeg crate
 * (ref:crates/ffmpeg/src/movie_decoder.rs:32-629):
 *   - preferred video stream selection with embedded-cover-art
 *     preference (ref:movie_decoder.rs:352 — a stream with the
 *     ATTACHED_PIC disposition wins outright),
 *   - seek ~10% into the container before grabbing a frame,
 *   - rotation read from the stream display matrix and reported to the
 *     caller (the Python side rotates the RGBA array; same output as
 *     the reference's rotation-aware filter graph),
 *   - RGBA conversion through swscale.
 *
 * Exported C ABI (ctypes):
 *   int  sd_video_frame(path, seek_fraction, &buf, &w, &h,
 *                       &rotation_deg, &is_cover, errbuf, errlen);
 *   int  sd_video_meta(path, &duration_s, &fps, &w, &h, &nb_frames,
 *                      codec_buf, codec_len);
 *   void sd_video_free(buf);
 */

#include <libavcodec/avcodec.h>
#include <libavformat/avformat.h>
#include <libavutil/display.h>
#include <libavutil/imgutils.h>
#include <libswscale/swscale.h>
#include <math.h>
#include <stdint.h>
#include <string.h>

static void set_err(char *errbuf, int errlen, const char *msg, int averr) {
    if (!errbuf || errlen <= 0) return;
    if (averr) {
        char avmsg[128];
        av_strerror(averr, avmsg, sizeof(avmsg));
        snprintf(errbuf, errlen, "%s: %s", msg, avmsg);
    } else {
        snprintf(errbuf, errlen, "%s", msg);
    }
}

/* rotation in degrees [0, 360) from the stream's display matrix */
static int stream_rotation(const AVStream *st) {
    #if LIBAVFORMAT_VERSION_MAJOR >= 60
    const AVPacketSideData *sd = av_packet_side_data_get(
        st->codecpar->coded_side_data, st->codecpar->nb_coded_side_data,
        AV_PKT_DATA_DISPLAYMATRIX);
    const uint8_t *matrix = sd ? sd->data : NULL;
    #else
    const uint8_t *matrix =
        av_stream_get_side_data(st, AV_PKT_DATA_DISPLAYMATRIX, NULL);
    #endif
    if (!matrix) return 0;
    double theta = av_display_rotation_get((const int32_t *)matrix);
    if (isnan(theta)) return 0;
    int deg = (int)lround(-theta);  /* display matrix counters rotation */
    deg %= 360;
    if (deg < 0) deg += 360;
    return deg;
}

static int frame_to_rgba(const AVFrame *frame, uint8_t **out, int *w,
                         int *h, char *errbuf, int errlen) {
    struct SwsContext *sws = sws_getContext(
        frame->width, frame->height, (enum AVPixelFormat)frame->format,
        frame->width, frame->height, AV_PIX_FMT_RGBA,
        SWS_BILINEAR, NULL, NULL, NULL);
    if (!sws) {
        set_err(errbuf, errlen, "swscale context failed", 0);
        return -1;
    }
    int stride = frame->width * 4;
    uint8_t *buf = av_malloc((size_t)stride * frame->height);
    if (!buf) {
        sws_freeContext(sws);
        set_err(errbuf, errlen, "out of memory", 0);
        return -1;
    }
    uint8_t *dst[4] = {buf, NULL, NULL, NULL};
    int dst_stride[4] = {stride, 0, 0, 0};
    sws_scale(sws, (const uint8_t *const *)frame->data, frame->linesize, 0,
              frame->height, dst, dst_stride);
    sws_freeContext(sws);
    *out = buf;
    *w = frame->width;
    *h = frame->height;
    return 0;
}

/* decode one packet's worth of image (cover art path) or the first
 * decodable frame from the current position */
static int decode_one_frame(AVCodecContext *ctx, AVFormatContext *fmt,
                            int stream_index, const AVPacket *only_pkt,
                            AVFrame *frame, char *errbuf, int errlen) {
    int ret;
    if (only_pkt) {
        ret = avcodec_send_packet(ctx, only_pkt);
        if (ret < 0) {
            set_err(errbuf, errlen, "send cover packet", ret);
            return -1;
        }
        avcodec_send_packet(ctx, NULL); /* flush */
        ret = avcodec_receive_frame(ctx, frame);
        if (ret < 0) {
            set_err(errbuf, errlen, "decode cover", ret);
            return -1;
        }
        return 0;
    }
    AVPacket *pkt = av_packet_alloc();
    if (!pkt) return -1;
    int tries = 2048; /* bounded walk to the next decodable frame */
    while (tries-- > 0) {
        ret = av_read_frame(fmt, pkt);
        if (ret < 0) {
            avcodec_send_packet(ctx, NULL);
            if (avcodec_receive_frame(ctx, frame) == 0) {
                av_packet_free(&pkt);
                return 0;
            }
            set_err(errbuf, errlen, "no decodable frame", ret);
            av_packet_free(&pkt);
            return -1;
        }
        if (pkt->stream_index == stream_index) {
            ret = avcodec_send_packet(ctx, pkt);
            av_packet_unref(pkt);
            if (ret < 0 && ret != AVERROR(EAGAIN)) {
                set_err(errbuf, errlen, "send packet", ret);
                av_packet_free(&pkt);
                return -1;
            }
            ret = avcodec_receive_frame(ctx, frame);
            if (ret == 0) {
                av_packet_free(&pkt);
                return 0;
            }
            if (ret != AVERROR(EAGAIN)) {
                set_err(errbuf, errlen, "receive frame", ret);
                av_packet_free(&pkt);
                return -1;
            }
        } else {
            av_packet_unref(pkt);
        }
    }
    av_packet_free(&pkt);
    set_err(errbuf, errlen, "frame walk budget exhausted", 0);
    return -1;
}

int sd_video_frame(const char *path, double seek_fraction, uint8_t **out,
                   int *out_w, int *out_h, int *out_rotation,
                   int *out_is_cover, char *errbuf, int errlen) {
    AVFormatContext *fmt = NULL;
    AVCodecContext *ctx = NULL;
    AVFrame *frame = NULL;
    int ret, rc = -1;

    ret = avformat_open_input(&fmt, path, NULL, NULL);
    if (ret < 0) {
        set_err(errbuf, errlen, "open", ret);
        return -1;
    }
    ret = avformat_find_stream_info(fmt, NULL);
    if (ret < 0) {
        set_err(errbuf, errlen, "stream info", ret);
        goto done;
    }

    /* embedded cover art wins outright (ref:movie_decoder.rs:352) */
    int stream_index = -1, is_cover = 0;
    for (unsigned i = 0; i < fmt->nb_streams; i++) {
        AVStream *st = fmt->streams[i];
        if (st->codecpar->codec_type == AVMEDIA_TYPE_VIDEO &&
            (st->disposition & AV_DISPOSITION_ATTACHED_PIC) &&
            st->attached_pic.size > 0) {
            stream_index = (int)i;
            is_cover = 1;
            break;
        }
    }
    if (stream_index < 0) {
        stream_index =
            av_find_best_stream(fmt, AVMEDIA_TYPE_VIDEO, -1, -1, NULL, 0);
        if (stream_index < 0) {
            set_err(errbuf, errlen, "no video stream", stream_index);
            goto done;
        }
    }
    AVStream *st = fmt->streams[stream_index];

    const AVCodec *codec = avcodec_find_decoder(st->codecpar->codec_id);
    if (!codec) {
        set_err(errbuf, errlen, "no decoder for codec", 0);
        goto done;
    }
    ctx = avcodec_alloc_context3(codec);
    if (!ctx) goto done;
    ret = avcodec_parameters_to_context(ctx, st->codecpar);
    if (ret < 0) {
        set_err(errbuf, errlen, "codec params", ret);
        goto done;
    }
    ret = avcodec_open2(ctx, codec, NULL);
    if (ret < 0) {
        set_err(errbuf, errlen, "open codec", ret);
        goto done;
    }

    if (!is_cover && fmt->duration > 0 && seek_fraction > 0) {
        int64_t ts = (int64_t)(fmt->duration * seek_fraction);
        /* offset containers (MPEG-TS captures) start at nonzero pts */
        if (fmt->start_time != AV_NOPTS_VALUE && fmt->start_time > 0)
            ts += fmt->start_time;
        /* seek on the default timebase; fall back to start on failure
         * (ref:movie_decoder.rs seeks then decodes forward) */
        if (av_seek_frame(fmt, -1, ts, AVSEEK_FLAG_BACKWARD) < 0)
            av_seek_frame(fmt, -1, 0, AVSEEK_FLAG_BACKWARD);
        avcodec_flush_buffers(ctx);
    }

    frame = av_frame_alloc();
    if (!frame) goto done;
    ret = decode_one_frame(ctx, fmt, stream_index,
                           is_cover ? &st->attached_pic : NULL, frame,
                           errbuf, errlen);
    if (ret < 0) goto done;

    if (frame_to_rgba(frame, out, out_w, out_h, errbuf, errlen) < 0)
        goto done;
    *out_rotation = stream_rotation(st);
    *out_is_cover = is_cover;
    rc = 0;

done:
    if (frame) av_frame_free(&frame);
    if (ctx) avcodec_free_context(&ctx);
    if (fmt) avformat_close_input(&fmt);
    return rc;
}

int sd_video_meta(const char *path, double *duration_s, double *fps,
                  int *w, int *h, int64_t *nb_frames, char *codec_buf,
                  int codec_len) {
    AVFormatContext *fmt = NULL;
    if (avformat_open_input(&fmt, path, NULL, NULL) < 0) return -1;
    if (avformat_find_stream_info(fmt, NULL) < 0) {
        avformat_close_input(&fmt);
        return -1;
    }
    int si = av_find_best_stream(fmt, AVMEDIA_TYPE_VIDEO, -1, -1, NULL, 0);
    if (si < 0) {
        avformat_close_input(&fmt);
        return -1;
    }
    AVStream *st = fmt->streams[si];
    *duration_s = fmt->duration > 0 ? fmt->duration / (double)AV_TIME_BASE
                                    : 0.0;
    AVRational fr = st->avg_frame_rate.num ? st->avg_frame_rate
                                           : st->r_frame_rate;
    *fps = fr.den ? fr.num / (double)fr.den : 0.0;
    *w = st->codecpar->width;
    *h = st->codecpar->height;
    *nb_frames = st->nb_frames;
    if (*nb_frames == 0 && *fps > 0 && *duration_s > 0)
        *nb_frames = (int64_t)llround(*duration_s * *fps);
    const char *name = avcodec_get_name(st->codecpar->codec_id);
    snprintf(codec_buf, codec_len, "%s", name ? name : "unknown");
    avformat_close_input(&fmt);
    return 0;
}

void sd_video_free(uint8_t *buf) { av_free(buf); }
