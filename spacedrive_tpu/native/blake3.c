/* Portable C BLAKE3 (hash mode) — the framework's native CPU hashing
 * runtime.
 *
 * Written from the public BLAKE3 specification; mirrors the Python
 * golden reference in ops/blake3_ref.py. Role in the framework:
 *   - honest multi-core CPU baseline for bench.py (the reference uses
 *     the Rust blake3 crate for cas_id, ref:core/src/object/cas.rs:3);
 *   - fast host-side fallback when no accelerator is attached;
 *   - streaming full-file hashing for the validator pipeline
 *     (ref:core/src/object/validation/hash.rs reads 1 MiB blocks).
 *
 * Exports a batched `b3_hash_many` that fans out over pthreads, plus a
 * one-shot `b3_hash` and a streaming init/update/finalize trio.
 */

#include <pthread.h>
#include <stdint.h>
#include <string.h>

#define CHUNK_LEN 1024u
#define BLOCK_LEN 64u

#define CHUNK_START (1u << 0)
#define CHUNK_END (1u << 1)
#define PARENT (1u << 2)
#define ROOT (1u << 3)

static const uint32_t IV[8] = {
    0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u, 0xA54FF53Au,
    0x510E527Fu, 0x9B05688Cu, 0x1F83D9ABu, 0x5BE0CD19u,
};

static const uint8_t MSG_PERM[16] = {2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8};

static inline uint32_t rotr32(uint32_t x, int r) { return (x >> r) | (x << (32 - r)); }

static inline void g(uint32_t v[16], int a, int b, int c, int d, uint32_t mx, uint32_t my) {
  v[a] = v[a] + v[b] + mx;
  v[d] = rotr32(v[d] ^ v[a], 16);
  v[c] = v[c] + v[d];
  v[b] = rotr32(v[b] ^ v[c], 12);
  v[a] = v[a] + v[b] + my;
  v[d] = rotr32(v[d] ^ v[a], 8);
  v[c] = v[c] + v[d];
  v[b] = rotr32(v[b] ^ v[c], 7);
}

/* Full 16-word output (needed for root blocks). */
static void compress(const uint32_t h[8], const uint32_t m_in[16], uint64_t counter,
                     uint32_t block_len, uint32_t flags, uint32_t out[16]) {
  uint32_t v[16];
  uint32_t m[16], tmp[16];
  memcpy(m, m_in, sizeof(m));
  for (int i = 0; i < 8; i++) v[i] = h[i];
  for (int i = 0; i < 4; i++) v[8 + i] = IV[i];
  v[12] = (uint32_t)counter;
  v[13] = (uint32_t)(counter >> 32);
  v[14] = block_len;
  v[15] = flags;
  for (int r = 0; r < 7; r++) {
    g(v, 0, 4, 8, 12, m[0], m[1]);
    g(v, 1, 5, 9, 13, m[2], m[3]);
    g(v, 2, 6, 10, 14, m[4], m[5]);
    g(v, 3, 7, 11, 15, m[6], m[7]);
    g(v, 0, 5, 10, 15, m[8], m[9]);
    g(v, 1, 6, 11, 12, m[10], m[11]);
    g(v, 2, 7, 8, 13, m[12], m[13]);
    g(v, 3, 4, 9, 14, m[14], m[15]);
    if (r < 6) {
      for (int i = 0; i < 16; i++) tmp[i] = m[MSG_PERM[i]];
      memcpy(m, tmp, sizeof(m));
    }
  }
  for (int i = 0; i < 8; i++) {
    out[i] = v[i] ^ v[i + 8];
    out[i + 8] = v[i + 8] ^ h[i];
  }
}

static void words_of_block(const uint8_t *block, uint32_t len, uint32_t w[16]) {
  uint8_t buf[BLOCK_LEN];
  memset(buf, 0, sizeof(buf));
  memcpy(buf, block, len);
  for (int i = 0; i < 16; i++) {
    w[i] = (uint32_t)buf[4 * i] | ((uint32_t)buf[4 * i + 1] << 8) |
           ((uint32_t)buf[4 * i + 2] << 16) | ((uint32_t)buf[4 * i + 3] << 24);
  }
}

/* CV (or root words when is_root) of one <=1024-byte chunk. */
static void chunk_cv(const uint8_t *chunk, uint32_t len, uint64_t counter, int is_root,
                     uint32_t out16[16]) {
  uint32_t h[8];
  memcpy(h, IV, sizeof(h));
  uint32_t n_blocks = len == 0 ? 1 : (len + BLOCK_LEN - 1) / BLOCK_LEN;
  for (uint32_t b = 0; b < n_blocks; b++) {
    uint32_t off = b * BLOCK_LEN;
    uint32_t blen = len - off > BLOCK_LEN ? BLOCK_LEN : len - off;
    uint32_t flags = 0;
    if (b == 0) flags |= CHUNK_START;
    if (b == n_blocks - 1) {
      flags |= CHUNK_END;
      if (is_root) flags |= ROOT;
    }
    uint32_t w[16];
    words_of_block(chunk + off, blen, w);
    compress(h, w, counter, blen, flags, out16);
    if (b < n_blocks - 1) memcpy(h, out16, 8 * sizeof(uint32_t));
  }
}

static void parent_cv(const uint32_t left[8], const uint32_t right[8], int is_root,
                      uint32_t out16[16]) {
  uint32_t m[16];
  memcpy(m, left, 8 * sizeof(uint32_t));
  memcpy(m + 8, right, 8 * sizeof(uint32_t));
  compress(IV, m, 0, BLOCK_LEN, PARENT | (is_root ? ROOT : 0), out16);
}

/* ---- streaming state (bounded memory over unbounded input) ---- */

typedef struct {
  uint32_t stack[64][8];
  uint64_t stack_bits; /* bit d set => stack[d] holds a 2^d-chunk subtree CV */
  uint64_t count;      /* chunks fully absorbed */
  uint8_t pending[CHUNK_LEN];
  uint32_t pending_len;
} b3_state;

void b3_init(b3_state *s) {
  s->stack_bits = 0;
  s->count = 0;
  s->pending_len = 0;
}

static void push_chunk_cv(b3_state *s, const uint32_t cv_in[8]) {
  uint32_t cv[8], out16[16];
  memcpy(cv, cv_in, sizeof(cv));
  s->count++;
  uint64_t count = s->count;
  int d = 0;
  while ((count & 1) == 0) {
    parent_cv(s->stack[d], cv, 0, out16);
    memcpy(cv, out16, sizeof(cv));
    s->stack_bits &= ~(1ull << d);
    count >>= 1;
    d++;
  }
  memcpy(s->stack[d], cv, sizeof(cv));
  s->stack_bits |= 1ull << d;
}

void b3_update(b3_state *s, const uint8_t *data, uint64_t len) {
  uint64_t off = 0;
  /* Hold the final chunk out: only absorb a chunk once at least one
   * byte beyond its boundary has been seen. */
  while (s->pending_len + (len - off) > CHUNK_LEN) {
    uint32_t take = CHUNK_LEN - s->pending_len;
    if (take > len - off) take = (uint32_t)(len - off);
    memcpy(s->pending + s->pending_len, data + off, take);
    s->pending_len += take;
    off += take;
    if (s->pending_len == CHUNK_LEN && off < len) {
      uint32_t out16[16];
      chunk_cv(s->pending, CHUNK_LEN, s->count, 0, out16);
      push_chunk_cv(s, out16);
      s->pending_len = 0;
    }
  }
  uint64_t rest = len - off;
  memcpy(s->pending + s->pending_len, data + off, rest);
  s->pending_len += (uint32_t)rest;
}

void b3_finalize(const b3_state *s, uint8_t *out, uint32_t out_len) {
  uint32_t out16[16];
  if (s->count == 0) {
    chunk_cv(s->pending, s->pending_len, 0, 1, out16);
  } else {
    uint32_t cv[8];
    chunk_cv(s->pending, s->pending_len, s->count, 0, out16);
    memcpy(cv, out16, sizeof(cv));
    int highest = 63;
    while (highest > 0 && !((s->count >> highest) & 1)) highest--;
    for (int d = 0; d < 64; d++) {
      if ((s->count >> d) & 1) {
        parent_cv(s->stack[d], cv, d == highest, out16);
        memcpy(cv, out16, sizeof(cv));
      }
    }
  }
  uint8_t bytes[64];
  for (int i = 0; i < 16; i++) {
    bytes[4 * i] = (uint8_t)out16[i];
    bytes[4 * i + 1] = (uint8_t)(out16[i] >> 8);
    bytes[4 * i + 2] = (uint8_t)(out16[i] >> 16);
    bytes[4 * i + 3] = (uint8_t)(out16[i] >> 24);
  }
  memcpy(out, bytes, out_len > 64 ? 64 : out_len);
}

void b3_hash(const uint8_t *data, uint64_t len, uint8_t *out, uint32_t out_len) {
  b3_state s;
  b3_init(&s);
  b3_update(&s, data, len);
  b3_finalize(&s, out, out_len);
}

/* ---- batched API: n messages in one flat buffer ---- */

typedef struct {
  const uint8_t *base;
  const uint64_t *offsets;
  const uint32_t *lens;
  uint8_t *out; /* 32 bytes per message */
  int32_t begin, end;
} hash_span;

static void *hash_worker(void *arg) {
  hash_span *sp = (hash_span *)arg;
  for (int32_t i = sp->begin; i < sp->end; i++) {
    b3_hash(sp->base + sp->offsets[i], sp->lens[i], sp->out + 32 * (uint64_t)i, 32);
  }
  return 0;
}

void b3_hash_many(const uint8_t *base, const uint64_t *offsets, const uint32_t *lens,
                  int32_t n, uint8_t *out, int32_t nthreads) {
  if (nthreads < 1) nthreads = 1;
  if (nthreads > 64) nthreads = 64;
  if (nthreads == 1 || n < 2) {
    hash_span sp = {base, offsets, lens, out, 0, n};
    hash_worker(&sp);
    return;
  }
  pthread_t tids[64];
  hash_span spans[64];
  int32_t per = (n + nthreads - 1) / nthreads;
  int32_t nt = 0;
  for (int32_t t = 0; t < nthreads; t++) {
    int32_t b = t * per, e = b + per > n ? n : b + per;
    if (b >= e) break;
    spans[nt] = (hash_span){base, offsets, lens, out, b, e};
    pthread_create(&tids[nt], 0, hash_worker, &spans[nt]);
    nt++;
  }
  for (int32_t t = 0; t < nt; t++) pthread_join(tids[t], 0);
}

uint32_t b3_state_size(void) { return (uint32_t)sizeof(b3_state); }
