"""Batched image embedding on TPU — the semantic-search device leg.

Same dispatch discipline as the thumbnail resize (ops/thumbnail_jax.py,
PR 4): ONE compiled program per (device set, batch-pad) pair, the batch
dim padded to a power of two so compile count stays bounded, dp-sharded
over the chip mesh via shard_map when more than one device can hold a
real row, and demoted down the DeviceLadder on failure. The per-image
math body lives in models/embedder.forward and is closed over by the
jitted single-device, sharded, and host programs alike — identical
math ⇒ identical vectors at every rung, which is what lets a
replicated index trust a locally recomputed vector.
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np

from ..models import embedder as _embedder


@functools.cache
def _embed_fn():
    """Lazily built jitted embed pass (jax imported on first use)."""
    import jax

    @jax.jit
    def embed(params, images):
        # [B, S, S, 3] f32 → [B, EMBED_DIM] f32
        return _embedder.forward(params, images)

    return embed


_sharded_embed_fns: dict[tuple, object] = {}


def _embed_fn_sharded(devices):
    """dp-sharded embed: the batch dim splits over a flat mesh, every
    device running the same forward on its local rows under shard_map —
    no collectives (the forward is per-row), so vectors stay
    bit-identical to the single-device call."""
    key = tuple(d.id for d in devices)
    fn = _sharded_embed_fns.get(key)
    if fn is None:
        import jax

        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        import numpy as _np

        mesh = Mesh(_np.array(list(devices)), ("dp",))

        @jax.jit
        def embed_sharded(params, images):
            def body(imgs):
                return _embedder.forward(params, imgs)

            return shard_map(
                body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
            )(images)

        fn = (mesh, embed_sharded)
        _sharded_embed_fns[key] = fn
    return fn


def _embed_chunk(images: np.ndarray, devs) -> np.ndarray:
    """Pad one chunk and run its device call; returns the
    [bpad, EMBED_DIM] f32 result (validated — a device returning the
    wrong shape is an error the caller can demote on, never a silent
    corruption)."""
    from ..utils import faults as _faults

    params = _embedder.params()
    n = images.shape[0]
    n_dev = len(devs) if devs else 1
    # power-of-two batch pad bounds compile count at log2(max-batch)
    # programs; a sharded call also rounds up to the device count so
    # rows divide evenly over the mesh
    bpad = 1 << max(0, (n - 1).bit_length())
    if n_dev > 1:
        bpad = max(bpad, n_dev)
        bpad += (-bpad) % n_dev
    if bpad != n:
        pad = np.zeros((bpad - n, *images.shape[1:]), images.dtype)
        batch = np.concatenate([images, pad], axis=0)
    else:
        batch = images
    spec = _faults.hit("embed.forward")
    if spec is not None:
        if spec.mode == "raise":
            raise _faults.InjectedFault("injected device failure (embed)")
        if spec.mode == "xla":
            raise _faults.device_error("embed.forward")
    if n_dev > 1:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..telemetry import metrics as _tm
        from .cas import shard_occupancy

        mesh, fn = _embed_fn_sharded(devs)
        _tm.SHARD_BATCH_ROWS.observe(bpad // n_dev, op="embed")
        for frac in shard_occupancy(n, bpad, n_dev):
            _tm.DEVICE_DISPATCH_OCCUPANCY.observe(frac, op="embed")
        out = np.asarray(fn(
            jax.device_put(params, NamedSharding(mesh, P())),
            jax.device_put(batch, NamedSharding(mesh, P("dp"))),
        ))
    elif devs:
        # single surviving device: committed inputs pin the jit there,
        # not on a default device that may be the dead one
        import jax

        out = np.asarray(_embed_fn()(
            jax.device_put(params, devs[0]), jax.device_put(batch, devs[0]),
        ))
    else:
        out = np.asarray(_embed_fn()(params, batch))
    if spec is not None and spec.mode == "wrong_shape":
        out = out[:, : _embedder.EMBED_DIM // 2]
    if out.shape != (bpad, _embedder.EMBED_DIM):
        raise ValueError(
            f"device embed returned shape {out.shape}, "
            f"expected {(bpad, _embedder.EMBED_DIM)}"
        )
    return out


def embed_batch(
    images: np.ndarray, devices: Sequence | None = None
) -> np.ndarray:
    """Embed a [N, S, S, 3] f32 batch → [N, EMBED_DIM] f32.

    With >1 local device (and at least one real row per chip) the batch
    dim dp-shards over the mesh; auto dispatches ride the degradation
    ladder (parallel.mesh.LADDER) — full mesh → surviving subset →
    single default device — with bit-identical vectors at every rung.
    Explicit `devices` stay strict and re-raise."""
    if images.ndim != 4 or images.shape[1:] != (
        _embedder.IMAGE_SIZE, _embedder.IMAGE_SIZE, 3
    ):
        raise ValueError(f"embed input shape {images.shape} is not "
                         f"[N, {_embedder.IMAGE_SIZE}, "
                         f"{_embedder.IMAGE_SIZE}, 3]")
    n = images.shape[0]
    if n == 0:
        return np.zeros((0, _embedder.EMBED_DIM), np.float32)
    if devices is not None:
        return _embed_chunk(images, list(devices))[:n]
    from ..parallel import mesh as _mesh

    # bounded: one attempt per rung plus one half-open probe — a tiny
    # reset_timeout must not oscillate probe/demote forever
    for attempt in range(4):
        devs, level = _mesh.ladder_devices()
        if level < _mesh.LEVEL_HOST and len(devs) > 1 and n >= len(devs):
            use = devs
        elif level == _mesh.LEVEL_SUBSET and devs:
            # unsharded at the subset rung: still pin to a surviving
            # chip, never the (possibly dead) default
            use = devs[:1]
        else:
            use = None
        try:
            out = _embed_chunk(images, use)
        except Exception as exc:  # noqa: BLE001 - demote & retry
            # always settle the ladder bookkeeping (a probe left
            # unreported would block re-arming), THEN decide whether
            # anything is left to demote to
            _mesh.LADDER.record_failure(level, devs)
            if level >= _mesh.LEVEL_HOST or attempt == 3:
                raise
            from ..telemetry import events as _events

            _events.record_error("embed.ladder", exc)
            continue
        if use is not None:
            _mesh.LADDER.record_success(level)
        else:
            # ran on the single default device — says nothing about
            # the rung's chips; release a held probe
            _mesh.LADDER.probe_inconclusive(level)
        return out[:n]
    raise RuntimeError("unreachable: embed ladder loop exhausted")
