"""TPU compute plane: batched content hashing, resizing, perceptual hashing."""

from __future__ import annotations

import os

_CACHE_CONFIGURED = False


def configure_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point XLA's persistent compilation cache at a stable directory so
    the BLAKE3/resize/pHash programs compile once per machine, not once
    per process (first compile of the 56-chunk BLAKE3 program costs
    ~10 s on a tunneled chip; a cache hit costs milliseconds). Safe to
    call repeatedly; first caller wins."""
    global _CACHE_CONFIGURED
    if _CACHE_CONFIGURED:
        return None
    cache_dir = cache_dir or os.environ.get(
        "SD_XLA_CACHE_DIR",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "spacedrive_tpu_xla",
        ),
    )
    try:
        import jax  # inside the guard: jax-less installs keep working

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        _CACHE_CONFIGURED = True
        return cache_dir
    except Exception:
        return None
