"""TPU compute plane: batched content hashing, resizing, perceptual hashing."""
