"""BLAKE3 chunk compression as a Pallas TPU kernel.

The chunk stage is ~94% of the hash FLOPs (16 blocks × 7 rounds of the
compression permutation per 1 KiB chunk; the tree merge above it is
O(log C)). This kernel runs that stage as one Pallas program over lane
tiles, reading the message words in their NATURAL layout `[N, 256]`
(chunk-major — exactly the bytes as they sit in HBM after a free host
uint32 view) and transposing each `[L, 256]` tile to `[256, L]` inside
VMEM so the VPU's 8×128 registers vectorize across chunk lanes. Message
schedules are host-precomputed (perm^r applied to static indices — no
in-kernel gathers).

Round 4 finding (device trace, PROFILE.md): the previous design fed the
kernel `[16, 16, N]` word-major data, which forced XLA to materialize a
~235 MB HBM transpose + byte-pack around a 0.8 ms kernel — ~13 ms of
data movement per 4096×57-chunk batch. Moving the transpose INSIDE the
kernel (VMEM, per-tile) and bitcasting on the HOST (numpy view — zero
copy) cut the dispatch from ~13.7 ms to ~5.4 ms measured on a v5e
(chained-marginal timing, distinct inputs); the in-VMEM transpose costs
~3.9 ms of the 5.4 and is the remaining optimization frontier.

On real TPUs BOTH loops — the 16-block walk and the 7 rounds — are
fully unrolled: a `fori_loop` carrying the `[8, L]` state costs a
Mosaic layout round-trip per block and measured 5.5× slower on a v5e.
Interpret mode (tests) keeps the block walk ROLLED instead — the
unrolled body is a ~5k-op graph whose CPU compile takes minutes
(see _build_kernel).

Bit-exactness contract is identical to ops/blake3_jax.py (golden-tested
against the reference vectors); `ops/blake3_jax.hash_batch` calls this
kernel when the backend is a real TPU (`SD_BLAKE3_PALLAS=0` opts out,
`=1` forces interpret mode elsewhere) and falls back to its XLA path on
any Pallas failure. Guide: /opt/skills/guides/pallas_guide.md.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from .blake3_ref import BLOCK_LEN, CHUNK_END, CHUNK_START, IV, MSG_PERMUTATION, ROOT

LANES = 2048  # big-batch lane tile: [2048, 256] words ≈ 2 MiB VMEM (scoped limit 16 MiB)
LANES_SMALL = 512  # small batches / interpret mode: avoid the pad-to-tile floor
_ROUNDS = 7


@functools.lru_cache(maxsize=1)
def _schedules() -> tuple[tuple[int, ...], ...]:
    """schedule[r][k] = original word index feeding slot k in round r
    (the permutation applied r times), so rounds unroll with static
    indices instead of in-kernel gathers."""
    perm = list(range(16))
    out = []
    for _ in range(_ROUNDS):
        out.append(tuple(perm))
        perm = [perm[i] for i in MSG_PERMUTATION]
    return tuple(out)


def _build_kernel(unroll: bool = True):
    """The chunk kernel. `unroll=True` (real TPU) inlines the 16-block
    walk — a fori_loop carrying the [8, L] state costs a Mosaic layout
    round-trip per block, measured 5.5× slower on a v5e. Interpret mode
    gets `unroll=False`: the unrolled body is a ~5k-op graph whose CPU
    compile takes MINUTES (the parity test ran hours), while the rolled
    loop compiles the body once; the block math is shared, so parity
    coverage is identical."""
    import jax
    import jax.numpy as jnp

    U = jnp.uint32
    schedules = _schedules()
    iv = [np.uint32(IV[i]) for i in range(8)]

    def rotr(x, r):
        return (x >> np.uint32(r)) | (x << np.uint32(32 - r))

    def kernel(words_ref, chunk_len_ref, is_root_ref, t_ref, out_ref):
        lanes = out_ref.shape[1]
        zeros = jnp.zeros((lanes,), U)
        # one in-VMEM transpose per tile: [L, 256] natural (contiguous
        # HBM reads) -> [256, L] so each message word is a lane vector.
        # Cheaper than the XLA HBM transpose it replaces (see module
        # docstring), and int32 idioms throughout — Mosaic has no
        # unsigned vector max (arith.maxui).
        wt = jnp.transpose(words_ref[...], (1, 0))
        # per-block block_len/flags/active derive from the compact
        # per-lane chunk_len IN-KERNEL: shipping them as [16, N] arrays
        # cost ~4 ms/batch of HBM traffic + XLA prologue on a v5e
        chunk_len = chunk_len_ref[0, :].astype(jnp.int32)
        n_blocks = jnp.maximum(1, (chunk_len + BLOCK_LEN - 1) // BLOCK_LEN)
        is_root = is_root_ref[0, :] != np.uint32(0)
        t_lo = t_ref[0, :]

        def block_step(b, h):
            """One 64-byte block over all lanes; `b` may be traced."""
            m = [wt[b * 16 + j] for j in range(16)]
            blen = jnp.clip(chunk_len - b * BLOCK_LEN, 0, BLOCK_LEN).astype(U)
            last = n_blocks == (b + 1)
            flags = jnp.where(last, U(CHUNK_END), U(0))
            flags = jnp.where(last & is_root, flags | U(ROOT), flags)
            flags = jnp.where(b == 0, flags | U(CHUNK_START), flags)
            act = n_blocks > b
            v = list(h) + [
                iv[0] + zeros, iv[1] + zeros, iv[2] + zeros, iv[3] + zeros,
                t_lo, zeros, blen, flags,
            ]

            def g(a, bb, c, d, mx, my):
                v[a] = v[a] + v[bb] + mx
                v[d] = rotr(v[d] ^ v[a], 16)
                v[c] = v[c] + v[d]
                v[bb] = rotr(v[bb] ^ v[c], 12)
                v[a] = v[a] + v[bb] + my
                v[d] = rotr(v[d] ^ v[a], 8)
                v[c] = v[c] + v[d]
                v[bb] = rotr(v[bb] ^ v[c], 7)

            for r in range(_ROUNDS):
                s = schedules[r]
                g(0, 4, 8, 12, m[s[0]], m[s[1]])
                g(1, 5, 9, 13, m[s[2]], m[s[3]])
                g(2, 6, 10, 14, m[s[4]], m[s[5]])
                g(3, 7, 11, 15, m[s[6]], m[s[7]])
                g(0, 5, 10, 15, m[s[8]], m[s[9]])
                g(1, 6, 11, 12, m[s[10]], m[s[11]])
                g(2, 7, 8, 13, m[s[12]], m[s[13]])
                g(3, 4, 9, 14, m[s[14]], m[s[15]])

            out = [v[i] ^ v[i + 8] for i in range(8)]
            return tuple(jnp.where(act, out[i], h[i]) for i in range(8))

        h = tuple(iv[i] + zeros for i in range(8))
        if unroll:
            for b in range(16):
                h = block_step(b, h)
        else:
            h = jax.lax.fori_loop(0, 16, block_step, h)

        for i in range(8):
            out_ref[i, :] = h[i]

    return kernel


@functools.lru_cache(maxsize=4)
def _chunk_cvs_call(interpret: bool, lanes: int):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel = _build_kernel(unroll=not interpret)
    mem = {} if interpret else {"memory_space": pltpu.VMEM}

    @functools.partial(jax.jit, static_argnames=())
    def run(words, chunk_len, is_root, t_lo):
        """words [N, 256] natural chunk-major; chunk_len/is_root/t_lo
        [1, N] (N a multiple of `lanes`) -> cvs [8, N] uint32."""
        n = words.shape[0]
        grid = (n // lanes,)
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8, n), jnp.uint32),
            grid=grid,
            in_specs=[
                pl.BlockSpec((lanes, 256), lambda i: (i, 0), **mem),
                pl.BlockSpec((1, lanes), lambda i: (0, i), **mem),
                pl.BlockSpec((1, lanes), lambda i: (0, i), **mem),
                pl.BlockSpec((1, lanes), lambda i: (0, i), **mem),
            ],
            out_specs=pl.BlockSpec((8, lanes), lambda i: (0, i), **mem),
            interpret=interpret,
        )(words, chunk_len, is_root, t_lo)

    return run


def pallas_mode() -> str | None:
    """'tpu' (real kernel), 'interpret', or None (disabled).

    Default: real kernel on TPU backends only. SD_BLAKE3_PALLAS=1
    forces interpret mode elsewhere (tests); =0 disables entirely.
    """
    env = os.environ.get("SD_BLAKE3_PALLAS")
    if env == "0":
        return None
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        return None
    if platform == "tpu":
        return "tpu"
    return "interpret" if env == "1" else None


def chunk_cvs(words, chunk_len, is_root, t_lo, *, interpret: bool):
    """Pad the lane dim to the chosen tile and run the kernel; returns
    [8, N]. `words` is [N, 256] natural layout; the other inputs are
    compact per-lane vectors [1, N] (block_len/flags/active derive
    in-kernel). Big batches use the wide tile (fewer grid steps); small
    batches and interpret mode use the small one so the pad-to-tile
    floor stays cheap."""
    import jax.numpy as jnp

    n = words.shape[0]
    lanes = LANES_SMALL if (interpret or n < 4 * LANES) else LANES
    pad = (-n) % lanes
    if pad:
        # pad lanes hash as zero-length chunks; their CVs are sliced off
        words = jnp.pad(words, ((0, pad), (0, 0)))
        chunk_len = jnp.pad(chunk_len, ((0, 0), (0, pad)))
        is_root = jnp.pad(is_root, ((0, 0), (0, pad)))
        t_lo = jnp.pad(t_lo, ((0, 0), (0, pad)))
    out = _chunk_cvs_call(interpret, lanes)(words, chunk_len, is_root, t_lo)
    return out[:, :n]
