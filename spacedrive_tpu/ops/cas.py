"""Content-addressing (cas_id) — sampling layout + batched TPU pipeline.

Bit-parity with the reference algorithm (ref:core/src/object/cas.rs:23-62):

    message = u64_le(size) || payload
    payload = whole file                          if size <= 100 KiB
            = file[0:8K]
              || file[8K + k*J : +10K]  k=0..3    J = (size - 16K) // 4
              || file[size-8K : size]             otherwise
    cas_id  = blake3(message).hex()[:16]

Large files therefore produce a *fixed* 57,352-byte message (57 chunks)
— the TPU hot bucket. Small files bucket by chunk count into a handful
of compiled shapes (ragged lengths are masked in-kernel).
"""

from __future__ import annotations

import hashlib
import os
import struct
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from .blake3_ref import (
    CHUNK_LEN,
    StreamingBlake3,
    chunk_chaining_value,
    parent_chaining_value,
    root_digest_from_pair,
)
# blake3_jax (and with it jax) loads lazily inside the device dispatch
# paths: the procpool worker runtime imports this module for its CPU
# halves (read_message / chunk caches / cas_ids "cpu") and must stay
# jax-free — a spawned worker paying a jax import to hash on host would
# defeat the slim-runtime contract (parallel/procworker.py).

SAMPLE_COUNT = 4
SAMPLE_SIZE = 10 * 1024
HEADER_OR_FOOTER_SIZE = 8 * 1024
MINIMUM_FILE_SIZE = 100 * 1024

LARGE_MSG_LEN = 8 + 2 * HEADER_OR_FOOTER_SIZE + SAMPLE_COUNT * SAMPLE_SIZE  # 57,352
LARGE_CHUNKS = (LARGE_MSG_LEN + 1023) // 1024  # 57
MAX_SMALL_MSG_LEN = 8 + MINIMUM_FILE_SIZE  # 102,408
# Small-file buckets by chunk count; compiled once each.
SMALL_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 101)


def sample_ranges(size: int) -> list[tuple[int, int]]:
    """(offset, length) reads composing the payload, matching the
    reference's read/seek sequence exactly."""
    if size <= MINIMUM_FILE_SIZE:
        return [(0, size)]
    jump = (size - 2 * HEADER_OR_FOOTER_SIZE) // SAMPLE_COUNT
    ranges = [(0, HEADER_OR_FOOTER_SIZE)]
    for k in range(SAMPLE_COUNT):
        ranges.append((HEADER_OR_FOOTER_SIZE + k * jump, SAMPLE_SIZE))
    ranges.append((size - HEADER_OR_FOOTER_SIZE, HEADER_OR_FOOTER_SIZE))
    return ranges


def message_from_bytes(content: bytes, size: int | None = None) -> bytes:
    """Assemble the hashed message for in-memory content."""
    size = len(content) if size is None else size
    parts = [struct.pack("<Q", size)]
    for off, ln in sample_ranges(size):
        parts.append(content[off:off + ln])
    return b"".join(parts)


def read_message(path: str | os.PathLike, size: int | None = None) -> bytes:
    """Read the sampling layout from disk (pread per range)."""
    if size is None:
        size = os.stat(path).st_size
    parts = [struct.pack("<Q", size)]
    with open(path, "rb", buffering=0) as f:
        for off, ln in sample_ranges(size):
            f.seek(off)
            buf = f.read(ln)
            if len(buf) != ln:
                raise OSError(f"short read at {off} in {path}")
            parts.append(buf)
    return b"".join(parts)


def message_len(size: int) -> int:
    """Length of the hashed message for a file of `size` bytes."""
    return 8 + size if size <= MINIMUM_FILE_SIZE else LARGE_MSG_LEN


# --- dirty-range rehash (incremental indexing, location/indexer/journal) ---
#
# The cas_id message is hashed by BLAKE3 as a Merkle tree over 1024-byte
# chunks. Caching a cheap content digest per chunk plus the tree's
# chaining values lets a warm pass on a modified file recompute only the
# chunks whose bytes actually changed (and their log-depth path of
# parents) — bit-identical to a full rehash, with zero bytes shipped to
# the device. Unchanged chunks cost one blake2b per 1 KiB (C-speed);
# only dirty chunks pay the BLAKE3 compression.
#
# Cache shape per file: `digests` (16-byte blake2b per chunk, built on
# EVERY journal record — cheap enough for the cold/device path) and
# `levels` (the CV tree, built the first time a file takes the host
# dirty-range path — the device path cannot observe interior CVs).

CHUNK_DIGEST_LEN = 16


def _split_chunks(message: bytes) -> list[bytes]:
    return [message[i:i + CHUNK_LEN] for i in range(0, len(message), CHUNK_LEN)]


def chunk_digests(message: bytes) -> list[bytes]:
    """Cheap per-chunk content digests (blake2b-128, C-speed) — the
    dirty detector, NOT part of the cas_id itself."""
    return [
        hashlib.blake2b(c, digest_size=CHUNK_DIGEST_LEN).digest()
        for c in _split_chunks(message)
    ]


@dataclass
class ChunkCache:
    """Per-file dirty-range state carried by the index journal."""

    msg_len: int
    digests: list[bytes]
    # CV tree: levels[0] = per-chunk CVs, each upper level the pairwise
    # parents (odd node carried up), topmost level exactly 2 nodes.
    # None until the file first takes the host dirty-range path.
    levels: list[list[bytes]] | None = None

    def to_payload(self) -> dict:
        return {
            "len": self.msg_len,
            "dig": self.digests,
            "cvs": self.levels,
        }

    @classmethod
    def from_payload(cls, obj: Any) -> "ChunkCache | None":
        """Strict validation: anything malformed returns None (the
        caller degrades to a cold rehash — never a wrong cas_id)."""
        if not isinstance(obj, dict):
            return None
        msg_len, digests, levels = obj.get("len"), obj.get("dig"), obj.get("cvs")
        if not isinstance(msg_len, int) or msg_len <= 0:
            return None
        n = (msg_len + CHUNK_LEN - 1) // CHUNK_LEN
        if (
            not isinstance(digests, list) or len(digests) != n
            or any(
                not isinstance(d, bytes) or len(d) != CHUNK_DIGEST_LEN
                for d in digests
            )
        ):
            return None
        if levels is not None:
            if not isinstance(levels, list) or not levels:
                return None
            want = n
            for i, level in enumerate(levels):
                if (
                    not isinstance(level, list) or len(level) != want
                    or any(not isinstance(cv, bytes) or len(cv) != 32 for cv in level)
                ):
                    return None
                want = (want + 1) // 2
            if len(levels[-1]) != 2:
                return None
        return cls(msg_len, list(digests), levels)


def build_chunk_cache(message: bytes) -> ChunkCache:
    """Digest-only cache (cheap) — recorded alongside a device-hashed
    cas_id so the FIRST in-place modification can already diff chunks."""
    return ChunkCache(len(message), chunk_digests(message))


def _build_levels(cvs: list[bytes]) -> list[list[bytes]]:
    levels = [cvs]
    while len(levels[-1]) > 2:
        cur = levels[-1]
        nxt = [
            parent_chaining_value(cur[j], cur[j + 1])
            for j in range(0, len(cur) - 1, 2)
        ]
        if len(cur) % 2:
            nxt.append(cur[-1])
        levels.append(nxt)
    return levels


def _root_cas_id(levels: list[list[bytes]]) -> str:
    top = levels[-1]
    return root_digest_from_pair(top[0], top[1], 8).hex()


def host_rehash_with_cache(message: bytes) -> tuple[str, ChunkCache]:
    """Full host rehash that CAPTURES the CV tree, so the next
    modification of this file pays only for its dirty chunks. Only
    valid for multi-chunk messages (single chunks use the ROOT flag)."""
    chunks = _split_chunks(message)
    if len(chunks) < 2:
        raise ValueError("host_rehash_with_cache needs >= 2 chunks")
    cvs = [chunk_chaining_value(c, i) for i, c in enumerate(chunks)]
    levels = _build_levels(cvs)
    cache = ChunkCache(len(message), chunk_digests(message), levels)
    return _root_cas_id(levels), cache


def dirty_range_rehash(
    message: bytes, cache: ChunkCache
) -> tuple[str, ChunkCache, int, int]:
    """Rehash `message` reusing `cache` from its previous version.
    Returns (cas_id, refreshed cache, dirty_chunks, bytes_rehashed) —
    the cas_id is bit-identical to a full rehash (golden-tested).

    Requires an unchanged message length (a size change moves every
    sample offset, so the whole message is new — callers full-rehash).
    """
    if len(message) != cache.msg_len:
        raise ValueError("message length changed; dirty-range does not apply")
    chunks = _split_chunks(message)
    if len(chunks) < 2:
        raise ValueError("dirty-range needs >= 2 chunks")
    digests = chunk_digests(message)
    dirty = [i for i, d in enumerate(digests) if d != cache.digests[i]]
    if cache.levels is None:
        # no CV tree yet (cas came off the device): one full host rehash
        # builds it; every later modification pays only its dirty chunks
        cas, fresh = host_rehash_with_cache(message)
        return cas, fresh, len(dirty), len(message)
    levels = [list(level) for level in cache.levels]
    hashed = 0
    for i in dirty:
        levels[0][i] = chunk_chaining_value(chunks[i], i)
        hashed += len(chunks[i])
    # bubble the dirty paths up: parent j covers children 2j / 2j+1;
    # an unpaired last node is carried (copied), not compressed
    dirty_nodes = set(dirty)
    for depth in range(len(levels) - 1):
        cur, nxt = levels[depth], levels[depth + 1]
        parents = set()
        for i in dirty_nodes:
            j = i // 2
            if j in parents:
                continue
            if 2 * j + 1 < len(cur):
                nxt[j] = parent_chaining_value(cur[2 * j], cur[2 * j + 1])
            else:
                nxt[j] = cur[2 * j]
            parents.add(j)
        dirty_nodes = parents
    return (
        _root_cas_id(levels),
        ChunkCache(cache.msg_len, digests, levels),
        len(dirty),
        hashed,
    )


def cas_id_cpu(path: str | os.PathLike, size: int | None = None) -> str:
    """Host-only cas_id (the reference's exact behavior), used as the
    default/fallback implementation and for parity tests."""
    msg = read_message(path, size)
    return StreamingBlake3().update(msg).hexdigest()[:16]


def cas_id_from_bytes_cpu(content: bytes) -> str:
    return StreamingBlake3().update(message_from_bytes(content)).hexdigest()[:16]


# The pad ladder and per-device dispatch cap live in the autotuner's
# policy module (parallel/autotune.py) — the ONE home for pipeline
# sizing constants (sdlint SD013). Re-exported here because the ladder
# is also the compiled-shape vocabulary this module packs against.
from ..parallel.autotune import BATCH_LADDER

DEVICE_BATCH = BATCH_LADDER[-1]  # max rows per dispatch PER DEVICE


def batch_ladder(n_devices: int = 1) -> tuple[int, ...]:
    """Global pad ladder for an n-device dp dispatch: every rung is the
    per-device warm rung × device count, so each chip always sees one
    of the SAME three compiled shapes (32/256/1024 rows) regardless of
    how many chips share the batch — tracing cost stays bounded at 3
    programs per (bucket, device count)."""
    n = max(1, n_devices)
    return BATCH_LADDER if n == 1 else tuple(r * n for r in BATCH_LADDER)


def device_batch(n_devices: int = 1) -> int:
    """Max rows per dispatch: DEVICE_BATCH per participating device."""
    return DEVICE_BATCH * max(1, n_devices)


def pack_canonical_batch(
    messages: Sequence[bytes], max_chunks: int, n_devices: int = 1
) -> tuple[np.ndarray, np.ndarray]:
    """The ONE batch-shape policy for device hashing: ≤device_batch(n)
    messages pack into a `(ladder_size, max_chunks*1024)` uint8 array +
    int32 lengths, the ladder scaled by `n_devices` (batch_ladder) so a
    dp-sharded dispatch divides evenly with warm per-device shapes. A
    fresh XLA shape costs seconds of tracing + executable load (worse
    on a tunneled chip) while a warm shape runs in ~40 ms, so every
    caller (cas_ids_begin, the validator) MUST pack through here. Pad
    rows hash 1 junk byte and get sliced off by the caller.

    The array starts uninitialized (np.empty) and each row writes its
    message + explicit zero tail — one pass over the buffer instead of
    a full zero-fill followed by prefix overwrites (the zero-fill was
    ~half the pack time at the 57 MB hot-bucket batch size)."""
    n = len(messages)
    cap = device_batch(n_devices)
    if n > cap:
        raise ValueError(f"pack at most {cap} messages, got {n}")
    n_pad = next(s for s in batch_ladder(n_devices) if s >= n)
    arr = np.empty((n_pad, max_chunks * 1024), np.uint8)
    lens = np.ones((n_pad,), np.int32)
    for j, msg in enumerate(messages):
        ln = len(msg)
        arr[j, :ln] = np.frombuffer(msg, np.uint8)
        arr[j, ln:] = 0
        lens[j] = ln
    arr[n:] = 0  # pad rows (length 1) must hash a zero byte
    return arr, lens


def _bucket_for(msg_len: int) -> int:
    chunks = max(1, (msg_len + 1023) // 1024)
    for b in SMALL_BUCKETS:
        if chunks <= b:
            return b
    raise ValueError(f"message too large for small buckets: {msg_len}")


@dataclass
class _Bucket:
    chunks: int
    indices: list[int]
    messages: list[bytes]


def shard_occupancy(n_real: int, n_pad: int, n_dev: int) -> list[float]:
    """Per-device real-row fraction of one sharded dispatch (device d
    owns rows [d*r, (d+1)*r) of the contiguously packed batch) — the
    caller observes these under its own literal `op` label."""
    r = n_pad // n_dev
    return [
        min(max(n_real - d * r, 0), r) / r for d in range(n_dev)
    ]


def cas_ids_begin(
    messages: Sequence[bytes], devices: Sequence[Any] | None = None,
    _depth: int = 0,
) -> Callable[[], list[str]]:
    """Dispatch device hashing WITHOUT blocking: batches go to the
    accelerator asynchronously (JAX dispatch) and the returned finisher
    materializes the hex ids. Splitting dispatch from completion lets a
    pipeline queue window N+1's transfer while N is still in flight —
    on a tunneled chip that hides most of the per-call latency
    (SURVEY §7 hard part #2).

    With >1 local device each batch is dp-sharded so ONE dispatch feeds
    every chip (blake3_jax.hash_batch devices=...). Explicitly passed
    `devices` always shard; the default policy shards a batch only when
    it fills at least half of the smallest sharded ladder rung
    (BATCH_LADDER[0] × n_devices ÷ 2) — tiny tails stay on one device
    where their warm 32-row shape is cheapest.

    Auto dispatches ride the degradation ladder (parallel.mesh.LADDER):
    a device failure demotes the NEXT attempt — full mesh → surviving
    chip subset → host reference path — and the failed batch is re-run
    at the demoted rung inside the same `finish()` call instead of
    failing the window (the host path is bit-identical, golden-tested).
    Explicit `devices` stay strict and re-raise."""
    from . import blake3_jax
    from ..parallel import mesh as _mesh

    if devices is not None:
        devs = list(devices)
        explicit = True
        level: int | None = None
    else:
        explicit = False
        if _depth >= 3:
            # recursion cap: go straight to the host path WITHOUT
            # consulting the ladder — ladder_devices() could hand this
            # doomed call the half-open probe and strand it
            from ..telemetry import metrics as _tm

            _tm.CAS_BACKEND_FALLBACK.inc()
            return lambda: cas_ids(messages, "cpu")
        devs, level = _mesh.ladder_devices()
        if level == _mesh.LEVEL_HOST:
            # demoted to (or stuck on) the host reference path — count
            # the degradation so a node quietly hashing on CPU shows up
            from ..telemetry import metrics as _tm

            _tm.CAS_BACKEND_FALLBACK.inc()
            return lambda: cas_ids(messages, "cpu")
    n_dev = len(devs)

    def _retry_demoted(exc: Exception) -> Callable[[], list[str]]:
        from ..telemetry import events as _events
        from ..telemetry import metrics as _tm

        _mesh.LADDER.record_failure(level, devs)
        _tm.CAS_BACKEND_FALLBACK.inc()
        _events.record_error("cas.ladder", exc)
        # bounded re-dispatch at the demoted rung (depth caps probe
        # oscillation when a test-sized reset_timeout is in effect)
        return cas_ids_begin(messages, _depth=_depth + 1)

    buckets: dict[int, _Bucket] = {}
    for i, msg in enumerate(messages):
        c = LARGE_CHUNKS if len(msg) == LARGE_MSG_LEN else _bucket_for(len(msg))
        b = buckets.setdefault(c, _Bucket(c, [], []))
        b.indices.append(i)
        b.messages.append(msg)

    # dispatch quantum: the autotuner's current per-device rung × device
    # count (static top rung = device_batch, bit-identical to the
    # pre-autotune path). Smaller rungs keep every compiled shape warm —
    # parts still pack through the same ladder (pack_canonical_batch).
    from ..parallel import autotune as _autotune

    step = min(
        device_batch(n_dev),
        _autotune.policy("identify").dispatch_rows_per_device()
        * max(1, n_dev),
    )
    in_flight: list[tuple[_Bucket, int, Any]] = []
    used_devices = False  # did any part actually shard over `devs`?
    try:
        for c, bucket in sorted(buckets.items()):
            for off in range(0, len(bucket.messages), step):
                part = bucket.messages[off : off + step]
                # shard-declined parts MUST fit the single-device pack cap:
                # with step = DEVICE_BATCH × n_dev a part can exceed
                # DEVICE_BATCH, so anything over the cap shards regardless
                # of the occupancy heuristic (only reachable at >64 devices)
                shard = n_dev > 1 and (
                    explicit
                    or len(part) * 2 >= n_dev * BATCH_LADDER[0]
                    or len(part) > DEVICE_BATCH
                )
                # at the SUBSET rung an unsharded tail must still land
                # on a SURVIVING chip, not the (possibly dead) default
                # device — pin it to the subset's first device
                single = (
                    devs[:1]
                    if not shard and not explicit
                    and level == _mesh.LEVEL_SUBSET and devs
                    else None
                )
                used_devices = used_devices or shard or single is not None
                arr, lens = pack_canonical_batch(
                    part, c, n_devices=n_dev if shard else 1
                )
                if shard:
                    from ..telemetry import metrics as _tm

                    for frac in shard_occupancy(len(part), arr.shape[0], n_dev):
                        _tm.DEVICE_DISPATCH_OCCUPANCY.observe(frac, op="blake3")
                in_flight.append(
                    (bucket, off, blake3_jax.hash_batch(
                        arr, lens, max_chunks=c,
                        devices=devs if shard else single,
                    ))
                )
    except Exception as exc:  # noqa: BLE001 - dispatch failure → demote
        if explicit:
            raise
        return _retry_demoted(exc)

    def finish() -> list[str]:
        out: list[str | None] = [None] * len(messages)
        try:
            for bucket, off, words in in_flight:
                part = bucket.indices[off : off + step]
                if getattr(words, "ndim", 2) != 2 or words.shape[1] != 8 \
                        or words.shape[0] < len(part):
                    raise ValueError(
                        f"device returned wrong-shaped digest batch "
                        f"{getattr(words, 'shape', '?')} for {len(part)} rows"
                    )
                for j, hx in enumerate(
                    blake3_jax.words_to_hex(words, 16)[: len(part)]
                ):
                    out[part[j]] = hx
        except Exception as exc:  # noqa: BLE001 - materialization → demote
            if explicit:
                raise
            return _retry_demoted(exc)()
        if not explicit:
            if used_devices:
                _mesh.LADDER.record_success(level)
            else:
                # the whole call ran unsharded on the default device —
                # it proved nothing about the rung's chips, so a held
                # half-open probe is released, never promoted
                _mesh.LADDER.probe_inconclusive(level)
        return out  # type: ignore[return-value]

    return finish


def cas_ids_batched(messages: Sequence[bytes]) -> list[str]:
    """cas_ids for pre-assembled messages, batched per chunk-bucket and
    hashed on the accelerator. Order-preserving."""
    return cas_ids_begin(messages)()


def cas_ids_for_paths(paths: Iterable[tuple[str, int]]) -> list[str]:
    """Batched cas_ids for (path, size) pairs: sampled reads on host,
    BLAKE3 on device."""
    msgs = [read_message(p, s) for p, s in paths]
    return cas_ids_batched(msgs)


def cas_ids_native_cpu(messages: Sequence[bytes]) -> list[str] | None:
    """Threaded C BLAKE3 path; None when the native lib is unavailable."""
    from .. import native

    digests = native.blake3_many(list(messages))
    if digests is None:
        return None
    return [d[:8].hex() for d in digests]


def cas_ids(messages: Sequence[bytes], backend: str = "auto") -> list[str]:
    """Backend-selected batched cas_ids.

    - "tpu"/"device": JAX accelerator batch (falls back if jax is
      unusable only under "auto").
    - "cpu": native C (threaded), then pure Python.
    - "auto": device if a non-CPU jax backend is live, else native C,
      else Python — the same default-with-fallback contract the
      north-star requires.
    """
    if not messages:
        return []
    if backend in ("tpu", "device"):
        return cas_ids_batched(messages)
    if backend == "cpu":
        got = cas_ids_native_cpu(messages)
        if got is not None:
            return got
        return [StreamingBlake3().update(m).hexdigest()[:16] for m in messages]
    # auto
    if _device_available():
        try:
            return cas_ids_batched(messages)
        except Exception as exc:  # noqa: BLE001 - fall back to host hashing
            # the degradation must be observable, not silent: count it
            # and put the bounded traceback on the flight recorder so a
            # node quietly hashing on CPU shows up in the debug bundle
            from ..telemetry import events as _events
            from ..telemetry import metrics as _tm

            _tm.CAS_BACKEND_FALLBACK.inc()
            _events.record_error("cas.auto", exc)
    return cas_ids(messages, "cpu")


_DEVICE_STATE: list[bool] | None = None


def _device_available() -> bool:
    global _DEVICE_STATE
    if _DEVICE_STATE is None:
        try:
            import jax

            _DEVICE_STATE = [jax.devices()[0].platform != "cpu"]
        except Exception:  # noqa: BLE001 - no usable accelerator
            _DEVICE_STATE = [False]
    return _DEVICE_STATE[0]
