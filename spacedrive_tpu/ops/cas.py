"""Content-addressing (cas_id) — sampling layout + batched TPU pipeline.

Bit-parity with the reference algorithm (ref:core/src/object/cas.rs:23-62):

    message = u64_le(size) || payload
    payload = whole file                          if size <= 100 KiB
            = file[0:8K]
              || file[8K + k*J : +10K]  k=0..3    J = (size - 16K) // 4
              || file[size-8K : size]             otherwise
    cas_id  = blake3(message).hex()[:16]

Large files therefore produce a *fixed* 57,352-byte message (57 chunks)
— the TPU hot bucket. Small files bucket by chunk count into a handful
of compiled shapes (ragged lengths are masked in-kernel).
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Sequence

import numpy as np

from .blake3_ref import StreamingBlake3
from . import blake3_jax

SAMPLE_COUNT = 4
SAMPLE_SIZE = 10 * 1024
HEADER_OR_FOOTER_SIZE = 8 * 1024
MINIMUM_FILE_SIZE = 100 * 1024

LARGE_MSG_LEN = 8 + 2 * HEADER_OR_FOOTER_SIZE + SAMPLE_COUNT * SAMPLE_SIZE  # 57,352
LARGE_CHUNKS = (LARGE_MSG_LEN + 1023) // 1024  # 57
MAX_SMALL_MSG_LEN = 8 + MINIMUM_FILE_SIZE  # 102,408
# Small-file buckets by chunk count; compiled once each.
SMALL_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 101)


def sample_ranges(size: int) -> list[tuple[int, int]]:
    """(offset, length) reads composing the payload, matching the
    reference's read/seek sequence exactly."""
    if size <= MINIMUM_FILE_SIZE:
        return [(0, size)]
    jump = (size - 2 * HEADER_OR_FOOTER_SIZE) // SAMPLE_COUNT
    ranges = [(0, HEADER_OR_FOOTER_SIZE)]
    for k in range(SAMPLE_COUNT):
        ranges.append((HEADER_OR_FOOTER_SIZE + k * jump, SAMPLE_SIZE))
    ranges.append((size - HEADER_OR_FOOTER_SIZE, HEADER_OR_FOOTER_SIZE))
    return ranges


def message_from_bytes(content: bytes, size: int | None = None) -> bytes:
    """Assemble the hashed message for in-memory content."""
    size = len(content) if size is None else size
    parts = [struct.pack("<Q", size)]
    for off, ln in sample_ranges(size):
        parts.append(content[off:off + ln])
    return b"".join(parts)


def read_message(path: str | os.PathLike, size: int | None = None) -> bytes:
    """Read the sampling layout from disk (pread per range)."""
    if size is None:
        size = os.stat(path).st_size
    parts = [struct.pack("<Q", size)]
    with open(path, "rb", buffering=0) as f:
        for off, ln in sample_ranges(size):
            f.seek(off)
            buf = f.read(ln)
            if len(buf) != ln:
                raise OSError(f"short read at {off} in {path}")
            parts.append(buf)
    return b"".join(parts)


def cas_id_cpu(path: str | os.PathLike, size: int | None = None) -> str:
    """Host-only cas_id (the reference's exact behavior), used as the
    default/fallback implementation and for parity tests."""
    msg = read_message(path, size)
    return StreamingBlake3().update(msg).hexdigest()[:16]


def cas_id_from_bytes_cpu(content: bytes) -> str:
    return StreamingBlake3().update(message_from_bytes(content)).hexdigest()[:16]


DEVICE_BATCH = 1024  # max rows per dispatch (see cas_ids_begin)
# the tail ladder: at most 3 compiled programs per bucket, and a
# 5-file tail pads to 32 rows, not 1024
BATCH_LADDER = (32, 256, DEVICE_BATCH)


def pack_canonical_batch(
    messages: Sequence[bytes], max_chunks: int
) -> tuple[np.ndarray, np.ndarray]:
    """The ONE batch-shape policy for device hashing: ≤DEVICE_BATCH
    messages pack into a `(ladder_size, max_chunks*1024)` uint8 array +
    int32 lengths. A fresh XLA shape costs seconds of tracing +
    executable load (worse on a tunneled chip) while a warm shape runs
    in ~40 ms, so every caller (cas_ids_begin, the validator) MUST pack
    through here. Pad rows hash 1 junk byte and get sliced off by the
    caller."""
    n = len(messages)
    if n > DEVICE_BATCH:
        raise ValueError(f"pack at most {DEVICE_BATCH} messages, got {n}")
    n_pad = next(s for s in BATCH_LADDER if s >= n)
    arr = np.zeros((n_pad, max_chunks * 1024), np.uint8)
    lens = np.ones((n_pad,), np.int32)
    for j, msg in enumerate(messages):
        arr[j, : len(msg)] = np.frombuffer(msg, np.uint8)
        lens[j] = len(msg)
    return arr, lens


def _bucket_for(msg_len: int) -> int:
    chunks = max(1, (msg_len + 1023) // 1024)
    for b in SMALL_BUCKETS:
        if chunks <= b:
            return b
    raise ValueError(f"message too large for small buckets: {msg_len}")


@dataclass
class _Bucket:
    chunks: int
    indices: list[int]
    messages: list[bytes]


def cas_ids_begin(messages: Sequence[bytes]) -> Callable[[], list[str]]:
    """Dispatch device hashing WITHOUT blocking: batches go to the
    accelerator asynchronously (JAX dispatch) and the returned finisher
    materializes the hex ids. Splitting dispatch from completion lets a
    pipeline queue window N+1's transfer while N is still in flight —
    on a tunneled chip that hides most of the per-call latency
    (SURVEY §7 hard part #2)."""
    buckets: dict[int, _Bucket] = {}
    for i, msg in enumerate(messages):
        c = LARGE_CHUNKS if len(msg) == LARGE_MSG_LEN else _bucket_for(len(msg))
        b = buckets.setdefault(c, _Bucket(c, [], []))
        b.indices.append(i)
        b.messages.append(msg)

    in_flight: list[tuple[_Bucket, int, Any]] = []
    for c, bucket in sorted(buckets.items()):
        for off in range(0, len(bucket.messages), DEVICE_BATCH):
            part = bucket.messages[off : off + DEVICE_BATCH]
            arr, lens = pack_canonical_batch(part, c)
            in_flight.append(
                (bucket, off, blake3_jax.hash_batch(arr, lens, max_chunks=c))
            )

    def finish() -> list[str]:
        out: list[str | None] = [None] * len(messages)
        for bucket, off, words in in_flight:
            part = bucket.indices[off : off + DEVICE_BATCH]
            for j, hx in enumerate(blake3_jax.words_to_hex(words, 16)[: len(part)]):
                out[part[j]] = hx
        return out  # type: ignore[return-value]

    return finish


def cas_ids_batched(messages: Sequence[bytes]) -> list[str]:
    """cas_ids for pre-assembled messages, batched per chunk-bucket and
    hashed on the accelerator. Order-preserving."""
    return cas_ids_begin(messages)()


def cas_ids_for_paths(paths: Iterable[tuple[str, int]]) -> list[str]:
    """Batched cas_ids for (path, size) pairs: sampled reads on host,
    BLAKE3 on device."""
    msgs = [read_message(p, s) for p, s in paths]
    return cas_ids_batched(msgs)


def cas_ids_native_cpu(messages: Sequence[bytes]) -> list[str] | None:
    """Threaded C BLAKE3 path; None when the native lib is unavailable."""
    from .. import native

    digests = native.blake3_many(list(messages))
    if digests is None:
        return None
    return [d[:8].hex() for d in digests]


def cas_ids(messages: Sequence[bytes], backend: str = "auto") -> list[str]:
    """Backend-selected batched cas_ids.

    - "tpu"/"device": JAX accelerator batch (falls back if jax is
      unusable only under "auto").
    - "cpu": native C (threaded), then pure Python.
    - "auto": device if a non-CPU jax backend is live, else native C,
      else Python — the same default-with-fallback contract the
      north-star requires.
    """
    if not messages:
        return []
    if backend in ("tpu", "device"):
        return cas_ids_batched(messages)
    if backend == "cpu":
        got = cas_ids_native_cpu(messages)
        if got is not None:
            return got
        return [StreamingBlake3().update(m).hexdigest()[:16] for m in messages]
    # auto
    if _device_available():
        try:
            return cas_ids_batched(messages)
        except Exception:  # noqa: BLE001 - fall back to host hashing
            pass
    return cas_ids(messages, "cpu")


_DEVICE_STATE: list[bool] | None = None


def _device_available() -> bool:
    global _DEVICE_STATE
    if _DEVICE_STATE is None:
        try:
            import jax

            _DEVICE_STATE = [jax.devices()[0].platform != "cpu"]
        except Exception:  # noqa: BLE001 - no usable accelerator
            _DEVICE_STATE = [False]
    return _DEVICE_STATE[0]
