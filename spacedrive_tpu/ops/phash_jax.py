"""Perceptual hashing + all-pairs similarity on the device.

BASELINE.json config 5 (full-library dedup) — no reference counterpart
(spacedrive dedups by exact cas_id only); this is the TPU-native
extension the survey's build plan calls for (SURVEY.md §7 compute
plane): batched 64-bit DCT pHash, then all-pairs Hamming distance as
one ±1 matmul on the MXU, shardable over a device mesh for
million-image libraries.

Math: image → grayscale 32×32 → 2-D DCT-II (two matmuls with the
orthonormal DCT basis — MXU work, not a specialized transform) → the
8×8 low-frequency block minus the DC term → threshold at the median →
64 bits. Similarity: with bits mapped to ±1, G = B @ B.T counts
(agreements − disagreements), so hamming = (64 − G) / 2 — an [N,64] ×
[64,N] matmul instead of N²·64 XOR/popcounts.
"""

from __future__ import annotations

import functools
from typing import Any

import numpy as np

HASH_BITS = 64
DCT_SIZE = 32
LOW_FREQ = 8


@functools.lru_cache(maxsize=4)
def _dct_basis(n: int = DCT_SIZE) -> np.ndarray:
    """Orthonormal DCT-II basis matrix [n, n]: X = C @ x @ C.T."""
    k = np.arange(n)[:, None]
    i = np.arange(n)[None, :]
    c = np.sqrt(2.0 / n) * np.cos(np.pi * (2 * i + 1) * k / (2 * n))
    c[0] /= np.sqrt(2.0)
    return c.astype(np.float32)


@functools.lru_cache(maxsize=1)
def _phash_fn():
    import jax
    import jax.numpy as jnp

    basis = jnp.asarray(_dct_basis())

    @jax.jit
    def phash_batch(gray: jax.Array) -> jax.Array:
        """float32[B, 32, 32] (0..1 grayscale) -> bool[B, 64]."""
        # 2-D DCT via two matmuls: C @ img @ C.T  (batched on the MXU)
        coeffs = jnp.einsum("ij,bjk,lk->bil", basis, gray, basis)
        low = coeffs[:, :LOW_FREQ, :LOW_FREQ].reshape(-1, LOW_FREQ * LOW_FREQ)
        ac = low.at[:, 0].set(0.0)  # drop the DC term
        med = jnp.median(ac[:, 1:], axis=1, keepdims=True)
        return ac > med

    return phash_batch


@functools.lru_cache(maxsize=1)
def _hamming_fn():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def hamming_all_pairs(bits: jax.Array) -> jax.Array:
        """bool[N, 64] -> uint8[N, N] pairwise Hamming distances."""
        pm = jnp.where(bits, 1.0, -1.0).astype(jnp.bfloat16)
        gram = (pm @ pm.T).astype(jnp.float32)  # agreements − disagreements
        return ((HASH_BITS - gram) * 0.5).astype(jnp.uint8)

    return hamming_all_pairs


def to_gray32(rgba: np.ndarray) -> np.ndarray:
    """HxWx4 uint8 → 32×32 float32 grayscale (area-mean downsample)."""
    from PIL import Image

    img = Image.fromarray(rgba[..., :3]).convert("L").resize(
        (DCT_SIZE, DCT_SIZE), Image.BILINEAR
    )
    return np.asarray(img, np.float32) / 255.0


def phash_batch(gray: np.ndarray) -> np.ndarray:
    """float32[B, 32, 32] → packed uint8[B, 8] hashes (big-endian bits)."""
    bits = np.asarray(_phash_fn()(gray))
    return np.packbits(bits, axis=1)


def phash_one(rgba: np.ndarray) -> bytes:
    return phash_batch(to_gray32(rgba)[None])[0].tobytes()


def unpack_hashes(hashes: list[bytes]) -> np.ndarray:
    """list of 8-byte hashes → bool[N, 64]."""
    arr = np.frombuffer(b"".join(hashes), np.uint8).reshape(-1, 8)
    return np.unpackbits(arr, axis=1).astype(bool)


def hamming_matrix(hashes: list[bytes]) -> np.ndarray:
    """All-pairs Hamming distances, device matmul (uint8[N, N])."""
    if not hashes:
        return np.zeros((0, 0), np.uint8)
    return np.asarray(_hamming_fn()(unpack_hashes(hashes)))


_sharded_fns: dict[tuple, Any] = {}


def _sharded_pairs_fn(mesh: Any):
    """One compiled program per mesh (jit caches key on the fn object,
    so the closure must be cached, not re-created per call)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    key = tuple(d.id for d in mesh.devices.flat)
    fn = _sharded_fns.get(key)
    if fn is None:
        fn = jax.jit(
            lambda b: (
                (
                    HASH_BITS
                    - (
                        (w := jnp.where(b, 1.0, -1.0).astype(jnp.bfloat16))
                        @ w.T
                    ).astype(jnp.float32)
                )
                * 0.5
            ).astype(jnp.uint8),
            in_shardings=NamedSharding(mesh, P("dp", None)),
            out_shardings=NamedSharding(mesh, P("dp", None)),
        )
        _sharded_fns[key] = fn
    return fn


def hamming_matrix_sharded(hashes: list[bytes], mesh: Any = None) -> np.ndarray:
    """Mesh-sharded all-pairs for large N: rows split over the 'dp'
    axis, each device holding the full ±1 matrix columns (64 wide —
    tiny), XLA inserting the all-gather (SURVEY §2.4 DP analogue)."""
    import jax
    from jax.sharding import Mesh

    if not hashes:
        return np.zeros((0, 0), np.uint8)
    if mesh is None:
        devices = jax.devices()
        mesh = Mesh(np.array(devices), ("dp",))
    bits = unpack_hashes(hashes)
    n = bits.shape[0]
    pad = (-n) % mesh.devices.size
    if pad:
        bits = np.concatenate([bits, np.zeros((pad, HASH_BITS), bool)])
    out = np.asarray(_sharded_pairs_fn(mesh)(bits))
    return out[:n, :n]


@functools.lru_cache(maxsize=1)
def _block_fn():
    import jax
    import jax.numpy as jnp

    weights = np.array([128, 64, 32, 16, 8, 4, 2, 1], np.uint8)

    @jax.jit
    def block(rows: jax.Array, all_bits: jax.Array,
              threshold: jax.Array) -> jax.Array:
        """bool[B, 64] × bool[N, 64] → packed match bitmap uint8[B, N/8].

        Thresholding happens ON DEVICE and only the packed bitmap comes
        back to the host — 8× less readback than distances, and the
        sparse-match common case decodes with one nonzero scan."""
        a = jnp.where(rows, 1.0, -1.0).astype(jnp.bfloat16)
        b = jnp.where(all_bits, 1.0, -1.0).astype(jnp.bfloat16)
        gram = (a @ b.T).astype(jnp.float32)
        dist = ((HASH_BITS - gram) * 0.5).astype(jnp.uint8)
        match = (dist <= threshold).reshape(rows.shape[0], -1, 8)
        return jnp.sum(
            match.astype(jnp.uint8) * jnp.asarray(weights), axis=-1
        ).astype(jnp.uint8)

    return block


PAIR_BLOCK = 4096


def near_pairs(hashes: list[bytes], threshold: int):
    """Yield (i, j) index pairs (i < j) within `threshold` bits, in
    fixed-size row blocks — device memory stays O(block × N), host
    transfers O(block × N / 8), and host decode touches only the
    nonzero bitmap bytes (sparse in the common case), so million-image
    libraries never materialize N²."""
    if not hashes:
        return
    bits = unpack_hashes(hashes)
    n = bits.shape[0]
    # one padded array serves as rows AND columns (PAIR_BLOCK is a
    # multiple of 8); phantom pad rows/cols are filtered on decode
    pad = (-n) % PAIR_BLOCK
    padded = (
        np.concatenate([bits, np.ones((pad, HASH_BITS), bool)]) if pad else bits
    )
    block = _block_fn()
    thr = np.uint8(max(0, min(HASH_BITS, threshold)))
    for off in range(0, n, PAIR_BLOCK):
        packed = np.asarray(
            block(padded[off : off + PAIR_BLOCK], padded, thr)
        )  # [B, P/8]
        brows, bbytes = np.nonzero(packed)  # only bytes with any match
        for r, byte_idx in zip(brows, bbytes):
            i = off + int(r)
            if i >= n:
                continue
            v = int(packed[r, byte_idx])
            base = int(byte_idx) * 8
            for bit in range(8):
                if v & (0x80 >> bit):
                    c = base + bit
                    if i < c < n:
                        yield i, c


def duplicate_groups(
    hashes: list[tuple[Any, bytes]], threshold: int = 8, **_compat: Any
) -> list[list[Any]]:
    """Group ids whose pHashes are within `threshold` bits (union-find
    over blockwise-thresholded pairs; never builds the N×N matrix)."""
    if not hashes:
        return []
    ids = [i for i, _h in hashes]
    n = len(ids)
    parent = list(range(n))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for r, c in near_pairs([h for _i, h in hashes], threshold):
        ra, rb = find(r), find(c)
        if ra != rb:
            parent[rb] = ra
    groups: dict[int, list[Any]] = {}
    for idx in range(n):
        groups.setdefault(find(idx), []).append(ids[idx])
    return [g for g in groups.values() if len(g) > 1]
