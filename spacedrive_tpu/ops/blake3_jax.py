"""Batched BLAKE3 on TPU via JAX/XLA.

Bit-exact with `blake3_ref` (golden-tested). Design, TPU-first:

- A batch of B messages, each padded to ``C * 1024`` bytes, hashes as
  ``N = B*C`` *independent* chunk lanes (BLAKE3 chunks chain from the IV
  with only a chunk counter, so every chunk of every file is parallel).
  One ``lax.scan`` of 16 steps walks the 64-byte blocks of all chunks at
  once; each step is one vectorized compression over ``[N]`` lanes —
  pure 32-bit VPU arithmetic, no data-dependent control flow.
- The chunk→root tree reduction runs level-by-level: level ``d`` pairs
  adjacent CVs with ONE batched parent compression over ``[B, C/2^d]``
  lanes. Odd leftovers per file are the binary digits of the chunk
  count; they are gathered per level and merged up the right spine at
  the end (masked, with per-file ROOT-flag selection). Total graph size
  stays ~O(log C) compressions, so XLA compiles fast for any bucket.
- Ragged lengths are handled with per-lane masks (block_len / flags /
  active selects); fixed ``C`` per compiled bucket keeps shapes static.

The reference hashes at most 56 KiB + 8 bytes per file for content
addressing (ref:core/src/object/cas.rs:10-21), i.e. C=57 is the hot
bucket; whole small files (≤100 KiB ⇒ C≤101) and full-file validation
(ref:core/src/object/validation/hash.rs) use larger buckets.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from .blake3_ref import CHUNK_END, CHUNK_START, IV, MSG_PERMUTATION, PARENT, ROOT

_U = jnp.uint32

CHUNK_LEN = 1024
BLOCK_LEN = 64


def _rotr(x: jax.Array, r: int) -> jax.Array:
    return (x >> _U(r)) | (x << _U(32 - r))


def _g(v: list[jax.Array], a: int, b: int, c: int, d: int, mx: jax.Array, my: jax.Array) -> None:
    v[a] = v[a] + v[b] + mx
    v[d] = _rotr(v[d] ^ v[a], 16)
    v[c] = v[c] + v[d]
    v[b] = _rotr(v[b] ^ v[c], 12)
    v[a] = v[a] + v[b] + my
    v[d] = _rotr(v[d] ^ v[a], 8)
    v[c] = v[c] + v[d]
    v[b] = _rotr(v[b] ^ v[c], 7)


import numpy as _np

_PERM = _np.array(MSG_PERMUTATION, _np.int32)  # host constant, safe under tracing


def _compress8(
    h: list[jax.Array],
    m: list[jax.Array],
    t_lo: jax.Array,
    block_len: jax.Array,
    flags: jax.Array,
) -> list[jax.Array]:
    """Vectorized compression; returns the 8 chaining-value words.

    Every argument is a (list of) uint32 array(s) with a common batch
    shape; 64-bit counters are split, t_hi pinned to 0 (4 TiB cap).
    The 7 rounds run as a `lax.scan` with the message schedule permuted
    by one gather per round — identical math to unrolling, but ~35×
    fewer HLO ops, which keeps XLA compile time sane for every bucket.
    """
    zeros = jnp.zeros_like(h[0])
    v = tuple(h) + (
        _U(IV[0]) + zeros, _U(IV[1]) + zeros, _U(IV[2]) + zeros, _U(IV[3]) + zeros,
        t_lo + zeros, zeros, block_len + zeros, flags + zeros,
    )
    m_arr = jnp.stack(m, axis=0)  # [16, ...]

    def round_body(carry, _):
        v, m = carry
        v = list(v)
        _g(v, 0, 4, 8, 12, m[0], m[1])
        _g(v, 1, 5, 9, 13, m[2], m[3])
        _g(v, 2, 6, 10, 14, m[4], m[5])
        _g(v, 3, 7, 11, 15, m[6], m[7])
        _g(v, 0, 5, 10, 15, m[8], m[9])
        _g(v, 1, 6, 11, 12, m[10], m[11])
        _g(v, 2, 7, 8, 13, m[12], m[13])
        _g(v, 3, 4, 9, 14, m[14], m[15])
        return (tuple(v), m[_PERM]), None

    (v, _), _ = jax.lax.scan(round_body, (v, m_arr), None, length=7)
    return [v[i] ^ v[i + 8] for i in range(8)]


def _parent_cvs(left: jax.Array, right: jax.Array, flags: jax.Array) -> jax.Array:
    """Batched parent-node compression. left/right: [..., 8] uint32."""
    h = [_U(IV[i]) + jnp.zeros_like(flags) for i in range(8)]
    m = [left[..., i] for i in range(8)] + [right[..., i] for i in range(8)]
    out = _compress8(h, m, jnp.zeros_like(flags), _U(BLOCK_LEN) + jnp.zeros_like(flags), flags)
    return jnp.stack(out, axis=-1)


def _as_words(msgs: jax.Array, max_chunks: int) -> jax.Array:
    """uint32[B, C*256] message words (natural LE order) from either a
    uint8[B, C*1024] byte array (device bitcast — the words ARE the
    little-endian byte stream) or an already-viewed uint32 array (the
    host path: `np.view(np.uint32)` is a zero-copy reinterpret, so
    numpy callers skip the device pass entirely)."""
    if msgs.dtype == jnp.uint32:
        return msgs
    b_dim = msgs.shape[0]
    return jax.lax.bitcast_convert_type(
        msgs.reshape(b_dim, max_chunks, 16, 16, 4), _U
    ).reshape(b_dim, max_chunks * 256)


def _chunk_cvs(words: jax.Array, lengths: jax.Array, max_chunks: int) -> tuple[jax.Array, jax.Array]:
    """All chunk chaining values.

    words: uint32[B, max_chunks*256] natural-order LE message words
    (see `_as_words`); lengths: int32[B].
    Returns (cvs: uint32[B, C, 8], n_chunks: int32[B]). Single-chunk
    files get their ROOT flag here.
    """
    b_dim, wpad = words.shape
    c_dim = max_chunks
    assert wpad == c_dim * 256

    lengths = lengths.astype(jnp.int32)
    n_chunks = jnp.maximum(1, (lengths + CHUNK_LEN - 1) // CHUNK_LEN)  # [B]

    n = b_dim * c_dim
    chunk_idx = jnp.repeat(jnp.arange(c_dim, dtype=jnp.int32)[None, :], b_dim, axis=0).reshape(n)
    len_n = jnp.repeat(lengths[:, None], c_dim, axis=1).reshape(n)
    nch_n = jnp.repeat(n_chunks[:, None], c_dim, axis=1).reshape(n)

    chunk_len = jnp.clip(len_n - chunk_idx * CHUNK_LEN, 0, CHUNK_LEN)  # [N]
    is_root_chunk = nch_n == 1  # single-chunk messages root at the chunk level
    t_lo = chunk_idx.astype(_U)

    mode = _pallas_mode_static.get("mode")
    if mode is not None:
        # Pallas kernel for the hot stage (ops/blake3_pallas.py): it
        # reads the natural [N, 256] layout (contiguous HBM — the
        # word-major transpose happens per-tile in VMEM) and derives
        # block_len/flags/active from the compact per-lane vectors, so
        # beyond the message words only [N]-sized arrays cross HBM
        from . import blake3_pallas

        h_fin8 = blake3_pallas.chunk_cvs(
            words.reshape(n, 256),
            chunk_len.astype(_U)[None, :],
            is_root_chunk.astype(_U)[None, :],
            t_lo[None, :],
            interpret=(mode == "interpret"),
        )  # [8, N]
        cvs = h_fin8.T.reshape(b_dim, c_dim, 8)
        return cvs, n_chunks

    # XLA fallback: word-major [blk, word, N] layout so each scan step
    # reads 16 contiguous [N] rows
    wm = words.reshape(b_dim, c_dim, 16, 16).transpose(2, 3, 0, 1).reshape(16, 16, n)

    n_blocks = jnp.maximum(1, (chunk_len + BLOCK_LEN - 1) // BLOCK_LEN)
    blk = jnp.arange(16, dtype=jnp.int32)[:, None]  # [16, 1]
    block_len = jnp.clip(chunk_len[None, :] - blk * BLOCK_LEN, 0, BLOCK_LEN)  # [16, N]
    active = blk < n_blocks[None, :]
    is_first = blk == 0
    is_last = blk == (n_blocks[None, :] - 1)
    flags = (
        jnp.where(is_first, _U(CHUNK_START), _U(0))
        | jnp.where(is_last, _U(CHUNK_END), _U(0))
        | jnp.where(is_last & is_root_chunk[None, :], _U(ROOT), _U(0))
    )

    h0 = [_U(IV[i]) + jnp.zeros((n,), _U) for i in range(8)]

    def step(h, xs):
        m_words, bl, fl, act = xs
        m = [m_words[k] for k in range(16)]
        out = _compress8(h, m, t_lo, bl.astype(_U), fl)
        h_new = [jnp.where(act, out[i], h[i]) for i in range(8)]
        return h_new, None

    h_fin, _ = jax.lax.scan(step, h0, (wm, block_len.astype(_U), flags, active))
    cvs = jnp.stack(h_fin, axis=-1).reshape(b_dim, c_dim, 8)
    return cvs, n_chunks


def _tree_reduce(cvs: jax.Array, n_chunks: jax.Array) -> jax.Array:
    """Reduce [B, C, 8] chunk CVs to [B, 8] root words.

    Level d pairs adjacent nodes; a file's leftover at level d exists
    iff bit d of its chunk count is set (binary-counter identity with
    the spec's incremental stack). The right spine then merges saved
    nodes lowest-level-first; the highest merge carries ROOT.
    """
    b_dim, c_dim, _ = cvs.shape
    if c_dim == 1:
        return cvs[:, 0, :]

    n_d = n_chunks  # nodes remaining at the current level, per file
    saved = []  # (bit_set[B], cv[B, 8]) per level, lowest first
    cur = cvs
    d = 0
    while cur.shape[1] > 1:
        width = cur.shape[1]
        bit = (n_d & 1) == 1
        idx = jnp.clip(n_d - 1, 0, width - 1)
        leftover = jnp.take_along_axis(cur, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0, :]
        saved.append((bit, leftover))

        pairs = width // 2
        left = cur[:, 0:2 * pairs:2, :]
        right = cur[:, 1:2 * pairs + 1:2, :]
        # The j==0 pair is the file's root iff exactly 2 nodes remain
        # here and no leftovers were saved below (n == 2 << d).
        is_root_pair = n_chunks == (2 << d)
        cols = jnp.arange(pairs, dtype=jnp.int32)
        flags = jnp.where(
            (cols[None, :] == 0) & is_root_pair[:, None], _U(PARENT | ROOT), _U(PARENT)
        )
        cur = _parent_cvs(left, right, flags)
        n_d = n_d >> 1
        d += 1
    # Top level: a single node remains.
    saved.append(((n_d & 1) == 1, cur[:, 0, :]))

    out = jnp.zeros((b_dim, 8), _U)
    started = jnp.zeros((b_dim,), bool)
    for d, (bit, cv) in enumerate(saved):
        # ROOT iff no higher bits remain above level d.
        is_top = (n_chunks >> (d + 1)) == 0
        flags = jnp.where(is_top, _U(PARENT | ROOT), _U(PARENT))
        merged = _parent_cvs(cv, out, flags)
        out = jnp.where(
            (bit & ~started)[:, None], cv,
            jnp.where((bit & started)[:, None], merged, out),
        )
        started = started | bit
    return out


# `_chunk_cvs` reads the chunk-stage backend from here at TRACE time;
# one jitted wrapper per mode keeps the jit cache from pinning a failed
# Pallas program onto the fallback path
_pallas_mode_static: dict = {"mode": None}


def _traced_hash_body(mode: str | None, msgs, lengths, max_chunks: int):
    """Chunk stage + tree reduce with the pallas-mode switch applied at
    trace time — the ONE hash body both the single-device and the
    shard_map per-device programs trace. (A second copy here is how the
    two paths would silently stop being bit-identical.)"""
    _pallas_mode_static["mode"] = mode  # runs at trace time
    try:
        cvs, n_chunks = _chunk_cvs(
            _as_words(msgs, max_chunks), lengths, max_chunks
        )
        return _tree_reduce(cvs, n_chunks)
    finally:
        _pallas_mode_static["mode"] = None


def _make_mode_impl(mode: str | None):
    @functools.partial(jax.jit, static_argnames=("max_chunks",))
    def impl(msgs, lengths, max_chunks):
        return _traced_hash_body(mode, msgs, lengths, max_chunks)

    return impl


_hash_batch_impl_modes = {
    mode: _make_mode_impl(mode) for mode in (None, "tpu", "interpret")
}

_pallas_disabled = [False]


def _resolve_pallas_mode() -> str | None:
    from . import blake3_pallas

    if _pallas_disabled[0]:
        return None
    return blake3_pallas.pallas_mode()


# --- multi-device dp dispatch ----------------------------------------------
#
# One dispatch feeds every chip: the batch dim is split over a flat
# `dp` mesh, each device runs the SAME chunk-stage (Pallas on TPU, XLA
# elsewhere) + tree reduce on its local rows under `shard_map` — the
# hash of a row never needs another row, so there are no collectives
# and per-device math is bit-identical to the single-device path.
# Compiled programs cache per (pallas mode, device set); shapes stay on
# the per-device warm ladder because cas.pack_canonical_batch pads the
# global batch to ladder-rung × device-count.

_sharded_impls: dict[tuple, Any] = {}


def _dp_mesh(devices):
    import numpy as np

    from jax.sharding import Mesh

    return Mesh(np.array(list(devices)), ("dp",))


def _sharded_impl(mode: str | None, devices, donate_input: bool = True):
    key = (mode, tuple(d.id for d in devices), donate_input)
    impl = _sharded_impls.get(key)
    if impl is None:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = _dp_mesh(devices)
        # donation frees the (large) message buffer for reuse the
        # moment the transfer is consumed; CPU backends don't implement
        # it and would only warn. Callers that re-hash a placed buffer
        # (bench's chained sweep) opt out.
        donate = (
            (0,) if donate_input and devices[0].platform != "cpu" else ()
        )

        @functools.partial(
            jax.jit, static_argnames=("max_chunks",), donate_argnums=donate
        )
        def impl(msgs, lengths, max_chunks):
            def body(m, l):
                return _traced_hash_body(mode, m, l, max_chunks)

            return shard_map(
                body, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P("dp")
            )(msgs, lengths)

        _sharded_impls[key] = impl
    return impl


def shard_put(arr, devices):
    """Place a batch on the flat `dp` mesh over `devices` (dim 0
    split, trailing dims replicated). A no-op when the array already
    has that sharding — bench pre-places its chained inputs through
    here so timed dispatches measure compute, not transfer."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(arr, NamedSharding(_dp_mesh(devices), P("dp")))


def _hash_batch_sharded(
    msgs, lengths, max_chunks: int, devices, donate_input: bool = True
) -> jax.Array:
    from ..telemetry import metrics as _tm

    _tm.SHARD_BATCH_ROWS.observe(msgs.shape[0] // len(devices), op="blake3")
    placed = shard_put(msgs, devices)
    placed_lens = shard_put(lengths, devices)
    mode = _resolve_pallas_mode()
    if mode is not None:
        try:
            return _sharded_impl(mode, devices, donate_input)(
                placed, placed_lens, max_chunks=max_chunks
            )
        except Exception:  # Mosaic/compile/runtime failure → XLA path
            import logging

            logging.getLogger(__name__).exception(
                "pallas blake3 failed; falling back to XLA permanently"
            )
            _pallas_disabled[0] = True
            # a runtime failure can land AFTER the placed buffer was
            # donated (deleted) to the failed program — re-place from
            # the caller's host array so the XLA retry runs in place
            placed = shard_put(msgs, devices)
            placed_lens = shard_put(lengths, devices)
    return _sharded_impl(None, devices, donate_input)(
        placed, placed_lens, max_chunks=max_chunks
    )


def hash_batch(msgs, lengths, max_chunks: int | None = None,
               devices=None, donate_input: bool = True) -> jax.Array:
    """Hash B messages. msgs: uint8[B, C*1024] (zero-padded) or its
    uint32[B, C*256] LE-word view; lengths: int32[B] actual byte
    counts. Returns uint32[B, 8] — the first 32 digest bytes as LE
    words (all the framework ever needs: cas_id is 8 bytes, validator
    checksum 32). Numpy byte arrays are reinterpreted as uint32 on the
    HOST (a zero-copy view — same transfer bytes, and the device skips
    the byte-pack pass entirely; see PROFILE.md). The chunk stage runs
    as a Pallas kernel on real TPUs (ops/blake3_pallas.py), XLA
    otherwise; any Pallas failure permanently falls back to the XLA
    path.

    `devices`: ≥2 devices shard the batch dim over a flat `dp` mesh
    (one dispatch feeds every chip; B must divide evenly — callers pad
    through cas.pack_canonical_batch). None/1 device keeps the classic
    single-device dispatch byte-for-byte."""
    import numpy as np

    from ..utils import faults as _faults

    spec = _faults.hit("device.blake3")
    if spec is not None:
        if spec.mode == "raise":
            raise _faults.InjectedFault("injected device failure (blake3)")
        if spec.mode == "xla":
            raise _faults.device_error("device.blake3")
        # "wrong_shape" falls through and truncates the result below —
        # exercising the caller-side digest-shape validation (cas)
    if not hasattr(msgs, "dtype"):  # lists / bytes-likes
        msgs = np.asarray(msgs, np.uint8)
    if isinstance(msgs, np.ndarray) and msgs.dtype == np.uint8:
        msgs = np.ascontiguousarray(msgs).view(np.uint32)
    if msgs.dtype not in (jnp.uint8, jnp.uint32):
        msgs = jnp.asarray(msgs, jnp.uint8)
    if max_chunks is None:
        words_per_chunk = 256 if msgs.dtype == jnp.uint32 else CHUNK_LEN
        max_chunks = msgs.shape[1] // words_per_chunk
    lengths = jnp.asarray(lengths, jnp.int32)
    out = None
    if devices is not None and len(devices) > 1:
        devices = list(devices)
        if msgs.shape[0] % len(devices):
            raise ValueError(
                f"batch of {msgs.shape[0]} rows does not divide over "
                f"{len(devices)} devices — pad through pack_canonical_batch"
            )
        out = _hash_batch_sharded(
            msgs, lengths, max_chunks, devices, donate_input
        )
    elif devices is not None and len(devices) == 1:
        # pin the single-device dispatch to THIS device (the ladder's
        # surviving chip) — committed inputs make jit execute there,
        # instead of on a default device that may be the dead one
        msgs = jax.device_put(msgs, devices[0])
        lengths = jax.device_put(lengths, devices[0])
    if out is None:
        mode = _resolve_pallas_mode()
        if mode is not None:
            try:
                out = _hash_batch_impl_modes[mode](
                    msgs, lengths, max_chunks=max_chunks
                )
            except Exception:  # Mosaic/compile/runtime failure → XLA path
                import logging

                logging.getLogger(__name__).exception(
                    "pallas blake3 failed; falling back to XLA permanently"
                )
                _pallas_disabled[0] = True
    if out is None:
        out = _hash_batch_impl_modes[None](msgs, lengths, max_chunks=max_chunks)
    if spec is not None and spec.mode == "wrong_shape":
        out = out[:, :4]
    return out


def words_to_digests(words, out_len: int = 32) -> list[bytes]:
    """Host-side: [B, 8] uint32 LE words -> digest bytes."""
    import numpy as np

    arr = np.asarray(words).astype("<u4")
    raw = arr.tobytes()
    stride = 32
    return [raw[i * stride:i * stride + out_len] for i in range(arr.shape[0])]


def words_to_hex(words, hex_chars: int = 64) -> list[str]:
    nbytes = (hex_chars + 1) // 2
    return [d.hex()[:hex_chars] for d in words_to_digests(words, nbytes)]
