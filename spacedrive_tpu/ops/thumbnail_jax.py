"""Batched thumbnail resize on TPU.

Parity targets (behavior, not implementation):
- ref:core/src/object/media/thumbnail/process.rs:394-461 — decode →
  `scale_dimensions` to TARGET_PX=262144 (≈512²) → Triangle-filter
  resize → EXIF-orientation correction → webp quality 30.
- ref:crates/images/src/lib.rs:89 — `scale_dimensions` keeps aspect and
  makes w*h ≈ target_px.
- ref:crates/ffmpeg/src/lib.rs:20-33 — video thumbs bound the max
  dimension to 256 instead.

TPU-first design. The reference resizes one image at a time on a CPU
pool. Here, decoded images are padded into a small set of canvas
*buckets* (squares + landscape halves; portraits transpose in — bounded
XLA compile shapes) and a whole batch is resized in
ONE device call per bucket via `jax.image.scale_and_translate`, vmapped
with *per-image* scale factors as traced arguments — so a single
compiled program handles arbitrary (h, w) inputs inside a bucket. XLA
lowers separable scale_and_translate to two weight matmuls per image,
which ride the MXU; `antialias=True` + `method="triangle"` is exactly
the reference's Triangle filter for downscale. Crop to the per-image
target dims, orientation flips, and webp encode stay on host (cheap,
variable-shape).
"""

from __future__ import annotations

import functools
import math
from typing import Sequence

import numpy as np

TARGET_PX = 262144  # ref:core/src/object/media/thumbnail/mod.rs:45
WEBP_QUALITY = 30  # ref:thumbnail/mod.rs:49
VIDEO_MAX_DIM = 256  # ref:thumbnail/process.rs:470

# Square input buckets (images are padded up to the next one). 4096 is
# the reference's max decodable dimension (ref:crates/images/src/consts.rs:33).
BUCKETS = (256, 512, 1024, 2048, 4096)
# Output canvas: covers aspect ratios up to 4:1 at TARGET_PX
# (tw = sqrt(262144·4) = 1024); more extreme aspects fall back to CPU.
OUT_CANVAS = 1024
MAX_ASPECT = (OUT_CANVAS * OUT_CANVAS) / TARGET_PX  # 4.0


def scale_dimensions(w: int, h: int, target_px: int = TARGET_PX) -> tuple[int, int]:
    """Aspect-preserving dims with w*h ≈ target_px; never upscales.

    Parity: ref:crates/images/src/lib.rs:89 (`scale_dimensions`).
    """
    if w * h <= target_px:
        return w, h
    ratio = math.sqrt(target_px / (w * h))
    return max(1, round(w * ratio)), max(1, round(h * ratio))


def video_dimensions(w: int, h: int, max_dim: int = VIDEO_MAX_DIM) -> tuple[int, int]:
    """Bound the max dimension (video thumbs, ref:sd_ffmpeg size=256)."""
    if max(w, h) <= max_dim:
        return w, h
    ratio = max_dim / max(w, h)
    return max(1, round(w * ratio)), max(1, round(h * ratio))


def bucket_for(h: int, w: int) -> tuple[int, int] | None:
    """Smallest canvas bucket holding (h, w) in its landscape
    orientation; None if over the cap.

    Buckets are (b, b) squares plus the (b/2, b) landscape half — most
    photos are 4:3/3:2/16:9, so the half canvas cuts the padded
    host→device transfer nearly 2× while keeping the compiled-shape
    count at 2 per ladder rung (the reason canvases exist at all:
    SURVEY §7 hard part 3, shape bucketing vs recompilation). Portrait
    images transpose into the landscape canvas on the host
    (resize_batch), so both orientations share one device call."""
    m = max(h, w)
    b = next((x for x in BUCKETS if m <= x), None)
    if b is None:
        return None
    half = b // 2
    # only the big rungs: for small canvases the halved payload saves
    # less than the ~5-20 s per-process executable load each extra
    # jitted shape costs on a tunneled chip
    if b >= 1024 and min(h, w) <= half:
        return (half, b)
    return (b, b)


def _one_resize(out_size: int):
    """Per-image resize body shared by the single-device and sharded
    bucket programs (identical math ⇒ identical pixels either way)."""
    import jax
    import jax.numpy as jnp

    def one(img, scale):
        out = jax.image.scale_and_translate(
            img.astype(jnp.float32),
            shape=(out_size, out_size, 4),
            spatial_dims=(0, 1),
            scale=scale,
            translation=jnp.zeros((2,), jnp.float32),
            method="triangle",
            antialias=True,
        )
        return jnp.clip(jnp.round(out), 0, 255).astype(jnp.uint8)

    return one


@functools.cache
def _resize_fn():
    """Lazily built jitted bucket-resize (jax imported on first use)."""
    import jax

    @functools.partial(jax.jit, static_argnames=("out_size",))
    def resize_bucket(canvases, scales, out_size: int):
        # [B, BH, BW, 4] uint8 RGBA canvases (square or landscape-half
        # buckets) + per-image [B, 2] (sy, sx) scales → [B, OUT, OUT, 4]
        # uint8, resized into the top-left
        # corner. One compiled program per (bucket, out) pair; the
        # per-image scale is a traced operand, so every (h, w) in the
        # bucket reuses it.
        return jax.vmap(_one_resize(out_size))(canvases, scales)

    return resize_bucket


_sharded_resize_fns: dict[tuple, object] = {}


def _resize_fn_sharded(devices):
    """dp-sharded bucket resize: the batch dim splits over a flat mesh,
    every device running the same vmapped per-image program on its
    local rows under shard_map — no collectives, so pixels stay
    bit-identical to the single-device call. One compiled program per
    (device set, bucket, out) like the single-device cache."""
    key = tuple(d.id for d in devices)
    fn = _sharded_resize_fns.get(key)
    if fn is None:
        import jax

        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        import numpy as _np

        mesh = Mesh(_np.array(list(devices)), ("dp",))

        @functools.partial(jax.jit, static_argnames=("out_size",))
        def resize_bucket_sharded(canvases, scales, out_size: int):
            def body(c, s):
                return jax.vmap(_one_resize(out_size))(c, s)

            return shard_map(
                body, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P("dp")
            )(canvases, scales)

        fn = (mesh, resize_bucket_sharded)
        _sharded_resize_fns[key] = fn
    return fn


def _auto_devices(n_rows: int):
    """Default sharding policy: all local devices once every device can
    hold at least one real image; smaller groups stay single-device
    (padding whole 4 MB canvases to feed idle chips is a net loss)."""
    from ..parallel.mesh import dispatch_devices

    devs = dispatch_devices()
    return devs if len(devs) > 1 and n_rows >= len(devs) else None


def _resize_bucket(
    images, targets, flip, idxs, bh: int, bw: int, out_size: int, devs
) -> np.ndarray:
    """Pack one bucket's canvases and run its device call; returns the
    [bpad, out, out, 4] uint8 result (validated — a device returning
    the wrong shape is an error the caller can demote on, never a
    silent corruption)."""
    from ..utils import faults as _faults

    n_dev = len(devs) if devs else 1
    # Pad the batch dim to the next power of two so compile count is
    # bounded at (buckets × log2 max-batch) programs, not one per
    # arbitrary group size; a sharded call also rounds up to the
    # device count so rows divide evenly over the mesh.
    bpad = 1 << max(0, (len(idxs) - 1).bit_length())
    if n_dev > 1:
        bpad = max(bpad, n_dev)
        bpad += (-bpad) % n_dev
    canv = np.zeros((bpad, bh, bw, 4), np.uint8)
    scales = np.ones((bpad, 2), np.float32)
    for j, i in enumerate(idxs):
        img = images[i]
        th, tw = targets[i]
        if flip[i]:
            img = np.transpose(img, (1, 0, 2))
            th, tw = tw, th
        h, w = img.shape[:2]
        # Edge-replicate into the padding so the antialias window
        # clamps at the image boundary instead of pulling in zeros
        # (the reference resampler clamps at edges too).
        canv[j, :h, :w] = img
        canv[j, h:, :w] = img[h - 1 : h, :]
        canv[j, :h, w:] = img[:, w - 1 : w]
        canv[j, h:, w:] = img[h - 1, w - 1]
        scales[j] = (th / h, tw / w)
    spec = _faults.hit("device.thumbnail")
    if spec is not None:
        if spec.mode == "raise":
            raise _faults.InjectedFault("injected device failure (thumbnail)")
        if spec.mode == "xla":
            raise _faults.device_error("device.thumbnail")
    if n_dev > 1:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..telemetry import metrics as _tm
        from .cas import shard_occupancy

        mesh, fn = _resize_fn_sharded(devs)
        _tm.SHARD_BATCH_ROWS.observe(bpad // n_dev, op="thumbnail")
        for frac in shard_occupancy(len(idxs), bpad, n_dev):
            _tm.DEVICE_DISPATCH_OCCUPANCY.observe(frac, op="thumbnail")
        sh = NamedSharding(mesh, P("dp"))
        out = np.asarray(fn(
            jax.device_put(canv, sh),
            jax.device_put(scales, sh),
            out_size=out_size,
        ))
    elif devs:
        # single surviving device: committed inputs pin the jit there,
        # not on a default device that may be the dead one
        import jax

        out = np.asarray(_resize_fn()(
            jax.device_put(canv, devs[0]), jax.device_put(scales, devs[0]),
            out_size=out_size,
        ))
    else:
        out = np.asarray(_resize_fn()(canv, scales, out_size=out_size))
    if spec is not None and spec.mode == "wrong_shape":
        out = out[:, : out_size // 2]
    if out.shape != (bpad, out_size, out_size, 4):
        raise ValueError(
            f"device resize returned shape {out.shape}, "
            f"expected {(bpad, out_size, out_size, 4)}"
        )
    return out


def resize_batch(
    images: Sequence[np.ndarray],
    targets: Sequence[tuple[int, int]],
    out_size: int = OUT_CANVAS,
    devices: Sequence | None = None,
) -> list[np.ndarray]:
    """Resize a batch of HxWx4 uint8 RGBA images to per-image (th, tw).

    Groups by input bucket, pads to the bucket canvas, runs one device
    call per bucket, crops on host. Returns resized uint8 arrays in
    input order. Images too large for any bucket or with th/tw beyond
    the output canvas must be filtered by the caller beforehand.

    With >1 local device (or an explicit `devices` list) the batch dim
    of each bucket call dp-shards over the chip mesh — one dispatch,
    every chip resizing its slice of the canvases.

    Auto dispatches ride the degradation ladder (parallel.mesh.LADDER):
    a failed bucket call demotes — full mesh → surviving subset →
    single default device (the per-image math is identical at every
    rung, so pixels never change) — and the bucket re-runs at the
    demoted rung instead of failing the chunk. Explicit `devices` stay
    strict and re-raise."""
    results: list[np.ndarray | None] = [None] * len(images)
    by_bucket: dict[tuple[int, int], list[int]] = {}
    flip: list[bool] = [False] * len(images)
    for i, img in enumerate(images):
        h, w = img.shape[:2]
        b = bucket_for(h, w)
        if b is None:
            raise ValueError(f"image {i} ({h}x{w}) exceeds max bucket")
        # portrait images ride the landscape half-canvas transposed
        # (cheap uint8 host transpose; un-transposed after the crop)
        flip[i] = b[0] < b[1] and h > w
        by_bucket.setdefault(b, []).append(i)

    for (bh, bw), idxs in by_bucket.items():
        if devices is not None:
            out = _resize_bucket(
                images, targets, flip, idxs, bh, bw, out_size, list(devices)
            )
        else:
            from ..parallel import mesh as _mesh

            # bounded: one attempt per rung plus one half-open probe —
            # a tiny reset_timeout must not oscillate probe/demote forever
            for attempt in range(4):
                devs, level = _mesh.ladder_devices()
                if (
                    level < _mesh.LEVEL_HOST
                    and len(devs) > 1 and len(idxs) >= len(devs)
                ):
                    use = devs
                elif level == _mesh.LEVEL_SUBSET and devs:
                    # unsharded at the subset rung: still pin to a
                    # surviving chip, never the (possibly dead) default
                    use = devs[:1]
                else:
                    use = None
                try:
                    out = _resize_bucket(
                        images, targets, flip, idxs, bh, bw, out_size, use
                    )
                except Exception as exc:  # noqa: BLE001 - demote & retry
                    # always settle the ladder bookkeeping (a probe left
                    # unreported would block re-arming), THEN decide
                    # whether anything is left to demote to
                    _mesh.LADDER.record_failure(level, devs)
                    if level >= _mesh.LEVEL_HOST or attempt == 3:
                        raise
                    from ..telemetry import events as _events

                    _events.record_error("thumbnail.ladder", exc)
                    continue
                if use is not None:
                    _mesh.LADDER.record_success(level)
                else:
                    # ran on the single default device — says nothing
                    # about the rung's chips; release a held probe
                    _mesh.LADDER.probe_inconclusive(level)
                break
        for j, i in enumerate(idxs):
            th, tw = targets[i]
            if flip[i]:
                results[i] = np.transpose(out[j, :tw, :th], (1, 0, 2))
            else:
                results[i] = out[j, :th, :tw]
    return results  # type: ignore[return-value]


def apply_orientation(arr: np.ndarray, orientation: int) -> np.ndarray:
    """EXIF orientation 1-8 → corrected array (host, zero-copy views
    where possible). Parity: ref:crates/media-metadata/src/image/
    orientation.rs applied post-resize (process.rs:421-428)."""
    if orientation == 2:
        return arr[:, ::-1]
    if orientation == 3:
        return arr[::-1, ::-1]
    if orientation == 4:
        return arr[::-1]
    if orientation == 5:
        return np.transpose(arr, (1, 0, 2))
    if orientation == 6:
        return np.transpose(arr[::-1], (1, 0, 2))
    if orientation == 7:
        return np.transpose(arr[::-1, ::-1], (1, 0, 2))
    if orientation == 8:
        return np.transpose(arr[:, ::-1], (1, 0, 2))
    return arr
