"""Pure-Python BLAKE3 (default hash mode) — the golden reference.

Written from the public BLAKE3 specification. This is the correctness
anchor for the batched JAX/Pallas implementations and the host-side
fallback for odd-sized inputs. The reference framework consumes BLAKE3
for content addressing (ref:core/src/object/cas.rs:3) and full-file
validation (ref:core/src/object/validation/hash.rs).

Only the plain hash mode is implemented (no keyed hash / derive-key):
that is all the indexing pipeline uses.
"""

from __future__ import annotations

import struct

MASK32 = 0xFFFFFFFF
IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)
MSG_PERMUTATION = (2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8)

CHUNK_START = 1 << 0
CHUNK_END = 1 << 1
PARENT = 1 << 2
ROOT = 1 << 3

BLOCK_LEN = 64
CHUNK_LEN = 1024


def _g(v: list[int], a: int, b: int, c: int, d: int, mx: int, my: int) -> None:
    v[a] = (v[a] + v[b] + mx) & MASK32
    v[d] ^= v[a]
    v[d] = ((v[d] >> 16) | (v[d] << 16)) & MASK32
    v[c] = (v[c] + v[d]) & MASK32
    v[b] ^= v[c]
    v[b] = ((v[b] >> 12) | (v[b] << 20)) & MASK32
    v[a] = (v[a] + v[b] + my) & MASK32
    v[d] ^= v[a]
    v[d] = ((v[d] >> 8) | (v[d] << 24)) & MASK32
    v[c] = (v[c] + v[d]) & MASK32
    v[b] ^= v[c]
    v[b] = ((v[b] >> 7) | (v[b] << 25)) & MASK32


def _round(v: list[int], m: list[int]) -> None:
    # Columns.
    _g(v, 0, 4, 8, 12, m[0], m[1])
    _g(v, 1, 5, 9, 13, m[2], m[3])
    _g(v, 2, 6, 10, 14, m[4], m[5])
    _g(v, 3, 7, 11, 15, m[6], m[7])
    # Diagonals.
    _g(v, 0, 5, 10, 15, m[8], m[9])
    _g(v, 1, 6, 11, 12, m[10], m[11])
    _g(v, 2, 7, 8, 13, m[12], m[13])
    _g(v, 3, 4, 9, 14, m[14], m[15])


def compress(
    chaining_value: tuple[int, ...] | list[int],
    block_words: list[int],
    counter: int,
    block_len: int,
    flags: int,
) -> list[int]:
    """The BLAKE3 compression function. Returns all 16 output words."""
    v = [
        chaining_value[0], chaining_value[1], chaining_value[2], chaining_value[3],
        chaining_value[4], chaining_value[5], chaining_value[6], chaining_value[7],
        IV[0], IV[1], IV[2], IV[3],
        counter & MASK32, (counter >> 32) & MASK32, block_len, flags,
    ]
    m = list(block_words)
    for r in range(7):
        _round(v, m)
        if r < 6:
            m = [m[MSG_PERMUTATION[i]] for i in range(16)]
    for i in range(8):
        v[i] ^= v[i + 8]
        v[i + 8] ^= chaining_value[i]
    return v


def _words_of_block(block: bytes) -> list[int]:
    padded = block + b"\x00" * (BLOCK_LEN - len(block))
    return list(struct.unpack("<16I", padded))


def _chunk_cv(chunk: bytes, counter: int, is_root: bool) -> list[int]:
    """Chaining value (or root words) of one ≤1024-byte chunk."""
    h = list(IV)
    n_blocks = max(1, (len(chunk) + BLOCK_LEN - 1) // BLOCK_LEN)
    for b in range(n_blocks):
        block = chunk[b * BLOCK_LEN:(b + 1) * BLOCK_LEN]
        flags = 0
        if b == 0:
            flags |= CHUNK_START
        if b == n_blocks - 1:
            flags |= CHUNK_END
            if is_root:
                flags |= ROOT
        out = compress(h, _words_of_block(block), counter, len(block), flags)
        h = out[:8] if b < n_blocks - 1 else out
    return h


def _parent(left_cv: list[int], right_cv: list[int], is_root: bool) -> list[int]:
    flags = PARENT | (ROOT if is_root else 0)
    return compress(IV, list(left_cv[:8]) + list(right_cv[:8]), 0, BLOCK_LEN, flags)


def blake3(data: bytes, out_len: int = 32) -> bytes:
    """One-shot BLAKE3 hash (≤64 bytes of output, enough for 64-hex digests)."""
    assert out_len <= 64, "extended XOF output not implemented"
    n_chunks = max(1, (len(data) + CHUNK_LEN - 1) // CHUNK_LEN)
    if n_chunks == 1:
        out = _chunk_cv(data, 0, is_root=True)
        return struct.pack("<16I", *out)[:out_len]

    # Binary-counter chunk stack (spec's incremental tree algorithm): the
    # last chunk is held out; slot d holds the CV of a complete 2^d-chunk
    # subtree.
    stack: list[list[int] | None] = [None] * 64
    for i in range(n_chunks - 1):
        chunk = data[i * CHUNK_LEN:(i + 1) * CHUNK_LEN]
        cv = _chunk_cv(chunk, i, is_root=False)[:8]
        count = i + 1
        d = 0
        while count & 1 == 0:
            cv = _parent(stack[d], cv, is_root=False)[:8]  # type: ignore[arg-type]
            stack[d] = None
            count >>= 1
            d += 1
        stack[d] = cv

    last = data[(n_chunks - 1) * CHUNK_LEN:]
    output = _chunk_cv(last, n_chunks - 1, is_root=False)[:8]
    remaining = n_chunks - 1
    highest = remaining.bit_length() - 1
    for d in range(64):
        if (remaining >> d) & 1:
            out16 = _parent(stack[d], output, is_root=(d == highest))  # type: ignore[arg-type]
            output = out16[:8]
    return struct.pack("<16I", *out16)[:out_len]  # noqa: F821 - n_chunks>1 guarantees a parent


def blake3_hex(data: bytes, out_len: int = 32) -> str:
    return blake3(data, out_len).hex()


# --- chunk-level tree API (incremental / dirty-range rehash) ---------------
#
# BLAKE3 is a Merkle tree over 1024-byte chunks: the root digest is a
# pure function of the per-chunk chaining values. Exposing the chunk CV
# and the CV→root merge lets a caller cache CVs per chunk and, when a
# file changes in place, recompute only the *dirty* chunks' CVs before
# re-merging — bit-identical to a full rehash (ops/cas.py dirty-range).


def chunk_chaining_value(chunk: bytes, counter: int) -> bytes:
    """Interior (non-root) chaining value of chunk number `counter` —
    32 bytes (8 LE u32 words). Only valid for multi-chunk messages: a
    single-chunk message compresses with the ROOT flag instead."""
    return struct.pack("<8I", *_chunk_cv(chunk, counter, is_root=False)[:8])


def parent_chaining_value(left: bytes, right: bytes) -> bytes:
    """Interior parent CV over two packed 32-byte child CVs."""
    out = _parent(
        list(struct.unpack("<8I", left)), list(struct.unpack("<8I", right)),
        is_root=False,
    )
    return struct.pack("<8I", *out[:8])


def root_digest_from_pair(left: bytes, right: bytes, out_len: int = 32) -> bytes:
    """Root digest when the whole tree reduces to two subtree CVs."""
    assert out_len <= 64, "extended XOF output not implemented"
    out = _parent(
        list(struct.unpack("<8I", left)), list(struct.unpack("<8I", right)),
        is_root=True,
    )
    return struct.pack("<16I", *out)[:out_len]


class StreamingBlake3:
    """Incremental hasher for unbounded inputs (validator full-file hash,
    ref:core/src/object/validation/hash.rs:9-25 reads 1MiB blocks).

    Bounded memory over unbounded file size: holds ≤1 chunk + log2 stack.
    """

    def __init__(self) -> None:
        self._stack: list[list[int] | None] = [None] * 64
        self._pending = b""
        self._count = 0  # chunks fully absorbed into the stack

    def update(self, data: bytes) -> "StreamingBlake3":
        # Walk an offset over a memoryview: no quadratic re-slicing of
        # the buffer on large updates.
        buf = self._pending + data if self._pending else data
        mv = memoryview(buf)
        off = 0
        # Keep at least one byte beyond a chunk boundary pending so the
        # final chunk is always held out for the root.
        while len(buf) - off > CHUNK_LEN:
            chunk = bytes(mv[off:off + CHUNK_LEN])
            off += CHUNK_LEN
            cv = _chunk_cv(chunk, self._count, is_root=False)[:8]
            self._count += 1
            count = self._count
            d = 0
            while count & 1 == 0:
                cv = _parent(self._stack[d], cv, is_root=False)[:8]  # type: ignore[arg-type]
                self._stack[d] = None
                count >>= 1
                d += 1
            self._stack[d] = cv
        self._pending = bytes(mv[off:])
        return self

    def digest(self, out_len: int = 32) -> bytes:
        if self._count == 0:
            out = _chunk_cv(self._pending, 0, is_root=True)
            return struct.pack("<16I", *out)[:out_len]
        output = _chunk_cv(self._pending, self._count, is_root=False)[:8]
        highest = self._count.bit_length() - 1
        out16: list[int] = []
        for d in range(64):
            if (self._count >> d) & 1:
                out16 = _parent(self._stack[d], output, is_root=(d == highest))  # type: ignore[arg-type]
                output = out16[:8]
        return struct.pack("<16I", *out16)[:out_len]

    def hexdigest(self, out_len: int = 32) -> str:
        return self.digest(out_len).hex()
