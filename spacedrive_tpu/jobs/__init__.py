"""Stateful job layer — long-running, persistent, resumable pipelines.

Parity: the reference's production job system (ref:core/src/job/):
`StatefulJob` (init → step queue → execute_step loop → finalize),
msgpack-serialized `JobState` persisted to the `job` table for
pause/resume and crash recovery, report/progress events, `queue_next`
chaining, and a manager with ingest/dispatch/pause/resume/cancel/
cold_resume.

TPU-first re-design: steps are *batch descriptors*; the generic runner
drives them through the task system so step execution interleaves with
other work and can suspend at batch boundaries (the only preemption
points a TPU dispatch allows).
"""

from .job import JobContext, JobError, StatefulJob, StepResult
from .report import JobReport, JobStatus, JobProgressEvent
from .manager import JobManager, JobBuilder

__all__ = [
    "JobContext",
    "JobError",
    "StatefulJob",
    "StepResult",
    "JobReport",
    "JobStatus",
    "JobProgressEvent",
    "JobManager",
    "JobBuilder",
]
