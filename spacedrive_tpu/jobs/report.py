"""JobReport + JobStatus + progress events.

Parity: ref:core/src/job/report.rs (status ints are DB/wire-stable,
:263-271) and the JobProgressEvent shape streamed to the frontend
(ref:core/src/job/worker.rs:39-50).
"""

from __future__ import annotations

import datetime as _dt
import enum
import uuid
from dataclasses import dataclass, field
from typing import Any

from ..db.database import LibraryDb, now_iso


class JobStatus(enum.IntEnum):
    QUEUED = 0
    RUNNING = 1
    COMPLETED = 2
    CANCELED = 3
    FAILED = 4
    PAUSED = 5
    COMPLETED_WITH_ERRORS = 6

    @property
    def is_finished(self) -> bool:
        return self in (
            JobStatus.COMPLETED,
            JobStatus.CANCELED,
            JobStatus.PAUSED,
            JobStatus.FAILED,
            JobStatus.COMPLETED_WITH_ERRORS,
        )


@dataclass
class JobProgressEvent:
    """Streamed on every progress change (ref:core/src/job/worker.rs:39-50)."""

    id: uuid.UUID
    library_id: uuid.UUID | None
    name: str
    task_count: int
    completed_task_count: int
    phase: str
    message: str
    estimated_completion: str  # ISO timestamp


@dataclass
class JobReport:
    id: uuid.UUID
    name: str
    action: str | None = None
    data: bytes | None = None          # serialized resume state
    metadata: dict[str, Any] = field(default_factory=dict)
    errors_text: list[str] = field(default_factory=list)
    created_at: str | None = None
    started_at: str | None = None
    completed_at: str | None = None
    parent_id: uuid.UUID | None = None
    status: JobStatus = JobStatus.QUEUED
    task_count: int = 0
    completed_task_count: int = 0
    phase: str = ""
    message: str = ""
    estimated_completion: str | None = None

    # --- persistence (job table, ref:core/prisma/schema.prisma:401-430) ---

    def create(self, db: LibraryDb) -> None:
        self.created_at = self.created_at or now_iso()
        db.insert(
            "job",
            id=self.id.bytes,
            name=self.name,
            action=self.action,
            status=int(self.status),
            errors_text="\n\n".join(self.errors_text) or None,
            data=self.data,
            metadata=_pack_meta(self.metadata),
            parent_id=self.parent_id.bytes if self.parent_id else None,
            task_count=self.task_count,
            completed_task_count=self.completed_task_count,
            date_estimated_completion=self.estimated_completion,
            date_created=self.created_at,
            date_started=self.started_at,
            date_completed=self.completed_at,
        )

    def update(self, db: LibraryDb) -> None:
        db.update(
            "job",
            {"id": self.id.bytes},
            status=int(self.status),
            errors_text="\n\n".join(self.errors_text) or None,
            data=self.data,
            metadata=_pack_meta(self.metadata),
            task_count=self.task_count,
            completed_task_count=self.completed_task_count,
            date_estimated_completion=self.estimated_completion,
            date_started=self.started_at,
            date_completed=self.completed_at,
        )

    @classmethod
    def from_row(cls, row: dict[str, Any]) -> "JobReport":
        return cls(
            id=uuid.UUID(bytes=row["id"]),
            name=row["name"] or "",
            action=row["action"],
            data=row["data"],
            metadata=_unpack_meta(row["metadata"]),
            errors_text=(row["errors_text"] or "").split("\n\n") if row["errors_text"] else [],
            created_at=row["date_created"],
            started_at=row["date_started"],
            completed_at=row["date_completed"],
            parent_id=uuid.UUID(bytes=row["parent_id"]) if row["parent_id"] else None,
            status=JobStatus(row["status"] if row["status"] is not None else 0),
            task_count=row["task_count"] or 0,
            completed_task_count=row["completed_task_count"] or 0,
            estimated_completion=row["date_estimated_completion"],
        )

    def progress_event(self, library_id: uuid.UUID | None = None) -> JobProgressEvent:
        eta = self.estimated_completion or now_iso()
        return JobProgressEvent(
            id=self.id,
            library_id=library_id,
            name=self.name,
            task_count=self.task_count,
            completed_task_count=self.completed_task_count,
            phase=self.phase,
            message=self.message,
            estimated_completion=eta,
        )

    def estimate_completion(self, elapsed_seconds: float) -> None:
        """ETA by linear extrapolation over completed tasks."""
        remaining = max(0, self.task_count - self.completed_task_count)
        if self.completed_task_count > 0 and remaining:
            per = elapsed_seconds / self.completed_task_count
            eta = _dt.datetime.now(_dt.timezone.utc) + _dt.timedelta(seconds=per * remaining)
            self.estimated_completion = eta.isoformat(timespec="milliseconds")


def _pack_meta(meta: dict[str, Any]) -> bytes | None:
    if not meta:
        return None
    import msgpack

    return msgpack.packb(meta, use_bin_type=True)


def _unpack_meta(raw: bytes | None) -> dict[str, Any]:
    if not raw:
        return {}
    import msgpack

    return msgpack.unpackb(raw, raw=False)
