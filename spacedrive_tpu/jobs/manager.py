"""JobManager — ingest/dispatch/pause/resume/cancel/cold_resume.

Parity: ref:core/src/job/manager.rs (Jobs::{ingest,dispatch,pause,
resume,cancel,cold_resume}) + JobBuilder chaining
(ref:core/src/location/mod.rs:455-472 spawns Indexer → FileIdentifier →
MediaProcessor chains). Reports persist in the library's `job` table;
progress streams over the library event bus as JobProgressEvent.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any

from ..db.database import now_iso
from ..tasks import TaskStatus, TaskSystem
from ..telemetry import trace as _trace
from ..telemetry.events import JOB_EVENTS
from ..utils.tasks import supervise
from .job import JobContext, JobRunnerTask, StatefulJob, status_for_result
from .report import JobProgressEvent, JobReport, JobStatus

logger = logging.getLogger(__name__)

# name -> class, for cold resume deserialization; populated by
# register_job (each job module registers itself at import).
JOB_REGISTRY: dict[str, type[StatefulJob]] = {}


def register_job(cls: type[StatefulJob]) -> type[StatefulJob]:
    JOB_REGISTRY[cls.NAME] = cls
    return cls


class JobBuilder:
    """JobBuilder(init_job).queue_next(other).spawn(manager, library)."""

    def __init__(self, job: StatefulJob):
        self.job = job

    def queue_next(self, job: StatefulJob) -> "JobBuilder":
        tail = self.job
        while tail.next_jobs:
            tail = tail.next_jobs[-1]
        tail.queue_next(job)
        return self

    async def spawn(self, manager: "JobManager", library: Any) -> uuid.UUID:
        await manager.ingest(self.job, library)
        return self.job.id


class JobManager:
    def __init__(self, task_system: TaskSystem | None = None):
        self.system = task_system or TaskSystem()
        self._active: dict[uuid.UUID, tuple[Any, JobContext]] = {}  # job id -> (handle, ctx)
        self._supervisors: set = set()
        self._supervisor_by_job: dict[uuid.UUID, Any] = {}

    # --- ingest & drive (ref:manager.rs:101-178) ---

    async def ingest(self, job: StatefulJob, library: Any, parent: JobReport | None = None) -> None:
        # the job's trace: the caller's (an rspc mutation, a watcher
        # flush, a parent job) when one is active, else a fresh root —
        # the whole chain and every batch it coalesces runs under it
        if job.trace_ctx is None:
            job.trace_ctx = _trace.current() or _trace.new_context()
        report = JobReport(
            id=job.id,
            name=job.NAME,
            action=self._action_string(job),
            parent_id=parent.id if parent else None,
            status=JobStatus.QUEUED,
        )
        report.create(library.db)
        JOB_EVENTS.emit("queued", job=job.NAME, id=str(job.id))
        # pass boundary marker: attribution's "last pass" resolves
        # through these instead of guessing from the span ring
        from ..telemetry import attrib as _attrib

        _attrib.mark_pass(job.NAME, job.trace_ctx.trace_id, "started")
        self._dispatch(job, library, report)

    def _dispatch(self, job: StatefulJob, library: Any, report: JobReport) -> None:
        ctx = JobContext(library, report, manager=self)
        report.status = JobStatus.RUNNING
        report.started_at = report.started_at or now_iso()
        report.update(library.db)
        JOB_EVENTS.emit("running", job=job.NAME, id=str(job.id))
        runner = JobRunnerTask(job, ctx)
        # dispatch under the job's context so the task-system boundary
        # carries it (cold resume re-enters here with the deserialized
        # context and the resumed job continues its original trace)
        with _trace.use(job.trace_ctx):
            handle = self.system.dispatch(runner)
        self._active[job.id] = (handle, ctx)
        # keep a strong ref: the loop only weak-refs tasks and a GC'd
        # supervisor would drop final status writes + job chaining
        sup = supervise(
            asyncio.ensure_future(self._supervise(job, library, handle, ctx)),
            self._supervisors, logger, f"job supervisor ({report.name})",
        )
        self._supervisor_by_job[job.id] = sup
        sup.add_done_callback(lambda _t, jid=job.id: self._supervisor_by_job.pop(jid, None))

    async def _supervise(self, job: StatefulJob, library: Any, handle, ctx: JobContext) -> None:
        result = await handle.wait()
        # close the job's final phase so sd_job_phase_seconds accounts
        # the full wall time, not just up to the last transition
        ctx._close_phase()
        report = ctx.report
        report.status = status_for_result(result.status, bool(job.errors))
        if result.status == TaskStatus.ERROR:
            if isinstance(result.error, asyncio.CancelledError):
                # a cancellation surfacing as ERROR (e.g. re-raised from
                # inside the job body during node shutdown) is not a
                # crash — no spurious failed transition, no error toast
                report.status = JobStatus.CANCELED
            else:
                report.errors_text.append(str(result.error))
        if report.status == JobStatus.PAUSED:
            report.data = job.serialize_state()  # resume state
        else:
            report.data = None
        if report.status.is_finished and report.status != JobStatus.PAUSED:
            report.completed_at = now_iso()
        if isinstance(result.output, dict):
            report.metadata.update(result.output)
        report.update(library.db)
        self._emit_progress(ctx)
        self._active.pop(job.id, None)
        logger.info("job %s -> %s", job.NAME, report.status.name)
        JOB_EVENTS.emit(
            "settled", job=job.NAME, id=str(job.id),
            status=report.status.name,
            errors=len(report.errors_text),
        )
        if job.trace_ctx is not None:
            from ..telemetry import attrib as _attrib

            _attrib.mark_pass(
                job.NAME, job.trace_ctx.trace_id, "settled",
                status=report.status.name,
            )

        self._notify_outcome(job, library, report)

        # chain: spawn queued next jobs on success (ref:mod.rs:213-231)
        if report.status in (JobStatus.COMPLETED, JobStatus.COMPLETED_WITH_ERRORS):
            self._invalidate_on_complete(job, library)
            for next_job in job.next_jobs:
                # chained jobs continue the originating trace: the
                # indexer → identifier → media chain is ONE user action
                if next_job.trace_ctx is None:
                    next_job.trace_ctx = job.trace_ctx
                await self.ingest(next_job, library, parent=report)

    @staticmethod
    def _notify_outcome(job: StatefulJob, library: Any, report: JobReport) -> None:
        """Persisted library notification for job outcomes the user
        should see (ref:lib.rs:267-278 emit_notification): failures,
        and the completion of a chain's last job. NOT notified:
        user-initiated cancels (the user already knows), intermediate
        chain stages (one toast per chain, not per stage), and jobs
        flagged `notify_outcome=False` (watcher-triggered rescans fire
        on every filesystem flush — toasting those would spam and grow
        the notification table without bound)."""
        if not getattr(job, "notify_outcome", True):
            return
        node = getattr(library, "node", None)
        if node is None or getattr(node, "notifications", None) is None:
            return
        failed = report.status == JobStatus.FAILED
        partial = report.status == JobStatus.COMPLETED_WITH_ERRORS
        # chain terminus: the last job of a chain (no queued successors)
        chain_done = (
            not job.next_jobs
            and report.status in (JobStatus.COMPLETED,
                                  JobStatus.COMPLETED_WITH_ERRORS)
        )
        if not (failed or chain_done):
            return
        message = None
        if failed and report.errors_text:
            message = report.errors_text[-1][:200]
        elif partial:
            n = len(report.errors_text) or len(job.errors)
            message = f"{n or 'some'} items failed"
            if report.errors_text:
                message += f"; last: {report.errors_text[-1][:150]}"
        try:
            node.notifications.emit_library(library.db, str(library.id), {
                "kind": "error" if failed else ("warning" if partial else "ok"),
                "job": job.NAME,
                "status": report.status.name,
                "message": message,
            })
        except Exception:  # noqa: BLE001 - notifying must never kill a job
            logger.debug("job outcome notification failed", exc_info=True)

    @staticmethod
    def _invalidate_on_complete(job: StatefulJob, library: Any) -> None:
        """Completed jobs invalidate the queries they changed so live
        frontends refetch (the reference's jobs call invalidate_query!
        in finalize, e.g. ref:indexer/indexer_job.rs); keys come from
        the job class's INVALIDATES tuple."""
        keys = getattr(job, "INVALIDATES", ())
        node = getattr(library, "node", None)
        if node is None or getattr(node, "event_bus", None) is None or not keys:
            return
        from ..api.invalidate import invalidate_query

        for key in keys:
            invalidate_query(node, key, library)

    # --- control (ref:manager.rs:222-267) ---

    async def pause(self, job_id: uuid.UUID) -> None:
        """Interrupt at the next step boundary and persist the
        serialized resume state (the reference serializes JobState on
        pause, ref:core/src/job/worker.rs pause handling)."""
        entry = self._active.get(job_id)
        if entry is None:
            return
        handle, ctx = entry
        await handle.pause()
        # job may complete before reaching a pause boundary — wait on
        # whichever happens first
        paused = asyncio.ensure_future(handle.wait_paused())
        done = asyncio.ensure_future(handle.wait())
        await asyncio.wait({paused, done}, return_when=asyncio.FIRST_COMPLETED)
        done.cancel()
        if not paused.done():
            paused.cancel()
            return  # finished instead of pausing; supervisor persists it
        runner = handle.task
        report = ctx.report
        report.status = JobStatus.PAUSED
        report.data = runner.job.serialize_state()
        report.update(ctx.library.db)
        JOB_EVENTS.emit("paused", job=report.name, id=str(job_id))
        self._emit_progress(ctx)

    async def resume(self, job_id: uuid.UUID) -> None:
        entry = self._active.get(job_id)
        if entry:
            await entry[0].resume()
            report = entry[1].report
            report.status = JobStatus.RUNNING
            report.update(entry[1].library.db)
            JOB_EVENTS.emit("resumed", job=report.name, id=str(job_id))

    async def cancel(self, job_id: uuid.UUID) -> None:
        entry = self._active.get(job_id)
        if entry:
            await entry[0].cancel()

    async def wait(self, job_id: uuid.UUID) -> JobReport | None:
        entry = self._active.get(job_id)
        if entry is None:
            return None
        await entry[0].wait()
        # the supervisor writes the final status after the task settles
        sup = self._supervisor_by_job.get(job_id)
        if sup is not None:
            await asyncio.shield(sup)
        return entry[1].report

    async def wait_idle(self) -> None:
        """Wait until no job is actively running (paused/parked jobs
        don't count — they only finish after resume)."""
        while True:
            waiters = [
                asyncio.ensure_future(h.wait())
                for jid, (h, _) in self._active.items()
                if h.task.id not in self.system._paused
            ]
            if not waiters:
                return
            done, pending = await asyncio.wait(waiters, return_when=asyncio.FIRST_COMPLETED)
            for p in pending:
                p.cancel()
            await asyncio.sleep(0)

    # --- crash recovery (ref:manager.rs:269-320) ---

    async def cold_resume(self, library: Any) -> int:
        """Re-dispatch persisted Paused/Running/Queued jobs at library
        load; unparseable ones are marked Canceled."""
        resumed = 0
        rows = library.db.query(
            "SELECT * FROM job WHERE status IN (?, ?, ?) AND parent_id IS NULL",
            (int(JobStatus.PAUSED), int(JobStatus.RUNNING), int(JobStatus.QUEUED)),
        )
        for row in rows:
            report = JobReport.from_row(row)
            if not report.data:
                report.status = JobStatus.CANCELED
                report.update(library.db)
                continue
            try:
                job = StatefulJob.deserialize_state(report.data, JOB_REGISTRY)
            except Exception:  # noqa: BLE001 - corrupt state is expected input
                logger.warning("cold_resume: dropping unparseable job %s", report.name)
                report.status = JobStatus.CANCELED
                report.update(library.db)
                continue
            self._dispatch(job, library, report)
            resumed += 1
        return resumed

    # --- events ---

    def _emit_progress(self, ctx: JobContext) -> None:
        library = ctx.library
        event = ctx.report.progress_event(getattr(library, "id", None))
        bus = getattr(library, "event_bus", None)
        if bus is not None:
            bus.emit(("JobProgress", event))
        # the jobs.progress subscription listens on the NODE bus
        # (CoreEvent::JobProgress, ref:api/mod.rs:54-58); each library
        # has its own private bus, so emit there too
        node_bus = getattr(getattr(library, "node", None), "event_bus", None)
        if node_bus is not None and node_bus is not bus:
            node_bus.emit(("JobProgress", event))

    @staticmethod
    def _action_string(job: StatefulJob) -> str:
        """"{action}(-{children})*" composition (ref:schema.prisma:405)."""
        parts = [job.NAME]
        tail = job.next_jobs
        while tail:
            parts.append(tail[-1].NAME)
            tail = tail[-1].next_jobs
        return "-".join(parts)


async def shutdown_jobs(manager: JobManager, library: Any) -> None:
    """Node shutdown: pause all running jobs so their state persists
    (the reference pauses via WorkerCommand::Shutdown)."""
    for job_id in list(manager._active):
        await manager.pause(job_id)
    await manager.wait_idle()
