"""StatefulJob — the resumable job contract + generic runner task.

Parity: ref:core/src/job/mod.rs:85-130 (trait: init → steps →
execute_step → finalize), :266-307 (serialized JobState{init, data,
steps, step_number, run_metadata}), :463-700 (generic run loop with
pause/cancel handling at step boundaries).

Steps and state are msgpack-serializable dicts so any job can be
persisted mid-flight and cold-resumed after a crash.
"""

from __future__ import annotations

import abc
import collections
import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, TYPE_CHECKING

import msgpack

from ..tasks import ExecStatus, Interrupter, InterruptionKind, Task
from ..telemetry import metrics as _tm
from ..telemetry import trace as _trace
from .report import JobReport, JobStatus

if TYPE_CHECKING:
    from .manager import JobManager

logger = logging.getLogger(__name__)


class JobError(Exception):
    """Critical job failure (job → Failed)."""


@dataclass
class StepResult:
    """Outcome of one step (ref JobStepOutput): optional extra steps to
    append, optional non-critical errors, metadata merge."""

    more_steps: list[dict] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)
    metadata: dict[str, Any] = field(default_factory=dict)


class JobContext:
    """What a job sees while running: the library handle, progress
    reporting, and node-level services (thumbnailer etc.)."""

    def __init__(self, library: Any, report: JobReport, manager: "JobManager | None" = None):
        self.library = library
        self.report = report
        self.manager = manager
        self._started = time.monotonic()
        self._phase: str | None = None
        self._phase_started = self._started

    def progress(
        self,
        *,
        task_count: int | None = None,
        completed_task_count: int | None = None,
        message: str | None = None,
        phase: str | None = None,
    ) -> None:
        r = self.report
        if task_count is not None:
            r.task_count = task_count
        if completed_task_count is not None:
            r.completed_task_count = completed_task_count
        if message is not None:
            r.message = message
        if phase is not None:
            if phase != self._phase:
                self._close_phase()
                self._phase = phase
            r.phase = phase
        r.estimate_completion(time.monotonic() - self._started)
        if self.manager is not None:
            self.manager._emit_progress(self)

    def _close_phase(self) -> None:
        """Observe the elapsed phase into sd_job_phase_seconds; the
        pre-first-phase stretch records as "init". Called on every
        phase transition and by the manager when the job settles."""
        now = time.monotonic()
        _tm.JOB_PHASE_SECONDS.observe(
            now - self._phase_started,
            job=self.report.name,
            phase=self._phase or "init",
        )
        self._phase_started = now


class StatefulJob(abc.ABC):
    """Subclass contract: override NAME, `init_job`, `execute_step`,
    optionally `finalize` and `IS_BATCHED`."""

    NAME: str = "unnamed"
    IS_BATCHED: bool = False  # batched jobs report per-batch progress

    def __init__(self, init: dict[str, Any] | None = None):
        self.id = uuid.uuid4()
        self.init: dict[str, Any] = init or {}
        self.data: dict[str, Any] = {}
        self.steps: collections.deque[dict] = collections.deque()
        self.step_number: int = 0
        self.run_metadata: dict[str, Any] = {}
        self.errors: list[str] = []
        self.initialized = False
        self.next_jobs: list["StatefulJob"] = []
        # distributed-trace context: minted/inherited at ingest, carried
        # through pause/resume (it serializes with the job state) and
        # down job chains, so one user action = one trace end to end
        self.trace_ctx: "_trace.TraceContext | None" = None

    # --- contract ---

    @abc.abstractmethod
    async def init_job(self, ctx: JobContext) -> None:
        """Populate `self.steps` (and `self.data`)."""

    @abc.abstractmethod
    async def execute_step(self, ctx: JobContext, step: dict, step_number: int) -> StepResult:
        ...

    async def finalize(self, ctx: JobContext) -> Any:
        return self.run_metadata

    # --- chaining (ref:core/src/job/mod.rs:213-231) ---

    def queue_next(self, job: "StatefulJob") -> "StatefulJob":
        self.next_jobs.append(job)
        return self

    def cleanup(self) -> None:
        """Release runtime-only resources; called by the runner on every
        exit path (done/paused/cancelled/failed). Must be idempotent."""

    # --- persistence (ref:core/src/job/mod.rs:266-307) ---

    def serialize_state(self) -> bytes:
        return msgpack.packb(
            {
                "id": self.id.bytes,
                "name": self.NAME,
                "init": self.init,
                "data": self.data,
                "steps": list(self.steps),
                "step_number": self.step_number,
                "run_metadata": self.run_metadata,
                "errors": self.errors,
                "initialized": self.initialized,
                "next_jobs": [j.serialize_state() for j in self.next_jobs],
                # a resumed job continues its original trace
                "trace": self.trace_ctx.to_wire() if self.trace_ctx else None,
            },
            use_bin_type=True,
        )

    @classmethod
    def deserialize_state(cls, raw: bytes, registry: dict[str, type["StatefulJob"]]) -> "StatefulJob":
        obj = msgpack.unpackb(raw, raw=False)
        job_cls = registry[obj["name"]]
        job = job_cls(obj["init"])
        job.id = uuid.UUID(bytes=obj["id"])
        job.data = obj["data"]
        job.steps = collections.deque(obj["steps"])
        job.step_number = obj["step_number"]
        job.run_metadata = obj["run_metadata"]
        job.errors = obj.get("errors", [])
        job.initialized = obj["initialized"]
        job.next_jobs = [
            StatefulJob.deserialize_state(r, registry) for r in obj.get("next_jobs", [])
        ]
        job.trace_ctx = _trace.TraceContext.from_wire(obj.get("trace"))
        return job


class JobRunnerTask(Task):
    """Drives one StatefulJob through the task system. Interruption is
    honored at step boundaries — the TPU-batch preemption model: a
    dispatched batch is atomic, pausing drains to the boundary and
    serializes what's left (ref run loop: core/src/job/mod.rs:463-700).
    """

    def __init__(self, job: StatefulJob, ctx: JobContext):
        super().__init__()
        self.job = job
        self.ctx = ctx
        self.output: Any = None

    async def run(self, interrupter: Interrupter) -> ExecStatus:
        job, ctx = self.job, self.ctx
        report = ctx.report
        # normally the task system installed the dispatch-time context;
        # a directly-driven runner (tests, ad-hoc tools) still continues
        # the job's own trace
        trace_token = (
            _trace.set_current(job.trace_ctx)
            if _trace.current() is None and job.trace_ctx is not None
            else None
        )
        try:
            if not job.initialized:
                await job.init_job(ctx)
                job.initialized = True
                report.task_count = max(report.task_count, len(job.steps))
                ctx.progress(task_count=report.task_count)

            while job.steps:
                kind = interrupter.check()
                if kind in (InterruptionKind.PAUSE, InterruptionKind.SUSPEND):
                    return ExecStatus.PAUSED
                if kind == InterruptionKind.CANCEL:
                    return ExecStatus.CANCELED

                step = job.steps.popleft()
                result = await job.execute_step(ctx, step, job.step_number)
                job.step_number += 1
                if result.more_steps:
                    job.steps.extend(result.more_steps)
                    report.task_count += len(result.more_steps)
                if result.errors:
                    job.errors.extend(result.errors)
                    report.errors_text.extend(result.errors)
                if result.metadata:
                    job.run_metadata.update(result.metadata)
                ctx.progress(completed_task_count=job.step_number)

            self.output = await job.finalize(ctx)
            return ExecStatus.DONE
        except JobError:
            raise
        except Exception as e:  # noqa: BLE001 - surfaced as job failure
            logger.exception("job %s failed", job.NAME)
            raise JobError(str(e)) from e
        finally:
            if trace_token is not None:
                _trace.reset_current(trace_token)
            # runs on DONE, pause, cancel, and failure alike — jobs
            # release runtime-only resources (thread pools, prefetch
            # buffers) here, never in finalize (which pause skips)
            try:
                job.cleanup()
            except Exception:
                logger.exception("job %s cleanup failed", job.NAME)


def status_for_result(status: "Any", had_errors: bool) -> JobStatus:
    from ..tasks import TaskStatus

    if status == TaskStatus.DONE:
        return JobStatus.COMPLETED_WITH_ERRORS if had_errors else JobStatus.COMPLETED
    # FORCED_ABORTION is the task coroutine being cancelled out from
    # under the job — loop teardown at node shutdown, or an explicit
    # force-abort. Either way nothing *failed*: recording it as FAILED
    # put a spurious `job.failed`-shaped settled event on the flight
    # ring (and an error toast) every time a node shut down mid-job.
    if status in (TaskStatus.CANCELED, TaskStatus.FORCED_ABORTION):
        return JobStatus.CANCELED
    if status in (TaskStatus.PAUSED, TaskStatus.SHUTDOWN):
        return JobStatus.PAUSED
    return JobStatus.FAILED
