"""Cloud sync actors — Sender / Receiver / Ingester per library.

Parity: ref:core/src/cloud/sync/{mod.rs,send.rs,receive.rs,ingest.rs} —
three actors declared per library when the CloudSync feature is on
(mod.rs:14-68): the **Sender** pushes this instance's ops past its
cloud watermark as packed collections (send.rs:13); the **Receiver**
polls the relay for other instances' collections and caches them into
the `cloud_crdt_operation` table (receive.rs:24-207), registering
unknown instances; the **Ingester** drains that cache through the
normal `receive_crdt_operation` path, `OPS_PER_REQUEST = 1000` per tick
(ingest.rs:8-21), deleting rows as they apply.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any

from ..db.database import now_iso
from ..sync.crdt import CompressedCRDTOperations
from ..sync.hlc import NTP64
from ..sync.ingest import receive_crdt_operation
from ..sync.manager import SyncManager, _record_id_blob
from ..telemetry import span as _span
from ..utils.resilience import BreakerOpen
from .api import CloudApiError, CloudClient

logger = logging.getLogger(__name__)

OPS_PER_REQUEST = 1000  # ref:core/src/cloud/sync/ingest.rs:21
POLL_INTERVAL = 1.0


class CloudSync:
    """The per-library actor trio (ref:cloud/sync/mod.rs declare_actors)."""

    def __init__(
        self,
        library: Any,
        client: CloudClient,
        *,
        poll_interval: float = POLL_INTERVAL,
    ):
        self.library = library
        self.sync: SyncManager = library.sync
        self.client = client
        self.poll_interval = poll_interval
        self._tasks: list[asyncio.Task] = []
        self._stopped = False
        self._notify = asyncio.Event()
        # watermarks
        self._sent_timestamp = NTP64(0)  # sender: last pushed local ts
        self._cursors: dict[str, int] = {}  # receiver: per-instance col id
        self.sent_ops = 0
        self.received_collections = 0
        self.ingested_ops = 0

    # --- lifecycle -----------------------------------------------------

    async def start(self) -> None:
        """Register library+instance with the relay, then run the trio."""
        lib_id = str(self.library.id)
        await self.client.create_library(lib_id, self.library.name)
        await self.client.add_instance(
            lib_id, str(self.sync.instance)
        )
        # resume the sender watermark: everything already pushed is
        # whatever the relay has seen; simplest correct resume is to
        # re-push from 0 — receivers dedupe via is_operation_old
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._sender(), name="cloud-send"),
            loop.create_task(self._receiver(), name="cloud-receive"),
            loop.create_task(self._ingester(), name="cloud-ingest"),
        ]
        self._unsub = self.library.event_bus.on(self._on_event)

    def _on_event(self, event: Any) -> None:
        if event == ("SyncMessage", "Created"):
            self._notify.set()

    async def shutdown(self) -> None:
        self._stopped = True
        if hasattr(self, "_unsub"):
            self._unsub()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass

    # --- sender (ref:send.rs) ------------------------------------------

    async def _sender(self) -> None:
        while not self._stopped:
            try:
                await self._send_tick()
            except (CloudApiError, BreakerOpen, asyncio.TimeoutError) as e:
                # expected while the relay is down / breaker-gated: the
                # next tick (or the breaker's half-open probe) retries
                logger.debug("cloud send failed: %s", e)
            except Exception:
                logger.exception("cloud sender crashed; continuing")
            try:
                await asyncio.wait_for(self._notify.wait(), self.poll_interval)
            except asyncio.TimeoutError:
                pass
            self._notify.clear()

    async def _send_tick(self) -> None:
        me = self.sync.instance
        while True:
            # only THIS instance's ops: mask every other instance out of
            # the page with a max watermark (send.rs pushes own ops only)
            clocks: list[tuple[uuid.UUID, NTP64]] = [(me, self._sent_timestamp)]
            for row in self.library.db.query("SELECT pub_id FROM instance"):
                other = uuid.UUID(bytes=row["pub_id"])
                if other != me:
                    clocks.append((other, NTP64((1 << 63) - 1)))
            ops = [
                op
                for op in self.sync.get_ops(
                    count=OPS_PER_REQUEST, clocks=clocks
                )
                if op.instance == me
            ]
            if not ops:
                return
            packed = CompressedCRDTOperations.compress(ops).pack()
            # the span installs a trace context, so the push carries it
            # to the relay (X-SD-Trace) and relay.push joins this trace
            async with _span("cloud.send", nbytes=len(packed)):
                await self.client.push_ops(
                    str(self.library.id), str(me), packed
                )
            self._sent_timestamp = ops[-1].timestamp
            self.sent_ops += len(ops)
            if len(ops) < OPS_PER_REQUEST:
                return

    # --- receiver (ref:receive.rs) -------------------------------------

    async def _receiver(self) -> None:
        while not self._stopped:
            try:
                await self._receive_tick()
            except (CloudApiError, BreakerOpen, asyncio.TimeoutError) as e:
                logger.debug("cloud receive failed: %s", e)
            except Exception:
                logger.exception("cloud receiver crashed; continuing")
            await asyncio.sleep(self.poll_interval)

    async def _receive_tick(self) -> None:
        collections = await self.client.pull_ops(
            str(self.library.id),
            str(self.sync.instance),
            dict(self._cursors),
        )
        for col in collections:
            ops = CompressedCRDTOperations.unpack(col["contents"]).expand()
            self._store_cloud_ops(ops)
            self._cursors[col["instance_uuid"]] = col["id"]
            self.received_collections += 1

    def _store_cloud_ops(self, ops: list[Any]) -> None:
        """Cache into cloud_crdt_operation (ref:receive.rs:24-207),
        creating instance rows for unseen instances."""
        db = self.library.db
        for op in ops:
            inst = db.find_one("instance", pub_id=op.instance.bytes)
            if inst is None:
                now = now_iso()
                iid = db.insert(
                    "instance",
                    pub_id=op.instance.bytes,
                    identity=b"",
                    node_id=b"",
                    node_name="",
                    node_platform=0,
                    last_seen=now,
                    date_created=now,
                )
            else:
                iid = inst["id"]
            db.execute(
                "INSERT OR IGNORE INTO cloud_crdt_operation "
                "(id, timestamp, model, record_id, kind, data, instance_id) "
                "VALUES (?, ?, ?, ?, ?, ?, ?)",
                (
                    op.id.bytes,
                    int(op.timestamp),
                    op.model,
                    _record_id_blob(op.record_id),
                    op.kind(),
                    op.pack(),
                    iid,
                ),
            )

    # --- ingester (ref:ingest.rs) --------------------------------------

    async def _ingester(self) -> None:
        while not self._stopped:
            try:
                applied = await asyncio.to_thread(self._ingest_tick)
                if applied:
                    continue  # drain the cache without sleeping
            except Exception:
                logger.exception("cloud ingester crashed; continuing")
            await asyncio.sleep(self.poll_interval)

    def _ingest_tick(self) -> int:
        rows = self.sync.get_cloud_ops(count=OPS_PER_REQUEST)
        applied = 0
        for op_id, op in rows:
            receive_crdt_operation(self.sync, op)
            self.library.db.delete("cloud_crdt_operation", id=op_id)
            applied += 1
        if applied:
            self.ingested_ops += applied
            self.library.event_bus.emit(("SyncMessage", "Ingested"))
        return applied
