"""Cloud relay server — the WAN sync rendezvous.

Parity role: the reference's closed-source Spacedrive cloud exposes
libraries / instances / sync *message collections* over REST
(ref:crates/cloud-api/src/lib.rs:35-61,120,203,359-448,485). This
framework ships the relay itself so WAN sync is self-hostable: an
aiohttp app storing, per library, the registered instances and each
instance's append-only op-collection log. Collections are opaque
msgpack blobs (CompressedCRDTOperations.pack()) keyed by a
monotonically increasing ULID-like row id; receivers poll with
`from_id` cursors exactly like the reference's
`messageCollections.get(instanceTimestamps)` flow.

Endpoints (JSON bodies; op payloads base64):
  POST /api/libraries                         {uuid, name}
  GET  /api/libraries/{lib}
  POST /api/libraries/{lib}/instances         {uuid, identity}
  GET  /api/libraries/{lib}/instances
  POST /api/libraries/{lib}/messageCollections     push one collection
  POST /api/libraries/{lib}/messageCollections/get  pull w/ cursors
"""

from __future__ import annotations

import asyncio
import base64
import itertools
import json as _json
import time
from typing import Any

from aiohttp import web

from ..telemetry import span as _span
from ..telemetry import tenants as _tenants
from ..telemetry import trace as _trace
from ..utils import faults as _faults


@web.middleware
async def _fault_middleware(request: web.Request, handler):
    """The ``relay.http`` injection point: 500s, slow responses, and
    truncated bodies, exercised against the CLIENT's retry/breaker
    policy (a production relay never ships with a plan installed)."""
    spec = _faults.hit("relay.http")
    if spec is None:
        return await handler(request)
    if spec.mode == "500":
        return web.Response(status=500, text="injected relay failure")
    if spec.mode == "timeout":
        await asyncio.sleep(spec.delay_s)
        return await handler(request)
    # "truncate": advertise the full body, send half, then drop the
    # connection — the client sees a mid-body EOF
    resp = await handler(request)
    body = resp.body if isinstance(resp.body, (bytes, bytearray)) else b""
    out = web.StreamResponse(status=resp.status)
    out.content_length = max(len(body), 2)
    await out.prepare(request)
    await out.write(bytes(body[: len(body) // 2]))
    transport = request.transport
    if transport is not None:
        transport.close()
    return out

# HTTP header carrying the telemetry.trace wire dict (JSON) so relay
# spans join the calling node's trace
TRACE_HEADER = "X-SD-Trace"
# HTTP header naming the pushing instance on telemetry federation
# calls (body `instance_uuid` is the fallback)
INSTANCE_HEADER = "X-SD-Instance"


def _request_trace(request: web.Request) -> "_trace.TraceContext | None":
    raw = request.headers.get(TRACE_HEADER)
    if not raw:
        return None
    try:
        return _trace.TraceContext.from_wire(_json.loads(raw))
    except ValueError:
        return None


class CloudRelay:
    def __init__(self, p2p_limits=None) -> None:
        self.libraries: dict[str, dict[str, Any]] = {}
        self._collection_ids = itertools.count(1)
        self.app = web.Application(middlewares=[_fault_middleware])
        self.app.add_routes(
            [
                web.post("/api/libraries", self._create_library),
                web.get("/api/libraries/{lib}", self._get_library),
                web.post("/api/libraries/{lib}/instances", self._add_instance),
                web.get("/api/libraries/{lib}/instances", self._list_instances),
                web.post(
                    "/api/libraries/{lib}/messageCollections", self._push
                ),
                web.post(
                    "/api/libraries/{lib}/messageCollections/get", self._pull
                ),
                web.post(
                    "/api/libraries/{lib}/telemetry", self._telemetry_push
                ),
                web.post(
                    "/api/libraries/{lib}/telemetry/get", self._telemetry_pull
                ),
            ]
        )
        self._runner: web.AppRunner | None = None
        self.port: int | None = None
        # WAN P2P rendezvous (p2p/relay.py): relayed Spacedrop /
        # files-over-P2P for non-LAN peers, not just sync
        from ..p2p.relay import RelayServer

        self.p2p_relay = RelayServer(limits=p2p_limits)
        self.p2p_port: int | None = None

    async def start(self, host: str = "127.0.0.1", port: int = 0,
                    p2p_port: int = 0) -> int:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        self.p2p_port = await self.p2p_relay.start(host, p2p_port)
        return self.port

    async def shutdown(self) -> None:
        await self.p2p_relay.shutdown()
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # --- handlers ------------------------------------------------------

    def _lib(self, request: web.Request) -> dict[str, Any]:
        lib = self.libraries.get(request.match_info["lib"])
        if lib is None:
            raise web.HTTPNotFound(text="library")
        return lib

    async def _create_library(self, request: web.Request) -> web.Response:
        body = await request.json()
        lib_id = body["uuid"]
        self.libraries.setdefault(
            lib_id,
            {"uuid": lib_id, "name": body.get("name", ""), "instances": {},
             "collections": []},
        )
        return web.json_response({"uuid": lib_id})

    async def _get_library(self, request: web.Request) -> web.Response:
        lib = self._lib(request)
        return web.json_response({"uuid": lib["uuid"], "name": lib["name"]})

    async def _add_instance(self, request: web.Request) -> web.Response:
        lib = self._lib(request)
        body = await request.json()
        lib["instances"][body["uuid"]] = {
            "uuid": body["uuid"],
            "identity": body.get("identity"),
            "node_name": body.get("node_name", ""),
        }
        return web.json_response({"ok": True})

    async def _list_instances(self, request: web.Request) -> web.Response:
        lib = self._lib(request)
        return web.json_response(list(lib["instances"].values()))

    async def _push(self, request: web.Request) -> web.Response:
        lib = self._lib(request)
        body = await request.json()
        with _trace.use(_request_trace(request)), _span("relay.push"):
            instance = body["instance_uuid"]
            if instance not in lib["instances"]:
                raise web.HTTPBadRequest(text="unknown instance")
            cid = next(self._collection_ids)
            lib["collections"].append(
                {
                    "id": cid,
                    "instance_uuid": instance,
                    "contents": body["contents"],  # base64 packed ops
                }
            )
            # the relay is the one surface every tenant's every device
            # hits — attribute pushes (and their payload weight) to
            # the library so a hot tenant is visible before fairness
            # enforcement (ROADMAP item 4) exists to act on it
            tenant = request.match_info.get("lib")
            _tenants.observe("relay_push", tenant)
            _tenants.observe_bytes(tenant, len(body["contents"]),
                                   outbound=False)
            return web.json_response({"id": cid})

    async def _pull(self, request: web.Request) -> web.Response:
        """Collections from OTHER instances after the caller's cursors:
        body {instance_uuid, cursors: {instance_uuid: last_seen_id}}."""
        lib = self._lib(request)
        body = await request.json()
        with _trace.use(_request_trace(request)), _span("relay.pull"):
            me = body["instance_uuid"]
            cursors = {k: int(v) for k, v in body.get("cursors", {}).items()}
            out = [
                c
                for c in lib["collections"]
                if c["instance_uuid"] != me
                and c["id"] > cursors.get(c["instance_uuid"], 0)
            ]
            page = out[: int(body.get("count", 100))]
            tenant = request.match_info.get("lib")
            _tenants.observe("relay_pull", tenant)
            _tenants.observe_bytes(
                tenant, sum(len(c["contents"]) for c in page),
                outbound=True)
            return web.json_response(page)


    # --- telemetry federation fallback (telemetry/federation.py) -------
    # Nodes without a direct P2P route to a peer exchange compact
    # snapshots through here: each instance pushes its latest snapshot
    # (overwrite, not append — only the freshest matters), and pulls
    # every OTHER instance's copy with its relay-side age, so the
    # puller's staleness clock keeps running while a snapshot sits here.

    async def _telemetry_push(self, request: web.Request) -> web.Response:
        lib = self._lib(request)
        body = await request.json()
        with _trace.use(_request_trace(request)), _span("relay.telemetry_push"):
            instance = request.headers.get(INSTANCE_HEADER) \
                or (body.get("instance_uuid") if isinstance(body, dict)
                    else None)
            if instance not in lib["instances"]:
                raise web.HTTPBadRequest(text="unknown instance")
            snapshot = body.get("snapshot") if isinstance(body, dict) else None
            if not isinstance(snapshot, dict):
                # malformed push is the CLIENT's error — 400, not a 500;
                # the relay stores any dict shape (it must keep relaying
                # for peers running a newer snapshot revision — version
                # checking is the puller's job, snapshot_compatible)
                raise web.HTTPBadRequest(text="snapshot must be an object")
            lib.setdefault("telemetry", {})[instance] = {
                "snapshot": snapshot,
                "pushed_at": time.time(),
            }
            return web.json_response({"ok": True})

    async def _telemetry_pull(self, request: web.Request) -> web.Response:
        lib = self._lib(request)
        body = await request.json()
        with _trace.use(_request_trace(request)), _span("relay.telemetry_pull"):
            me = request.headers.get(INSTANCE_HEADER) \
                or body.get("instance_uuid")
            now = time.time()
            out = [
                {
                    "instance_uuid": inst,
                    "snapshot": entry["snapshot"],
                    "age_seconds": round(now - entry["pushed_at"], 3),
                }
                for inst, entry in lib.get("telemetry", {}).items()
                if inst != me
            ]
            return web.json_response(out)


def b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


def unb64(data: str) -> bytes:
    return base64.b64decode(data)
