"""Cloud relay sync: self-hostable relay server, typed client, actors.

Parity: ref:core/src/cloud (sender/receiver/ingester actors) +
crates/cloud-api (REST client); the relay server itself replaces the
reference's closed-source cloud so WAN sync works self-hosted.
"""

from .api import CloudApiError, CloudClient
from .relay import CloudRelay
from .sync import OPS_PER_REQUEST, CloudSync

__all__ = [
    "CloudApiError",
    "CloudClient",
    "CloudRelay",
    "CloudSync",
    "OPS_PER_REQUEST",
]
