"""Typed cloud REST client.

Parity: ref:crates/cloud-api/src/lib.rs — `library::{create,get}`
(:120,203), `library::instances` (:359), `sync::messageCollections::
{request_add(push), get}` (:448,485) against the relay's REST surface.
One aiohttp session per client; all methods raise `CloudApiError` on
non-2xx like the reference's `Result<_, rspc::Error>` surface.

Every request rides the shared relay resilience policy: bounded
decorrelated-jitter retries on network failures and 5xx, a per-origin
circuit breaker (a dead relay costs one fast ``BreakerOpen`` per
cycle, not a timeout ladder), and ambient-deadline clipping. A 4xx is
the CLIENT's error — it neither retries nor feeds the breaker. A
mid-body EOF (``aiohttp`` payload error while reading the response)
trips the breaker like any transport failure: a relay that truncates
bodies is as dead as one that refuses connections.
"""

from __future__ import annotations

import json as _json
from typing import Any

import aiohttp

from ..telemetry import tenants as _tenants
from ..telemetry import trace as _trace
from ..utils.resilience import (
    PASS,
    RETRY,
    ResiliencePolicy,
    RetryPolicy,
)
from .relay import INSTANCE_HEADER, TRACE_HEADER, b64, unb64


class CloudApiError(Exception):
    def __init__(self, message: str, status: int | None = None):
        super().__init__(message)
        self.status = status  # None = transport-level failure


def _relay_classify(exc: BaseException) -> str:
    if isinstance(exc, CloudApiError) and exc.status is not None \
            and exc.status < 500:
        return PASS  # the relay answered; the request was bad — ours
    return RETRY


RELAY_POLICY = ResiliencePolicy(
    "relay",
    RetryPolicy(max_attempts=3, base_delay=0.05, max_delay=2.0,
                attempt_timeout=30.0),
    failure_threshold=5,
    reset_timeout=30.0,
    classify=_relay_classify,
)


class CloudClient:
    def __init__(self, api_origin: str):
        self.origin = api_origin.rstrip("/")
        self._session: aiohttp.ClientSession | None = None

    async def _request(
        self, method: str, path: str, json: Any = None,
        headers: dict[str, str] | None = None,
    ) -> Any:
        return await RELAY_POLICY.call(
            self.origin,
            lambda: self._request_once(method, path, json, headers),
        )

    async def _request_once(
        self, method: str, path: str, json: Any = None,
        headers: dict[str, str] | None = None,
    ) -> Any:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        # trace context rides an HTTP header so relay-side spans join
        # the pushing/pulling node's trace
        wire = _trace.wire_current()
        if wire:
            headers = {**(headers or {}), TRACE_HEADER: _json.dumps(wire)}
        try:
            async with self._session.request(
                method, f"{self.origin}{path}", json=json, headers=headers
            ) as resp:
                if resp.status >= 400:
                    raise CloudApiError(
                        f"{method} {path} -> {resp.status}: {await resp.text()}",
                        status=resp.status,
                    )
                # reading the body can hit a mid-stream EOF — that is a
                # transport failure (status=None), so it retries AND
                # feeds the per-origin breaker
                return await resp.json()
        except aiohttp.ClientError as e:
            raise CloudApiError(f"{method} {path} failed: {e}") from e

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    # --- libraries (ref:lib.rs:120,203) --------------------------------

    async def create_library(self, library_uuid: str, name: str) -> Any:
        return await self._request(
            "POST", "/api/libraries", {"uuid": library_uuid, "name": name}
        )

    async def get_library(self, library_uuid: str) -> Any:
        return await self._request("GET", f"/api/libraries/{library_uuid}")

    # --- instances (ref:lib.rs:359) ------------------------------------

    async def add_instance(
        self, library_uuid: str, instance_uuid: str, identity: str = "",
        node_name: str = "",
    ) -> Any:
        return await self._request(
            "POST",
            f"/api/libraries/{library_uuid}/instances",
            {"uuid": instance_uuid, "identity": identity, "node_name": node_name},
        )

    async def list_instances(self, library_uuid: str) -> list[Any]:
        return await self._request(
            "GET", f"/api/libraries/{library_uuid}/instances"
        )

    # --- message collections (ref:lib.rs:448,485) ----------------------

    async def push_ops(
        self, library_uuid: str, instance_uuid: str, packed_ops: bytes
    ) -> int:
        out = await self._request(
            "POST",
            f"/api/libraries/{library_uuid}/messageCollections",
            {"instance_uuid": instance_uuid, "contents": b64(packed_ops)},
        )
        # node-side mirror of the relay's accounting: which of OUR
        # libraries spends the relay link, in raw payload bytes
        _tenants.observe_bytes(library_uuid, len(packed_ops),
                               outbound=True)
        return out["id"]

    async def pull_ops(
        self,
        library_uuid: str,
        instance_uuid: str,
        cursors: dict[str, int],
        count: int = 100,
    ) -> list[dict[str, Any]]:
        out = await self._request(
            "POST",
            f"/api/libraries/{library_uuid}/messageCollections/get",
            {"instance_uuid": instance_uuid, "cursors": cursors, "count": count},
        )
        for c in out:
            c["contents"] = unb64(c["contents"])
        _tenants.observe_bytes(
            library_uuid, sum(len(c["contents"]) for c in out),
            outbound=False)
        return out

    # --- telemetry federation fallback (telemetry/federation.py) -------

    async def push_telemetry(
        self, library_uuid: str, instance_uuid: str, snapshot: dict[str, Any]
    ) -> Any:
        return await self._request(
            "POST",
            f"/api/libraries/{library_uuid}/telemetry",
            {"instance_uuid": instance_uuid, "snapshot": snapshot},
            headers={INSTANCE_HEADER: instance_uuid},
        )

    async def pull_telemetry(
        self, library_uuid: str, instance_uuid: str
    ) -> list[dict[str, Any]]:
        return await self._request(
            "POST",
            f"/api/libraries/{library_uuid}/telemetry/get",
            {"instance_uuid": instance_uuid},
            headers={INSTANCE_HEADER: instance_uuid},
        )
