"""Typed cloud REST client.

Parity: ref:crates/cloud-api/src/lib.rs — `library::{create,get}`
(:120,203), `library::instances` (:359), `sync::messageCollections::
{request_add(push), get}` (:448,485) against the relay's REST surface.
One aiohttp session per client; all methods raise `CloudApiError` on
non-2xx like the reference's `Result<_, rspc::Error>` surface.
"""

from __future__ import annotations

import json as _json
from typing import Any

import aiohttp

from ..telemetry import trace as _trace
from .relay import INSTANCE_HEADER, TRACE_HEADER, b64, unb64


class CloudApiError(Exception):
    pass


class CloudClient:
    def __init__(self, api_origin: str):
        self.origin = api_origin.rstrip("/")
        self._session: aiohttp.ClientSession | None = None

    async def _request(
        self, method: str, path: str, json: Any = None,
        headers: dict[str, str] | None = None,
    ) -> Any:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        # trace context rides an HTTP header so relay-side spans join
        # the pushing/pulling node's trace
        wire = _trace.wire_current()
        if wire:
            headers = {**(headers or {}), TRACE_HEADER: _json.dumps(wire)}
        try:
            async with self._session.request(
                method, f"{self.origin}{path}", json=json, headers=headers
            ) as resp:
                if resp.status >= 400:
                    raise CloudApiError(
                        f"{method} {path} -> {resp.status}: {await resp.text()}"
                    )
                return await resp.json()
        except aiohttp.ClientError as e:
            raise CloudApiError(f"{method} {path} failed: {e}") from e

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    # --- libraries (ref:lib.rs:120,203) --------------------------------

    async def create_library(self, library_uuid: str, name: str) -> Any:
        return await self._request(
            "POST", "/api/libraries", {"uuid": library_uuid, "name": name}
        )

    async def get_library(self, library_uuid: str) -> Any:
        return await self._request("GET", f"/api/libraries/{library_uuid}")

    # --- instances (ref:lib.rs:359) ------------------------------------

    async def add_instance(
        self, library_uuid: str, instance_uuid: str, identity: str = "",
        node_name: str = "",
    ) -> Any:
        return await self._request(
            "POST",
            f"/api/libraries/{library_uuid}/instances",
            {"uuid": instance_uuid, "identity": identity, "node_name": node_name},
        )

    async def list_instances(self, library_uuid: str) -> list[Any]:
        return await self._request(
            "GET", f"/api/libraries/{library_uuid}/instances"
        )

    # --- message collections (ref:lib.rs:448,485) ----------------------

    async def push_ops(
        self, library_uuid: str, instance_uuid: str, packed_ops: bytes
    ) -> int:
        out = await self._request(
            "POST",
            f"/api/libraries/{library_uuid}/messageCollections",
            {"instance_uuid": instance_uuid, "contents": b64(packed_ops)},
        )
        return out["id"]

    async def pull_ops(
        self,
        library_uuid: str,
        instance_uuid: str,
        cursors: dict[str, int],
        count: int = 100,
    ) -> list[dict[str, Any]]:
        out = await self._request(
            "POST",
            f"/api/libraries/{library_uuid}/messageCollections/get",
            {"instance_uuid": instance_uuid, "cursors": cursors, "count": count},
        )
        for c in out:
            c["contents"] = unb64(c["contents"])
        return out

    # --- telemetry federation fallback (telemetry/federation.py) -------

    async def push_telemetry(
        self, library_uuid: str, instance_uuid: str, snapshot: dict[str, Any]
    ) -> Any:
        return await self._request(
            "POST",
            f"/api/libraries/{library_uuid}/telemetry",
            {"instance_uuid": instance_uuid, "snapshot": snapshot},
            headers={INSTANCE_HEADER: instance_uuid},
        )

    async def pull_telemetry(
        self, library_uuid: str, instance_uuid: str
    ) -> list[dict[str, Any]]:
        return await self._request(
            "POST",
            f"/api/libraries/{library_uuid}/telemetry/get",
            {"instance_uuid": instance_uuid},
            headers={INSTANCE_HEADER: instance_uuid},
        )
