"""Task contract for the execution plane.

Mirrors the reference's `Task` trait and interruption machinery
(ref:crates/task-system/src/task.rs:81-148): a task runs to an
ExecStatus, checking its Interrupter at safe points; the system can
pause, cancel, or force-abort it, and priority tasks can suspend
non-priority ones mid-run.
"""

from __future__ import annotations

import abc
import asyncio
import enum
import itertools
import uuid
from dataclasses import dataclass
from typing import Any


class ExecStatus(enum.Enum):
    """What a task's `run` returned (ref:task.rs:81-85)."""

    DONE = "done"
    PAUSED = "paused"
    CANCELED = "canceled"


class InterruptionKind(enum.Enum):
    PAUSE = "pause"
    CANCEL = "cancel"
    SUSPEND = "suspend"  # priority preemption; worker will requeue


class TaskStatus(enum.Enum):
    """Final disposition reported through the handle
    (ref:task.rs TaskStatus)."""

    DONE = "done"
    PAUSED = "paused"
    CANCELED = "canceled"
    FORCED_ABORTION = "forced_abortion"
    ERROR = "error"
    SHUTDOWN = "shutdown"  # system shut down; task returned for persistence


class Interrupter:
    """Cooperative interruption point. Tasks call `check()` (cheap) at
    batch boundaries; long waits use `wait_interrupt(timeout)`."""

    def __init__(self) -> None:
        self._kind: InterruptionKind | None = None
        self._event = asyncio.Event()

    def interrupt(self, kind: InterruptionKind) -> None:
        # cancel wins over pause/suspend; first non-cancel sticks
        if self._kind is None or kind == InterruptionKind.CANCEL:
            self._kind = kind
        self._event.set()

    def check(self) -> InterruptionKind | None:
        """Non-blocking: the pending interruption, if any."""
        return self._kind

    async def wait_interrupt(self, timeout: float | None = None) -> InterruptionKind | None:
        try:
            await asyncio.wait_for(self._event.wait(), timeout)
        except asyncio.TimeoutError:
            return None
        return self._kind

    def clear(self) -> None:
        self._kind = None
        self._event = asyncio.Event()


_task_counter = itertools.count(1)


class Task(abc.ABC):
    """A resumable unit of work. Subclasses hold their own progress
    state so a Paused/suspended task continues where it left off when
    re-run (the contract the job steps rely on)."""

    priority: bool = False

    def __init__(self, *, priority: bool | None = None) -> None:
        self.id = uuid.uuid4()
        self.seq = next(_task_counter)
        if priority is not None:
            self.priority = priority

    @abc.abstractmethod
    async def run(self, interrupter: Interrupter) -> ExecStatus:
        ...

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {str(self.id)[:8]} prio={self.priority}>"


@dataclass
class TaskResult:
    status: TaskStatus
    output: Any = None
    error: BaseException | None = None
    task: Task | None = None  # returned for PAUSED / SHUTDOWN persistence


class TaskHandle:
    """Control + completion future for a dispatched task
    (ref:task.rs TaskHandle: pause/cancel/resume/force_abort)."""

    def __init__(self, task: Task, system: "Any") -> None:
        self.task = task
        self._system = system
        self._done: asyncio.Future[TaskResult] = asyncio.get_running_loop().create_future()
        self._paused_event = asyncio.Event()

    # -- completion --

    def _resolve(self, result: TaskResult) -> None:
        if not self._done.done():
            self._done.set_result(result)

    def _on_paused(self) -> None:
        self._paused_event.set()

    async def wait_paused(self) -> None:
        await self._paused_event.wait()

    async def wait(self) -> TaskResult:
        # shielded: cancelling one waiter must not cancel the shared
        # result future other waiters (e.g. the job supervisor) hold
        return await asyncio.shield(self._done)

    def done(self) -> bool:
        return self._done.done()

    # -- control --

    async def pause(self) -> None:
        await self._system._interrupt(self.task.id, InterruptionKind.PAUSE)

    async def cancel(self) -> None:
        await self._system._interrupt(self.task.id, InterruptionKind.CANCEL)

    async def resume(self) -> None:
        await self._system._resume(self.task.id)

    async def force_abort(self) -> None:
        await self._system._force_abort(self.task.id)
