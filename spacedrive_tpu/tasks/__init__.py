"""Execution plane: the interruptible task system.

Parity contract (ref:crates/task-system/src/task.rs:81-148,
system.rs:38-461, worker/): `Task.run(interrupter)` returning
Done/Paused/Canceled, pause/cancel/force-abort, priority tasks that
suspend running non-priority ones, round-robin + least-loaded dispatch,
work stealing, and shutdown that hands unfinished tasks back for
persistence.

TPU-first re-design: workers are asyncio tasks on the host — their job
in this framework is to *assemble fixed-shape batches* and await device
steps, so cooperative scheduling (not OS threads) is the right model;
CPU-bound work (decode, IO) goes through executors.
"""

from .task import (
    ExecStatus,
    Interrupter,
    InterruptionKind,
    Task,
    TaskHandle,
    TaskStatus,
)
from .system import TaskSystem

__all__ = [
    "ExecStatus",
    "Interrupter",
    "InterruptionKind",
    "Task",
    "TaskHandle",
    "TaskStatus",
    "TaskSystem",
]
