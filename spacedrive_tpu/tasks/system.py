"""TaskSystem — cooperative multi-worker scheduler with work stealing.

Parity: ref:crates/task-system/src/system.rs (round-robin `dispatch`,
least-loaded `dispatch_many`, worker-per-core), worker/mod.rs:282
(stealing), worker/runner.rs:46-115 (priority suspension), and the
shutdown contract that returns unfinished tasks to the caller
(ref:src/task.rs:69-71). Implemented over one asyncio loop: "workers"
are concurrent coroutines, which matches this framework's workload
(batch assembly + device-step awaiting + async IO) on TPU hosts.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import time
from typing import Iterable

from ..telemetry import metrics as _tm
from ..telemetry import trace as _trace
from .task import (
    ExecStatus,
    Interrupter,
    InterruptionKind,
    Task,
    TaskHandle,
    TaskResult,
    TaskStatus,
)

logger = logging.getLogger(__name__)


class _Worker:
    def __init__(self, system: "TaskSystem", index: int):
        self.system = system
        self.index = index
        self.queue: collections.deque[TaskHandle] = collections.deque()
        self.current: TaskHandle | None = None
        self.current_interrupter: Interrupter | None = None
        self.current_coro: asyncio.Task | None = None
        self.wakeup = asyncio.Event()
        self.runner: asyncio.Task | None = None

    # -- queue ops --

    def enqueue(self, handle: TaskHandle) -> None:
        handle._enqueued_at = time.monotonic()
        if handle.task.priority:
            self.queue.appendleft(handle)
            # suspend a running non-priority task so the priority one
            # starts now (ref:worker/runner.rs:46-115)
            if (
                self.current is not None
                and not self.current.task.priority
                and self.current_interrupter is not None
            ):
                self.current_interrupter.interrupt(InterruptionKind.SUSPEND)
        else:
            self.queue.append(handle)
        self.wakeup.set()

    def load(self) -> int:
        return len(self.queue) + (1 if self.current else 0)

    def steal_from(self) -> TaskHandle | None:
        """Steal from the back (oldest non-priority work)."""
        if self.queue:
            return self.queue.pop()
        return None

    # -- main loop --

    async def run_loop(self) -> None:
        while True:
            if self.system._shutting_down:
                # stop immediately; queued tasks are returned to the
                # caller by shutdown(), not drained (ref:system.rs:224)
                return
            handle = self._next() or self.system._steal(self.index)
            if handle is None:
                self.wakeup.clear()
                try:
                    await asyncio.wait_for(self.wakeup.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    continue
                continue
            await self._execute(handle)

    def _next(self) -> TaskHandle | None:
        while self.queue:
            handle = self.queue.popleft()
            if not handle.done():
                return handle
        return None

    @staticmethod
    def _trace_dispatch(handle: TaskHandle,
                        now: float) -> "_trace.TraceContext | None":
        """The execution-side half of dispatch propagation: record a
        synthetic "task.dispatch" span covering the queue wait, and
        return the context the task body should run under (child of the
        dispatcher's span). None when the dispatcher had no trace."""
        ctx = getattr(handle, "_trace_ctx", None)
        if ctx is None:
            return None
        span_id = _trace.new_span_id()
        enqueued = getattr(handle, "_enqueued_at", None)
        wait = max(0.0, now - enqueued) if enqueued is not None else 0.0
        _trace.record_span({
            "stage": "task.dispatch",
            "seconds": wait,
            "t0": time.time() - wait,
            "trace_id": ctx.trace_id,
            "span_id": span_id,
            "parent_id": ctx.span_id,
        })
        return _trace.TraceContext(ctx.trace_id, span_id)

    async def _execute(self, handle: TaskHandle) -> None:
        task = handle.task
        now = time.monotonic()
        enqueued = getattr(handle, "_enqueued_at", None)
        if enqueued is not None:
            _tm.TASK_QUEUE_WAIT.observe(now - enqueued)
        dispatched = getattr(handle, "_dispatched_at", None)
        if dispatched is not None:
            # first execution only: a suspended/stolen task re-entering
            # would double-count its dispatch latency
            _tm.TASK_DISPATCH_LATENCY.observe(now - dispatched)
            handle._dispatched_at = None
        busy = len(self.system._running) + 1  # including us
        _tm.TASK_BATCH_OCCUPANCY.observe(busy / self.system.worker_count)
        # Trace propagation across the dispatch boundary: the worker
        # coroutine has its own contextvars, so the causality captured
        # at dispatch() rides the handle. A synthetic "task.dispatch"
        # span records the queue wait, and everything the task opens
        # nests under it via the ambient context.
        exec_ctx = self._trace_dispatch(handle, now)
        trace_token = (
            _trace.set_current(exec_ctx) if exec_ctx is not None else None
        )
        interrupter = Interrupter()
        self.current = handle
        self.current_interrupter = interrupter
        self.system._running[task.id] = self
        self.current_coro = asyncio.ensure_future(task.run(interrupter))
        try:
            status = await self.current_coro
        except asyncio.CancelledError:
            handle._resolve(TaskResult(TaskStatus.FORCED_ABORTION, task=task))
            return
        except Exception as e:  # noqa: BLE001 - task errors are data
            logger.exception("task %r failed", task)
            handle._resolve(TaskResult(TaskStatus.ERROR, error=e, task=task))
            return
        finally:
            self.current = None
            self.current_interrupter = None
            self.current_coro = None
            self.system._running.pop(task.id, None)
            if trace_token is not None:
                _trace.reset_current(trace_token)

        kind = interrupter.check()
        if status == ExecStatus.DONE:
            handle._resolve(TaskResult(TaskStatus.DONE, output=getattr(task, "output", None)))
        elif status == ExecStatus.CANCELED:
            handle._resolve(TaskResult(TaskStatus.CANCELED, task=task))
        elif status == ExecStatus.PAUSED:
            if kind == InterruptionKind.SUSPEND:
                # transparent preemption: task goes back on our queue
                handle._enqueued_at = time.monotonic()
                self.queue.append(handle)
                self.wakeup.set()
            elif kind == InterruptionKind.CANCEL:
                handle._resolve(TaskResult(TaskStatus.CANCELED, task=task))
            elif self.system._shutting_down:
                handle._resolve(TaskResult(TaskStatus.SHUTDOWN, task=task))
                self.system._shutdown_leftover.append(task)
            else:
                self.system._paused[task.id] = handle
                handle._on_paused()


class TaskSystem:
    """Dispatch tasks over `worker_count` cooperative workers.

    `dispatch` round-robins; `dispatch_many` fills least-loaded first
    (ref:system.rs:404-461). `shutdown()` pauses everything and returns
    the unfinished Task objects for persistence.
    """

    def __init__(self, worker_count: int | None = None):
        self.worker_count = worker_count or os.cpu_count() or 1
        self.workers = [_Worker(self, i) for i in range(self.worker_count)]
        self._rr = 0
        self._running: dict = {}
        self._paused: dict = {}
        self._handles: dict = {}
        self._shutdown_leftover: list[Task] = []
        self._shutting_down = False
        self._started = False
        self._procpool_held = False

    # -- lifecycle --

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        # the execute leg's escape hatch from the GIL: task bodies
        # dispatch CPU-bound stages onto the multi-process plane
        # (parallel/procpool.py — mesh shard hashing, journal match,
        # link prep, thumb software path), so the pool's lifecycle
        # rides this system's. Refcounted like the host profiler: a
        # bare TaskSystem (tests, tools) gets workers under SD_PROCS>0
        # without a Node, and a Node's own hold stacks harmlessly.
        # SD_PROCS=0: start() returns False and spawns nothing.
        from ..parallel import procpool as _procpool

        self._procpool_held = _procpool.POOL.start()
        for w in self.workers:
            w.runner = asyncio.ensure_future(w.run_loop())

    async def shutdown(self) -> list[Task]:
        """Stop workers; returns queued/paused/suspended tasks
        (ref:system.rs:224-258)."""
        self._shutting_down = True
        if self._procpool_held:
            from ..parallel import procpool as _procpool

            _procpool.POOL.stop()
            self._procpool_held = False
        for w in self.workers:
            if w.current_interrupter is not None:
                w.current_interrupter.interrupt(InterruptionKind.PAUSE)
            w.wakeup.set()
        for w in self.workers:
            if w.runner is not None:
                await w.runner
        leftover: list[Task] = list(self._shutdown_leftover)
        for w in self.workers:
            while w.queue:
                handle = w.queue.popleft()
                if not handle.done():
                    handle._resolve(TaskResult(TaskStatus.SHUTDOWN, task=handle.task))
                    leftover.append(handle.task)
        for handle in list(self._paused.values()):
            handle._resolve(TaskResult(TaskStatus.SHUTDOWN, task=handle.task))
            leftover.append(handle.task)
        self._paused.clear()
        return leftover

    # -- dispatch --

    def dispatch(self, task: Task) -> TaskHandle:
        self.start()
        handle = TaskHandle(task, self)
        handle._dispatched_at = time.monotonic()
        # batches carry the trace of the caller that coalesced them;
        # the worker re-installs it before running the task body
        handle._trace_ctx = _trace.current()
        _tm.TASKS_DISPATCHED.inc()
        self._handles[task.id] = handle
        worker = self.workers[self._rr % self.worker_count]
        self._rr += 1
        worker.enqueue(handle)
        return handle

    def dispatch_many(self, tasks: Iterable[Task]) -> list[TaskHandle]:
        self.start()
        handles = []
        now = time.monotonic()
        ctx = _trace.current()
        for task in tasks:
            handle = TaskHandle(task, self)
            handle._dispatched_at = now
            handle._trace_ctx = ctx
            _tm.TASKS_DISPATCHED.inc()
            self._handles[task.id] = handle
            min(self.workers, key=lambda w: w.load()).enqueue(handle)
            handles.append(handle)
        return handles

    # -- stealing --

    def _steal(self, thief_index: int) -> TaskHandle | None:
        donors = sorted(
            (w for w in self.workers if w.index != thief_index),
            key=lambda w: len(w.queue),
            reverse=True,
        )
        for donor in donors:
            handle = donor.steal_from()
            if handle is not None:
                # the local mirror of the mesh plane's
                # sd_work_steals_total: how often workers rebalance —
                # persistent zero under load means queues never skew
                # (or dispatch_many is doing the leveling alone)
                _tm.TASK_STEALS.inc()
                logger.debug("worker %d stole %r from %d", thief_index, handle.task, donor.index)
                return handle
        return None

    # -- control plane (used by TaskHandle) --

    async def _interrupt(self, task_id, kind: InterruptionKind) -> None:
        worker = self._running.get(task_id)
        if worker is not None and worker.current_interrupter is not None:
            worker.current_interrupter.interrupt(kind)
            return
        # not running: find it queued or paused
        handle = self._paused.pop(task_id, None)
        if handle is not None:
            if kind == InterruptionKind.CANCEL:
                handle._resolve(TaskResult(TaskStatus.CANCELED, task=handle.task))
            else:
                self._paused[task_id] = handle
            return
        for w in self.workers:
            for handle in list(w.queue):
                if handle.task.id == task_id:
                    w.queue.remove(handle)
                    if kind == InterruptionKind.CANCEL:
                        handle._resolve(TaskResult(TaskStatus.CANCELED, task=handle.task))
                    else:
                        self._paused[task_id] = handle
                        handle._on_paused()
                    return

    async def _resume(self, task_id) -> None:
        handle = self._paused.pop(task_id, None)
        if handle is not None:
            handle._paused_event.clear()
            min(self.workers, key=lambda w: w.load()).enqueue(handle)

    async def _force_abort(self, task_id) -> None:
        worker = self._running.get(task_id)
        if worker is not None and worker.current_coro is not None:
            worker.current_coro.cancel()
            return
        await self._interrupt(task_id, InterruptionKind.CANCEL)

    # -- introspection --

    def pending_count(self) -> int:
        return sum(w.load() for w in self.workers) + len(self._paused)
