"""`python -m spacedrive_tpu` → the sdx CLI."""

import sys

from .cli import main

sys.exit(main())
