"""Persistent telemetry history — sampled time-series that survive
restarts.

Every metric in the registry dies with the process; every federation
snapshot ages out of the cache in a minute. That makes "is sync lag
getting worse week over week?" unanswerable — exactly the question the
SLO burn-rate engine (``telemetry/slo.py``) and the perf-trajectory
gate (``tools/bench_compare.py``) need answered. This module is the
smallest durable answer:

- a :class:`HistoryWriter` samples a configurable **allowlist** of
  derived series (sync lag, observed files/s, interactive p99,
  protected-shed counters, autotune knobs — see
  :func:`default_samplers`) every ``SD_HISTORY_INTERVAL_S`` seconds
  into an **append-only segment store** under
  ``<data_dir>/telemetry_history/``;
- segments are JSON-lines files named by their first sample's epoch
  (``seg-<epoch>.jsonl``) — append-only, so a crash mid-write costs at
  most one truncated line (the reader skips it);
- **retention**: oldest segments are deleted past a byte budget;
  **downsampling**: segments older than a horizon are compacted K:1
  (mean over each K-record stripe, min/max preserved) so a month of
  history costs kilobytes, not the raw sample stream;
- the writer keeps a bounded **in-memory tail** of recent samples — the
  SLO evaluator's fast read path (no disk I/O per ``GET /health``).
  ``telemetry.reset()`` clears tails (test isolation) without touching
  the durable segments.

Reading is process-independent: :func:`read` merges segments in time
order, so ``sdx slo`` and ``tools/bench_compare.py`` can gate against a
node's history from outside the node process — and a node restarted on
the same data dir continues the same series.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Iterable

DIR_NAME = "telemetry_history"

DEFAULT_INTERVAL_S = 10.0
SEGMENT_MAX_RECORDS = 512       # rotate after this many samples
RETENTION_BYTES = 4 << 20       # delete oldest segments past this
DOWNSAMPLE_AFTER_S = 24 * 3600.0  # compact segments older than this
DOWNSAMPLE_STRIDE = 8           # K:1 compaction
TAIL_SAMPLES = 720              # in-memory tail (~2 h at 10 s)

#: every live writer, so telemetry.reset() can clear in-memory tails
#: without the registry knowing about node lifecycles
_writers: "weakref.WeakSet[HistoryWriter]" = weakref.WeakSet()


def history_dir(data_dir: str | os.PathLike) -> str:
    return os.path.join(os.fspath(data_dir), DIR_NAME)


def enabled() -> bool:
    return os.environ.get("SD_HISTORY", "1") != "0"


def interval_s() -> float:
    try:
        return max(0.05, float(os.environ.get("SD_HISTORY_INTERVAL_S",
                                              str(DEFAULT_INTERVAL_S))))
    except ValueError:
        return DEFAULT_INTERVAL_S


# --- the default metric allowlist ---------------------------------------


def _p99(samples: list[float]) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def default_samplers() -> dict[str, Callable[[], float]]:
    """The sampled allowlist. Counters are recorded CUMULATIVE (readers
    diff); gauges/derived rates are instantaneous. Every sampler is a
    cheap registry read — the writer must never become the load."""
    from ..parallel import autotune as _autotune
    from .registry import REGISTRY
    from .snapshot import counter_value, gauge_value, histogram_recent

    def sync_lag_max() -> float:
        fam = REGISTRY.get("sd_sync_lag_seconds")
        if fam is None:
            return 0.0
        with fam._lock:
            vals = [s.value for s in fam._series.values()]
        return max(vals, default=0.0)

    def protected_sheds() -> float:
        return (
            counter_value("sd_gate_requests_total",
                          klass="control", outcome="shed")
            + counter_value("sd_gate_requests_total",
                            klass="sync", outcome="shed")
        )

    from . import sampler as _sampler

    def profile_share(group: str) -> Callable[[], float]:
        def read() -> float:
            return _sampler.SAMPLER.group_shares().get(group, 0.0)

        return read

    samplers: dict[str, Callable[[], float]] = {
        # cumulative top-frame-group shares from the host profiler —
        # the continuous record bench_compare gates attribution drift
        # against (a pass whose sql share doubles week-over-week fails
        # even if no bench round ran in between)
        f"profile_share_{g}": profile_share(g)
        for g in _sampler.HISTORY_GROUPS
    }
    samplers.update({
        "files_per_s": lambda: _autotune.observed_files_per_s("identify")
        or 0.0,
        "sync_lag_max_s": sync_lag_max,
        "interactive_p99_ms": lambda: _p99(
            histogram_recent("sd_serve_request_seconds", klass="interactive")
        ) * 1e3,
        "goodput_admitted_total": lambda: sum(
            counter_value("sd_gate_requests_total", klass=k,
                          outcome="admitted")
            for k in ("interactive", "background")
        ),
        "protected_sheds_total": protected_sheds,
        "event_loop_lag_s": lambda: gauge_value("sd_event_loop_lag_seconds"),
        "breaker_open": lambda: gauge_value("sd_breaker_open"),
        "autotune_window_scale": lambda: gauge_value(
            "sd_autotune_window_scale", workload="identify"),
        "autotune_batch_rung": lambda: gauge_value(
            "sd_autotune_batch_rung", workload="identify"),
    })
    from . import resources as _resources

    if _resources.enabled():
        # growth surfaces for the trend SLO class — gated so
        # SD_RESOURCES=0 leaves the sampled allowlist (and every
        # history record) byte-identical to a pre-resources node
        samplers.update({
            "resource_rss_mb": lambda: gauge_value(
                "sd_resource_rss_bytes") / 1e6,
            "resource_fds": lambda: gauge_value("sd_resource_fds"),
            "resource_threads": lambda: gauge_value(
                "sd_resource_threads"),
            "resource_journal_rows": lambda: gauge_value(
                "sd_resource_inventory", kind="journal_rows"),
            "resource_oplog_rows": lambda: gauge_value(
                "sd_resource_inventory", kind="oplog_rows"),
            "resource_history_bytes": lambda: gauge_value(
                "sd_resource_inventory", kind="history_bytes"),
        })
    from . import tenants as _tenants

    if _tenants.enabled():
        # fairness surfaces for the tenant_fairness SLO — gated so
        # SD_TENANT_OBS=0 leaves the sampled allowlist (and every
        # history record) byte-identical to a pre-tenants node
        samplers.update({
            "tenant_fairness_index": _tenants.fairness_index,
            "tenant_dominant_share": _tenants.dominant_share,
        })
    return samplers


# --- the writer ----------------------------------------------------------


class HistoryWriter:
    """Owns one node's history directory: samples on a timer (started/
    stopped with the node), rotates/retains/downsamples segments, and
    keeps the in-memory tail the SLO evaluator reads."""

    def __init__(self, directory: str,
                 samplers: dict[str, Callable[[], float]] | None = None,
                 *,
                 segment_max_records: int = SEGMENT_MAX_RECORDS,
                 retention_bytes: int = RETENTION_BYTES,
                 downsample_after_s: float = DOWNSAMPLE_AFTER_S):
        self.dir = os.fspath(directory)
        self._samplers = samplers
        self.segment_max_records = segment_max_records
        self.retention_bytes = retention_bytes
        self.downsample_after_s = downsample_after_s
        self.tail: deque[dict[str, Any]] = deque(maxlen=TAIL_SAMPLES)
        # short-TTL memo for the disk fallback of recent(): until the
        # in-memory tail spans the asked window (cold start, right
        # after a restart) every /health hit would otherwise re-parse
        # the whole segment store
        self._disk_memo: tuple[float, float, list] | None = None
        self._lock = threading.Lock()
        # the tail is appended from the to_thread sampler and iterated
        # on the event loop (health/SLO reads) — deque iteration during
        # mutation raises, so every touch goes through this cheap lock
        # (separate from _lock, which is held across file writes)
        self._tail_lock = threading.Lock()
        self._seg_path: str | None = None
        self._seg_records = 0
        self._task: Any = None
        self._tasks: set = set()
        self._stopped = False
        _writers.add(self)

    def _sampler_map(self) -> dict[str, Callable[[], float]]:
        if self._samplers is None:
            self._samplers = default_samplers()
        return self._samplers

    # -- sampling ---------------------------------------------------------

    def sample(self, now: float | None = None) -> dict[str, Any]:
        """Take one sample: read every allowlisted series, append the
        record to the current segment, and push it onto the tail.
        Individual sampler failures degrade to absent keys — one broken
        series must not stop the history of the others."""
        rec: dict[str, Any] = {"ts": now if now is not None else time.time()}
        values: dict[str, float] = {}
        for name, fn in self._sampler_map().items():
            try:
                values[name] = round(float(fn()), 6)
            except Exception:  # noqa: BLE001 - samplers degrade, never fail
                continue
        rec["v"] = values
        self._append(rec)
        with self._tail_lock:
            self.tail.append(rec)
        _tm_samples_inc()
        return rec

    def _append(self, rec: dict[str, Any]) -> None:
        with self._lock:
            os.makedirs(self.dir, exist_ok=True)
            if (self._seg_path is None
                    or self._seg_records >= self.segment_max_records):
                self._rotate(rec["ts"])
            assert self._seg_path is not None
            with open(self._seg_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            self._seg_records += 1

    def _rotate(self, ts: float) -> None:
        """Open a fresh segment; then (best-effort) downsample old
        segments and enforce the retention budget. Caller holds the
        lock."""
        self._seg_path = os.path.join(
            self.dir, f"seg-{int(ts * 1000):015d}.jsonl"
        )
        self._seg_records = 0
        try:
            self._downsample()
            self._retain()
        except OSError:  # maintenance must never block sampling
            pass

    def _segments(self) -> list[str]:
        try:
            names = sorted(
                n for n in os.listdir(self.dir)
                if n.startswith("seg-") and n.endswith(".jsonl")
            )
        except OSError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def _downsample(self) -> None:
        horizon = time.time() - self.downsample_after_s
        for path in self._segments():
            if path == self._seg_path:
                continue
            recs = _read_segment(path)
            if not recs or recs[-1].get("ts", 0.0) > horizon:
                continue
            if all(r.get("ds") for r in recs):
                continue  # already compacted: rewriting it is pure I/O
            out = _downsample_records(recs, DOWNSAMPLE_STRIDE)
            ds_path = path[: -len(".jsonl")] + ".ds.jsonl"
            with open(ds_path, "w", encoding="utf-8") as f:
                for rec in out:
                    f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            os.replace(ds_path, path)

    def _retain(self) -> None:
        paths = self._segments()
        total = sum(os.path.getsize(p) for p in paths if os.path.exists(p))
        # oldest-first deletion, never the live segment
        for path in paths:
            if total <= self.retention_bytes:
                break
            if path == self._seg_path:
                continue
            try:
                size = os.path.getsize(path)
                os.remove(path)
            except OSError:
                continue  # size NOT deducted: the bytes are still there
            total -= size

    # -- read paths -------------------------------------------------------

    def recent(self, seconds: float, now: float | None = None) \
            -> list[dict[str, Any]]:
        """Samples within the window, tail-first (no disk I/O when the
        tail covers it — the per-/health SLO read path), falling back
        to the segment store for windows longer than the tail."""
        now = now if now is not None else time.time()
        since = now - seconds
        with self._tail_lock:
            tail_all = list(self.tail)
        tail = [r for r in tail_all if r.get("ts", 0.0) >= since]
        if tail_all and tail_all[0].get("ts", float("inf")) <= since:
            return tail
        memo = self._disk_memo
        if memo is not None and memo[0] <= since \
                and time.monotonic() - memo[1] < 5.0:
            disk = memo[2]
        else:
            disk = read(self.dir, since=since)
            self._disk_memo = (since, time.monotonic(), disk)
        # merge: disk records from BEFORE the tail's coverage (older
        # generations, pre-reset samples) + the always-fresh tail — a
        # memoized disk read can never hide the newest samples
        tail_start = tail_all[0].get("ts", 0.0) if tail_all \
            else float("inf")
        older = [
            r for r in disk
            if since <= r.get("ts", 0.0) <= now
            and r.get("ts", 0.0) < tail_start
        ]
        return older + tail

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        """Begin periodic sampling on the running loop (Node.start)."""
        import asyncio
        import logging

        from ..utils.tasks import supervise

        if not enabled():
            return
        if self._task is not None and not self._task.done():
            return
        self._stopped = False
        self._task = supervise(
            asyncio.get_running_loop().create_task(self._run()),
            self._tasks, logging.getLogger(__name__), "telemetry history",
        )

    async def stop(self) -> None:
        self._stopped = True
        task = self._task
        self._task = None
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except BaseException:  # noqa: BLE001 - cancellation cleanup
                pass

    async def _run(self) -> None:
        import asyncio

        # prime the tail from the previous generation's segments (off
        # the loop): without this, every /health or federation read in
        # the first tail-window after a restart would fall back to a
        # synchronous full-store parse on the event loop
        await asyncio.to_thread(self._prime_tail)
        while not self._stopped:
            await asyncio.sleep(interval_s())
            if self._stopped:
                return
            # registry reads are lock-cheap; file append is small — but
            # keep the disk write off the loop anyway (a slow disk is
            # exactly the incident history must survive recording)
            await asyncio.to_thread(self.sample)

    def _prime_tail(self) -> None:
        with self._tail_lock:
            if self.tail:
                return
            recs = read(self.dir)
            for rec in recs[-(self.tail.maxlen or TAIL_SAMPLES):]:
                self.tail.append(rec)

    def reset_tail(self) -> None:
        with self._tail_lock:
            self.tail.clear()


def _tm_samples_inc() -> None:
    from . import metrics as _tm

    _tm.HISTORY_SAMPLES.inc()


# --- reading (process-independent) ---------------------------------------


def _read_segment(path: str) -> list[dict[str, Any]]:
    out: list[dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail line from a crash mid-append
                if isinstance(rec, dict) and "ts" in rec:
                    out.append(rec)
    except OSError:
        return []
    return out


def read(directory: str, *, since: float | None = None,
         until: float | None = None,
         names: Iterable[str] | None = None) -> list[dict[str, Any]]:
    """All samples in time order across every segment (restart
    boundaries included — that is the point). ``names`` filters the
    value dict of each record."""
    directory = os.fspath(directory)
    try:
        seg_names = sorted(
            n for n in os.listdir(directory)
            if n.startswith("seg-") and n.endswith(".jsonl")
        )
    except OSError:
        return []
    if since is not None and len(seg_names) > 1:
        # segment names encode their first sample's epoch-ms: a segment
        # whose SUCCESSOR starts before `since` cannot hold any record
        # in the window — skip parsing it (an SLO window read over a
        # mature store touches one or two segments, not all of them)
        def start_of(name: str) -> float:
            try:
                return int(name[len("seg-"):-len(".jsonl")]) / 1000.0
            except ValueError:
                return float("-inf")  # odd name: never pruned

        keep_from = 0
        for i in range(1, len(seg_names)):
            if start_of(seg_names[i]) <= since:
                keep_from = i
        seg_names = seg_names[keep_from:]
    out: list[dict[str, Any]] = []
    keep = set(names) if names is not None else None
    for name in seg_names:
        for rec in _read_segment(os.path.join(directory, name)):
            ts = rec.get("ts", 0.0)
            if since is not None and ts < since:
                continue
            if until is not None and ts > until:
                continue
            if keep is not None:
                rec = dict(rec, v={
                    k: v for k, v in (rec.get("v") or {}).items()
                    if k in keep
                })
            out.append(rec)
    out.sort(key=lambda r: r.get("ts", 0.0))
    return out


def series(directory: str, name: str, *, since: float | None = None,
           until: float | None = None) -> list[tuple[float, float]]:
    """One named series as (ts, value) pairs — the bench_compare read
    path."""
    out: list[tuple[float, float]] = []
    for rec in read(directory, since=since, until=until, names=(name,)):
        v = (rec.get("v") or {}).get(name)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out.append((rec["ts"], float(v)))
    return out


def _downsample_records(recs: list[dict[str, Any]],
                        stride: int) -> list[dict[str, Any]]:
    """K:1 mean compaction preserving per-stripe min/max (so an old
    spike survives downsampling as ``<name>__max``)."""
    out: list[dict[str, Any]] = []
    for i in range(0, len(recs), stride):
        stripe = recs[i:i + stride]
        if not stripe:
            continue
        if len(stripe) == 1 or stripe[0].get("ds"):
            out.extend(stripe)
            continue
        names: set[str] = set()
        for r in stripe:
            names |= set((r.get("v") or {}).keys())
        v: dict[str, float] = {}
        for n in names:
            vals = [
                r["v"][n] for r in stripe
                if isinstance((r.get("v") or {}).get(n), (int, float))
                and not isinstance(r["v"][n], bool)
            ]
            if not vals:
                continue
            v[n] = round(sum(vals) / len(vals), 6)
            v[n + "__min"] = round(min(vals), 6)
            v[n + "__max"] = round(max(vals), 6)
        out.append({
            "ts": stripe[0]["ts"],
            "ts_end": stripe[-1]["ts"],
            "n": len(stripe),
            "ds": True,
            "v": v,
        })
    return out


def reset_tails() -> None:
    """Clear every live writer's in-memory tail (telemetry.reset());
    durable segments are deliberately untouched — they are data-dir
    state, not process state."""
    for w in list(_writers):
        w.reset_tail()
