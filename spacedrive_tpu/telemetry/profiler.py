"""Optional ``jax.profiler`` hooks around the pipeline driver.

When ``SD_JAX_PROFILE=<logdir>`` is set, the identify pipeline wraps
its run in ``jax.profiler.start_trace``/``stop_trace`` so device-side
traces (XLA ops, transfers) land next to the host-side Chrome trace
this subsystem exports. Everything here is no-op-safe: unset env, a
missing/CPU-only jax, or a profiler that refuses to start all degrade
to "no profile", never to a failed job. Start/stop is refcounted so
overlapping drivers (indexer chain + a watcher rescan) share one
profiler session instead of crashing on double-start.
"""

from __future__ import annotations

import logging
import os
import threading

logger = logging.getLogger(__name__)

ENV_VAR = "SD_JAX_PROFILE"

_lock = threading.Lock()
_depth = 0
_active_dir: str | None = None


def profile_start(tag: str = "pipeline") -> bool:
    """Begin (or join) a device profile session. Returns True when a
    session is active after the call."""
    global _depth, _active_dir
    logdir = os.environ.get(ENV_VAR)
    if not logdir:
        return False
    with _lock:
        if _depth > 0:
            _depth += 1
            return True
        try:
            import jax

            jax.profiler.start_trace(os.path.join(logdir, tag))
        except Exception as e:  # noqa: BLE001 - profiling is best-effort
            logger.debug("jax profiler start failed: %s", e)
            return False
        _depth = 1
        _active_dir = logdir
        logger.info("jax profiler tracing into %s", logdir)
        return True


def profile_stop() -> None:
    """Release one hold on the session; the last release stops it."""
    global _depth, _active_dir
    with _lock:
        if _depth == 0:
            return
        _depth -= 1
        if _depth > 0:
            return
        _active_dir = None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 - profiling is best-effort
            logger.debug("jax profiler stop failed: %s", e)


def profiling_active() -> bool:
    with _lock:
        return _depth > 0
