"""Peer-identity metric labels — capped, stable short-hashes.

Raw instance/peer identifiers (uuid4 pub_ids, ed25519 identity
strings) must NEVER ride metric labels: every new peer would mint a
fresh series until the family's cardinality cap silently folds samples
into ``__overflow__``, and the label itself would leak a long-lived
identifier into every scrape. ``peer_label`` is the one sanctioned
mapping: a stable 8-hex-char BLAKE2 digest of the identifier —

- stable: the same instance hashes to the same label across restarts,
  so dashboards and alerts can track one replica over time;
- capped: 8 hex chars bound the label length, and the per-family series
  cap (``registry.MAX_SERIES_PER_FAMILY``) bounds the count — a mesh
  larger than the cap degrades to ``__overflow__`` instead of eating
  memory;
- non-reversible: a scrape consumer learns "some peer", not which
  ed25519 identity (mesh-level correlation needs the /mesh surface,
  which maps labels back to peers explicitly for operators).

sdlint SD010 enforces adoption: any metric label fed from a
peer/instance-shaped value that is not wrapped in ``peer_label`` is a
lint error.
"""

from __future__ import annotations

import hashlib
import uuid
from typing import Any

PEER_LABEL_HEX_CHARS = 8


def peer_label(peer_id: Any) -> str:
    """The metric-label form of a peer/instance identifier.

    Accepts a ``uuid.UUID`` (instance pub_id), ``bytes`` (raw pub_id /
    identity key), or any object whose ``str()`` names the peer (a
    ``RemoteIdentity``). Returns a stable 8-hex-char digest.
    """
    if isinstance(peer_id, uuid.UUID):
        raw = peer_id.bytes
    elif isinstance(peer_id, (bytes, bytearray)):
        raw = bytes(peer_id)
    else:
        raw = str(peer_id).encode()
    return hashlib.blake2b(raw, digest_size=8).hexdigest()[:PEER_LABEL_HEX_CHARS]
