"""Telemetry federation — pull-aggregating peer snapshots across the mesh.

After PR 3 every metric, span, and ring was strictly node-local; this
module is the cross-node half: each node can serve a **compact,
versioned snapshot** of its own health (over the P2P ``TELEMETRY``
wire request, or pushed to / pulled from the cloud relay for peers
with no direct route), and a ``FederationCache`` on the asking node
holds the freshest snapshot per peer with explicit staleness tracking
— Prometheus-federation-style pull aggregation, sized for a personal
mesh rather than a Monarch deployment.

Staleness rules (the contract ``GET /mesh`` exposes):

- a snapshot is **fresh** while its age is under ``STALE_AFTER``
  seconds; the cache re-pulls a peer only when its snapshot is older
  than ``REFRESH_INTERVAL`` (pull-through, so a burst of /mesh hits
  doesn't stampede the mesh);
- past ``STALE_AFTER`` the entry is **stale** and the peer's mesh
  verdict becomes ``unhealthy`` regardless of what the old snapshot
  claimed — a peer we cannot hear from is a peer we must assume sick;
- pull failures keep the last snapshot (aging toward stale) and record
  the error, so the operator sees *both* "last known state" and "we
  can't reach it anymore".

The snapshot deliberately carries metric *values* (counters/gauges,
histogram sum+count), health verdicts, replication watermarks, and
event-ring digests — never raw ring payloads or span bodies. Those
stay on the owning node and travel only inside an explicitly requested
(and locally redacted) debug bundle.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from . import metrics as _tm
from .events import all_events
from .peers import peer_label
from .registry import REGISTRY, Histogram

SNAPSHOT_VERSION = 1
STALE_AFTER = 60.0       # seconds until a cached snapshot counts as stale
REFRESH_INTERVAL = 5.0   # min age before the cache re-pulls a peer


# --- the local snapshot (what a node serves about itself) ---------------


def _compact_metrics() -> dict[str, Any]:
    """Counter/gauge values and histogram sum+count per series, keyed
    ``name{label=value,...}`` — the smallest shape that still lets the
    mesh view answer 'how much' questions without shipping buckets."""
    out: dict[str, Any] = {}
    with REGISTRY._lock:
        for name, fam in REGISTRY._families.items():
            series: dict[str, Any] = {}
            for key, s in fam._series.items():
                labelstr = ",".join(
                    f"{n}={v}" for n, v in zip(fam.label_names, key)
                )
                if isinstance(fam, Histogram):
                    series[labelstr] = {"sum": s.sum, "count": s.count}
                else:
                    series[labelstr] = s.value
            out[name] = series
    return out


def _ring_digests() -> dict[str, Any]:
    """Per-ring length, newest timestamp, and type counts — enough to
    see 'the error ring is filling with watcher failures' from across
    the mesh without shipping payloads (which may embed paths or
    messages that only the owning node's bundle redaction may touch)."""
    from .events import drop_counts

    drops = drop_counts()
    out: dict[str, Any] = {}
    for ring_name, events in all_events().items():
        types: dict[str, int] = {}
        for e in events:
            t = str(e.get("type", "?"))
            types[t] = types.get(t, 0) + 1
        out[ring_name] = {
            "len": len(events),
            "last_ts": events[-1].get("ts") if events else None,
            "types": types,
        }
        if drops.get(ring_name):
            # overflow honesty crosses the mesh too: a saturated ring
            # on a peer should read as "suffix", not "quiet"
            out[ring_name]["dropped"] = drops[ring_name]
    return out


def local_snapshot(node: Any = None) -> dict[str, Any]:
    """The compact, versioned self-snapshot a node serves to the mesh
    (P2P TELEMETRY responder, relay push, and the ``local`` half of
    ``GET /mesh``). With a serve runtime the computation rides a short
    TTL cache: it walks every metric family, refreshes per-peer lag
    gauges, and runs the journal's ``location_stats()`` — dashboard
    polls and TELEMETRY responders inside one window cost ONE
    computation instead of N (treat the returned dict as read-only)."""
    if node is not None:
        from ..serve import runtime_for

        serve = runtime_for(node)
        if serve is not None:
            return serve.meta.get_sync(
                ("local_snapshot",),
                lambda: _local_snapshot(node),
                ttl_s=serve.policy.snapshot_ttl_s,
            )
    return _local_snapshot(node)


def _local_snapshot(node: Any = None) -> dict[str, Any]:
    from . import health as _health
    from . import sampler as _sampler
    from . import tenants as _tenants

    snap: dict[str, Any] = {
        "v": SNAPSHOT_VERSION,
        "ts": time.time(),
        "health": _health.evaluate(node),
        "metrics": _compact_metrics(),
        "rings": _ring_digests(),
        # host-profiler digest (totals, state split, top frame groups,
        # capture count) — like ring digests, never stacks or payloads:
        # those stay on the owning node behind an explicit profile pull
        "profile": _sampler.SAMPLER.summary(),
    }
    if _tenants.enabled():
        # per-tenant heavy-hitter digest (hashed labels, a few numbers
        # per surface) so every peer's /mesh shows who is spending
        # each shared surface mesh-wide; gated so SD_TENANT_OBS=0
        # keeps the snapshot shape identical to a pre-tenants node
        snap["tenants"] = _tenants.digest()
    if node is not None:
        cfg = node.config.config
        libraries: dict[str, Any] = {}
        for lib in getattr(getattr(node, "libraries", None), "libraries",
                           {}).values():
            try:
                from ..location.indexer.journal import IndexJournal

                libraries[str(lib.id)] = {
                    "name": lib.name,
                    "instance_label": peer_label(lib.sync.instance),
                    # library head: the newest HLC this node has seen
                    # (created or applied) — peers compare it against
                    # their own head to measure real replication gaps
                    # (telemetry.health._replication_gaps)
                    "head_seconds": lib.sync.clock.peek_last().as_unix(),
                    "watermarks": lib.sync.replication_watermarks(),
                    "lag_seconds": lib.sync.observe_replication_lag(),
                    # per-location index-journal effectiveness (entry
                    # counts from the DB, hit rate / bytes saved from
                    # this process) — the warm-pass story, mesh-wide
                    "index_journal": IndexJournal(lib.db).location_stats(),
                }
            except Exception:  # noqa: BLE001 - snapshots degrade, never fail
                libraries[str(lib.id)] = {"name": getattr(lib, "name", "?")}
        snap["node"] = {
            "id": str(cfg.id),
            "name": cfg.name,
            "libraries": libraries,
        }
    return snap


def snapshot_compatible(snap: Any) -> bool:
    """Versioned-decode guard: a peer running a newer wire revision
    may serve a shape we cannot interpret — treat as no snapshot."""
    return isinstance(snap, dict) and snap.get("v") == SNAPSHOT_VERSION


# --- the per-peer cache (what a node knows about everyone else) ---------


class FederationCache:
    """Freshest-known snapshot per peer + staleness bookkeeping.

    Keys are opaque peer ids chosen by the puller (the P2P
    ``RemoteIdentity`` string for direct peers, ``instance:<uuid>`` for
    relay-only instances). ``mesh()`` is the read path behind
    ``GET /mesh`` / rspc ``telemetry.mesh`` / ``sdx mesh-status``.
    """

    def __init__(self, stale_after: float = STALE_AFTER,
                 refresh_interval: float = REFRESH_INTERVAL):
        self.stale_after = stale_after
        self.refresh_interval = refresh_interval
        self._lock = threading.Lock()
        self._peers: dict[str, dict[str, Any]] = {}

    def store(self, peer_id: str, snapshot: dict[str, Any],
              transport: str = "p2p", age_seconds: float = 0.0) -> None:
        """A successful pull: remember the snapshot and when we got it.
        ``age_seconds`` backdates relayed copies — a snapshot that sat
        on the relay for a minute is already a minute old, and must go
        stale on the same clock as a direct pull would. A backdated (or
        late-arriving) copy never replaces a FRESHER one: a stale relay
        row must not mark a peer unhealthy seconds after a direct P2P
        pull proved it alive."""
        fetched_at = time.time() - max(0.0, float(age_seconds))
        _tm.FED_PULLS.inc(result="relay" if transport == "relay" else "p2p")
        with self._lock:
            entry = self._peers.setdefault(str(peer_id), {})
            if entry.get("fetched_at", float("-inf")) > fetched_at:
                return
            entry.update(
                snapshot=snapshot,
                fetched_at=fetched_at,
                transport=transport,
                error=None,
            )

    def record_failure(self, peer_id: str, error: str) -> None:
        """A failed pull: keep the last snapshot (aging), note the error."""
        with self._lock:
            entry = self._peers.setdefault(str(peer_id), {})
            entry["error"] = str(error)[:300]
            entry["failed_at"] = time.time()
        _tm.FED_PULLS.inc(result="error")

    def fresh_snapshots(self) -> dict[str, dict[str, Any]]:
        """Snapshot per peer, restricted to entries younger than the
        staleness horizon — the corroboration source for health's
        replication-gap verdicts (a stale snapshot must not vouch for
        anything)."""
        now = time.time()
        with self._lock:
            return {
                pid: entry["snapshot"]
                for pid, entry in self._peers.items()
                if entry.get("snapshot") is not None
                and entry.get("fetched_at") is not None
                and now - entry["fetched_at"] < self.stale_after
            }

    def needs_refresh(self, peer_id: str) -> bool:
        with self._lock:
            entry = self._peers.get(str(peer_id))
            if entry is None or "fetched_at" not in entry:
                return True
            return time.time() - entry["fetched_at"] >= self.refresh_interval

    def due_relay_peers(self) -> list[str]:
        """Peers we only know through the relay whose snapshot has aged
        past the refresh interval — the signal that a relay exchange is
        worth its HTTP round-trips on an otherwise-quiet refresh."""
        with self._lock:
            pids = [
                pid for pid, entry in self._peers.items()
                if entry.get("transport") == "relay"
            ]
        return [pid for pid in pids if self.needs_refresh(pid)]

    def forget(self, peer_id: str) -> None:
        with self._lock:
            self._peers.pop(str(peer_id), None)

    def clear(self) -> None:
        with self._lock:
            self._peers.clear()

    def mesh(self) -> dict[str, Any]:
        """Per-peer view: snapshot + age + staleness + rolled verdict.
        A stale peer is verdict-``unhealthy`` no matter how healthy its
        last snapshot looked — silence is a symptom."""
        from .health import UNHEALTHY, UNKNOWN

        now = time.time()
        with self._lock:
            items = [(pid, dict(entry)) for pid, entry in self._peers.items()]
        peers: dict[str, Any] = {}
        fresh_n = stale_n = 0
        for pid, entry in items:
            snap = entry.get("snapshot")
            fetched_at = entry.get("fetched_at")
            age = (now - fetched_at) if fetched_at is not None else None
            stale = age is None or age >= self.stale_after
            if stale:
                stale_n += 1
            else:
                fresh_n += 1
            if snap is not None:
                own = snap.get("health", {}).get("status", UNKNOWN)
            else:
                own = UNKNOWN
            verdict = UNHEALTHY if stale else own
            label = peer_label(pid)
            # the JOIN KEY between this mesh view and the per-peer sync
            # metric series: sync labels hash the instance pub_id, not
            # the transport identity this cache keys by — surface each
            # snapshot's instance labels so operators (and dashboards)
            # can correlate sd_sync_lag_seconds{peer=...} with a peer
            # entry without reversing any hash
            instance_labels = sorted({
                lib.get("instance_label")
                for lib in ((snap or {}).get("node") or {})
                .get("libraries", {}).values()
                if isinstance(lib, dict) and lib.get("instance_label")
            })
            peers[pid] = {
                "peer_label": label,
                "instance_labels": instance_labels,
                "age_seconds": age,
                "stale": stale,
                "verdict": verdict,
                "transport": entry.get("transport"),
                "error": entry.get("error"),
                "snapshot": snap,
            }
            if age is not None:
                _tm.FED_SNAPSHOT_AGE.set(age, peer=label)
        _tm.FED_PEERS.set(fresh_n, state="fresh")
        _tm.FED_PEERS.set(stale_n, state="stale")
        return {
            "ts": now,
            "stale_after_seconds": self.stale_after,
            "peers": peers,
        }


def mesh_status(node: Any) -> dict[str, Any]:
    """The full ``GET /mesh`` payload: this node's own snapshot plus
    the federation cache's view of everyone else."""
    p2p = getattr(node, "p2p", None)
    cache: FederationCache | None = getattr(p2p, "federation", None)
    return {
        "local": local_snapshot(node),
        "mesh": cache.mesh() if cache is not None else {"peers": {}},
    }


async def mesh_status_cached(
    node: Any, *, refresh: bool = True, force: bool = False,
) -> dict[str, Any]:
    """``GET /mesh`` / rspc ``telemetry.mesh`` read path: the federation
    refresh + snapshot computation behind the serve cache's
    single-flight, so N concurrent dashboards cost one refresh round
    and one ``mesh_status`` walk per TTL window. ``force`` coalesces
    concurrent callers but never serves a stored view; without a serve
    runtime this is exactly the pre-serve direct path."""
    from ..serve import runtime_for

    async def load() -> dict[str, Any]:
        p2p = getattr(node, "p2p", None)
        if p2p is not None and refresh:
            await p2p.refresh_federation(force=force)
        return mesh_status(node)

    serve = runtime_for(node)
    if serve is None:
        return await load()
    result = await serve.meta.get(
        ("mesh", bool(refresh), bool(force)),
        load,
        ttl_s=0.0 if force else serve.policy.mesh_ttl_s,
        stale_ok=serve.gate.in_brownout(),
    )
    return result.value
