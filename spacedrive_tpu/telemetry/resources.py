"""Resource-growth observability — the slow leaks a bench never sees.

Every bench in this repo runs seconds; every leak that matters runs
hours. A stranded fd per pass, an index-journal that tracks pass count
instead of corpus size, a serve cache whose weight accounting drifts —
none of them move a files/s headline, all of them kill a node at
production scale. This module is the instrument: a low-rate resource
sampler (refcounted with the Node like the host profiler in
``telemetry/sampler.py``) reading the process's own growth surfaces
and publishing them as ``sd_resource_*`` gauges:

- ``/proc/self`` facts: RSS bytes, open-fd count, OS thread count
  (portable fallbacks where /proc is absent);
- procpool worker RSS summed over the multi-process execution plane's
  live workers (``/proc/<pid>/statm``);
- in-process inventories over a **fixed kind vocabulary**
  (:data:`INVENTORY_KINDS`): index-journal and op-log row counts,
  serve-cache entries/bytes, history-store bytes — registered by the
  Node as providers because they need node state — plus the built-in
  flight-ring drop total.

The history writer samples the gauges into the persistent store
(``resource_*`` series, ``telemetry/history.py``), where the **trend
SLO class** (``telemetry/slo.py``, ``kind="trend"``) judges bounded
growth slopes over sliding windows: RSS ≤ X MB/h after warmup, fd
count flat. A trend breach flips the ``resources`` health subsystem
unhealthy and opens one triggered profile capture (the sampler's
cooldown hysteresis guarantees exactly one window per incident), and
the gauges ride federation onto ``GET /mesh`` with zero new wire
surface — ``_compact_metrics`` ships every registry family already.

Contract: ``SD_RESOURCES=0`` is a true no-op — ``start()`` spawns
nothing, no ``resource_*`` history series, no trend SLOs, and pass
output is bit-identical either way. ``telemetry.reset()`` clears the
last-sample state and releases any test-planted leaks; registered
providers are node lifecycle, not data, and survive reset the way the
profiler's refcount does.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

DEFAULT_INTERVAL_S = 5.0

#: the fixed inventory vocabulary — the ``kind`` label domain of
#: ``sd_resource_inventory`` (SD007: label sets stay enum-like).
#: ``ring_drops`` is built-in; the rest are node-registered providers.
INVENTORY_KINDS = ("journal_rows", "oplog_rows", "serve_cache_entries",
                   "serve_cache_bytes", "history_bytes", "ring_drops")


def enabled() -> bool:
    return os.environ.get("SD_RESOURCES", "1") != "0"


def interval_s() -> float:
    raw = os.environ.get("SD_RESOURCE_INTERVAL_S")
    if raw is None:
        return DEFAULT_INTERVAL_S
    try:
        return min(3600.0, max(0.05, float(raw)))
    except ValueError:
        return DEFAULT_INTERVAL_S


# --- /proc readers (portable fallbacks, never raise) ----------------------


def _proc_status() -> tuple[float, float]:
    """(rss_bytes, thread_count) from ``/proc/self/status``; falls back
    to ``resource.getrusage`` + ``threading.active_count`` off-Linux."""
    rss = 0.0
    threads = 0.0
    try:
        with open("/proc/self/status", encoding="ascii",
                  errors="replace") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = float(line.split()[1]) * 1024.0
                elif line.startswith("Threads:"):
                    threads = float(line.split()[1])
    except OSError:
        pass
    if rss == 0.0:
        try:
            import resource as _resource

            # ru_maxrss is KiB on Linux (peak, not current — an honest
            # upper bound where /proc is missing)
            rss = float(
                _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss
            ) * 1024.0
        except Exception:  # noqa: BLE001 - resource reads degrade, never fail
            pass
    if threads == 0.0:
        threads = float(threading.active_count())
    return rss, threads


def fd_count() -> float:
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return 0.0


def _pid_rss_bytes(pid: int) -> float:
    try:
        with open(f"/proc/{pid}/statm", encoding="ascii") as f:
            pages = int(f.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, IndexError, ValueError):
        return 0.0


def _procpool_rss() -> float:
    """Summed RSS of the multi-process plane's live workers (0 with
    SD_PROCS=0 — the pool spawned nothing)."""
    from ..parallel import procpool as _procpool

    total = 0.0
    for w in list(getattr(_procpool.POOL, "_workers", ())):
        proc = getattr(w, "proc", None)
        pid = getattr(proc, "pid", None)
        if pid is not None and proc.poll() is None:
            total += _pid_rss_bytes(pid)
    return total


def _ring_drops() -> float:
    from . import events as _events

    return float(sum(_events.drop_counts().values()))


# --- the sampler ----------------------------------------------------------


class ResourceSampler:
    """The process-wide resource sampler. One instance per process
    (:data:`SAMPLER`); ``start``/``stop`` are refcounted because two
    in-process nodes (the loopback test mesh) share one address space —
    RSS and fds are process facts, so the first stop must not blind
    the survivor."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop_event = threading.Event()
        self._refs = 0
        #: node-registered inventory readers, name ∈ INVENTORY_KINDS
        self._providers: dict[str, Callable[[], float]] = {}
        #: most recent published sample (health signals read this)
        self._last: dict[str, float] = {}
        self._last_ts: float | None = None
        self._samples = 0
        # test-leak hook state: REAL stranded fds + byte buffers, so
        # the planted-leak test proves the whole chain (kernel fd table
        # → /proc read → gauge → history → trend SLO → health/capture)
        self._leaked_fds: list[int] = []
        self._leaked_blobs: list[bytearray] = []

    # -- lifecycle --------------------------------------------------------

    def start(self) -> bool:
        """Add one hold; the first hold spawns the thread. Returns True
        when sampling is running after the call (False under
        ``SD_RESOURCES=0`` — a true no-op)."""
        if not enabled():
            return False
        with self._lock:
            self._refs += 1
            if self._thread is not None and self._thread.is_alive():
                return True
            self._stop_event.clear()
            self._thread = threading.Thread(
                target=self._run, name="sd-resources", daemon=True,
            )
            self._thread.start()
            return True

    def stop(self) -> None:
        """Release one hold; the last release stops the thread."""
        with self._lock:
            self._refs = max(0, self._refs - 1)
            if self._refs > 0:
                return
            thread = self._thread
            self._thread = None
            self._stop_event.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _run(self) -> None:
        while not self._stop_event.is_set():
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - a sampler must never crash the host
                pass
            self._stop_event.wait(interval_s())

    # -- providers --------------------------------------------------------

    def register_provider(self, name: str,
                          fn: Callable[[], float]) -> None:
        """Register one inventory reader under a fixed kind. Last
        registration wins (a restarted node re-registers over its own
        previous closure)."""
        if name not in INVENTORY_KINDS:
            raise ValueError(
                f"unknown inventory kind {name!r} "
                f"(kinds: {', '.join(INVENTORY_KINDS)})"
            )
        with self._lock:
            self._providers[name] = fn

    def unregister_provider(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    # -- sampling ---------------------------------------------------------

    def sample_once(self, now: float | None = None) -> dict[str, float]:
        """Take one sample: read /proc + every registered inventory,
        publish the gauges, remember the values. Individual provider
        failures degrade to 0 for that kind — one broken inventory must
        not blind the others. Callable synchronously (tests, the soak
        harness's deterministic clock); the thread calls it on its
        interval."""
        from . import metrics as _tm

        rss, threads = _proc_status()
        fds = fd_count()
        pool_rss = _procpool_rss()
        with self._lock:
            providers = dict(self._providers)
        # every kind always present: absent providers read an explicit
        # 0 in the returned values too, so readers (the soak harness)
        # never key-error on a node that hasn't registered inventories
        inv: dict[str, float] = dict.fromkeys(INVENTORY_KINDS, 0.0)
        inv["ring_drops"] = _ring_drops()
        for name, fn in providers.items():
            try:
                inv[name] = float(fn())
            except Exception:  # noqa: BLE001 - providers degrade, never fail
                inv[name] = 0.0
        _tm.RESOURCE_RSS.set(rss)
        _tm.RESOURCE_FDS.set(fds)
        _tm.RESOURCE_THREADS.set(threads)
        _tm.RESOURCE_PROCPOOL_RSS.set(pool_rss)
        # one literal call site per kind: the label domain is fixed by
        # construction (SD007) and absent providers read an explicit 0
        _tm.RESOURCE_INVENTORY.set(inv.get("journal_rows", 0.0),
                                   kind="journal_rows")
        _tm.RESOURCE_INVENTORY.set(inv.get("oplog_rows", 0.0),
                                   kind="oplog_rows")
        _tm.RESOURCE_INVENTORY.set(inv.get("serve_cache_entries", 0.0),
                                   kind="serve_cache_entries")
        _tm.RESOURCE_INVENTORY.set(inv.get("serve_cache_bytes", 0.0),
                                   kind="serve_cache_bytes")
        _tm.RESOURCE_INVENTORY.set(inv.get("history_bytes", 0.0),
                                   kind="history_bytes")
        _tm.RESOURCE_INVENTORY.set(inv.get("ring_drops", 0.0),
                                   kind="ring_drops")
        values = {
            "rss_bytes": rss,
            "fds": fds,
            "threads": threads,
            "procpool_rss_bytes": pool_rss,
            **inv,
        }
        with self._lock:
            self._last = values
            self._last_ts = now if now is not None else time.time()
            self._samples += 1
        return values

    # -- reads ------------------------------------------------------------

    def last(self) -> dict[str, float]:
        with self._lock:
            return dict(self._last)

    def last_ts(self) -> float | None:
        with self._lock:
            return self._last_ts

    def sample_count(self) -> int:
        with self._lock:
            return self._samples

    def summary(self) -> dict[str, Any]:
        """The compact digest the ``resources`` health subsystem embeds
        (and federation therefore ships): last values + sample count,
        never paths or identifiers."""
        if not enabled():
            return {"enabled": False}
        with self._lock:
            return {
                "enabled": True,
                "running": self.running(),
                "samples": self._samples,
                "last_ts": self._last_ts,
                "last": dict(self._last),
            }

    # -- test-leak hook ----------------------------------------------------

    def leak_for_test(self, fds: int = 0, mb: int = 0) -> None:
        """Strand real resources so the planted-leak test exercises the
        actual /proc read path, not a mock: ``fds`` open descriptors on
        /dev/null, ``mb`` MiB of live bytearray. Released by
        :meth:`release_leaks` (which ``reset()`` calls)."""
        with self._lock:
            for _ in range(fds):
                self._leaked_fds.append(os.open(os.devnull, os.O_RDONLY))
            if mb:
                self._leaked_blobs.append(bytearray(mb << 20))

    def release_leaks(self) -> None:
        with self._lock:
            fds, self._leaked_fds = self._leaked_fds, []
            self._leaked_blobs.clear()
        for fd in fds:
            try:
                os.close(fd)
            except OSError:
                pass

    def reset(self) -> None:
        """Test isolation (rides ``telemetry.reset()``): drop the
        last-sample state and release planted leaks. Providers,
        refcounts and the thread survive — reset is about *data*, not
        lifecycle (the profiler's contract)."""
        self.release_leaks()
        with self._lock:
            self._last = {}
            self._last_ts = None
            self._samples = 0


#: the process-wide resource sampler every consumer reads
SAMPLER = ResourceSampler()


def reset() -> None:
    SAMPLER.reset()


def node_providers(node: Any) -> dict[str, Callable[[], float]]:
    """The inventory readers a Node registers at start (and
    unregisters at shutdown): each needs node state the module can't
    reach on its own. Every closure is defensive — a mid-shutdown
    read returns 0, never raises into the sampler thread."""

    def _sum_over_libraries(sql: str) -> float:
        total = 0.0
        for lib in list(
            getattr(getattr(node, "libraries", None), "libraries",
                    {}).values()
        ):
            try:
                row = lib.db.query_one(sql)
                total += float(next(iter(row.values())) or 0)
            except Exception:  # noqa: BLE001 - inventory reads degrade, never fail
                continue
        return total

    def journal_rows() -> float:
        return _sum_over_libraries(
            "SELECT COUNT(*) AS n FROM index_journal")

    def oplog_rows() -> float:
        return _sum_over_libraries(
            "SELECT COUNT(*) AS n FROM crdt_operation")

    def _serve_snapshots() -> list[dict[str, Any]]:
        serve = getattr(node, "serve", None)
        if serve is None:
            return []
        out = []
        for region in ("queries", "thumbs", "meta"):
            cache = getattr(serve, region, None)
            if cache is not None:
                try:
                    out.append(cache.snapshot())
                except Exception:  # noqa: BLE001 - inventory reads degrade
                    continue
        return out

    def serve_cache_entries() -> float:
        return float(sum(s.get("entries", 0) for s in _serve_snapshots()))

    def serve_cache_bytes() -> float:
        return float(sum(s.get("weight", 0) for s in _serve_snapshots()))

    def history_bytes() -> float:
        directory = getattr(getattr(node, "history", None), "dir", None)
        if not directory:
            return 0.0
        total = 0.0
        try:
            for name in os.listdir(directory):
                try:
                    total += os.path.getsize(os.path.join(directory, name))
                except OSError:
                    continue
        except OSError:
            return 0.0
        return total

    return {
        "journal_rows": journal_rows,
        "oplog_rows": oplog_rows,
        "serve_cache_entries": serve_cache_entries,
        "serve_cache_bytes": serve_cache_bytes,
        "history_bytes": history_bytes,
    }
