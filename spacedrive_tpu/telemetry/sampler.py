"""Continuous host sampling profiler — name every frame inside the GIL gap.

PR 12's attribution engine ends the wall-clock story at an anonymous
bucket: the *unattributed gap*, the per-entry Python orchestration no
span covers (the GIL signature that also explains BENCH_E2E
``config_mesh``'s 0.12 scaling efficiency). The reference's execution
layer is a multi-threaded Rust task system whose contention any native
profiler can see; our Python mirror had no host-side profiler at all.
This module is that instrument, stdlib-only:

- a daemon thread walks ``sys._current_frames()`` at ``SD_PROFILE_HZ``
  (default ~19 Hz, deliberately off-beat so it never phase-locks with
  10 Hz samplers or 1 Hz tickers) and folds each thread's stack into a
  bounded **collapsed-stack accumulator**;
- every sample is tagged with a **thread kind** (event loop / feeder /
  to_thread worker / other; the sampler's own thread is exempt from
  its own accounting) and an **execution state** from per-thread
  CPU-time deltas (``time.pthread_getcpuclockid`` +
  ``clock_gettime`` where available, leaf-frame heuristics otherwise):
  ``cpu`` (burning cycles), ``wait`` (parked in a known blocking
  primitive — select/epoll/lock/sleep), or ``gil_wait`` (runnable but
  not running: low CPU with a non-blocking leaf frame — the per-frame
  GIL-wait estimate);
- a declarative **frame → group classifier** names the code a sample
  sits in (journal consult, SQL prep, msgpack, decode/encode, CRDT
  ingest, …) so ``telemetry/attrib.py`` can decompose its ``gap`` and
  ``host_cpu`` buckets into *which code* ate the time;
- **triggered deep captures**: an SLO warn/breach, loop-lag health
  degradation, or serve-gate brownout entry opens one bounded
  high-rate capture window (``SD_PROFILE_CAPTURE_HZ`` for
  ``SD_PROFILE_CAPTURE_S``), kept in a ring of recent windows — the
  flight recorder gains "what was Python doing when it went bad".
  Hysteresis: one window per ``SD_PROFILE_COOLDOWN_S``, so a flapping
  signal can never storm windows.

Exports: ``folded()`` (flamegraph.pl collapsed-stack text),
``profile()`` (the JSON document behind ``GET /profile`` / rspc
``telemetry.profile`` / ``sdx profile``), ``summary()`` (the compact
digest riding every federation snapshot onto ``GET /mesh``), and
``chrome_events()`` (capture-window samples merged into the
``GET /trace`` Chrome-trace export).

Contract: ``SD_PROFILE=0`` is a true no-op — ``start()`` spawns
nothing, ``trigger()`` refuses, every export reports disabled — and
profiling never touches pipeline data, so pass output is bit-identical
either way (golden-tested). The sampler measures its own tick cost and
publishes the duty cycle as ``sd_profile_overhead_ratio``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Any

# --- knobs ----------------------------------------------------------------

DEFAULT_HZ = 19.0           # off-beat by design
DEFAULT_CAPTURE_HZ = 97.0   # deep-capture rate (also off-beat)
DEFAULT_CAPTURE_S = 3.0     # deep-capture window length
DEFAULT_COOLDOWN_S = 30.0   # min seconds between capture windows

MAX_STACK_DEPTH = 48        # frames kept per sample (leafward)
MAX_STACKS = 4096           # distinct collapsed stacks tracked
TIMELINE_SAMPLES = 65536    # recent (ts, kind, state, group) records
CAPTURE_RING = 8            # recent deep-capture windows retained
CAPTURE_MAX_SAMPLES = 4096  # per-window sample bound
FOLDED_MAX_BYTES = 256 * 1024  # wire/bundle bound for folded text

#: execution states (fixed vocabulary)
CPU = "cpu"
GIL_WAIT = "gil_wait"
WAIT = "wait"
STATES = (CPU, GIL_WAIT, WAIT)

#: thread kinds (fixed vocabulary; the sampler's own thread is skipped)
KIND_LOOP = "loop"
KIND_FEEDER = "feeder"
KIND_WORKER = "worker"
KIND_OTHER = "other"

#: capture-trigger reasons (fixed vocabulary — trigger() refuses others
#: so the ring's reason field stays auditable)
TRIGGER_REASONS = ("slo_warn", "slo_breach", "loop_lag", "brownout",
                   "manual")

#: CPU duty cycle at/above which a thread counts as on-CPU for the tick
ON_CPU_DUTY = 0.33


def enabled() -> bool:
    return os.environ.get("SD_PROFILE", "1") != "0"


def _clamped_float(raw: str | None, default: float, lo: float,
                   hi: float) -> float:
    if raw is None:
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return min(hi, max(lo, v))


def base_hz() -> float:
    return _clamped_float(os.environ.get("SD_PROFILE_HZ"),
                          DEFAULT_HZ, 1.0, 250.0)


def capture_hz() -> float:
    return _clamped_float(os.environ.get("SD_PROFILE_CAPTURE_HZ"),
                          DEFAULT_CAPTURE_HZ, 1.0, 500.0)


def capture_seconds() -> float:
    return _clamped_float(os.environ.get("SD_PROFILE_CAPTURE_S"),
                          DEFAULT_CAPTURE_S, 0.1, 60.0)


def cooldown_seconds() -> float:
    return _clamped_float(os.environ.get("SD_PROFILE_COOLDOWN_S"),
                          DEFAULT_COOLDOWN_S, 0.0, 3600.0)


# --- frame naming ---------------------------------------------------------

_PKG_MARKER = os.sep + "spacedrive_tpu" + os.sep


#: parent directories that are filesystem scaffolding, not packages
_NON_PKG_PARENTS = ("site-packages", "dist-packages", "lib", "lib64", "")


def _module_of(filename: str) -> str:
    """Short module-ish name for a code filename: package-relative
    dotted path for our own tree, ``pkg.basename`` for external
    packages (``asyncio.base_events``, ``msgpack.fallback``), bare
    basename for top-level modules — never a user path, so folded
    profiles are redaction-clean by construction."""
    i = filename.rfind(_PKG_MARKER)
    if i >= 0:
        rel = filename[i + len(_PKG_MARKER):]
        if rel.endswith(".py"):
            rel = rel[:-3]
        return rel.replace(os.sep, ".")
    d, base = os.path.split(filename)
    if base.endswith(".py"):
        base = base[:-3]
    parent = os.path.basename(d)
    if parent.startswith("python") or parent in _NON_PKG_PARENTS:
        return base
    return parent if base == "__init__" else f"{parent}.{base}"


#: per-code-object frame-name memo: code objects are immutable and
#: long-lived, so the expensive filename→module derivation runs once
#: per distinct code object instead of once per frame per tick. Keyed
#: by the code object itself (an id() key could alias after GC reuse);
#: the cap bounds both the dict and the code objects it pins.
_CODE_NAMES: dict[Any, str] = {}
_CODE_NAMES_MAX = 8192


def _frame_name(code: Any) -> str:
    name = _CODE_NAMES.get(code)
    if name is None:
        if len(_CODE_NAMES) >= _CODE_NAMES_MAX:
            _CODE_NAMES.clear()
        name = f"{_module_of(code.co_filename)}:{code.co_name}"
        _CODE_NAMES[code] = name
    return name


def fold_stack(frame: Any, max_depth: int = MAX_STACK_DEPTH) -> list[str]:
    """Root-first ``module:function`` names for one thread's stack."""
    names: list[str] = []
    f = frame
    while f is not None and len(names) < max_depth:
        names.append(_frame_name(f.f_code))
        f = f.f_back
    names.reverse()
    return names


# --- frame → group classifier --------------------------------------------

#: declarative (group, module-prefix…) table, leaf-to-root first match.
#: Order matters: the earlier row wins when one stack crosses several
#: families (a journal consult calling sqlite3 names "journal" only if
#: the leafmost matching frame is the journal's — the sqlite3 leaf
#: correctly names "sql").
FRAME_GROUPS: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("journal", ("location.indexer.journal",)),
    ("sql", ("db.database", "db.migrations", "sqlite3")),
    ("walk", ("location.indexer.walk", "location.indexer.rules")),
    ("linking", ("object.file_identifier",)),
    ("crdt_ingest", ("sync.",)),
    ("msgpack", ("msgpack", "p2p.wire", "p2p.protocol")),
    ("decode", ("PIL", "object.media.media_data")),
    ("encode", ("object.media.thumbnail",)),
    ("device_dispatch", ("ops.", "jax", "jaxlib", "numpy")),
    ("feeder", ("parallel.feeder",)),
    ("autotune", ("parallel.autotune",)),
    ("task_system", ("tasks.",)),
    ("jobs", ("jobs.",)),
    ("indexer", ("location.",)),
    ("serve", ("serve.", "api.", "aiohttp")),
    ("p2p", ("p2p.", "cloud.")),
    ("telemetry", ("telemetry.",)),
    ("loop_idle", ("selectors", "asyncio.base_events",
                   "asyncio.selector_events")),
    ("asyncio", ("asyncio.",)),
    ("thread_wait", ("threading", "queue", "futures.")),
)

#: the bounded group vocabulary history samplers + /mesh summaries use
GROUP_NAMES = tuple(g for g, _ in FRAME_GROUPS) + ("other",)

#: the curated subset persisted as history series (one float per group
#: per 10 s sample — the full vocabulary would triple every record for
#: groups that rarely move; these are the gap-decomposition movers)
HISTORY_GROUPS = ("journal", "sql", "linking", "crdt_ingest", "msgpack",
                  "decode", "encode", "loop_idle", "other")


#: scaffolding frames every thread carries near its root — they must
#: not name a group, or every worker sample would read "thread_wait"
_SCAFFOLD_FRAMES = frozenset({
    "threading:_bootstrap", "threading:_bootstrap_inner", "threading:run",
    "futures.thread:_worker",
})


def classify_stack(names: list[str]) -> str:
    """Name the frame group of one folded stack. Two passes, both
    leaf→root: the first frame matching a declared module family names
    the group; failing that, the first DOTTED module (a real package —
    our tree or an external one) names it by its top segment (``node``,
    ``json``, …) so project code outside the declared families still
    reads as named code. Only stacks touching no package at all are
    ``other`` (the honesty bucket the ≥70%-decomposed acceptance bar
    measures)."""
    for name in reversed(names):
        if name in _SCAFFOLD_FRAMES:
            continue
        mod = name.split(":", 1)[0]
        for group, prefixes in FRAME_GROUPS:
            for p in prefixes:
                if mod == p or mod.startswith(p):
                    return group
    for name in reversed(names):
        if name in _SCAFFOLD_FRAMES:
            continue
        mod = name.split(":", 1)[0]
        if "." in mod and not mod.startswith("<"):
            return mod.split(".", 1)[0]
    return "other"


#: leaf function names that mark a low-CPU thread as genuinely parked
#: (waiting on IO/locks/timers) rather than runnable-but-not-running
_WAIT_LEAF_FUNCS = frozenset({
    "wait", "_wait", "wait_for", "select", "poll", "epoll", "kqueue",
    "accept", "recv", "recvfrom", "recv_into", "read", "readline",
    "readinto", "sleep", "acquire", "get", "join", "getaddrinfo",
    "_recv_bytes", "settimeout", "flush", "fsync", "connect",
})
#: leaf modules whose presence means "blocked in C below this frame":
#: an idle executor worker's Python leaf is ``futures.thread:_worker``
#: while it sits inside SimpleQueue.get (a C call with no frame)
_WAIT_LEAF_MODULES = ("selectors", "socket", "ssl", "subprocess",
                      "futures.thread", "queue")


def _leaf_is_waity(names: list[str]) -> bool:
    if not names:
        return False
    mod, _, func = names[-1].partition(":")
    bare = func.lstrip("_")
    if bare in _WAIT_LEAF_FUNCS or "wait" in bare:
        # "wait" in the leaf name covers the private variants
        # (_wait_for_tstate_lock, sock_recv's await shims, …)
        return True
    return any(mod == m or mod.startswith(m + ".")
               for m in _WAIT_LEAF_MODULES)


# --- the sampler ----------------------------------------------------------


class CaptureWindow:
    """One bounded high-rate capture: per-sample timeline + its own
    collapsed-stack counts, finalized into the capture ring."""

    __slots__ = ("reason", "opened_ts", "until_monotonic", "hz",
                 "samples", "stack_counts", "closed", "duration_s")

    def __init__(self, reason: str, opened_ts: float,
                 until_monotonic: float, hz: float):
        self.reason = reason
        self.opened_ts = opened_ts
        self.until_monotonic = until_monotonic
        self.hz = hz
        self.samples: list[tuple[float, str, str, str]] = []
        self.stack_counts: dict[str, int] = {}
        self.closed = False
        self.duration_s = 0.0

    def to_doc(self, top_k: int = 8) -> dict[str, Any]:
        groups: dict[str, int] = {}
        for _, _, _, group in self.samples:
            groups[group] = groups.get(group, 0) + 1
        total = max(1, len(self.samples))
        return {
            "reason": self.reason,
            "opened_ts": round(self.opened_ts, 3),
            "duration_s": round(self.duration_s, 3),
            "hz": self.hz,
            "samples": len(self.samples),
            "closed": self.closed,
            "top_groups": [
                {"group": g, "samples": n, "share": round(n / total, 4)}
                for g, n in sorted(groups.items(), key=lambda kv: kv[1],
                                   reverse=True)[:top_k]
            ],
            "top_stacks": [
                {"stack": s, "samples": n}
                for s, n in sorted(self.stack_counts.items(),
                                   key=lambda kv: kv[1], reverse=True)[:top_k]
            ],
        }


class Sampler:
    """The process-wide continuous profiler. One instance per process
    (:data:`SAMPLER`); ``start``/``stop`` are refcounted because two
    in-process nodes (the loopback test mesh) share one interpreter —
    the first stop must not kill the survivor's profile."""

    def __init__(self, hz: float | None = None):
        self._hz_override = hz
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop_event = threading.Event()
        self._refs = 0
        self._loop_idents: set[int] = set()
        # accumulator state (guarded by _lock)
        self._stacks: dict[tuple[str, str, str], int] = {}
        self._stacks_dropped = 0
        self._group_counts: dict[tuple[str, str], int] = {}
        self._kind_counts: dict[str, int] = {}
        self._state_counts: dict[str, int] = {}
        self._total_samples = 0
        self._started_ts: float | None = None
        self._timeline: deque[tuple[float, str, str, str]] = deque(
            maxlen=TIMELINE_SAMPLES)
        # per-thread CPU clock bookkeeping (sampler thread only)
        self._cpu_prev: dict[int, tuple[float, float]] = {}
        # triggered captures
        self._capture: CaptureWindow | None = None
        self._captures: deque[CaptureWindow] = deque(maxlen=CAPTURE_RING)
        self._last_capture_open = float("-inf")
        # self-accounting
        self._self_seconds = 0.0
        self._ticks = 0

    # -- lifecycle --------------------------------------------------------

    def start(self) -> bool:
        """Add one hold on the sampler; the first hold spawns the
        thread. Returns True when sampling is running after the call
        (False under ``SD_PROFILE=0`` — a true no-op)."""
        if not enabled():
            return False
        with self._lock:
            self._refs += 1
            if self._thread is not None and self._thread.is_alive():
                return True
            self._stop_event.clear()
            if self._started_ts is None:
                self._started_ts = time.time()
            self._thread = threading.Thread(
                target=self._run, name="sd-profiler", daemon=True,
            )
            self._thread.start()
            return True

    def stop(self) -> None:
        """Release one hold; the last release stops the thread."""
        with self._lock:
            self._refs = max(0, self._refs - 1)
            if self._refs > 0:
                return
            thread = self._thread
            self._thread = None
            self._stop_event.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def register_loop_thread(self) -> None:
        """Tag the CALLING thread as an event-loop thread (Node.start
        runs on its loop). Kind classification reads this set."""
        with self._lock:
            self._loop_idents.add(threading.get_ident())

    def reset(self) -> None:
        """Test isolation (rides ``telemetry.reset()``): clear the
        accumulators, timeline, capture ring, and trigger/cooldown
        state. The thread (and refcounts) survive — reset is about
        *data*, not lifecycle."""
        with self._lock:
            self._stacks.clear()
            self._stacks_dropped = 0
            self._group_counts.clear()
            self._kind_counts.clear()
            self._state_counts.clear()
            self._total_samples = 0
            self._timeline.clear()
            self._cpu_prev.clear()
            self._capture = None
            self._captures.clear()
            self._last_capture_open = float("-inf")
            self._self_seconds = 0.0
            self._ticks = 0
            self._started_ts = time.time() if self.running() else None

    # -- triggered deep captures ------------------------------------------

    def trigger(self, reason: str) -> bool:
        """Open a bounded high-rate capture window for ``reason``
        (fixed vocabulary). Hysteresis: while a window is active, or
        within the cooldown of the last open, the trigger is absorbed —
        a flapping SLO can never storm windows. Returns True when a NEW
        window opened."""
        if not enabled() or not self.running():
            return False
        if reason not in TRIGGER_REASONS:
            raise ValueError(
                f"unknown capture trigger {reason!r} "
                f"(reasons: {', '.join(TRIGGER_REASONS)})"
            )
        now_m = time.monotonic()
        with self._lock:
            if self._capture is not None and not self._capture.closed:
                return False
            if now_m - self._last_capture_open < cooldown_seconds():
                return False
            self._capture = CaptureWindow(
                reason, time.time(), now_m + capture_seconds(),
                capture_hz(),
            )
            self._last_capture_open = now_m
        from . import metrics as _tm

        _tm.PROFILE_CAPTURES.inc()
        return True

    def _close_capture_locked(self, now_m: float) -> None:
        cap = self._capture
        if cap is None:
            return
        cap.closed = True
        cap.duration_s = max(
            0.0, capture_seconds() - max(0.0, cap.until_monotonic - now_m))
        self._captures.append(cap)
        self._capture = None

    # -- the sampling thread ----------------------------------------------

    def _run(self) -> None:
        while not self._stop_event.is_set():
            t0 = time.monotonic()
            c0 = time.thread_time()
            try:
                self._tick(t0)
            except Exception:  # noqa: BLE001 - a profiler must never crash the host
                pass
            cost = time.monotonic() - t0
            # overhead accounting uses the sampler thread's own CPU
            # time: under load the thread is descheduled mid-tick, and
            # that parked wall time is not cost imposed on the host
            with self._lock:
                self._self_seconds += time.thread_time() - c0
                self._ticks += 1
                in_capture = (self._capture is not None
                              and not self._capture.closed)
            hz = capture_hz() if in_capture else (
                self._hz_override or base_hz())
            self._publish_overhead()
            self._stop_event.wait(max(0.0, (1.0 / hz) - cost))

    def _publish_overhead(self) -> None:
        if self._ticks % 16 != 0:
            return
        started = self._started_ts
        if started is None:
            return
        elapsed = max(1e-6, time.time() - started)
        from . import metrics as _tm

        _tm.PROFILE_OVERHEAD.set(min(1.0, self._self_seconds / elapsed))
        _tm.PROFILE_STACKS.set(len(self._stacks))

    def _thread_states(self) -> dict[int, tuple[str, float | None]]:
        """(kind, cpu-duty) per live thread ident, sampler excluded.
        Duty is None when the per-thread CPU clock is unavailable (first
        sight of a thread, or no pthread_getcpuclockid)."""
        self_ident = threading.get_ident()
        now_m = time.monotonic()
        out: dict[int, tuple[str, float | None]] = {}
        names = {t.ident: t.name for t in threading.enumerate()
                 if t.ident is not None}
        with self._lock:
            loop_idents = set(self._loop_idents)
        for ident, name in names.items():
            if ident == self_ident:
                continue
            if ident in loop_idents or name == "MainThread":
                kind = KIND_LOOP
            elif name.startswith("sd-window-pipeline"):
                kind = KIND_FEEDER
            elif name.startswith(("asyncio_", "ThreadPoolExecutor")):
                kind = KIND_WORKER
            else:
                kind = KIND_OTHER
            duty: float | None = None
            cpu = _thread_cpu_seconds(ident)
            if cpu is not None:
                prev = self._cpu_prev.get(ident)
                self._cpu_prev[ident] = (now_m, cpu)
                if prev is not None:
                    dt = now_m - prev[0]
                    if dt > 1e-6:
                        duty = max(0.0, (cpu - prev[1]) / dt)
            out[ident] = (kind, duty)
        # forget exited threads so the clock map stays bounded
        for gone in set(self._cpu_prev) - set(out):
            self._cpu_prev.pop(gone, None)
        return out

    def _tick(self, now_m: float) -> None:
        states = self._thread_states()
        frames = sys._current_frames()
        ts = time.time()
        records: list[tuple[str, str, str, str]] = []
        for ident, frame in frames.items():
            meta = states.get(ident)
            if meta is None:
                continue  # the sampler itself, or a thread born mid-tick
            kind, duty = meta
            names = fold_stack(frame)
            if not names:
                continue
            # a stack that is ALL thread scaffolding is a C-extension
            # thread (torch/onnx pools, C waiters) blocked below Python
            # — parked, not GIL-starved
            scaffold_only = all(n in _SCAFFOLD_FRAMES for n in names)
            if duty is not None and duty >= ON_CPU_DUTY:
                state = CPU
            elif scaffold_only or _leaf_is_waity(names):
                state = WAIT
            elif duty is None:
                # no per-thread clock: fall back to the leaf heuristic
                state = CPU
            else:
                state = GIL_WAIT
            group = classify_stack(names)
            records.append((kind, state, ";".join(names), group))
        del frames
        with self._lock:
            cap = self._capture
            if cap is not None and not cap.closed \
                    and now_m >= cap.until_monotonic:
                self._close_capture_locked(now_m)
                cap = None
            for kind, state, stack, group in records:
                key = (kind, state, stack)
                if key in self._stacks:
                    self._stacks[key] += 1
                elif len(self._stacks) < MAX_STACKS:
                    self._stacks[key] = 1
                else:
                    self._stacks_dropped += 1
                gk = (state, group)
                self._group_counts[gk] = self._group_counts.get(gk, 0) + 1
                self._kind_counts[kind] = self._kind_counts.get(kind, 0) + 1
                self._state_counts[state] = \
                    self._state_counts.get(state, 0) + 1
                self._total_samples += 1
                self._timeline.append((ts, kind, state, group))
                if cap is not None and not cap.closed:
                    if len(cap.samples) < CAPTURE_MAX_SAMPLES:
                        cap.samples.append((ts, kind, state, group))
                    cap.stack_counts[stack] = \
                        cap.stack_counts.get(stack, 0) + 1
        from . import metrics as _tm

        _tm.PROFILE_SAMPLES.inc(len(records))

    # -- reads ------------------------------------------------------------

    def samples_between(self, t0: float, t1: float) \
            -> list[tuple[float, str, str, str]]:
        """Timeline records with ``t0 <= ts <= t1`` — the attribution
        engine's gap-decomposition read path."""
        with self._lock:
            recs = list(self._timeline)
        return [r for r in recs if t0 <= r[0] <= t1]

    def folded(self, max_bytes: int = FOLDED_MAX_BYTES) -> str:
        """flamegraph.pl collapsed-stack text. Synthetic
        ``kind;state`` root frames prefix every stack so one flamegraph
        splits by thread kind and execution state; biggest stacks
        first, truncated at ``max_bytes`` (biggest-first means
        truncation drops only the tail of tiny stacks)."""
        with self._lock:
            items = sorted(self._stacks.items(), key=lambda kv: kv[1],
                           reverse=True)
        out: list[str] = []
        size = 0
        for (kind, state, stack), count in items:
            line = f"{kind};{state};{stack} {count}\n"
            size += len(line)
            if size > max_bytes:
                break
            out.append(line)
        return "".join(out)

    def group_shares(self) -> dict[str, float]:
        """Cumulative per-group sample shares over every state (the
        history allowlist's ``profile_share_*`` series)."""
        with self._lock:
            total = self._total_samples
            counts: dict[str, int] = {}
            for (_state, group), n in self._group_counts.items():
                counts[group] = counts.get(group, 0) + n
        if not total:
            return {}
        return {g: round(n / total, 6) for g, n in counts.items()}

    def profile(self, top_k: int = 24) -> dict[str, Any]:
        """The full JSON profile document (``GET /profile``)."""
        if not enabled():
            return {"enabled": False}
        with self._lock:
            total = self._total_samples
            started = self._started_ts
            group_counts = dict(self._group_counts)
            kind_counts = dict(self._kind_counts)
            state_counts = dict(self._state_counts)
            stacks_n = len(self._stacks)
            dropped = self._stacks_dropped
            captures = [c.to_doc() for c in self._captures]
            active = self._capture
            if active is not None and not active.closed:
                captures.append(active.to_doc())
            self_seconds = self._self_seconds
        duration = (time.time() - started) if started else 0.0
        groups: dict[str, dict[str, Any]] = {}
        for (state, group), n in group_counts.items():
            g = groups.setdefault(group, {"samples": 0, "states": {}})
            g["samples"] += n
            g["states"][state] = g["states"].get(state, 0) + n
        top = sorted(groups.items(), key=lambda kv: kv[1]["samples"],
                     reverse=True)[:top_k]
        return {
            "enabled": True,
            "running": self.running(),
            "hz": self._hz_override or base_hz(),
            "started_ts": started,
            "duration_s": round(duration, 3),
            "samples": total,
            "threads": kind_counts,
            "states": state_counts,
            "stacks": stacks_n,
            "dropped_stacks": dropped,
            "overhead_ratio": round(
                self_seconds / duration, 6) if duration > 0 else 0.0,
            "frame_groups": [
                {
                    "group": g,
                    "samples": d["samples"],
                    "share": round(d["samples"] / total, 4) if total else 0.0,
                    "states": d["states"],
                }
                for g, d in top
            ],
            "captures": captures,
        }

    def summary(self, top_k: int = 5) -> dict[str, Any]:
        """The compact digest riding federation snapshots → ``GET
        /mesh``: totals, state split, top frame groups, capture count.
        Never stacks or paths — digests only, like ring digests."""
        if not enabled():
            return {"enabled": False}
        with self._lock:
            total = self._total_samples
            started = self._started_ts
            state_counts = dict(self._state_counts)
            group_counts = dict(self._group_counts)
            captures_n = len(self._captures)
            last = self._captures[-1].reason if self._captures else None
            if self._capture is not None and not self._capture.closed:
                captures_n += 1
                last = self._capture.reason
        counts: dict[str, int] = {}
        for (_state, group), n in group_counts.items():
            counts[group] = counts.get(group, 0) + n
        return {
            "enabled": True,
            "running": self.running(),
            "samples": total,
            "duration_s": round(time.time() - started, 3) if started else 0.0,
            "states": state_counts,
            "top_groups": [
                {"group": g, "share": round(n / total, 4)}
                for g, n in sorted(counts.items(), key=lambda kv: kv[1],
                                   reverse=True)[:top_k]
            ] if total else [],
            "captures": captures_n,
            "last_capture_reason": last,
        }

    def chrome_events(self) -> list[dict[str, Any]]:
        """Capture-window samples as Chrome-trace instant events on a
        dedicated ``host-profile`` lane, merged into ``GET /trace`` so
        Perfetto shows *what Python was doing* beside the span rows."""
        with self._lock:
            caps = list(self._captures)
            if self._capture is not None:
                caps.append(self._capture)
        pid = os.getpid()
        events: list[dict[str, Any]] = []
        if not caps:
            return events
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
            "args": {"name": "host-profile (triggered captures)"},
        })
        for cap in caps:
            events.append({
                "name": f"capture:{cap.reason}", "cat": "profile",
                "ph": "i", "s": "g",
                "ts": int(cap.opened_ts * 1e6), "pid": pid, "tid": 1,
                "args": {"reason": cap.reason, "hz": cap.hz,
                         "samples": len(cap.samples)},
            })
            for ts, kind, state, group in cap.samples:
                events.append({
                    "name": group, "cat": "profile", "ph": "i", "s": "t",
                    "ts": int(ts * 1e6), "pid": pid, "tid": 1,
                    "args": {"kind": kind, "state": state},
                })
        return events

    def captures_snapshot(self) -> list[dict[str, Any]]:
        with self._lock:
            docs = [c.to_doc() for c in self._captures]
            if self._capture is not None and not self._capture.closed:
                docs.append(self._capture.to_doc())
        return docs


def _thread_cpu_seconds(ident: int) -> float | None:
    """Another thread's cumulative CPU seconds via its pthread CPU
    clock, or None where the platform can't say (non-Linux, exited
    thread). The graceful-fallback half of the on-CPU classifier."""
    getclock = getattr(time, "pthread_getcpuclockid", None)
    if getclock is None:
        return None
    try:
        return time.clock_gettime(getclock(ident))
    except (OverflowError, OSError, ValueError):
        return None


#: the process-wide sampler every consumer reads
SAMPLER = Sampler()


def trigger(reason: str) -> bool:
    """Module-level trigger hook (SLO engine, loop-lag monitor, serve
    gate). No-op unless the sampler is enabled AND running."""
    return SAMPLER.trigger(reason)


def reset() -> None:
    SAMPLER.reset()


async def mesh_profile(node: Any) -> dict[str, Any]:
    """The mesh-wide profile view: this node's full profile plus every
    reachable peer's (pulled over the TELEMETRY wire's ``profile_pull``
    op). A vanished peer degrades the view to ``partial`` with the
    failure recorded — the trace_pull contract, never a block."""
    doc: dict[str, Any] = {
        "local": SAMPLER.profile(),
        "mesh": {},
        "partial": False,
    }
    manager = getattr(node, "p2p", None)
    if manager is not None:
        profiles, failures = await manager.pull_remote_profiles()
        doc["mesh"] = {
            label: p.get("profile") for label, p in profiles.items()
        }
        doc["partial"] = bool(failures)
        if failures:
            doc["pull_failures"] = failures
    return doc


# --- attribution decomposition -------------------------------------------


def decompose_segments(segments: list[tuple[float, float]],
                       bucket_seconds: float) -> dict[str, Any] | None:
    """Decompose one attribution bucket's wall time into named frame
    groups: timeline samples landing inside the bucket's critical-path
    segments vote by group, and the bucket's seconds split
    proportionally. ``coverage`` is the fraction of votes carrying a
    named (non-``other``) group — the honesty figure the ≥70% bar
    gates. Returns None when profiling is off or no sample landed in
    the window (the report simply omits the decomposition)."""
    if not enabled() or not segments:
        return None
    t_lo = min(s[0] for s in segments)
    t_hi = max(s[1] for s in segments)
    recs = SAMPLER.samples_between(t_lo, t_hi)
    if not recs:
        return None
    spans = sorted(segments)
    counts: dict[str, int] = {}
    total = 0
    import bisect

    starts = [s[0] for s in spans]
    for ts, _kind, state, group in recs:
        if state == WAIT:
            # a thread parked in select/locks/queues is not executing
            # the bucket — only runnable samples (on-CPU or GIL-wait)
            # vote, or every idle daemon thread would dilute the split
            continue
        i = bisect.bisect_right(starts, ts) - 1
        if i < 0 or ts > spans[i][1]:
            continue
        counts[group] = counts.get(group, 0) + 1
        total += 1
    if not total:
        return None
    named = total - counts.get("other", 0)
    return {
        "samples": total,
        "coverage": round(named / total, 4),
        "groups": {
            g: round(bucket_seconds * n / total, 6)
            for g, n in sorted(counts.items(), key=lambda kv: kv[1],
                               reverse=True)
        },
    }
