"""telemetry.snapshot — the JSON read path.

The same registry the /metrics endpoint scrapes, shaped for rspc
consumers (the explorer's diagnostics pane) and for bench.py, which
builds its reported JSON from here so the benchmark and the live
system can never disagree about what was measured.
"""

from __future__ import annotations

from typing import Any

from .registry import REGISTRY
from .spans import recent_spans


def snapshot() -> dict[str, Any]:
    return {
        "metrics": REGISTRY.snapshot(),
        "spans": recent_spans(),
    }


def histogram_recent(name: str, **labels: Any) -> list[float]:
    """Raw recent observations of a histogram series ([] when the
    metric is unknown) — bench.py's median/spread source."""
    fam = REGISTRY.get(name)
    if fam is None or not hasattr(fam, "recent"):
        return []
    return fam.recent(**labels)


def gauge_value(name: str, default: float = 0.0, **labels: Any) -> float:
    fam = REGISTRY.get(name)
    if fam is None or not hasattr(fam, "value"):
        return default
    return fam.value(**labels)


def counter_value(name: str, default: float = 0.0, **labels: Any) -> float:
    return gauge_value(name, default, **labels)
