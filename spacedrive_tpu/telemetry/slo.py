"""SLO registry + multi-window burn-rate evaluation over history.

Health verdicts (``telemetry/health.py``) answer "is the node OK right
now"; an SLO answers the operator contract question: *are we spending
our error budget faster than we can afford?* Each
:class:`SLO` declares a **good-sample predicate** over one history
series (``telemetry/history.py``) and a target good-fraction; the
evaluator computes the classic multi-window **burn rate** — the bad
fraction divided by the error budget — over a fast and a slow window:

- ``burn = bad_fraction / (1 - target)``: burn 1.0 spends exactly the
  budget over the window; 14.4 over 5 minutes is the page-worthy pace
  (a 30-day budget gone in ~2 days — the SRE-workbook default);
- **breach** requires the fast AND slow windows to burn past their
  thresholds (the standard guard against paging on a blip);
- **warn** is the fast window alone.

Counter-shaped SLOs (``protected sheds == 0``) use zero-tolerance
semantics instead: ANY increase of the cumulative counter within the
fast window is an immediate breach — a protected-class shed is a
serve-layer bug, not budget spend.

The registry is declarative and process-global (:data:`REGISTRY`,
seeded with :func:`default_slos`); evaluation state (last verdicts,
for delta-free reads) is cached per process and cleared by
``telemetry.reset()``. The ``slo`` health subsystem wraps
:func:`evaluate` so every federation snapshot — and therefore every
peer's ``GET /mesh`` — carries this node's SLO posture with zero new
wire surface. Read paths: rspc ``telemetry.slo``, ``sdx slo``.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

#: SRE-workbook-shaped defaults: (window_seconds, burn_threshold)
FAST_WINDOW_S = 300.0
SLOW_WINDOW_S = 3600.0
FAST_BURN = 14.4
SLOW_BURN = 6.0

OK = "ok"
WARN = "warn"
BREACH = "breach"
NO_DATA = "no_data"

_RANK = {OK: 0, NO_DATA: 0, WARN: 1, BREACH: 2}


@dataclass(frozen=True)
class SLO:
    """One declarative objective over a history series.

    ``kind``:
    - ``upper``: a sample is good while ``value <= objective``;
    - ``lower``: good while ``value >= objective`` — with
      ``ignore_zero`` (pass throughput) samples at 0 are idle, not bad;
    - ``zero_tolerance``: the series is a cumulative counter; ANY
      increase inside the fast window breaches.
    """

    name: str
    series: str
    objective: float
    kind: str = "upper"  # upper | lower | zero_tolerance
    target: float = 0.99
    description: str = ""
    ignore_zero: bool = False
    fast_window_s: float = FAST_WINDOW_S
    slow_window_s: float = SLOW_WINDOW_S
    fast_burn: float = FAST_BURN
    slow_burn: float = SLOW_BURN

    def is_good(self, value: float) -> bool | None:
        """None = the sample doesn't count (idle)."""
        if self.ignore_zero and value == 0:
            return None
        if self.kind == "lower":
            return value >= self.objective
        return value <= self.objective


def default_slos() -> list[SLO]:
    objective = float(os.environ.get("SD_SLO_INTERACTIVE_P99_MS", "250"))
    throughput = float(os.environ.get("SD_SLO_FILES_PER_S", "50"))
    return [
        SLO("interactive_p99", series="interactive_p99_ms",
            objective=objective, kind="upper", target=0.99,
            description="serve-layer interactive request p99 under "
                        f"{objective:g} ms"),
        SLO("sync_lag", series="sync_lag_max_s", objective=600.0,
            kind="upper", target=0.99,
            description="worst per-peer replication lag under the sync "
                        "unhealthy bar (600 s)"),
        SLO("pass_throughput", series="files_per_s", objective=throughput,
            kind="lower", target=0.95, ignore_zero=True,
            description=f"observed identify throughput ≥ {throughput:g} "
                        "files/s while a pass is running (idle samples "
                        "don't count)"),
        SLO("protected_sheds", series="protected_sheds_total",
            objective=0.0, kind="zero_tolerance", target=1.0,
            description="control/sync-class sheds are contractually zero "
                        "— any increase is an immediate breach"),
    ]


class SloRegistry:
    """Named SLOs + the last evaluation (process-global)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._slos: dict[str, SLO] = {}
        self.last_evaluation: dict[str, Any] | None = None
        for s in default_slos():
            self._slos[s.name] = s

    def register(self, slo: SLO) -> None:
        with self._lock:
            self._slos[slo.name] = slo

    def get(self, name: str) -> SLO | None:
        with self._lock:
            return self._slos.get(name)

    def all(self) -> list[SLO]:
        with self._lock:
            return list(self._slos.values())

    def reset(self) -> None:
        """telemetry.reset(): restore defaults, drop evaluation state."""
        with self._lock:
            self._slos = {s.name: s for s in default_slos()}
            self.last_evaluation = None


REGISTRY = SloRegistry()


# --- evaluation ----------------------------------------------------------


def _window_stats(slo: SLO, samples: list[tuple[float, float]]) \
        -> dict[str, Any]:
    good = bad = 0
    for _, v in samples:
        verdict = slo.is_good(v)
        if verdict is None:
            continue
        if verdict:
            good += 1
        else:
            bad += 1
    counted = good + bad
    bad_fraction = (bad / counted) if counted else 0.0
    budget = max(1e-9, 1.0 - slo.target)
    return {
        "samples": counted,
        "bad": bad,
        "bad_fraction": round(bad_fraction, 4),
        "burn": round(bad_fraction / budget, 2),
    }


def _counter_increase(samples: list[tuple[float, float]]) -> float:
    vals = [v for _, v in samples]
    if len(vals) < 2:
        return 0.0
    # cumulative counter: restart resets read as no increase (monotonic
    # re-baselining), increases sum across the window
    inc = 0.0
    prev = vals[0]
    for v in vals[1:]:
        if v > prev:
            inc += v - prev
        prev = v
    return inc


def evaluate_slo(slo: SLO, samples_for: Callable[[float],
                                                 list[tuple[float, float]]],
                 now: float | None = None) -> dict[str, Any]:
    """One SLO against a window-reader ``samples_for(seconds) ->
    [(ts, value)]``."""
    fast = samples_for(slo.fast_window_s)
    slow = samples_for(slo.slow_window_s)
    current = fast[-1][1] if fast else (slow[-1][1] if slow else None)
    doc: dict[str, Any] = {
        "name": slo.name,
        "series": slo.series,
        "kind": slo.kind,
        "objective": slo.objective,
        "target": slo.target,
        "description": slo.description,
        "current": current,
    }
    if slo.kind == "zero_tolerance":
        inc = _counter_increase(fast)
        doc["windows"] = {
            "fast": {"seconds": slo.fast_window_s, "samples": len(fast),
                     "increase": inc},
        }
        if not fast:
            doc["status"] = NO_DATA
        else:
            doc["status"] = BREACH if inc > 0 else OK
        return doc
    f, s = _window_stats(slo, fast), _window_stats(slo, slow)
    doc["windows"] = {
        "fast": {"seconds": slo.fast_window_s, **f,
                 "burn_threshold": slo.fast_burn},
        "slow": {"seconds": slo.slow_window_s, **s,
                 "burn_threshold": slo.slow_burn},
    }
    if f["samples"] == 0 and s["samples"] == 0:
        doc["status"] = NO_DATA
    elif f["burn"] >= slo.fast_burn and s["burn"] >= slo.slow_burn:
        doc["status"] = BREACH
    elif f["burn"] >= slo.fast_burn:
        doc["status"] = WARN
    else:
        doc["status"] = OK
    return doc


def evaluate(history: Any = None, *, directory: str | None = None,
             now: float | None = None) -> dict[str, Any]:
    """Every registered SLO against a history source: a live
    :class:`~spacedrive_tpu.telemetry.history.HistoryWriter` (tail-backed
    fast path — the /health + federation read), or a bare history
    ``directory`` (``sdx slo`` offline / post-restart)."""
    from . import metrics as _tm

    now = now if now is not None else time.time()
    results: list[dict[str, Any]] = []
    worst = NO_DATA
    for slo in REGISTRY.all():
        if history is not None:
            def samples_for(seconds: float, _s=slo) \
                    -> list[tuple[float, float]]:
                recs = history.recent(seconds, now=now)
                return [
                    (r["ts"], float(r["v"][_s.series]))
                    for r in recs
                    if isinstance((r.get("v") or {}).get(_s.series),
                                  (int, float))
                    and not isinstance(r["v"][_s.series], bool)
                ]
        elif directory is not None:
            from .history import series as _series

            def samples_for(seconds: float, _s=slo) \
                    -> list[tuple[float, float]]:
                return _series(directory, _s.series, since=now - seconds,
                               until=now)
        else:
            def samples_for(seconds: float) -> list[tuple[float, float]]:
                return []
        doc = evaluate_slo(slo, samples_for, now=now)
        results.append(doc)
        if _RANK[doc["status"]] > _RANK[worst] or (
            worst == NO_DATA and doc["status"] == OK
        ):
            # rank-0 tie: an evaluated-and-met objective upgrades the
            # rollup from "no data" to "ok"
            worst = doc["status"]
        _tm.SLO_STATUS.set(_RANK[doc["status"]], slo=slo.name)
    evaluation = {"ts": now, "status": worst, "slos": results}
    _tm.SLO_EVALUATIONS.inc()
    REGISTRY.last_evaluation = evaluation
    # a warn/breach opens a host-profiler deep-capture window: the
    # flight recorder gains "what was Python doing when the budget
    # started burning". The sampler's own hysteresis absorbs repeats —
    # health polls re-evaluating a burning SLO open ONE window per
    # cooldown, never a storm.
    from . import sampler as _sampler

    if worst == BREACH:
        _sampler.trigger("slo_breach")
    elif worst == WARN:
        _sampler.trigger("slo_warn")
    return evaluation


def reset() -> None:
    REGISTRY.reset()
