"""SLO registry + multi-window burn-rate evaluation over history.

Health verdicts (``telemetry/health.py``) answer "is the node OK right
now"; an SLO answers the operator contract question: *are we spending
our error budget faster than we can afford?* Each
:class:`SLO` declares a **good-sample predicate** over one history
series (``telemetry/history.py``) and a target good-fraction; the
evaluator computes the classic multi-window **burn rate** — the bad
fraction divided by the error budget — over a fast and a slow window:

- ``burn = bad_fraction / (1 - target)``: burn 1.0 spends exactly the
  budget over the window; 14.4 over 5 minutes is the page-worthy pace
  (a 30-day budget gone in ~2 days — the SRE-workbook default);
- **breach** requires the fast AND slow windows to burn past their
  thresholds (the standard guard against paging on a blip);
- **warn** is the fast window alone.

Counter-shaped SLOs (``protected sheds == 0``) use zero-tolerance
semantics instead: ANY increase of the cumulative counter within the
fast window is an immediate breach — a protected-class shed is a
serve-layer bug, not budget spend.

Growth-shaped SLOs (``kind="trend"``) bound a *slope*, not a level:
the objective is the maximum allowed least-squares slope in
units-per-hour over a sliding ``trend_window_s`` of the series,
excluding a ``warmup_s`` prefix (caches filling and JIT warmup look
like leaks for the first minutes of any process). A breach requires
the full-window slope AND the recent-half slope to exceed the
objective with an absolute ``min_delta`` actually accumulated — a
leak must be ongoing and material, not a historical step or float
noise on a flat line. This is how the resource sampler's RSS/fd
series (``telemetry/resources.py``) become gated regressions.

The registry is declarative and process-global (:data:`REGISTRY`,
seeded with :func:`default_slos`); evaluation state (last verdicts,
for delta-free reads) is cached per process and cleared by
``telemetry.reset()``. The ``slo`` health subsystem wraps
:func:`evaluate` so every federation snapshot — and therefore every
peer's ``GET /mesh`` — carries this node's SLO posture with zero new
wire surface. Read paths: rspc ``telemetry.slo``, ``sdx slo``.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

#: SRE-workbook-shaped defaults: (window_seconds, burn_threshold)
FAST_WINDOW_S = 300.0
SLOW_WINDOW_S = 3600.0
FAST_BURN = 14.4
SLOW_BURN = 6.0

#: trend-class defaults: slope judged over a sliding 30 min window,
#: first 2 min excluded as warmup, at least 8 post-warmup samples
TREND_WINDOW_S = 1800.0
TREND_WARMUP_S = 120.0
TREND_MIN_SAMPLES = 8

OK = "ok"
WARN = "warn"
BREACH = "breach"
NO_DATA = "no_data"

_RANK = {OK: 0, NO_DATA: 0, WARN: 1, BREACH: 2}


@dataclass(frozen=True)
class SLO:
    """One declarative objective over a history series.

    ``kind``:
    - ``upper``: a sample is good while ``value <= objective``;
    - ``lower``: good while ``value >= objective`` — with
      ``ignore_zero`` (pass throughput) samples at 0 are idle, not bad;
    - ``zero_tolerance``: the series is a cumulative counter; ANY
      increase inside the fast window breaches;
    - ``trend``: ``objective`` is the max allowed growth slope in
      series-units **per hour** over ``trend_window_s`` (samples inside
      the first ``warmup_s`` of the window are excluded); breach needs
      both the full-window and recent-half slopes over the objective
      AND a total accumulated delta ≥ ``min_delta``.
    """

    name: str
    series: str
    objective: float
    kind: str = "upper"  # upper | lower | zero_tolerance | trend
    target: float = 0.99
    description: str = ""
    ignore_zero: bool = False
    fast_window_s: float = FAST_WINDOW_S
    slow_window_s: float = SLOW_WINDOW_S
    fast_burn: float = FAST_BURN
    slow_burn: float = SLOW_BURN
    trend_window_s: float = TREND_WINDOW_S
    warmup_s: float = TREND_WARMUP_S
    min_samples: int = TREND_MIN_SAMPLES
    min_delta: float = 0.0

    def is_good(self, value: float) -> bool | None:
        """None = the sample doesn't count (idle)."""
        if self.ignore_zero and value == 0:
            return None
        if self.kind == "lower":
            return value >= self.objective
        return value <= self.objective


def default_slos() -> list[SLO]:
    from . import resources as _resources
    from . import tenants as _tenants

    objective = float(os.environ.get("SD_SLO_INTERACTIVE_P99_MS", "250"))
    throughput = float(os.environ.get("SD_SLO_FILES_PER_S", "50"))
    slos = [
        SLO("interactive_p99", series="interactive_p99_ms",
            objective=objective, kind="upper", target=0.99,
            description="serve-layer interactive request p99 under "
                        f"{objective:g} ms"),
        SLO("sync_lag", series="sync_lag_max_s", objective=600.0,
            kind="upper", target=0.99,
            description="worst per-peer replication lag under the sync "
                        "unhealthy bar (600 s)"),
        SLO("pass_throughput", series="files_per_s", objective=throughput,
            kind="lower", target=0.95, ignore_zero=True,
            description=f"observed identify throughput ≥ {throughput:g} "
                        "files/s while a pass is running (idle samples "
                        "don't count)"),
        SLO("protected_sheds", series="protected_sheds_total",
            objective=0.0, kind="zero_tolerance", target=1.0,
            description="control/sync-class sheds are contractually zero "
                        "— any increase is an immediate breach"),
    ]
    if _resources.enabled():
        # gated on the sampler knob so SD_RESOURCES=0 stays a true
        # no-op: no resource_* series, no trend SLOs over them, no new
        # sd_slo_status labels — the pass output is golden-identical
        rss_mb_h = float(os.environ.get("SD_SLO_RSS_MB_PER_H", "64"))
        fd_h = float(os.environ.get("SD_SLO_FD_PER_H", "50"))
        window = float(os.environ.get("SD_RESOURCE_TREND_WINDOW_S",
                                      str(TREND_WINDOW_S)))
        warmup = float(os.environ.get("SD_RESOURCE_WARMUP_S",
                                      str(TREND_WARMUP_S)))
        slos += [
            SLO("rss_growth", series="resource_rss_mb",
                objective=rss_mb_h, kind="trend",
                trend_window_s=window, warmup_s=warmup,
                min_delta=rss_mb_h / 4.0,
                description="process RSS growth slope bounded to "
                            f"{rss_mb_h:g} MB/h after warmup — a "
                            "steeper sustained slope is a leak, not "
                            "load"),
            SLO("fd_growth", series="resource_fds",
                objective=fd_h, kind="trend",
                trend_window_s=window, warmup_s=warmup,
                min_delta=max(8.0, fd_h / 4.0),
                description="open-fd count flat at steady state "
                            f"(slope ≤ {fd_h:g} fds/h) — growth means "
                            "descriptors are being stranded"),
        ]
    if _tenants.enabled():
        # gated on SD_TENANT_OBS so =0 stays a true no-op: no
        # tenant_fairness_index series, no SLO over it, no new
        # sd_slo_status labels — serve output golden-identical
        fairness_floor = float(
            os.environ.get("SD_SLO_TENANT_FAIRNESS", "0.5"))
        slos += [
            SLO("tenant_fairness", series="tenant_fairness_index",
                objective=fairness_floor, kind="lower", target=0.95,
                description="Jain's fairness index over resident "
                            "serve-surface tenants stays ≥ "
                            f"{fairness_floor:g} — sustained burn "
                            "means one library is starving the rest "
                            "(ROADMAP item 4's enforcement signal)"),
        ]
    return slos


class SloRegistry:
    """Named SLOs + the last evaluation (process-global)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._slos: dict[str, SLO] = {}
        self.last_evaluation: dict[str, Any] | None = None
        for s in default_slos():
            self._slos[s.name] = s

    def register(self, slo: SLO) -> None:
        with self._lock:
            self._slos[slo.name] = slo

    def get(self, name: str) -> SLO | None:
        with self._lock:
            return self._slos.get(name)

    def all(self) -> list[SLO]:
        with self._lock:
            return list(self._slos.values())

    def reset(self) -> None:
        """telemetry.reset(): restore defaults, drop evaluation state."""
        with self._lock:
            self._slos = {s.name: s for s in default_slos()}
            self.last_evaluation = None


REGISTRY = SloRegistry()


# --- evaluation ----------------------------------------------------------


def _window_stats(slo: SLO, samples: list[tuple[float, float]]) \
        -> dict[str, Any]:
    good = bad = 0
    for _, v in samples:
        verdict = slo.is_good(v)
        if verdict is None:
            continue
        if verdict:
            good += 1
        else:
            bad += 1
    counted = good + bad
    bad_fraction = (bad / counted) if counted else 0.0
    budget = max(1e-9, 1.0 - slo.target)
    return {
        "samples": counted,
        "bad": bad,
        "bad_fraction": round(bad_fraction, 4),
        "burn": round(bad_fraction / budget, 2),
    }


def _counter_increase(samples: list[tuple[float, float]]) -> float:
    vals = [v for _, v in samples]
    if len(vals) < 2:
        return 0.0
    # cumulative counter: restart resets read as no increase (monotonic
    # re-baselining), increases sum across the window
    inc = 0.0
    prev = vals[0]
    for v in vals[1:]:
        if v > prev:
            inc += v - prev
        prev = v
    return inc


def _slope_per_h(samples: list[tuple[float, float]]) -> float:
    """Least-squares slope of (ts, value) in units per hour."""
    n = len(samples)
    if n < 2:
        return 0.0
    t0 = samples[0][0]
    xs = [t - t0 for t, _ in samples]
    ys = [v for _, v in samples]
    mean_x = sum(xs) / n
    mean_y = sum(ys) / n
    denom = sum((x - mean_x) ** 2 for x in xs)
    if denom <= 0:
        return 0.0
    slope = sum((x - mean_x) * (y - mean_y)
                for x, y in zip(xs, ys)) / denom
    return slope * 3600.0


def _evaluate_trend(slo: SLO,
                    samples: list[tuple[float, float]]) -> dict[str, Any]:
    """Trend verdict over one window of post-warmup samples."""
    kept = samples
    if samples:
        cutoff = samples[0][0] + slo.warmup_s
        kept = [(t, v) for t, v in samples if t >= cutoff]
    doc: dict[str, Any] = {
        "seconds": slo.trend_window_s,
        "samples": len(kept),
        "warmup_excluded": len(samples) - len(kept),
        "min_delta": slo.min_delta,
    }
    if len(kept) < max(2, slo.min_samples):
        doc.update(slope_per_h=0.0, recent_slope_per_h=0.0, delta=0.0,
                   status=NO_DATA)
        return doc
    slope = _slope_per_h(kept)
    recent = _slope_per_h(kept[len(kept) // 2:])
    delta = kept[-1][1] - kept[0][1]
    doc.update(slope_per_h=round(slope, 3),
               recent_slope_per_h=round(recent, 3),
               delta=round(delta, 3))
    material = delta >= slo.min_delta
    if slope > slo.objective and recent > slo.objective and material:
        doc["status"] = BREACH
    elif slope > slo.objective and material:
        # the full window regressed but the recent half flattened —
        # the growth stopped (a filled cache, a completed pass), so
        # surface it without flipping health
        doc["status"] = WARN
    else:
        doc["status"] = OK
    return doc


def evaluate_slo(slo: SLO, samples_for: Callable[[float],
                                                 list[tuple[float, float]]],
                 now: float | None = None) -> dict[str, Any]:
    """One SLO against a window-reader ``samples_for(seconds) ->
    [(ts, value)]``."""
    if slo.kind == "trend":
        window = samples_for(slo.trend_window_s)
        trend = _evaluate_trend(slo, window)
        return {
            "name": slo.name,
            "series": slo.series,
            "kind": slo.kind,
            "objective": slo.objective,
            "target": slo.target,
            "description": slo.description,
            "current": window[-1][1] if window else None,
            "windows": {"trend": {k: v for k, v in trend.items()
                                  if k != "status"}},
            "status": trend["status"],
        }
    fast = samples_for(slo.fast_window_s)
    slow = samples_for(slo.slow_window_s)
    current = fast[-1][1] if fast else (slow[-1][1] if slow else None)
    doc: dict[str, Any] = {
        "name": slo.name,
        "series": slo.series,
        "kind": slo.kind,
        "objective": slo.objective,
        "target": slo.target,
        "description": slo.description,
        "current": current,
    }
    if slo.kind == "zero_tolerance":
        inc = _counter_increase(fast)
        doc["windows"] = {
            "fast": {"seconds": slo.fast_window_s, "samples": len(fast),
                     "increase": inc},
        }
        if not fast:
            doc["status"] = NO_DATA
        else:
            doc["status"] = BREACH if inc > 0 else OK
        return doc
    f, s = _window_stats(slo, fast), _window_stats(slo, slow)
    doc["windows"] = {
        "fast": {"seconds": slo.fast_window_s, **f,
                 "burn_threshold": slo.fast_burn},
        "slow": {"seconds": slo.slow_window_s, **s,
                 "burn_threshold": slo.slow_burn},
    }
    if f["samples"] == 0 and s["samples"] == 0:
        doc["status"] = NO_DATA
    elif f["burn"] >= slo.fast_burn and s["burn"] >= slo.slow_burn:
        doc["status"] = BREACH
    elif f["burn"] >= slo.fast_burn:
        doc["status"] = WARN
    else:
        doc["status"] = OK
    return doc


def evaluate(history: Any = None, *, directory: str | None = None,
             now: float | None = None) -> dict[str, Any]:
    """Every registered SLO against a history source: a live
    :class:`~spacedrive_tpu.telemetry.history.HistoryWriter` (tail-backed
    fast path — the /health + federation read), or a bare history
    ``directory`` (``sdx slo`` offline / post-restart)."""
    from . import metrics as _tm

    now = now if now is not None else time.time()
    results: list[dict[str, Any]] = []
    worst = NO_DATA
    for slo in REGISTRY.all():
        if history is not None:
            def samples_for(seconds: float, _s=slo) \
                    -> list[tuple[float, float]]:
                recs = history.recent(seconds, now=now)
                return [
                    (r["ts"], float(r["v"][_s.series]))
                    for r in recs
                    if isinstance((r.get("v") or {}).get(_s.series),
                                  (int, float))
                    and not isinstance(r["v"][_s.series], bool)
                ]
        elif directory is not None:
            from .history import series as _series

            def samples_for(seconds: float, _s=slo) \
                    -> list[tuple[float, float]]:
                return _series(directory, _s.series, since=now - seconds,
                               until=now)
        else:
            def samples_for(seconds: float) -> list[tuple[float, float]]:
                return []
        doc = evaluate_slo(slo, samples_for, now=now)
        results.append(doc)
        if _RANK[doc["status"]] > _RANK[worst] or (
            worst == NO_DATA and doc["status"] == OK
        ):
            # rank-0 tie: an evaluated-and-met objective upgrades the
            # rollup from "no data" to "ok"
            worst = doc["status"]
        _tm.SLO_STATUS.set(_RANK[doc["status"]], slo=slo.name)
    evaluation = {"ts": now, "status": worst, "slos": results}
    _tm.SLO_EVALUATIONS.inc()
    REGISTRY.last_evaluation = evaluation
    # a warn/breach opens a host-profiler deep-capture window: the
    # flight recorder gains "what was Python doing when the budget
    # started burning". The sampler's own hysteresis absorbs repeats —
    # health polls re-evaluating a burning SLO open ONE window per
    # cooldown, never a storm.
    from . import sampler as _sampler

    if worst == BREACH:
        _sampler.trigger("slo_breach")
    elif worst == WARN:
        _sampler.trigger("slo_warn")
    return evaluation


def reset() -> None:
    REGISTRY.reset()
