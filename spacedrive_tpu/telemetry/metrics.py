"""Metric families for the TPU dispatch path — one definition site.

Naming follows Prometheus conventions with an ``sd_`` prefix:
``_total`` counters, ``_seconds`` histograms, base-unit gauges. Label
cardinality stays deliberately tiny (stage/result/job names) — see
registry.MAX_SERIES_PER_FAMILY for the backstop.

Hot paths import these handles directly (module attribute access, no
lookup or allocation per event); everything registers on the process
default ``REGISTRY`` so /metrics, telemetry.snapshot, and bench.py all
read the same series.
"""

from __future__ import annotations

from .registry import (
    BYTE_BUCKETS,
    RATIO_BUCKETS,
    REGISTRY,
    TIME_BUCKETS,
)

# --- task system (tasks/system.py) -----------------------------------------

TASK_QUEUE_WAIT = REGISTRY.histogram(
    "sd_task_queue_wait_seconds",
    "time a task spent queued on a worker before execution started",
)
TASK_DISPATCH_LATENCY = REGISTRY.histogram(
    "sd_task_dispatch_latency_seconds",
    "dispatch() call to first execution start, per task",
)
TASK_BATCH_OCCUPANCY = REGISTRY.histogram(
    "sd_task_batch_occupancy",
    "fraction of workers busy when a task starts executing",
    buckets=RATIO_BUCKETS,
)
TASK_STEALS = REGISTRY.counter(
    "sd_task_steals_total",
    "tasks stolen between local task-system workers (the in-process "
    "mirror of the mesh plane's sd_work_steals_total)",
)
TASKS_DISPATCHED = REGISTRY.counter(
    "sd_tasks_dispatched_total", "tasks handed to the task system",
)

# --- host→device feeder (parallel/feeder.py) --------------------------------

FEEDER_H2D_BYTES = REGISTRY.counter(
    "sd_feeder_h2d_bytes_total",
    "bytes staged for host→device transfer by the window pipeline",
)
FEEDER_FETCH_SECONDS = REGISTRY.histogram(
    "sd_feeder_fetch_seconds",
    "producer-side time to read+dispatch one window",
)
FEEDER_WAIT_SECONDS = REGISTRY.histogram(
    "sd_feeder_wait_seconds",
    "consumer-side time blocked waiting for the next window",
)
FEEDER_INFLIGHT = REGISTRY.gauge(
    "sd_feeder_inflight_depth",
    "ready windows parked in the pipeline queue",
)
FEEDER_PREFETCH = REGISTRY.counter(
    "sd_feeder_prefetch_total",
    "window handoffs by outcome",
    labels=("result",),  # hit | miss
)

# --- file identifier (object/file_identifier/job.py) ------------------------

IDENTIFIER_FILES = REGISTRY.counter(
    "sd_identifier_files_total",
    "file_paths pushed through cas_id identification",
)
IDENTIFIER_BATCH_FILL = REGISTRY.histogram(
    "sd_identifier_batch_fill_ratio",
    "rows in an identify window relative to the configured chunk size",
    buckets=RATIO_BUCKETS,
)
IDENTIFIER_STAGE_SECONDS = REGISTRY.histogram(
    "sd_identifier_stage_seconds",
    "per-window time split between device hash and DB linking",
    labels=("stage",),  # hash | db
)

# --- thumbnailer (object/media/thumbnail/actor.py) --------------------------

THUMB_FILES = REGISTRY.counter(
    "sd_thumbnailer_files_total",
    "thumbnail outcomes",
    labels=("result",),  # generated | skipped | error
)
THUMB_BATCH_FILL = REGISTRY.histogram(
    "sd_thumbnail_batch_fill_ratio",
    "images in a device chunk relative to the device-count-scaled "
    "chunk size (DEVICE_BATCH × accelerator_count)",
    buckets=RATIO_BUCKETS,
)
THUMB_STAGE_SECONDS = REGISTRY.histogram(
    "sd_thumbnail_stage_seconds",
    "per-chunk time split across the pipelined stages: host decode, "
    "device resize, host webp encode+store",
    labels=("stage",),  # decode | device | encode
)

# --- semantic search (models/embedder.py, object/search/index.py) -----------

EMBED_FILES = REGISTRY.counter(
    "sd_embed_files_total",
    "media-pipeline embedding outcomes per file: embedded (vector "
    "computed and persisted), skipped (journal vouched — unchanged "
    "bytes), error (undecodable image)",
    labels=("result",),  # embedded | skipped | error
)
EMBED_STAGE_SECONDS = REGISTRY.histogram(
    "sd_embed_stage_seconds",
    "per-chunk time split across the embedding stages: host/pool "
    "decode, device forward, DB+sync write",
    labels=("stage",),  # decode | forward | write
)
SEARCH_QUERIES = REGISTRY.counter(
    "sd_search_queries_total",
    "semantic search queries by scoring path (device = jitted matmul "
    "top-k, host = numpy fallback after a device failure)",
    labels=("path",),  # device | host
)
SEARCH_QUERY_SECONDS = REGISTRY.histogram(
    "sd_search_query_seconds",
    "end-to-end semantic query latency: probe embed + index scoring "
    "+ row hydration",
)
SEARCH_INDEX_VECTORS = REGISTRY.gauge(
    "sd_search_index_vectors",
    "vectors in the most recently refreshed per-library search index",
)

# --- udp stream (p2p/udpstream.py) ------------------------------------------

UDP_RETRANSMITS = REGISTRY.counter(
    "sd_udp_retransmits_total",
    "segments re-sent (fast retransmit + RTO bursts)",
)
UDP_RWND_STALLS = REGISTRY.counter(
    "sd_udp_rwnd_stalls_total",
    "zero-window stalls that armed the persist-probe timer",
)
UDP_BAD_ACKS = REGISTRY.counter(
    "sd_udp_bad_acks_total",
    "ACKs ignored because they acknowledged beyond the flight",
)
UDP_ACK_RTT = REGISTRY.histogram(
    "sd_udp_ack_rtt_seconds",
    "clean (Karn-filtered) ACK round-trip samples",
)

# --- jobs (jobs/job.py + jobs/manager.py) -----------------------------------

JOB_PHASE_SECONDS = REGISTRY.histogram(
    "sd_job_phase_seconds",
    "wall time per job phase (phase transitions via ctx.progress)",
    labels=("job", "phase"),
)

# --- bench (bench.py) -------------------------------------------------------

BENCH_LINK_PROBE_GBPS = REGISTRY.gauge(
    "sd_bench_link_probe_gbps",
    "latest host→device link probe (device_put bandwidth)",
)
# bench reads its median/spread back out of these rings, so they must
# hold every sample of the largest plausible SD_BENCH_REPEATS run —
# the default 128-sample ring would silently truncate repeats > 128
BENCH_DEVICE_BATCH_SECONDS = REGISTRY.histogram(
    "sd_bench_device_batch_seconds",
    "marginal device compute per chained batch (bench.py)",
    recent_samples=4096,
)
BENCH_E2E_BATCH_SECONDS = REGISTRY.histogram(
    "sd_bench_e2e_batch_seconds",
    "end-to-end host→device→digest time per batch (bench.py)",
    recent_samples=4096,
)

# --- multi-device dp dispatch (ops/blake3_jax.py + ops/thumbnail_jax.py) ----

# rows-per-device of a sharded dispatch: powers of two covering the
# batch ladder (32..1024 per device) with headroom for bigger rungs
ROW_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)

SHARD_BATCH_ROWS = REGISTRY.histogram(
    "sd_device_shard_batch_rows",
    "rows each device receives in a dp-sharded dispatch",
    labels=("op",),  # blake3 | thumbnail | embed
    buckets=ROW_BUCKETS,
)
DEVICE_DISPATCH_OCCUPANCY = REGISTRY.histogram(
    "sd_device_dispatch_occupancy",
    "fraction of a device's shard rows holding real (non-pad) work, "
    "one observation per device per sharded dispatch",
    labels=("op",),  # blake3 | thumbnail | embed
    buckets=RATIO_BUCKETS,
)
CAS_BACKEND_FALLBACK = REGISTRY.counter(
    "sd_cas_backend_fallback_total",
    "cas_ids('auto') device failures that degraded to the host backend",
)

# --- index journal (location/indexer/journal.py) ----------------------------

INDEX_JOURNAL_OPS = REGISTRY.counter(
    "sd_index_journal_ops_total",
    "index-journal consults by verdict: hit (identity matches, cached "
    "result reused), miss (no usable entry), invalidated (entry present "
    "but stale/identity changed), bypassed (journal disabled or entry "
    "corrupt — degraded to a cold pass)",
    labels=("result",),  # hit | miss | invalidated | bypassed
)
INDEX_JOURNAL_BYTES_SAVED = REGISTRY.counter(
    "sd_index_journal_bytes_saved_total",
    "bytes NOT read/hashed/shipped because the journal vouched for them "
    "(journal hits plus clean chunks of dirty-range rehashes)",
)
INDEX_BYTES_HASHED = REGISTRY.counter(
    "sd_index_bytes_hashed_total",
    "message bytes actually hashed by the identifier (device batches "
    "plus dirty chunks of host dirty-range rehashes)",
)

# --- pipeline device/host split (identify + thumbnail drivers) --------------

PIPELINE_DEVICE_SECONDS = REGISTRY.histogram(
    "sd_pipeline_device_seconds",
    "per-batch device time (hash materialization / device resize)",
    labels=("pipeline",),  # identify | thumbnail
)
PIPELINE_HOST_SECONDS = REGISTRY.histogram(
    "sd_pipeline_host_seconds",
    "per-batch host time (window wait + DB linking / image decode)",
    labels=("pipeline",),  # identify | thumbnail
)

# --- sync / replication (sync/ingest.py + sync/manager.py) ------------------
# Per-peer series label by telemetry.peers.peer_label (capped stable
# short-hash of the instance pub_id) — NEVER the raw identifier
# (sdlint SD010).

SYNC_OPS = REGISTRY.counter(
    "sd_sync_ops_total",
    "CRDT ops ingested from remote instances, by outcome",
    labels=("result",),  # applied | stale | tombstone
)
SYNC_LAG = REGISTRY.gauge(
    "sd_sync_lag_seconds",
    "replication lag per remote instance: wall-clock now minus the "
    "latest applied HLC timestamp from that peer",
    labels=("peer",),
)
SYNC_WATERMARK = REGISTRY.gauge(
    "sd_sync_watermark_seconds",
    "latest applied HLC timestamp per remote instance (unix seconds)",
    labels=("peer",),
)
HLC_DELTA_GUARD = REGISTRY.counter(
    "sd_hlc_delta_guard_total",
    "remote ops rejected because their HLC timestamp exceeded the "
    "delta guard (clock too far in the future)",
)
HLC_CLOCK_SKEW = REGISTRY.gauge(
    "sd_hlc_clock_skew_seconds",
    "last observed remote-op HLC timestamp minus local wall clock, "
    "per remote instance (positive = remote clock ahead)",
    labels=("peer",),
)
SYNC_INGEST_BACKLOG = REGISTRY.gauge(
    "sd_sync_ingest_backlog",
    "ops fetched by the ingest actor and not yet applied (current batch)",
)

# --- telemetry federation (telemetry/federation.py + p2p) -------------------

FED_PULLS = REGISTRY.counter(
    "sd_federation_pulls_total",
    "peer telemetry-snapshot pulls by outcome and transport",
    labels=("result",),  # p2p | relay | error
)
FED_SNAPSHOT_AGE = REGISTRY.gauge(
    "sd_federation_snapshot_age_seconds",
    "age of the freshest cached snapshot per peer",
    labels=("peer",),
)
FED_PEERS = REGISTRY.gauge(
    "sd_federation_peers",
    "peers currently tracked by the federation cache, by freshness",
    labels=("state",),  # fresh | stale
)

# --- mesh work-stealing (p2p/work.py + location/indexer/mesh.py) ------------

WORK_SHARDS = REGISTRY.counter(
    "sd_work_shards_total",
    "distributed work shards by outcome: published (added to a "
    "session), completed_local / completed_remote (first completion, by "
    "executor side), duplicate (a re-stolen or raced shard completed "
    "again — idempotent merge absorbed it), expired (lease deadline "
    "passed; shard returned to the steal pool), refused (claim denied "
    "by health verdict or breaker). `stage` is the shard's pipeline "
    "stage from the scheduler registry ('any' when the outcome has no "
    "shard context, e.g. a refused claim)",
    labels=("result", "stage"),
)
WORK_STEALS = REGISTRY.counter(
    "sd_work_steals_total",
    "shards leased to remote peers (one increment per shard per grant), "
    "labeled by the claiming peer's short-hash and the shard's stage",
    labels=("peer", "stage"),
)
WORK_LEASE_SECONDS = REGISTRY.histogram(
    "sd_work_lease_seconds",
    "lease durations granted to shard claims (sized per stage from the "
    "claimer's self-reported throughput, the Controller's per-stage "
    "target, or the static default — in that order)",
    labels=("stage",),
    buckets=(1, 5, 10, 30, 60, 120, 300),
)
WORK_STAGE_RATE = REGISTRY.gauge(
    "sd_work_stage_rate_files_per_s",
    "per-stage shard throughput EWMA observed by this node's executors "
    "(the execution continuum's lease-sizing input; see "
    "parallel/scheduler.py)",
    labels=("stage",),
)
WORK_STAGE_LEASE_TARGET = REGISTRY.gauge(
    "sd_work_stage_lease_target_seconds",
    "the Controller's per-stage lease target: the lease a default-sized "
    "shard would get at the stage's observed rate (0 until the stage "
    "has run; the WORK board's fallback when a claimer reports no rate)",
    labels=("stage",),
)

# --- resilience + fault plane (utils/resilience.py + utils/faults.py) -------
# Per-target breaker detail stays on the `resilience` flight ring
# (bounded; values may carry peer_label short-hashes) — the metric
# families here are deliberately label-free so the series space stays
# O(1) no matter how many peers/relays a node talks to.

FAULTS_INJECTED = REGISTRY.counter(
    "sd_faults_injected_total",
    "fault-plane activations (chaos testing only; 0 in production)",
)
RESILIENCE_RETRIES = REGISTRY.counter(
    "sd_resilience_retries_total",
    "backoff sleeps taken by resilience-policy retry ladders",
)
BREAKER_TRANSITIONS = REGISTRY.counter(
    "sd_breaker_transitions_total",
    "circuit-breaker state transitions, by state entered",
    labels=("state",),  # open | half_open | closed
)
BREAKER_OPEN = REGISTRY.gauge(
    "sd_breaker_open",
    "circuit breakers currently open across all policies/targets",
)
DEVICE_DEMOTION = REGISTRY.gauge(
    "sd_device_demotion_level",
    "device dispatch degradation rung: 0 = full mesh, 1 = surviving "
    "chip subset, 2 = host reference path",
)
FEEDER_RESTARTS = REGISTRY.counter(
    "sd_feeder_restarts_total",
    "window-pipeline producer threads restarted after a crash",
)

# --- multi-process execution plane (parallel/procpool.py) -------------------
# Counters/histograms here are OWNER-side series. Workers accumulate
# into their own per-process registry and ship additive deltas back
# with each batch result (registry.merge_delta) — gauges never merge.

PROCPOOL_WORKERS = REGISTRY.gauge(
    "sd_procpool_workers",
    "worker processes currently alive in the multi-process execution "
    "plane (0 = SD_PROCS disabled or pool stopped)",
)
PROCPOOL_JOBS = REGISTRY.counter(
    "sd_procpool_jobs_total",
    "pool batches by outcome: ok (result + telemetry delta merged), "
    "error (worker raised — the call site falls back to its inline "
    "path), retried (re-dispatched after a worker died mid-batch)",
    labels=("result",),  # ok | error | retried
)
PROCPOOL_DISPATCH_SECONDS = REGISTRY.histogram(
    "sd_procpool_dispatch_seconds",
    "owner-side submit cost per batch (msgpack serialization + queue "
    "put — the IPC tax the PipelinePolicy batch quantum amortizes)",
)
PROCPOOL_ROUNDTRIP_SECONDS = REGISTRY.histogram(
    "sd_procpool_roundtrip_seconds",
    "submit-to-result wall time per pool batch",
)
PROCPOOL_BATCH_ROWS = REGISTRY.histogram(
    "sd_procpool_batch_rows",
    "rows per shipped pool batch (sized by the per-workload "
    "PipelinePolicy procpool quantum)",
    buckets=ROW_BUCKETS,
)
PROCPOOL_RESTARTS = REGISTRY.counter(
    "sd_procpool_restarts_total",
    "worker processes restarted after dying mid-batch (each dead "
    "worker's in-flight batches are re-dispatched exactly once)",
)

# --- closed-loop autotuner (parallel/autotune.py) ---------------------------

AUTOTUNE_DECISIONS = REGISTRY.counter(
    "sd_autotune_decisions_total",
    "autotuner knob adjustments, by workload and direction",
    labels=("workload", "action"),  # identify|thumbnail × promote|demote
)
AUTOTUNE_WINDOW_SCALE = REGISTRY.gauge(
    "sd_autotune_window_scale",
    "current multiplier on the static host window / chunk rows",
    labels=("workload",),
)
AUTOTUNE_RUNG = REGISTRY.gauge(
    "sd_autotune_batch_rung",
    "current per-device dispatch rung index into the batch ladder "
    "(0 = smallest, never above the DeviceLadder demotion cap)",
    labels=("workload",),
)
AUTOTUNE_DEPTH_EXTRA = REGISTRY.gauge(
    "sd_autotune_depth_extra",
    "additive adjustment the autotuner applies to the feeder depth",
    labels=("workload",),
)
AUTOTUNE_POOL_SCALE = REGISTRY.gauge(
    "sd_autotune_pool_scale",
    "current multiplier on the static procpool batch quantum (the "
    "Controller grows it when the per-batch dispatch share says the "
    "IPC tax dominates, shrinks it on long roundtrips or underfilled "
    "batches)",
    labels=("workload",),
)

# --- serve layer: admission gate + read cache (spacedrive_tpu/serve/) -------

GATE_REQUESTS = REGISTRY.counter(
    "sd_gate_requests_total",
    "admission-gate outcomes per priority class: admitted (ran), "
    "queued (parked for a slot before running), shed (fast-failed "
    "429/SHED)",
    labels=("klass", "outcome"),  # control|sync|interactive|background
)
GATE_INFLIGHT = REGISTRY.gauge(
    "sd_gate_inflight",
    "requests currently holding an admission slot, per priority class",
    labels=("klass",),
)
GATE_QUEUE_SECONDS = REGISTRY.histogram(
    "sd_gate_queue_seconds",
    "time a request spent parked waiting for an admission slot",
    labels=("klass",),
)
GATE_MODE = REGISTRY.gauge(
    "sd_gate_mode",
    "serve-gate mode: 0 = normal, 1 = brownout (degraded serving — "
    "stale cache answers allowed, background sheds immediately)",
)
SERVE_CACHE_OPS = REGISTRY.counter(
    "sd_serve_cache_ops_total",
    "read-path cache outcomes per region: hit, miss (loaded), stale "
    "(brownout stale-while-revalidate answer), coalesced (rode another "
    "caller's in-flight load), bypass",
    labels=("cache", "result"),  # query|thumb|meta
)
SERVE_CACHE_ENTRIES = REGISTRY.gauge(
    "sd_serve_cache_entries",
    "live entries per cache region",
    labels=("cache",),
)
SERVE_CACHE_INVALIDATIONS = REGISTRY.counter(
    "sd_serve_cache_invalidations_total",
    "cache entries dropped by the invalidation plane, by trigger: "
    "local (mutation via invalidate_query) or sync (remote ops applied "
    "by the ingest actor)",
    labels=("source",),  # local | sync
)
SYNC_TXN_COMBINED = REGISTRY.counter(
    "sd_sync_txn_combined_total",
    "per-op SQLite transactions avoided by write-combined sync ingest "
    "(ops coalesced into a shared transaction, minus the one "
    "transaction that carried them)",
)

# --- flight-recorder drop accounting (telemetry/events.py) ------------------

RING_DROPPED = REGISTRY.counter(
    "sd_ring_dropped_total",
    "flight-recorder events silently displaced by ring overflow (the "
    "bounded deque dropped its oldest entry to admit a new one) — a "
    "nonzero count means the debug bundle's rings are a suffix, not "
    "the whole story",
    labels=("ring",),
)

# --- critical-path attribution (telemetry/attrib.py) ------------------------

ATTRIB_REPORTS = REGISTRY.counter(
    "sd_attrib_reports_total",
    "critical-path attribution reports computed (GET /attrib, rspc "
    "telemetry.attrib, sdx attrib, bench_e2e summaries)",
)
ATTRIB_BUCKET_SECONDS = REGISTRY.gauge(
    "sd_attrib_bucket_seconds",
    "wall-clock seconds per attribution bucket of the most recent "
    "report: device / host_cpu / link / queue_wait / gap (the "
    "unattributed-gap bucket is the GIL signature)",
    labels=("bucket",),
)
ATTRIB_PULL_FAILURES = REGISTRY.counter(
    "sd_attrib_pull_failures_total",
    "remote trace_pull exchanges that failed during distributed trace "
    "assembly (the report degrades to partial, never blocks)",
)

# --- telemetry history + SLO engine (telemetry/history.py, telemetry/slo.py)

HISTORY_SAMPLES = REGISTRY.counter(
    "sd_history_samples_total",
    "samples appended to the persistent telemetry history segment store",
)
SLO_EVALUATIONS = REGISTRY.counter(
    "sd_slo_evaluations_total",
    "SLO registry evaluations (health reads, federation snapshots, "
    "sdx slo)",
)
SLO_STATUS = REGISTRY.gauge(
    "sd_slo_status",
    "latest per-SLO verdict: 0 = ok/no-data, 1 = warn (fast-window "
    "burn), 2 = breach (fast AND slow windows burning)",
    labels=("slo",),
)

# --- serve request latency (api/server.py admission middleware) -------------

SERVE_REQUEST_SECONDS = REGISTRY.histogram(
    "sd_serve_request_seconds",
    "admitted HTTP request wall time per priority class (handler run "
    "under its admission slot) — the interactive series is the "
    "interactive_p99 SLO input",
    labels=("klass",),
)

# --- continuous host profiler (telemetry/sampler.py) ------------------------
# Deliberately label-free: the per-kind / per-state / per-group splits
# live in the profile document and federation summary, not the series
# space — the sampler must stay O(1) registry cost at any stack shape.

PROFILE_SAMPLES = REGISTRY.counter(
    "sd_profile_samples_total",
    "thread-stack samples folded into the continuous host profiler's "
    "collapsed-stack accumulator (one per live thread per tick; the "
    "sampler's own thread is exempt from its own accounting)",
)
PROFILE_CAPTURES = REGISTRY.counter(
    "sd_profile_captures_total",
    "triggered deep-capture windows opened (SLO warn/breach, loop-lag "
    "degradation, brownout entry, manual) — hysteresis guarantees at "
    "most one per cooldown, so a flapping signal cannot storm this",
)
PROFILE_STACKS = REGISTRY.gauge(
    "sd_profile_stacks",
    "distinct collapsed stacks currently tracked by the profiler's "
    "bounded accumulator (cap: 4096; overflow folds into a drop count "
    "reported by the profile document)",
)
PROFILE_OVERHEAD = REGISTRY.gauge(
    "sd_profile_overhead_ratio",
    "the profiler's self-measured duty cycle: cumulative sampling CPU "
    "time over wall time since start — the ≤5% overhead contract's "
    "always-on witness",
)

# --- resource-growth sampler (telemetry/resources.py) -----------------------
# Process-level growth surfaces sampled at low rate; the history store
# turns these gauges into resource_* series and the trend SLO class
# judges their slopes (leaks show up as gated regressions, not OOMs).

RESOURCE_RSS = REGISTRY.gauge(
    "sd_resource_rss_bytes",
    "resident set size of this process from /proc/self/status (VmRSS); "
    "the rss_growth trend SLO bounds its slope in MB/h after warmup",
)
RESOURCE_FDS = REGISTRY.gauge(
    "sd_resource_fds",
    "open file descriptors in this process (/proc/self/fd count); the "
    "fd_growth trend SLO expects this flat at steady state",
)
RESOURCE_THREADS = REGISTRY.gauge(
    "sd_resource_threads",
    "OS threads in this process (/proc/self/status Threads:)",
)
RESOURCE_PROCPOOL_RSS = REGISTRY.gauge(
    "sd_resource_procpool_rss_bytes",
    "summed resident set size of live procpool workers "
    "(/proc/<pid>/statm over the multi-process plane; 0 with SD_PROCS=0)",
)
RESOURCE_INVENTORY = REGISTRY.gauge(
    "sd_resource_inventory",
    "in-process inventory sizes over a fixed kind vocabulary: "
    "journal_rows, oplog_rows (summed over open libraries), "
    "serve_cache_entries, serve_cache_bytes, history_bytes, ring_drops "
    "— journal/oplog rows should track corpus size, not pass count",
    labels=("kind",),
)

# --- event loop health (telemetry/events.py LoopLagMonitor) -----------------

EVENT_LOOP_LAG = REGISTRY.gauge(
    "sd_event_loop_lag_seconds",
    "latest sampled event-loop scheduling lag",
)

# --- spans (telemetry/spans.py) ---------------------------------------------

SPAN_SECONDS = REGISTRY.histogram(
    "sd_span_seconds",
    "pipeline span wall time by stage",
    labels=("stage",),
)
SPAN_BYTES = REGISTRY.counter(
    "sd_span_bytes_total",
    "bytes attributed to pipeline spans by stage",
    labels=("stage",),
)

# --- per-tenant accounting (telemetry/tenants.py) ---------------------------

TENANT_OPS = REGISTRY.counter(
    "sd_tenant_ops_total",
    "per-tenant observations by surface (serve, cache_hit/miss, "
    "relay_push/pull, p2p_sync/work/telemetry, ingest, bytes_in/out — "
    "byte surfaces weight by payload size); tenant labels are blake2b "
    "tenant_label hashes for sketch residents, with every non-resident "
    "folded into the aggregated `other` bucket so a million-library "
    "relay stays inside the series cap",
    labels=("surface", "tenant"),
)
TENANT_SECONDS = REGISTRY.histogram(
    "sd_tenant_request_seconds",
    "request latency for sketch-resident tenants (serve surface), "
    "`other` aggregates the non-resident tail",
    labels=("surface", "tenant"),
)
TENANT_FAIRNESS = REGISTRY.gauge(
    "sd_tenant_fairness_index",
    "Jain's fairness index over resident tenant counts per surface: "
    "1.0 = equal shares, -> 1/n under a single dominant tenant; "
    "feeds the tenant_fairness SLO via the history series",
    labels=("surface",),
)
TENANT_DOMINANT = REGISTRY.gauge(
    "sd_tenant_dominant_share",
    "largest resident tenant's share of the surface total",
    labels=("surface",),
)
TENANT_RESIDENTS = REGISTRY.gauge(
    "sd_tenant_sketch_residents",
    "tenants currently resident in the surface's space-saving sketch "
    "(bounded by SD_TENANT_TOPK)",
    labels=("surface",),
)
