"""Telemetry — metrics registry, pipeline spans, snapshot/scrape APIs.

The observability layer for the TPU dispatch path (BENCH_r05's lesson:
device compute at 610k files/s with e2e at 489 files/s was only
explainable by ad-hoc prints — now the queue waits, batch occupancy,
H2D byte counts, and per-phase durations are first-class series).

Surface:

- ``REGISTRY`` / ``counter`` / ``gauge`` / ``histogram`` — the
  process-wide metrics registry (Prometheus text via ``render()``);
- ``metrics`` — every predeclared family for the hot paths;
- ``span(stage, nbytes=0)`` — sync/async context manager recording
  per-stage wall time and bytes;
- ``snapshot()`` — the JSON read path (rspc ``telemetry.snapshot``,
  bench.py);
- ``render()`` — Prometheus exposition text (the ``/metrics`` route);
- ``trace`` / ``trace_export()`` — distributed trace ids on every span,
  exported as Chrome-trace JSON (the ``/trace`` route);
- ``events`` — flight-recorder rings; ``debug_bundle()`` — the redacted
  support artifact (docs/observability.md);
- ``reset()`` — test isolation across metrics, spans, traces, rings.
"""

from . import (
    attrib,
    events,
    federation,
    health,
    history,
    metrics,
    resources,
    sampler,
    slo,
    tenants,
    trace,
)
from .registry import (
    BYTE_BUCKETS,
    MAX_SERIES_PER_FAMILY,
    OVERFLOW_LABEL,
    RATIO_BUCKETS,
    REGISTRY,
    TIME_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from .snapshot import counter_value, gauge_value, histogram_recent, snapshot
from .spans import Span, clear_recent, current_span, recent_spans, span


def render() -> str:
    return REGISTRY.render()


def reset() -> None:
    """Test/bench isolation: zero every metric series AND clear the
    span ring, the trace ring, every flight-recorder ring, the
    attribution report cache + pass markers, SLO evaluation state, the
    host profiler's accumulators + capture-window ring + trigger
    state, the resource sampler's last-sample state + planted test
    leaks, the tenant plane's heavy-hitter sketches, and every
    history writer's in-memory tail (durable history
    segments are data-dir state and deliberately survive)."""
    REGISTRY.reset()
    clear_recent()
    trace.clear()
    events.clear_all()
    attrib.reset()
    slo.reset()
    sampler.reset()
    resources.reset()
    tenants.reset()
    history.reset_tails()
    # the index journal's per-location runtime counters + stats cache
    # live like registry series (lazy import: journal imports metrics)
    from ..location.indexer.journal import reset_runtime

    reset_runtime()
    # the execution continuum's per-stage throughput EWMAs and the
    # Controller's derived lease targets are registry-like state too
    from ..parallel import scheduler as _scheduler

    _scheduler.reset()


def trace_export(trace_id=None):
    """Chrome-trace-event JSON of the completed-span ring, with the
    host profiler's capture-window samples merged onto a dedicated
    ``host-profile`` lane (the ``GET /trace`` + ``telemetry.trace_export``
    payload — Perfetto shows what Python was doing beside the spans).
    With a ``trace_id`` filter, profiler events are clipped to the
    filtered spans' time range — captures from unrelated incidents
    must not stretch one trace's timeline into a sliver."""
    doc = trace.export(trace_id)
    profile_events = sampler.SAMPLER.chrome_events()
    if trace_id is not None and profile_events:
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        if not spans:
            return doc
        lo = min(e["ts"] for e in spans)
        hi = max(e["ts"] + e.get("dur", 0) for e in spans)
        profile_events = [
            e for e in profile_events
            if e.get("ph") == "M" or lo <= e.get("ts", 0) <= hi
        ]
        if all(e.get("ph") == "M" for e in profile_events):
            profile_events = []  # nothing landed in-window: no lane
    doc["traceEvents"].extend(profile_events)
    return doc


def debug_bundle(node=None, data_dir=None):
    """The redacted debug bundle dict (see telemetry.bundle)."""
    from .bundle import build_bundle

    return build_bundle(node, data_dir)


def counter(name: str, help: str = "", labels=()):
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels=()):
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels=(), buckets=TIME_BUCKETS):
    return REGISTRY.histogram(name, help, labels, buckets)


__all__ = [
    "REGISTRY", "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "TIME_BUCKETS", "RATIO_BUCKETS", "BYTE_BUCKETS",
    "MAX_SERIES_PER_FAMILY", "OVERFLOW_LABEL",
    "metrics", "span", "Span", "current_span", "recent_spans",
    "clear_recent", "snapshot", "histogram_recent", "gauge_value",
    "counter_value", "render", "counter", "gauge", "histogram",
    "trace", "events", "reset", "trace_export", "debug_bundle",
    "health", "federation", "attrib", "history", "slo", "sampler",
    "resources", "tenants",
]
