"""Per-tenant observability plane — bounded-cardinality accounting.

ROADMAP open item 4 (million-user relay fairness) needs a control
signal before it can have an enforcement loop: *which* library or
instance is consuming each shared surface, and how unevenly. This
module builds that signal the way the burn-rate plane (PR 12) built
the scheduler's (PR 19): observability first, enforcement next.

The cardinality problem is structural — a relay serving a million
libraries cannot mint a metric series per library. So every surface
gets a **space-saving heavy-hitter sketch** (Metwally et al., the
Misra–Gries family): at most ``K`` resident tenants with exact-ish
counters (each carries an explicit overestimate bound ``err``, the
count it inherited on eviction-replacement), plus a single aggregated
``other`` bucket for everything that never earned residency. Resident
counts are exact for tenants that were never evicted (``err == 0``) —
under zipf-shaped load the true top-K land there with high
probability, which the multi-tenant ``bench_serve`` leg measures as
top-K **recall vs an exact oracle** (gated ≥ 0.9).

Tenant keys are NEVER raw identifiers: :func:`tenant_label` is the
``peers.peer_label`` discipline (blake2b, 8 hex chars) applied to
library/instance ids, enforced tree-wide by sdlint SD027. The label
is what rides metrics, ``/tenants``, federation digests, and debug
bundles — a planted UUID must never appear on any of them.

Surfaces (fixed vocabulary — tap sites pass these constants):

- ``serve``          rspc/HTTP serve-plane requests per library
                     (api/router.py exec, with admitted latency)
- ``cache_hit``      serve read-cache hits (hit/stale/coalesced)
- ``cache_miss``     serve read-cache loader runs per library
- ``relay_push``     relay-side op pushes per library (cloud/relay.py)
- ``relay_pull``     relay-side op pulls per library
- ``p2p_sync``       P2P SYNC/SYNC_REQUEST responder ops per library
- ``p2p_work``       P2P WORK responder ops per library
- ``p2p_telemetry``  P2P TELEMETRY responder ops per remote instance
- ``ingest``         CRDT ops committed per origin instance
- ``bytes_in``       payload bytes received, weighted by size
- ``bytes_out``      payload bytes served, weighted by size

Derived signals ride the existing planes with zero new wire surface:
Jain's fairness index + dominant-share gauges per surface, the
``tenant_fairness_index`` history series feeding a ``tenant_fairness``
SLO (multi-window burn rates), a ``tenants`` health subsystem
federated onto every peer's ``GET /mesh``, ``GET /tenants`` +
rspc ``telemetry.tenants`` + ``sdx tenants`` read paths, and a
redaction-clean debug-bundle section.

``SD_TENANT_OBS=0`` is a true no-op: no sketches, no tenant history
series, no ``tenant_fairness`` SLO, no health subsystem signal, no
federation digest — served bytes stay golden bit-identical.
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Any

from . import metrics as _tm
from .peers import peer_label
from .registry import TIME_BUCKETS

#: fixed surface vocabulary (see module docstring); tap sites pass
#: these strings as constants so the ``surface`` metric label stays
#: bounded by construction
SURFACES = (
    "serve",
    "cache_hit",
    "cache_miss",
    "relay_push",
    "relay_pull",
    "p2p_sync",
    "p2p_work",
    "p2p_telemetry",
    "ingest",
    "bytes_in",
    "bytes_out",
)

#: the aggregated non-resident bucket label
OTHER = "other"

#: surfaces whose sketch counts contribute to the serve-side fairness
#: posture read by the health subsystem (byte surfaces are weighted
#: by payload size and would drown request fairness)
_FAIRNESS_SURFACE = "serve"


def enabled() -> bool:
    """SD_TENANT_OBS=0 disables the whole plane (true no-op)."""
    return os.environ.get("SD_TENANT_OBS", "1") != "0"


def topk() -> int:
    """Sketch residency K (per surface), bounded to keep the
    per-tenant metric families inside the registry's series cap."""
    try:
        k = int(os.environ.get("SD_TENANT_TOPK", "8"))
    except ValueError:
        k = 8
    return max(1, min(k, 16))


def tenant_label(tenant_id: Any) -> str:
    """Short stable hash of a library/instance id — the only form a
    tenant identity may take on a metric label, ring entry, history
    record, federation digest, or debug bundle (sdlint SD027).

    Same blake2b discipline (and therefore the same label namespace)
    as ``peers.peer_label``: UUIDs hash by their bytes so the DB's
    string form and the wire's UUID form agree — the serve/cache taps
    see the request's *string* library id while p2p/sync taps hold
    ``uuid.UUID`` objects, and one tenant must not split into two
    labels across surfaces (any ``uuid.UUID()``-parsable spelling —
    uppercase, undashed, urn: — folds to the same label).
    """
    if isinstance(tenant_id, str):
        try:
            tenant_id = uuid.UUID(tenant_id)
        except ValueError:
            pass
    return peer_label(tenant_id)


class SpaceSavingSketch:
    """Space-saving top-K heavy hitters with an aggregated tail.

    ``counts[label]`` is an upper bound on the tenant's true count;
    ``errs[label]`` is the slack (the count inherited when the tenant
    replaced the previous minimum resident — 0 means exact). ``other``
    accumulates observations attributed to evicted/non-resident
    tenants so ``total`` is always exact. Residents also carry a
    fixed-bucket latency histogram (TIME_BUCKETS) when the surface
    observes durations.
    """

    __slots__ = ("k", "counts", "errs", "hists", "total", "other",
                 "evictions")

    def __init__(self, k: int) -> None:
        self.k = k
        self.counts: dict[str, float] = {}
        self.errs: dict[str, float] = {}
        self.hists: dict[str, list[int]] = {}
        self.total = 0.0
        self.other = 0.0
        self.evictions = 0

    def observe(self, label: str, n: float,
                seconds: float | None) -> bool:
        """Count ``n`` for ``label``; returns True while the tenant is
        resident after the observation (callers label metric series
        ``other`` otherwise)."""
        self.total += n
        counts = self.counts
        if label in counts:
            counts[label] += n
        elif len(counts) < self.k:
            counts[label] = n
            self.errs[label] = 0.0
        else:
            victim = min(counts, key=counts.__getitem__)
            floor = counts[victim]
            # the victim's observations stay accounted in ``other``;
            # the newcomer inherits the floor as its overestimate
            self.other += floor - self.errs[victim]
            del counts[victim]
            del self.errs[victim]
            self.hists.pop(victim, None)
            counts[label] = floor + n
            self.errs[label] = floor
            self.evictions += 1
        if seconds is not None:
            hist = self.hists.get(label)
            if hist is None:
                hist = self.hists[label] = [0] * (len(TIME_BUCKETS) + 1)
            for i, bound in enumerate(TIME_BUCKETS):
                if seconds <= bound:
                    hist[i] += 1
                    break
            else:
                hist[-1] += 1
        return True

    def fairness_index(self) -> float:
        """Jain's fairness index over resident counts: 1.0 when every
        resident tenant gets an equal share, → 1/n under a single
        dominant tenant. 1.0 when idle or single-tenant (nothing to
        be unfair about)."""
        xs = list(self.counts.values())
        if len(xs) < 2:
            return 1.0
        sq = sum(x * x for x in xs)
        if sq <= 0:
            return 1.0
        s = sum(xs)
        return (s * s) / (len(xs) * sq)

    def dominant_share(self) -> float:
        """Largest resident count over the exact surface total."""
        if not self.counts or self.total <= 0:
            return 0.0
        return max(self.counts.values()) / self.total

    def residents(self) -> list[dict[str, Any]]:
        """Resident rows, largest first, with share + error bound and
        a coarse latency read (p50/p99 from the fixed buckets)."""
        rows = []
        total = self.total or 1.0
        for label, count in sorted(self.counts.items(),
                                   key=lambda kv: -kv[1]):
            row: dict[str, Any] = {
                "tenant": label,
                "count": count,
                "err": self.errs.get(label, 0.0),
                "share": count / total,
            }
            hist = self.hists.get(label)
            if hist is not None and sum(hist) > 0:
                row["p50_s"] = _bucket_quantile(hist, 0.50)
                row["p99_s"] = _bucket_quantile(hist, 0.99)
            rows.append(row)
        return rows


def _bucket_quantile(hist: list[int], q: float) -> float:
    """Upper bucket bound holding the q-quantile (inf bucket reports
    the largest finite bound — a floor, honest enough for a sketch)."""
    n = sum(hist)
    rank = q * n
    seen = 0.0
    for i, c in enumerate(hist):
        seen += c
        if seen >= rank and c:
            return TIME_BUCKETS[i] if i < len(TIME_BUCKETS) \
                else TIME_BUCKETS[-1]
    return TIME_BUCKETS[-1]


class TenantPlane:
    """Per-surface sketches behind one lock (tap sites are hot but
    the work per observation is O(K) dict ops at K ≤ 16)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sketches: dict[str, SpaceSavingSketch] = {}

    def observe(self, surface: str, tenant_id: Any, n: float = 1.0,
                seconds: float | None = None) -> None:
        if tenant_id is None or n <= 0:
            return
        label = tenant_label(tenant_id)
        with self._lock:
            sketch = self._sketches.get(surface)
            if sketch is None:
                sketch = self._sketches[surface] = \
                    SpaceSavingSketch(topk())
            resident_before = (label in sketch.counts
                               or len(sketch.counts) < sketch.k)
            sketch.observe(label, n, seconds)
            fairness = sketch.fairness_index()
            dominant = sketch.dominant_share()
            nres = len(sketch.counts)
        # metric series only ever carry resident labels or ``other``
        # — non-residents fold so cardinality is bounded by K+1 per
        # surface with the registry overflow cap as the backstop
        if not resident_before:
            label = OTHER
        _tm.TENANT_OPS.inc(n, surface=surface, tenant=label)
        if seconds is not None:
            _tm.TENANT_SECONDS.observe(
                seconds, surface=surface, tenant=label)
        _tm.TENANT_FAIRNESS.set(fairness, surface=surface)
        _tm.TENANT_DOMINANT.set(dominant, surface=surface)
        _tm.TENANT_RESIDENTS.set(nres, surface=surface)

    def fairness_index(self, surface: str = _FAIRNESS_SURFACE) -> float:
        with self._lock:
            sketch = self._sketches.get(surface)
            return sketch.fairness_index() if sketch else 1.0

    def dominant_share(self, surface: str = _FAIRNESS_SURFACE) -> float:
        with self._lock:
            sketch = self._sketches.get(surface)
            return sketch.dominant_share() if sketch else 0.0

    def snapshot(self) -> dict[str, Any]:
        """Full read path (``GET /tenants``, rspc, bundle): hashed
        labels only — redaction-clean by construction."""
        doc: dict[str, Any] = {"enabled": enabled(), "k": topk(),
                               "surfaces": {}}
        if not enabled():
            return doc
        with self._lock:
            for surface, sketch in sorted(self._sketches.items()):
                doc["surfaces"][surface] = {
                    "total": sketch.total,
                    "other": sketch.other,
                    "evictions": sketch.evictions,
                    "fairness_index": sketch.fairness_index(),
                    "dominant_share": sketch.dominant_share(),
                    "residents": sketch.residents(),
                }
        return doc

    def digest(self) -> dict[str, Any]:
        """Compact federation digest riding ``_local_snapshot`` — a
        few numbers + top-3 labels per surface, never raw ids."""
        out: dict[str, Any] = {}
        with self._lock:
            for surface, sketch in sorted(self._sketches.items()):
                total = sketch.total or 1.0
                top = sorted(sketch.counts.items(),
                             key=lambda kv: -kv[1])[:3]
                out[surface] = {
                    "total": sketch.total,
                    "tenants": len(sketch.counts),
                    "fairness": round(sketch.fairness_index(), 4),
                    "dominant": round(sketch.dominant_share(), 4),
                    "top": [{"tenant": t, "share": round(c / total, 4)}
                            for t, c in top],
                }
        return out

    def reset(self) -> None:
        with self._lock:
            self._sketches.clear()


PLANE = TenantPlane()


def observe(surface: str, tenant_id: Any, n: float = 1.0,
            seconds: float | None = None) -> None:
    """Record ``n`` observations for a tenant on a surface; the ONE
    tap-site entry point. No-op when the plane is disabled or the
    call site has no tenant identity (``tenant_id is None``)."""
    if not enabled():
        return
    PLANE.observe(surface, tenant_id, n, seconds)


def observe_bytes(tenant_id: Any, n: int, *, outbound: bool) -> None:
    """Payload-byte accounting — a sketch weighted by size, so the
    heavy hitters are the bandwidth hogs, not the chattiest."""
    if not enabled():
        return
    PLANE.observe("bytes_out" if outbound else "bytes_in",
                  tenant_id, float(n))


def fairness_index(surface: str = _FAIRNESS_SURFACE) -> float:
    """History-sampler read: 1.0 when idle/disabled (fair by vacuity
    — the SLO's lower-bound objective never burns on an idle node)."""
    if not enabled():
        return 1.0
    return PLANE.fairness_index(surface)


def dominant_share(surface: str = _FAIRNESS_SURFACE) -> float:
    if not enabled():
        return 0.0
    return PLANE.dominant_share(surface)


def snapshot() -> dict[str, Any]:
    return PLANE.snapshot()


def digest() -> dict[str, Any]:
    return PLANE.digest()


def reset() -> None:
    """telemetry.reset() hook — drop every sketch (the fairness
    gauges and tenant_fairness SLO state are registry/SLO state and
    reset through their own planes)."""
    PLANE.reset()
