"""Pipeline spans — per-stage wall time + byte accounting.

The walk → identify → hash → thumbnail pipeline reports its stage
timings through spans: a context manager (sync AND async — nesting
propagates through ``contextvars``, so concurrent asyncio tasks can't
cross-contaminate parentage) that on exit

- observes ``sd_span_seconds{stage=…}`` and, when bytes were attached,
  ``sd_span_bytes_total{stage=…}``;
- appends a record to a bounded in-memory ring the ``telemetry.
  snapshot`` procedure exposes, so the explorer can show "where did the
  last index pass spend its time" without a scrape pipeline;
- debug-logs through the `utils.tracing` logging tree (target
  ``spacedrive_tpu.telemetry``), honoring SD_LOG filters.

Stages are dotted paths: a span opened inside another records as
``parent.child`` (e.g. ``identify.hash``), keeping label cardinality
proportional to the pipeline's actual shape.

Every span also carries distributed-trace identity (``trace_id``/
``span_id``/``parent_id``, see ``telemetry.trace``): a nested span
inherits its parent's trace; a root span adopts the ambient
``trace.current()`` context installed by a boundary (task dispatch, job
resume, a P2P header) or mints a fresh trace. Completed spans land in
the trace ring for Chrome-trace export, and spans slower than
``events.SLOW_OP_SECONDS`` fire the slow-op watchdog ring.
"""

from __future__ import annotations

import contextvars
import logging
import threading
import time
from collections import deque
from typing import Any

from . import events as _events
from . import metrics
from . import trace as _trace

logger = logging.getLogger(__name__)

RECENT_SPANS = 256

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "sd_current_span", default=None
)
_recent: deque[dict[str, Any]] = deque(maxlen=RECENT_SPANS)
_recent_lock = threading.Lock()


class Span:
    """One timed pipeline stage. Use via ``span(...)``:

        with span("identify.hash", nbytes=len(batch)):
            ...
        async with span("walk"):
            ...
    """

    __slots__ = (
        "stage", "nbytes", "path", "_t0", "_t0_wall", "_token",
        "_trace_token", "duration", "trace_id", "span_id", "parent_id",
        "fields",
    )

    def __init__(self, stage: str, nbytes: int = 0):
        self.stage = stage
        self.nbytes = int(nbytes)
        self.fields: dict[str, Any] | None = None
        self.path = stage  # parent-prefixed on enter
        self._t0 = 0.0
        self._t0_wall = 0.0
        self._token: contextvars.Token | None = None
        self._trace_token: contextvars.Token | None = None
        self.duration: float | None = None
        self.trace_id: str = ""
        self.span_id: str = ""
        self.parent_id: str | None = None

    def add_bytes(self, n: int) -> None:
        """Attribute more bytes mid-span (e.g. per-file in a loop)."""
        self.nbytes += int(n)

    def annotate(self, **fields: Any) -> None:
        """Attach small scalar fields to the span record (ring + trace
        export) — e.g. the index-journal verdict counts of an identify
        window. Keep values to scalars; this is NOT a payload channel."""
        if self.fields is None:
            self.fields = {}
        self.fields.update(fields)

    # -- sync protocol --

    def __enter__(self) -> "Span":
        parent = _current.get()
        if parent is not None:
            self.path = f"{parent.path}.{self.stage}"
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            # no enclosing span: join the ambient trace context a
            # boundary installed (dispatch, resume, wire) or start fresh
            ctx = _trace.current()
            if ctx is not None:
                self.trace_id = ctx.trace_id
                self.parent_id = ctx.span_id
            else:
                self.trace_id = _trace.new_trace_id()
        self.span_id = _trace.new_span_id()
        self._token = _current.set(self)
        self._trace_token = _trace.set_current(
            _trace.TraceContext(self.trace_id, self.span_id)
        )
        self._t0_wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.duration = time.perf_counter() - self._t0
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if self._trace_token is not None:
            _trace.reset_current(self._trace_token)
            self._trace_token = None
        metrics.SPAN_SECONDS.observe(self.duration, stage=self.path)
        if self.nbytes:
            metrics.SPAN_BYTES.inc(self.nbytes, stage=self.path)
        rec = {
            "stage": self.path,
            "seconds": self.duration,
            "bytes": self.nbytes,
            "error": exc_type.__name__ if exc_type is not None else None,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
        }
        if self.fields:
            rec["fields"] = dict(self.fields)
        with _recent_lock:
            _recent.append(rec)
        _trace.record_span({**rec, "t0": self._t0_wall})
        if self.duration >= _events.SLOW_OP_SECONDS:
            _events.watchdog_slow_op(self.path, self.duration)
        logger.debug("span %s: %.3fms%s", self.path, self.duration * 1e3,
                     f" {self.nbytes}B" if self.nbytes else "")

    # -- async protocol (same semantics; contextvars carry across await) --

    async def __aenter__(self) -> "Span":
        return self.__enter__()

    async def __aexit__(self, exc_type, exc, tb) -> None:
        self.__exit__(exc_type, exc, tb)


def span(stage: str, nbytes: int = 0) -> Span:
    return Span(stage, nbytes)


def current_span() -> Span | None:
    return _current.get()


def recent_spans() -> list[dict[str, Any]]:
    """Most-recent-last completed spans (bounded ring)."""
    with _recent_lock:
        return list(_recent)


def clear_recent() -> None:
    with _recent_lock:
        _recent.clear()
