"""Pipeline spans — per-stage wall time + byte accounting.

The walk → identify → hash → thumbnail pipeline reports its stage
timings through spans: a context manager (sync AND async — nesting
propagates through ``contextvars``, so concurrent asyncio tasks can't
cross-contaminate parentage) that on exit

- observes ``sd_span_seconds{stage=…}`` and, when bytes were attached,
  ``sd_span_bytes_total{stage=…}``;
- appends a record to a bounded in-memory ring the ``telemetry.
  snapshot`` procedure exposes, so the explorer can show "where did the
  last index pass spend its time" without a scrape pipeline;
- debug-logs through the `utils.tracing` logging tree (target
  ``spacedrive_tpu.telemetry``), honoring SD_LOG filters.

Stages are dotted paths: a span opened inside another records as
``parent.child`` (e.g. ``identify.hash``), keeping label cardinality
proportional to the pipeline's actual shape.
"""

from __future__ import annotations

import contextvars
import logging
import threading
import time
from collections import deque
from typing import Any

from . import metrics

logger = logging.getLogger(__name__)

RECENT_SPANS = 256

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "sd_current_span", default=None
)
_recent: deque[dict[str, Any]] = deque(maxlen=RECENT_SPANS)
_recent_lock = threading.Lock()


class Span:
    """One timed pipeline stage. Use via ``span(...)``:

        with span("identify.hash", nbytes=len(batch)):
            ...
        async with span("walk"):
            ...
    """

    __slots__ = ("stage", "nbytes", "path", "_t0", "_token", "duration")

    def __init__(self, stage: str, nbytes: int = 0):
        self.stage = stage
        self.nbytes = int(nbytes)
        self.path = stage  # parent-prefixed on enter
        self._t0 = 0.0
        self._token: contextvars.Token | None = None
        self.duration: float | None = None

    def add_bytes(self, n: int) -> None:
        """Attribute more bytes mid-span (e.g. per-file in a loop)."""
        self.nbytes += int(n)

    # -- sync protocol --

    def __enter__(self) -> "Span":
        parent = _current.get()
        if parent is not None:
            self.path = f"{parent.path}.{self.stage}"
        self._token = _current.set(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.duration = time.perf_counter() - self._t0
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        metrics.SPAN_SECONDS.observe(self.duration, stage=self.path)
        if self.nbytes:
            metrics.SPAN_BYTES.inc(self.nbytes, stage=self.path)
        rec = {
            "stage": self.path,
            "seconds": self.duration,
            "bytes": self.nbytes,
            "error": exc_type.__name__ if exc_type is not None else None,
        }
        with _recent_lock:
            _recent.append(rec)
        logger.debug("span %s: %.3fms%s", self.path, self.duration * 1e3,
                     f" {self.nbytes}B" if self.nbytes else "")

    # -- async protocol (same semantics; contextvars carry across await) --

    async def __aenter__(self) -> "Span":
        return self.__enter__()

    async def __aexit__(self, exc_type, exc, tb) -> None:
        self.__exit__(exc_type, exc, tb)


def span(stage: str, nbytes: int = 0) -> Span:
    return Span(stage, nbytes)


def current_span() -> Span | None:
    return _current.get()


def recent_spans() -> list[dict[str, Any]]:
    """Most-recent-last completed spans (bounded ring)."""
    with _recent_lock:
        return list(_recent)


def clear_recent() -> None:
    with _recent_lock:
        _recent.clear()
