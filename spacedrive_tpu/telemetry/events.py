"""Flight recorder — bounded per-subsystem rings of structured events.

Metrics answer "how much"; the flight recorder answers "what happened
just before it went wrong". Each subsystem gets a small always-on ring
of structured events (job state transitions, p2p connects/retransmits,
watcher bursts, errors with tracebacks, slow-op watchdog firings,
event-loop-lag samples). Rings are bounded deques — a retransmit storm
can never grow memory — and dump wholesale into the debug bundle
(``telemetry.bundle``).

Cardinality discipline (enforced by sdlint SD009): the event ``type``
is a CONSTANT string and field *names* are literal keyword arguments.
Field *values* may be dynamic — they are payload inside a bounded ring,
not label sets inside a metrics family.

Handles are module-level ``*_EVENTS`` constants, mirroring how hot
paths import metric handles from ``telemetry.metrics``.
"""

from __future__ import annotations

import threading
import time
import traceback as _tb
from collections import deque
from typing import Any

RING_CAPACITY = 512
MAX_TRACEBACK_CHARS = 8192

# spans slower than this fire a watchdog event (see spans.Span.__exit__)
SLOW_OP_SECONDS = 1.0


class EventRing:
    """One subsystem's bounded event log. ``emit`` is safe from any
    thread; events carry a wall-clock timestamp and, when a trace is
    active, the trace id that caused them."""

    def __init__(self, name: str, capacity: int = RING_CAPACITY):
        self.name = name
        self._ring: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        # honest-overflow accounting: a bounded ring that silently
        # displaces its oldest events reads as "nothing else happened";
        # the drop counter says how much story is missing
        self.dropped = 0

    def emit(self, type: str, **fields: Any) -> None:
        from . import metrics, trace

        ctx = trace.current()
        rec: dict[str, Any] = {"ts": time.time(), "type": type}
        if ctx is not None:
            rec["trace_id"] = ctx.trace_id
        if fields:
            rec["fields"] = fields
        with self._lock:
            overflowed = (
                self._ring.maxlen is not None
                and len(self._ring) >= self._ring.maxlen
            )
            if overflowed:
                self.dropped += 1
            self._ring.append(rec)
        if overflowed:
            # outside the ring lock: the registry has its own
            metrics.RING_DROPPED.inc(ring=self.name)

    def snapshot(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


_rings: dict[str, EventRing] = {}
_rings_lock = threading.Lock()


def ring(name: str, capacity: int = RING_CAPACITY) -> EventRing:
    """Get-or-create a named ring (idempotent, like metric families)."""
    with _rings_lock:
        r = _rings.get(name)
        if r is None:
            r = _rings[name] = EventRing(name, capacity)
        return r


def all_events() -> dict[str, list[dict[str, Any]]]:
    """Every ring's contents, for the debug bundle / rspc snapshot."""
    with _rings_lock:
        rings = list(_rings.values())
    return {r.name: r.snapshot() for r in rings}


def drop_counts() -> dict[str, int]:
    """Per-ring overflow drops since the last clear — rides the debug
    bundle next to the ring payloads so a consumer can tell a quiet
    ring from a saturated one."""
    with _rings_lock:
        rings = list(_rings.values())
    return {r.name: r.dropped for r in rings if r.dropped}


def clear_all() -> None:
    with _rings_lock:
        rings = list(_rings.values())
    for r in rings:
        r.clear()


# --- the predeclared subsystem rings -----------------------------------

JOB_EVENTS = ring("jobs")          # job state transitions
P2P_EVENTS = ring("p2p")           # connects, stream opens, retransmits
SYNC_EVENTS = ring("sync")         # ingest accept/reject transitions, delta-guard trips
WATCHER_EVENTS = ring("watcher")   # debounced burst flushes
ERROR_EVENTS = ring("errors")      # uncaught exceptions w/ tracebacks
WATCHDOG_EVENTS = ring("watchdog")  # slow-op firings
LOOP_EVENTS = ring("loop")         # event-loop-lag samples over threshold
FAULT_EVENTS = ring("faults")      # injected-fault activations (utils/faults)
RESILIENCE_EVENTS = ring("resilience")  # retries, breaker transitions, demotions
AUTOTUNE_EVENTS = ring("autotune")  # closed-loop tuning decisions (w/ trace_id)
WORK_EVENTS = ring("work")         # mesh work-stealing: publishes, leases, steals, expiries
SERVE_EVENTS = ring("serve")       # admission gate: sheds (w/ trace_id), mode transitions


def record_error(source: str, exc: BaseException | None,
                 exc_info: tuple | None = None) -> None:
    """One uncaught exception into the error ring, traceback bounded.
    ``source`` names the hook that caught it (excepthook / thread /
    loop) — a fixed vocabulary, not a runtime string."""
    if exc_info is None and exc is not None:
        exc_info = (type(exc), exc, exc.__traceback__)
    if exc_info is None:
        return
    tb_text = "".join(_tb.format_exception(*exc_info))[-MAX_TRACEBACK_CHARS:]
    ERROR_EVENTS.emit(
        "exception",
        source=source,
        exc_type=getattr(exc_info[0], "__name__", str(exc_info[0])),
        message=str(exc_info[1])[:500],
        traceback=tb_text,
    )


def watchdog_slow_op(stage: str, seconds: float) -> None:
    """A span exceeded SLOW_OP_SECONDS (called by spans on exit)."""
    WATCHDOG_EVENTS.emit("slow_op", stage=stage, seconds=round(seconds, 4))


class LoopLagMonitor:
    """Samples event-loop scheduling lag: sleeps ``interval`` and
    measures how late the wakeup lands. Every sample updates the
    ``sd_event_loop_lag_seconds`` gauge; samples past ``warn_s`` also
    land in the loop ring (the flight-recorder record of 'the loop was
    starved right before the incident')."""

    def __init__(self, interval: float = 0.5, warn_s: float = 0.2):
        self.interval = interval
        self.warn_s = warn_s
        self._task: Any = None
        self._tasks: set = set()
        self._stopped = False

    def start(self) -> None:
        import asyncio
        import logging

        from ..utils.tasks import supervise

        if self._task is not None and not self._task.done():
            return
        self._stopped = False
        self._task = supervise(
            asyncio.get_running_loop().create_task(self._run()),
            self._tasks, logging.getLogger(__name__), "loop-lag monitor",
        )

    async def stop(self) -> None:
        self._stopped = True
        task = self._task
        self._task = None
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except BaseException:  # noqa: BLE001 - cancellation cleanup
                pass

    async def _run(self) -> None:
        import asyncio

        from . import metrics as _tm

        from . import sampler as _sampler

        while not self._stopped:
            t0 = time.monotonic()
            await asyncio.sleep(self.interval)
            lag = max(0.0, (time.monotonic() - t0) - self.interval)
            _tm.EVENT_LOOP_LAG.set(lag)
            if lag >= self.warn_s:
                LOOP_EVENTS.emit("lag", seconds=round(lag, 4))
                # loop-lag degradation opens a deep-capture window: the
                # profiler names the frames that starved the loop. The
                # sampler's cooldown absorbs a sustained-lag sample
                # train into ONE window.
                _sampler.trigger("loop_lag")
