"""Debug bundle — one redacted JSON artifact for support/diagnosis.

Everything a "my 1M-file index pass stalled" report needs, collected
from the live process: node config (secrets stripped), metrics
snapshot, recent spans + the trace ring summary, every flight-recorder
ring, and versions/env. Produced by the ``telemetry.debug_bundle`` rspc
procedure and ``python -m spacedrive_tpu debug-bundle``.

Redaction is two layered passes, both applied before the bundle leaves
the process:

1. key-name based and recursive — any mapping key containing a
   secret-ish token (``identity``, ``key``, ``secret``, ``password``,
   ``token``, ``master``, …) has its value replaced. Applied to the
   node config, env, AND the event rings' fields.
2. value based — every string that was redacted by key in the config
   (the node identity hex, planted API tokens, …) is additionally
   scrubbed out of every string in the whole bundle, because secrets
   travel: an exception message or traceback captured by the error
   ring may embed the very value the config redaction hid.

The smoke test plants a key in the config AND leaks it through an
exception into the error ring, then asserts the serialized bundle is
clean either way.
"""

from __future__ import annotations

import json
import os
import platform
import sys
import time
from typing import Any

REDACTED = "[redacted]"

# substrings that mark a mapping key as secret-bearing
SECRET_KEY_TOKENS = (
    "identity", "key", "secret", "password", "token", "master",
    "credential", "private",
)

# env vars worth shipping; everything else stays home (env is a classic
# secret-leak vector: SD_CLOUD_TOKEN=… must never ride a bundle)
ENV_PREFIXES = ("SD_", "JAX_", "XLA_")


def _key_is_secret(key: str) -> bool:
    low = key.lower()
    return any(tok in low for tok in SECRET_KEY_TOKENS)


def redact(obj: Any) -> Any:
    """Deep-copy ``obj`` with secret-keyed values replaced. Lists and
    tuples recurse; scalar leaves pass through untouched."""
    if isinstance(obj, dict):
        return {
            k: (REDACTED if _key_is_secret(str(k)) else redact(v))
            for k, v in obj.items()
        }
    if isinstance(obj, (list, tuple)):
        return [redact(v) for v in obj]
    return obj


MIN_SECRET_LEN = 8  # don't value-scrub trivially short strings


def collect_secret_values(obj: Any) -> set[str]:
    """Every string a key-based ``redact`` of ``obj`` would hide —
    the concrete secret VALUES, for the second scrub pass."""
    out: set[str] = set()
    if isinstance(obj, dict):
        for k, v in obj.items():
            if _key_is_secret(str(k)):
                if isinstance(v, str) and len(v) >= MIN_SECRET_LEN:
                    out.add(v)
            else:
                out |= collect_secret_values(v)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            out |= collect_secret_values(v)
    return out


def scrub_values(obj: Any, secrets: set[str]) -> Any:
    """Replace every occurrence of a known secret value inside every
    string of ``obj`` — exception messages and tracebacks in the error
    ring can embed secrets no key-based pass can see."""
    if not secrets:
        return obj
    if isinstance(obj, str):
        for s in secrets:
            if s in obj:
                obj = obj.replace(s, REDACTED)
        return obj
    if isinstance(obj, dict):
        return {k: scrub_values(v, secrets) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [scrub_values(v, secrets) for v in obj]
    return obj


def _versions() -> dict[str, Any]:
    out: dict[str, Any] = {
        "python": sys.version,
        "platform": platform.platform(),
        "machine": platform.machine(),
    }
    for mod in ("jax", "jaxlib", "numpy", "aiohttp", "msgpack", "PIL"):
        m = sys.modules.get(mod)
        if m is not None:
            out[mod] = getattr(m, "__version__", "?")
    return out


def _env() -> dict[str, str]:
    return redact({
        k: v for k, v in os.environ.items()
        if k.startswith(ENV_PREFIXES)
    })


def _raw_node_config(node: Any = None, data_dir: str | None = None) -> Any:
    """The node's config dict, UNredacted (internal: the raw values
    seed the value-scrub pass). With no live node, read ``node.json``
    straight off the data dir (offline CLI path)."""
    if node is not None:
        try:
            return node.config.config.to_dict()
        except Exception:  # noqa: BLE001 - bundles degrade, never fail
            return None
    if data_dir:
        path = os.path.join(os.fspath(data_dir), "node.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None
    return None


def _libraries(node: Any) -> list[dict[str, Any]]:
    out = []
    for lib in getattr(getattr(node, "libraries", None), "libraries",
                       {}).values():
        try:
            out.append({
                "id": str(lib.id),
                "name": lib.name,
                "file_paths": lib.db.count("file_path"),
                "objects": lib.db.count("object"),
                "jobs": lib.db.count("job"),
            })
        except Exception:  # noqa: BLE001 - a closing DB must not kill bundles
            out.append({"id": str(lib.id), "name": lib.name})
    return out


def _tenants_snapshot() -> dict[str, Any]:
    from . import tenants as _tenants

    return _tenants.snapshot()


def build_bundle(node: Any = None, data_dir: str | None = None) -> dict[str, Any]:
    """Assemble the bundle dict (JSON-serializable, already redacted)."""
    from . import trace as _trace
    from .events import all_events
    from .events import drop_counts as _drop_counts
    from .snapshot import snapshot as _snapshot

    from . import sampler as _sampler

    trace_events = _trace.recent()
    snap = _snapshot()
    raw_config = _raw_node_config(node, data_dir)
    bundle: dict[str, Any] = {
        "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "versions": _versions(),
        "env": _env(),
        "node_config": redact(raw_config) if raw_config else raw_config,
        "metrics": snap["metrics"],
        "spans": snap["spans"],
        "trace_summary": {
            "spans": len(trace_events),
            "traces": len({r.get("trace_id") for r in trace_events}),
        },
        # key-based pass over ring fields too (a field literally named
        # "token"/"key" gets hidden even before the value scrub)
        "events": redact(all_events()),
        # per-ring overflow drops: a ring that displaced events is a
        # suffix of the story, and the bundle must say so
        "ring_drops": _drop_counts(),
        # host-profiler evidence: the full profile document plus the
        # bounded folded collapsed-stack text (frame names only —
        # module:function, never filesystem paths or values), so a
        # support bundle answers "what was Python doing" offline
        "profile": {
            "doc": _sampler.SAMPLER.profile(),
            "folded": _sampler.SAMPLER.folded(max_bytes=64 * 1024),
        },
        # per-tenant accounting snapshot: redaction-clean by
        # construction — every tenant key is a blake2b tenant_label
        # hash, never a raw library/instance UUID (sdlint SD027)
        "tenants": _tenants_snapshot(),
    }
    if node is not None:
        bundle["libraries"] = _libraries(node)
    # second pass: the concrete secret VALUES the key-based passes hid
    # (identity keypair hex, tokens, secret-keyed env vars) are
    # scrubbed out of every string in the bundle — tracebacks in the
    # error ring included
    secrets = collect_secret_values(raw_config)
    secrets |= collect_secret_values(dict(os.environ))
    return scrub_values(bundle, secrets)


def render_bundle(node: Any = None, data_dir: str | None = None) -> str:
    return json.dumps(build_bundle(node, data_dir), indent=2, default=str)
