"""In-process metrics registry — counters, gauges, fixed-bucket histograms.

The observability spine for the dispatch path (ROADMAP: explain the
bottleneck from inside the system). Prometheus-style semantics without
the client library: every metric is a *family* (name + help + label
names) holding one series per label-value tuple, guarded by one
registry-wide lock so hot-path updates from worker threads (the window
pipeline producer, to_thread hashers) and the event loop never race.

Deliberate deviations from a full Prometheus client, sized for this
process:

- label cardinality is capped per family (``MAX_SERIES_PER_FAMILY``);
  past the cap new label sets fold into a reserved ``__overflow__``
  series instead of growing memory without bound — a hot path must
  never be able to DoS its own telemetry;
- histograms keep a small ring of raw observations (``recent()``) so
  in-process consumers (bench.py, telemetry.snapshot) can compute
  medians/spreads from the same source the /metrics endpoint scrapes —
  one set of numbers, two read paths;
- unlabeled counters/gauges materialize their default series at
  registration, so a metric that has not fired yet still renders as an
  explicit zero (absence means "not wired", zero means "wired, idle").
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Any, Iterable, Sequence

MAX_SERIES_PER_FAMILY = 64
OVERFLOW_LABEL = "__overflow__"
RECENT_SAMPLES = 128

# latency buckets: 1 ms .. 30 s covers queue waits through job phases
TIME_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
# occupancy / fill-ratio buckets: [0, 1] with emphasis near full
RATIO_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 1.0)
# byte-size buckets: 4 KiB .. 1 GiB in powers of ~8
BYTE_BUCKETS = (
    4096.0, 32768.0, 262144.0, 2097152.0, 16777216.0,
    134217728.0, 1073741824.0,
)


class _Series:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0


class _HistogramSeries:
    __slots__ = ("bucket_counts", "sum", "count", "recent")

    def __init__(self, n_buckets: int,
                 recent_samples: int = RECENT_SAMPLES) -> None:
        self.bucket_counts = [0] * (n_buckets + 1)  # +1 = +Inf
        self.sum = 0.0
        self.count = 0
        self.recent: deque[float] = deque(maxlen=recent_samples)


class _Family:
    """Shared family plumbing: label resolution + cardinality cap."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 label_names: Sequence[str]):
        self._registry = registry
        self._lock = registry._lock
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._series: dict[tuple[str, ...], Any] = {}
        if not self.label_names:
            self._series[()] = self._new_series()

    def _new_series(self) -> Any:
        raise NotImplementedError

    def _resolve(self, labels: dict[str, Any]) -> Any:
        """Series for a label set; caller holds the lock. Unknown label
        names are a programming error; cardinality overflow is not —
        it folds into the __overflow__ series."""
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        key = tuple(str(labels[n]) for n in self.label_names)
        series = self._series.get(key)
        if series is None:
            if len(self._series) >= MAX_SERIES_PER_FAMILY:
                key = tuple(OVERFLOW_LABEL for _ in self.label_names)
                series = self._series.get(key)
                if series is None:
                    series = self._new_series()
                    self._series[key] = series
                return series
            series = self._new_series()
            self._series[key] = series
        return series

    def _peek(self, labels: dict[str, Any]) -> Any:
        """Series for a label set WITHOUT creating it; caller holds the
        lock. Read paths must use this: a probing read (dashboard,
        snapshot helper, typo'd label) must not mint a permanent series
        or eat into the family's cardinality cap."""
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        key = tuple(str(labels[n]) for n in self.label_names)
        return self._series.get(key)

    def _reset(self) -> None:
        keep = self._series.keys() if not self.label_names else ()
        fresh = {k: self._new_series() for k in keep}
        self._series = fresh


class Counter(_Family):
    kind = "counter"

    def _new_series(self) -> _Series:
        return _Series()

    def inc(self, n: float = 1.0, **labels: Any) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters are monotonic (inc {n})")
        with self._lock:
            self._resolve(labels).value += n

    def value(self, **labels: Any) -> float:
        with self._lock:
            s = self._peek(labels)
            return s.value if s is not None else 0.0


class Gauge(_Family):
    kind = "gauge"

    def _new_series(self) -> _Series:
        return _Series()

    def set(self, v: float, **labels: Any) -> None:
        with self._lock:
            self._resolve(labels).value = float(v)

    def inc(self, n: float = 1.0, **labels: Any) -> None:
        with self._lock:
            self._resolve(labels).value += n

    def dec(self, n: float = 1.0, **labels: Any) -> None:
        self.inc(-n, **labels)

    def value(self, **labels: Any) -> float:
        with self._lock:
            s = self._peek(labels)
            return s.value if s is not None else 0.0


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 label_names: Sequence[str],
                 buckets: Sequence[float] = TIME_BUCKETS,
                 recent_samples: int = RECENT_SAMPLES):
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs or any(not math.isfinite(b) for b in bs):
            raise ValueError(f"{name}: buckets must be finite and non-empty")
        self.buckets = bs
        self.recent_samples = recent_samples
        super().__init__(registry, name, help, label_names)

    def _new_series(self) -> _HistogramSeries:
        return _HistogramSeries(len(self.buckets), self.recent_samples)

    def observe(self, v: float, **labels: Any) -> None:
        v = float(v)
        with self._lock:
            s = self._resolve(labels)
            i = 0
            while i < len(self.buckets) and v > self.buckets[i]:
                i += 1
            s.bucket_counts[i] += 1
            s.sum += v
            s.count += 1
            s.recent.append(v)

    def recent(self, **labels: Any) -> list[float]:
        """Raw recent observations — the in-process read path bench.py
        and telemetry.snapshot share with the scrape endpoint."""
        with self._lock:
            s = self._peek(labels)
            return list(s.recent) if s is not None else []

    def stats(self, **labels: Any) -> dict[str, float]:
        with self._lock:
            s = self._peek(labels)
            if s is None:
                return {"sum": 0.0, "count": 0}
            return {"sum": s.sum, "count": s.count}


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class MetricsRegistry:
    """Create-or-get metric families; render Prometheus text."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._families: dict[str, _Family] = {}

    def _register(self, cls, name: str, help: str,
                  labels: Sequence[str], **kw) -> Any:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise ValueError(
                        f"{name} already registered as {fam.kind}")
                return fam
            fam = cls(self, name, help, labels, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = TIME_BUCKETS,
                  recent_samples: int = RECENT_SAMPLES) -> Histogram:
        return self._register(Histogram, name, help, labels,
                              buckets=buckets,
                              recent_samples=recent_samples)

    def get(self, name: str) -> _Family | None:
        with self._lock:
            return self._families.get(name)

    def reset(self) -> None:
        """Zero every series (tests / bench isolation). Families and
        their pre-registered default series survive."""
        with self._lock:
            for fam in self._families.values():
                fam._reset()

    # --- render ---------------------------------------------------------

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: list[str] = []
        with self._lock:
            for name, fam in sorted(self._families.items()):
                if fam.help:
                    out.append(f"# HELP {name} {fam.help}")
                out.append(f"# TYPE {name} {fam.kind}")
                for key, s in fam._series.items():
                    base = _labelstr(fam.label_names, key)
                    if isinstance(fam, Histogram):
                        cum = 0
                        for b, c in zip(fam.buckets, s.bucket_counts):
                            cum += c
                            le = _labelstr(
                                fam.label_names + ("le",),
                                key + (_fmt(b),))
                            out.append(f"{name}_bucket{le} {cum}")
                        cum += s.bucket_counts[-1]
                        le = _labelstr(fam.label_names + ("le",),
                                       key + ("+Inf",))
                        out.append(f"{name}_bucket{le} {cum}")
                        out.append(f"{name}_sum{base} {_fmt(s.sum)}")
                        out.append(f"{name}_count{base} {s.count}")
                    else:
                        out.append(f"{name}{base} {_fmt(s.value)}")
        return "\n".join(out) + "\n"

    # --- cross-process deltas (parallel/procpool.py) --------------------
    #
    # The multi-process execution plane keeps this registry single-
    # writer per process: pool workers accumulate into their OWN
    # registry (same families — both sides import telemetry.metrics)
    # and ship a msgpack-plain delta blob back with each batch result;
    # the owner merges it here. Counters and histograms merge by
    # addition (monotonic / mergeable by construction); gauges are
    # deliberately excluded — they are point-in-time statements only
    # the owning process may make.

    def delta_capture(self) -> dict[str, Any]:
        """Compact additive state: {family: {label-key-tuple-as-list:
        …}} rendered as parallel lists so the blob stays msgpack-plain."""
        with self._lock:
            counters: dict[str, list] = {}
            hists: dict[str, list] = {}
            for name, fam in self._families.items():
                if isinstance(fam, Counter):
                    rows = [
                        [list(key), s.value]
                        for key, s in fam._series.items() if s.value
                    ]
                    if rows:
                        counters[name] = rows
                elif isinstance(fam, Histogram):
                    rows = [
                        [list(key), s.sum, s.count,
                         list(s.bucket_counts), list(s.recent)]
                        for key, s in fam._series.items() if s.count
                    ]
                    if rows:
                        hists[name] = rows
            return {"c": counters, "h": hists}

    @staticmethod
    def delta_diff(before: dict[str, Any],
                   after: dict[str, Any]) -> dict[str, Any]:
        """after − before, per series. New observations in a histogram
        ring are its trailing ``count_after − count_before`` samples
        (the ring may have dropped older ones — then the whole ring is
        the best available tail)."""
        out: dict[str, Any] = {"c": {}, "h": {}}
        prev_c = {
            (name, tuple(key)): value
            for name, rows in before.get("c", {}).items()
            for key, value in rows
        }
        for name, rows in after.get("c", {}).items():
            kept = []
            for key, value in rows:
                d = value - prev_c.get((name, tuple(key)), 0.0)
                if d > 0:
                    kept.append([key, d])
            if kept:
                out["c"][name] = kept
        prev_h = {
            (name, tuple(key)): (s, n, bc)
            for name, rows in before.get("h", {}).items()
            for key, s, n, bc, _recent in rows
        }
        for name, rows in after.get("h", {}).items():
            kept = []
            for key, s, n, bc, recent in rows:
                ps, pn, pbc = prev_h.get((name, tuple(key)), (0.0, 0, None))
                dn = n - pn
                if dn <= 0:
                    continue
                dbc = (
                    [b - p for b, p in zip(bc, pbc)] if pbc is not None
                    else list(bc)
                )
                kept.append([key, s - ps, dn, dbc, recent[-dn:]])
            if kept:
                out["h"][name] = kept
        return out

    def merge_delta(self, delta: dict[str, Any]) -> None:
        """Fold a worker-shipped delta into this registry. Unknown
        families/label shapes are skipped (version drift between owner
        and worker must never corrupt owner series)."""
        with self._lock:
            for name, rows in (delta.get("c") or {}).items():
                fam = self._families.get(name)
                if not isinstance(fam, Counter):
                    continue
                for key, value in rows:
                    if len(key) != len(fam.label_names) or value <= 0:
                        continue
                    fam._resolve(dict(zip(fam.label_names, key))).value += value
            for name, rows in (delta.get("h") or {}).items():
                fam = self._families.get(name)
                if not isinstance(fam, Histogram):
                    continue
                for key, s, n, bc, recent in rows:
                    if len(key) != len(fam.label_names) or n <= 0 \
                            or len(bc) != len(fam.buckets) + 1:
                        continue
                    series = fam._resolve(dict(zip(fam.label_names, key)))
                    series.sum += s
                    series.count += n
                    for i, b in enumerate(bc):
                        series.bucket_counts[i] += b
                    series.recent.extend(recent)

    # --- snapshot (rspc + bench read path) ------------------------------

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            out: dict[str, Any] = {}
            for name, fam in self._families.items():
                series = []
                for key, s in fam._series.items():
                    labels = dict(zip(fam.label_names, key))
                    if isinstance(fam, Histogram):
                        series.append({
                            "labels": labels,
                            "sum": s.sum,
                            "count": s.count,
                            "buckets": {
                                _fmt(b): c for b, c in
                                zip(fam.buckets, s.bucket_counts)
                            },
                            "recent": list(s.recent),
                        })
                    else:
                        series.append({"labels": labels, "value": s.value})
                out[name] = {"type": fam.kind, "help": fam.help,
                             "series": series}
            return out


def _labelstr(names: Iterable[str], values: Iterable[str]) -> str:
    pairs = [f'{n}="{_escape(v)}"' for n, v in zip(names, values)]
    return "{" + ",".join(pairs) + "}" if pairs else ""


# The process-wide default registry: hot paths import their metric
# handles from telemetry.metrics, which registers on this instance.
REGISTRY = MetricsRegistry()
