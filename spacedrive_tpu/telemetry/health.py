"""Health model — rolls raw telemetry signals into verdicts.

Metrics answer "how much", the health model answers the on-call
question: *is this node OK, and if not, which subsystem?* Each
subsystem's verdict derives from signals the registry / flight
recorder already collects — no new probes, no background task; the
evaluation is a pure read over existing state, cheap enough to run on
every ``GET /health`` hit and inside every federation snapshot.

Verdict vocabulary (ordered): ``healthy`` < ``degraded`` <
``unhealthy``; ``unknown`` means "no signal yet" and never worsens the
rollup (a node that has not dispatched a batch is idle, not sick).

Subsystems and their signals:

- ``event_loop`` — the loop-lag sampler's gauge (a starved loop stalls
  every actor at once);
- ``feeder`` — recent consumer-side wait times (a stalled H2D feeder
  starves the device);
- ``device`` — recent dispatch occupancy (chips mostly hauling pad
  rows means the batch ladder is misconfigured);
- ``p2p`` — retransmit / zero-window / failure *episode* rate off the
  p2p flight ring;
- ``sync`` — the federation-corroborated replication head gap (how far
  a fresh peer snapshot's library head is ahead of ours) plus
  delta-guard trips; raw wall-clock lag rides along as a signal but
  never drives the verdict — it grows on a healthy idle mesh;
- ``resilience`` — open circuit breakers (utils/resilience) and the
  device degradation-ladder level: a node fast-failing a dead relay or
  hashing on a chip subset still works, but reads degraded until the
  half-open probe / ladder re-arm succeeds;
- ``resources`` — the resource sampler's growth posture: trend-SLO
  verdicts over RSS/fd slopes (a sustained leak is unhealthy long
  before the OOM) plus the last sampled inventory as signals.

Thresholds are module constants, deliberately lenient: a health
verdict that cries wolf gets ignored.
"""

from __future__ import annotations

import time
from typing import Any

from .registry import REGISTRY
from .snapshot import counter_value, gauge_value, histogram_recent

HEALTHY = "healthy"
DEGRADED = "degraded"
UNHEALTHY = "unhealthy"
UNKNOWN = "unknown"

_RANK = {HEALTHY: 0, UNKNOWN: 0, DEGRADED: 1, UNHEALTHY: 2}

# event-loop scheduling lag (seconds)
LOOP_LAG_DEGRADED = 0.2
LOOP_LAG_UNHEALTHY = 1.0
# feeder consumer wait (seconds, worst recent sample)
FEEDER_WAIT_DEGRADED = 1.0
FEEDER_WAIT_UNHEALTHY = 5.0
# device dispatch occupancy (mean of recent observations)
OCCUPANCY_DEGRADED = 0.25
# p2p failure episodes per minute over the ring window
P2P_EPISODES_DEGRADED = 30.0
P2P_EPISODES_UNHEALTHY = 120.0
P2P_EPISODE_TYPES = ("rto_timeout", "rwnd_stall", "bad_ack", "stream_failed")
P2P_WINDOW_SECONDS = 60.0
# replication head gap (seconds a peer's library head is ahead of ours,
# corroborated by a FRESH federation snapshot — see _sync below)
SYNC_GAP_DEGRADED = 60.0
SYNC_GAP_UNHEALTHY = 600.0
# a resident tenant holding this share of the serve surface (with at
# least one other tenant present) degrades the tenants subsystem even
# before the fairness SLO burns
DOMINANT_DEGRADED = 0.95


def _verdict(status: str, reason: str | None = None,
             **signals: Any) -> dict[str, Any]:
    out: dict[str, Any] = {"status": status}
    if reason:
        out["reason"] = reason
    if signals:
        out["signals"] = signals
    return out


def _event_loop() -> dict[str, Any]:
    lag = gauge_value("sd_event_loop_lag_seconds")
    if lag >= LOOP_LAG_UNHEALTHY:
        return _verdict(UNHEALTHY, f"event-loop lag {lag:.2f}s", lag_seconds=lag)
    if lag >= LOOP_LAG_DEGRADED:
        return _verdict(DEGRADED, f"event-loop lag {lag:.2f}s", lag_seconds=lag)
    return _verdict(HEALTHY, lag_seconds=lag)


def _feeder() -> dict[str, Any]:
    waits = histogram_recent("sd_feeder_wait_seconds")
    if not waits:
        return _verdict(UNKNOWN, "no feeder activity")
    worst = max(waits)
    if worst >= FEEDER_WAIT_UNHEALTHY:
        return _verdict(UNHEALTHY, f"feeder stall {worst:.2f}s",
                        worst_wait_seconds=worst)
    if worst >= FEEDER_WAIT_DEGRADED:
        return _verdict(DEGRADED, f"feeder wait {worst:.2f}s",
                        worst_wait_seconds=worst)
    return _verdict(HEALTHY, worst_wait_seconds=worst)


def _device() -> dict[str, Any]:
    # the degradation ladder outranks occupancy: a node that demoted to
    # a chip subset (or all the way to the host reference path) is still
    # CORRECT, but an operator must see it — it is running at a fraction
    # of its provisioned throughput until the re-arm probe succeeds
    demotion = gauge_value("sd_device_demotion_level")
    if demotion >= 2:
        return _verdict(
            DEGRADED,
            "device dispatch demoted to the host reference path",
            demotion_level=demotion,
        )
    if demotion >= 1:
        return _verdict(
            DEGRADED,
            "device dispatch demoted to a surviving chip subset",
            demotion_level=demotion,
        )
    samples: list[float] = []
    for op in ("blake3", "thumbnail"):
        samples.extend(histogram_recent("sd_device_dispatch_occupancy", op=op))
    if not samples:
        return _verdict(UNKNOWN, "no sharded dispatches")
    mean = sum(samples) / len(samples)
    if mean < OCCUPANCY_DEGRADED:
        return _verdict(
            DEGRADED,
            f"mean dispatch occupancy {mean:.2f} — chips mostly hauling pad rows",
            mean_occupancy=mean,
        )
    return _verdict(HEALTHY, mean_occupancy=mean, demotion_level=demotion)


def _resilience() -> dict[str, Any]:
    """Breaker plane: open circuits mean some target (relay, peer) is
    being fast-failed right now. Degraded — the node itself still
    works, but a dependency is being routed around."""
    from ..utils.resilience import breaker_snapshot

    open_n = gauge_value("sd_breaker_open")
    retries = counter_value("sd_resilience_retries_total")
    signals = {"open_breakers": open_n, "retries_total": retries,
               "breakers": breaker_snapshot()}
    if open_n > 0:
        return _verdict(
            DEGRADED, f"{int(open_n)} circuit breaker(s) open", **signals
        )
    return _verdict(HEALTHY, **signals)


def _p2p() -> dict[str, Any]:
    from .events import P2P_EVENTS

    now = time.time()
    episodes = [
        e for e in P2P_EVENTS.snapshot()
        if e.get("type") in P2P_EPISODE_TYPES
        and now - e.get("ts", 0.0) <= P2P_WINDOW_SECONDS
    ]
    rate = len(episodes) * 60.0 / P2P_WINDOW_SECONDS
    if rate >= P2P_EPISODES_UNHEALTHY:
        return _verdict(UNHEALTHY, f"{rate:.0f} failure episodes/min",
                        episodes_per_min=rate)
    if rate >= P2P_EPISODES_DEGRADED:
        return _verdict(DEGRADED, f"{rate:.0f} failure episodes/min",
                        episodes_per_min=rate)
    return _verdict(HEALTHY, episodes_per_min=rate)


def _replication_gaps(node: Any) -> dict[str, float]:
    """Per-peer head gap, CORROBORATED: how far each fresh federation
    snapshot's library head (latest HLC that peer has seen) is ahead of
    ours. ~0 on a converged mesh — idle or busy — and positive only
    when a peer demonstrably holds ops we have not applied. This is the
    signal verdicts act on; raw wall-clock lag cannot distinguish
    'replica behind' from 'nothing to replicate'."""
    cache = getattr(getattr(node, "p2p", None), "federation", None)
    if cache is None:
        return {}
    our_heads: dict[str, float] = {}
    for lib in getattr(getattr(node, "libraries", None), "libraries",
                       {}).values():
        try:
            our_heads[str(lib.id)] = lib.sync.clock.peek_last().as_unix()
        except Exception:  # noqa: BLE001 - health reads never fail
            continue
    gaps: dict[str, float] = {}
    for pid, snap in cache.fresh_snapshots().items():
        libs = (snap.get("node") or {}).get("libraries") or {}
        worst = 0.0
        seen = False
        for lib_id, entry in libs.items():
            head = entry.get("head_seconds") if isinstance(entry, dict) else None
            ours = our_heads.get(str(lib_id))
            if head is None or ours is None:
                continue
            seen = True
            worst = max(worst, float(head) - ours)
        if seen:
            from .peers import peer_label

            gaps[peer_label(pid)] = worst
    return gaps


def _sync(node: Any = None) -> dict[str, Any]:
    lags: dict[str, float] = {}
    if node is not None:
        # refresh the gauges from live watermarks so dashboards see
        # honest time-since-last-applied-op even while idle (the gauge
        # would otherwise freeze at the last ingest)
        for lib in getattr(getattr(node, "libraries", None), "libraries",
                           {}).values():
            try:
                lags.update(lib.sync.observe_replication_lag())
            except Exception:  # noqa: BLE001 - health reads never fail
                continue
    else:
        fam = REGISTRY.get("sd_sync_lag_seconds")
        if fam is not None:
            with fam._lock:
                lags = {k[0]: s.value for k, s in fam._series.items() if k}
    guard_trips = counter_value("sd_hlc_delta_guard_total")
    gaps = _replication_gaps(node)
    signals = {"lag_seconds": lags, "delta_guard_trips": guard_trips,
               "head_gap_seconds": gaps}
    if not lags and not gaps:
        v = _verdict(UNKNOWN, "no replication peers")
        if guard_trips:
            v = _verdict(DEGRADED, f"{int(guard_trips)} delta-guard trips",
                         delta_guard_trips=guard_trips)
        return v
    # verdicts key off the corroborated head gap ONLY. Raw wall-lag
    # (now − last applied op) grows on a perfectly healthy idle mesh,
    # so it must never flip a node unhealthy — a probe acting on
    # GET /health's 503 would drain idle-but-fine nodes. (The /mesh
    # staleness rule separately covers 'peer gone silent'.)
    if gaps:
        worst_peer, worst = max(gaps.items(), key=lambda kv: kv[1])
        if worst >= SYNC_GAP_UNHEALTHY:
            return _verdict(
                UNHEALTHY,
                f"{worst:.0f}s of peer {worst_peer}'s ops not yet applied",
                **signals)
        if worst >= SYNC_GAP_DEGRADED:
            return _verdict(
                DEGRADED,
                f"{worst:.0f}s of peer {worst_peer}'s ops not yet applied",
                **signals)
    if guard_trips:
        return _verdict(DEGRADED, f"{int(guard_trips)} delta-guard trips",
                        **signals)
    return _verdict(HEALTHY, **signals)


def _serve(node: Any = None) -> dict[str, Any]:
    """Serve layer: the admission gate's overload posture. Brownout or
    active interactive shedding is degraded — the node still answers,
    but it is refusing work and serving stale cache entries. A shed in
    the control or sync class is UNHEALTHY: those classes must never
    shed (the gate's own contract), so a nonzero count is a serve-layer
    bug an operator must see."""
    from ..serve import runtime_for

    serve = runtime_for(node) if node is not None else None
    if serve is None:
        return _verdict(UNKNOWN, "serve gate disabled or absent")
    snap = serve.gate.snapshot()
    classes = snap["classes"]
    protected_shed = sum(
        c["shed_total"] for k, c in classes.items()
        if not c.get("sheddable", True)
    )
    signals = {
        "mode": snap["mode"],
        "classes": classes,
        "caches": serve.snapshot()["caches"],
    }
    if protected_shed:
        return _verdict(
            UNHEALTHY,
            f"{protected_shed} control/sync request(s) shed — protected "
            "classes must never shed",
            **signals,
        )
    if snap["mode"] == "brownout":
        return _verdict(DEGRADED, "read path in brownout", **signals)
    return _verdict(HEALTHY, **signals)


def _slo(node: Any = None) -> dict[str, Any]:
    """SLO burn-rate posture (telemetry/slo.py over the node's
    persistent history). A breach — fast AND slow windows burning the
    error budget past their thresholds (or any protected-class shed)
    — is UNHEALTHY: the node is violating its stated contract, not
    merely degraded. A fast-window-only burn is DEGRADED (the warn
    stage of the standard multi-window alert). No history, or no
    samples yet, reads UNKNOWN and never worsens the rollup."""
    from . import slo as _slo_mod

    history = getattr(node, "history", None) if node is not None else None
    if history is None:
        return _verdict(UNKNOWN, "no telemetry history")
    evaluation = _slo_mod.evaluate(history)
    breached = [s["name"] for s in evaluation["slos"]
                if s["status"] == _slo_mod.BREACH]
    warned = [s["name"] for s in evaluation["slos"]
              if s["status"] == _slo_mod.WARN]
    signals = {"slos": {
        s["name"]: {"status": s["status"], "current": s.get("current")}
        for s in evaluation["slos"]
    }}
    if breached:
        return _verdict(
            UNHEALTHY, f"SLO breach: {', '.join(sorted(breached))}",
            **signals)
    if warned:
        return _verdict(
            DEGRADED,
            f"fast-window burn: {', '.join(sorted(warned))}", **signals)
    if evaluation["status"] == _slo_mod.NO_DATA:
        return _verdict(UNKNOWN, "no history samples yet")
    return _verdict(HEALTHY, **signals)


def _resources() -> dict[str, Any]:
    """Resource-growth posture (telemetry/resources.py + the trend SLO
    class). The verdict keys off the trend SLOs' verdicts from the
    evaluation the ``slo`` subsystem just ran — a sustained RSS/fd
    growth slope past its bar is UNHEALTHY (the node is leaking toward
    an OOM, on a schedule), a flattened-but-regressed window is
    DEGRADED. Disabled sampling (SD_RESOURCES=0) or no samples yet
    reads UNKNOWN and never worsens the rollup."""
    from . import resources as _res
    from . import slo as _slo_mod

    if not _res.enabled():
        return _verdict(UNKNOWN, "resource sampling disabled")
    summary = _res.SAMPLER.summary()
    if not summary.get("last"):
        return _verdict(UNKNOWN, "no resource samples yet",
                        running=summary.get("running", False))
    trend_names = {s.name for s in _slo_mod.REGISTRY.all()
                   if s.kind == "trend"}
    evaluation = _slo_mod.REGISTRY.last_evaluation or {}
    trends = {s["name"]: s for s in evaluation.get("slos", ())
              if s["name"] in trend_names}
    breached = sorted(n for n, s in trends.items()
                      if s["status"] == _slo_mod.BREACH)
    warned = sorted(n for n, s in trends.items()
                    if s["status"] == _slo_mod.WARN)
    signals = {
        "last": summary["last"],
        "samples": summary["samples"],
        "trends": {
            n: {"status": s["status"],
                **(s.get("windows", {}).get("trend") or {})}
            for n, s in trends.items()
        },
    }
    if breached:
        return _verdict(
            UNHEALTHY,
            f"resource growth past its slope bar: {', '.join(breached)}",
            **signals)
    if warned:
        return _verdict(
            DEGRADED,
            f"resource growth regressed: {', '.join(warned)}", **signals)
    return _verdict(HEALTHY, **signals)


def _tenants() -> dict[str, Any]:
    """Per-tenant fairness posture (telemetry/tenants.py + the
    ``tenant_fairness`` SLO). A burning fairness SLO is UNHEALTHY —
    one library is starving the rest on the serve surface, the exact
    condition ROADMAP item 4's enforcement loop exists to prevent; a
    fast-window warn or a dominant tenant holding nearly the whole
    surface is DEGRADED. Disabled accounting (SD_TENANT_OBS=0) or an
    idle plane reads UNKNOWN and never worsens the rollup."""
    from . import slo as _slo_mod
    from . import tenants as _ten

    if not _ten.enabled():
        return _verdict(UNKNOWN, "tenant accounting disabled")
    dig = _ten.digest()
    if not dig:
        return _verdict(UNKNOWN, "no tenant observations yet")
    evaluation = _slo_mod.REGISTRY.last_evaluation or {}
    fairness_slo = next(
        (s for s in evaluation.get("slos", ())
         if s["name"] == "tenant_fairness"), None)
    serve = dig.get("serve", {})
    signals = {
        "surfaces": len(dig),
        "serve_fairness": serve.get("fairness"),
        "serve_dominant": serve.get("dominant"),
        "slo": fairness_slo["status"] if fairness_slo else None,
        "digest": dig,
    }
    if fairness_slo and fairness_slo["status"] == _slo_mod.BREACH:
        return _verdict(
            UNHEALTHY,
            "tenant_fairness burning both windows — a tenant is "
            "starving the serve surface", **signals)
    if fairness_slo and fairness_slo["status"] == _slo_mod.WARN:
        return _verdict(
            DEGRADED, "tenant_fairness fast-window burn", **signals)
    if (serve.get("tenants", 0) >= 2
            and (serve.get("dominant") or 0.0) >= DOMINANT_DEGRADED):
        return _verdict(
            DEGRADED,
            f"dominant tenant holds {serve['dominant']:.0%} of the "
            "serve surface", **signals)
    return _verdict(HEALTHY, **signals)


def evaluate(node: Any = None) -> dict[str, Any]:
    """The full health rollup: per-subsystem verdicts plus the overall
    status (worst subsystem; ``unknown`` counts as healthy)."""
    subsystems = {
        "event_loop": _event_loop(),
        "feeder": _feeder(),
        "device": _device(),
        "p2p": _p2p(),
        "sync": _sync(node),
        "resilience": _resilience(),
        "serve": _serve(node),
        "slo": _slo(node),
        # MUST come after "slo": the trend verdicts they read are the
        # ones _slo just computed into REGISTRY.last_evaluation
        "resources": _resources(),
        "tenants": _tenants(),
    }
    overall = HEALTHY
    for v in subsystems.values():
        if _RANK[v["status"]] > _RANK[overall]:
            overall = v["status"]
    out = {"status": overall, "subsystems": subsystems}
    try:
        # the autotuner's knob state rides health (and therefore every
        # federation snapshot → GET /mesh): a node quietly running at a
        # demoted rung or 8× windows is a capacity fact operators need
        from ..parallel.autotune import snapshot as _autotune_snapshot

        out["autotune"] = _autotune_snapshot()
    except Exception:  # noqa: BLE001 - health reads never fail
        pass
    return out
