"""Critical-path attribution — where did a pass's wall-clock go?

The ROADMAP re-anchor is blunt: device compute is solved and the honest
frontier is host-side scaling (one GIL serializing per-entry
orchestration; a ~0.03 GB/s host→device link pinning e2e). Spans
(PR 3) record *that* time passed in a stage; this module answers the
operator question — **which resource was the pass actually waiting
on, across the whole mesh, and is it getting worse?**

Given a ``trace_id`` (or "the last pass", via the job-boundary markers
``jobs/manager.py`` drops here), it:

1. assembles the full distributed span forest — local spans from the
   trace ring plus executor-side spans pulled from mesh peers over the
   ``TELEMETRY`` wire's ``trace_pull`` op (``p2p/manager.py``), riding
   the PR 6 resilience policies so a vanished peer degrades the report
   to *partial* instead of blocking it;
2. computes the **critical path**: a sweep over span boundaries
   attributes every wall-clock slice of the pass window to the most
   blocking active span (resource priority, then nesting depth) —
   slices no span covers, and slices only orchestration spans cover,
   are the *unattributed gap*: the GIL signature;
3. buckets the path's time:

   - ``device``      — on-chip compute (hash materialization, resize);
   - ``host_cpu``    — Python/SQL host work (walk, decode, encode, DB
     linking, journal, sync ingest);
   - ``link``        — host→device feeder plus every network leg (P2P,
     relay, cloud);
   - ``queue_wait``  — task-system queue time and admission waits;
   - ``gap``         — wall time attributable to no instrumented stage
     (per-entry Python orchestration between spans — on this rig, the
     GIL).

Buckets partition the pass window exactly (they always sum to the
window), so "buckets sum ≥ 90% of measured wall time" is a statement
about span *coverage* of the pass, and the tier-1 proof injects a
deterministic ``feeder.fetch`` stall and asserts the link bucket —
and only the link bucket — absorbs it.

Surfaces: ``GET /attrib``, rspc ``telemetry.attrib``, ``sdx attrib
[trace_id]``. Reports are cached per trace (bounded; cleared by
``telemetry.reset()``) and the HTTP surface additionally rides the
serve meta cache so dashboard polls don't re-pull the mesh.

Cross-node caveat: remote spans carry the *remote* node's wall clock.
The in-process test mesh shares one clock; on a real mesh, NTP-level
skew shifts remote segments by the skew amount — the bucket split
stays sane because skewed spans still land inside the pass window,
but sub-millisecond cross-node ordering is not a promise this module
makes.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Iterable

from . import metrics as _tm
from . import trace as _trace

#: bucket vocabulary (stable: bench_e2e + bench_compare gate on it)
DEVICE = "device"
HOST_CPU = "host_cpu"
LINK = "link"
QUEUE_WAIT = "queue_wait"
GAP = "gap"
BUCKETS = (DEVICE, HOST_CPU, LINK, QUEUE_WAIT, GAP)

#: when two spans cover the same wall slice, the more "blocking"
#: resource wins: device compute outranks host work outranks transport
#: outranks queueing; orchestration/container spans (GAP) never outrank
#: a real stage. Ties break by nesting depth (innermost span wins).
_PRIORITY = {DEVICE: 4, HOST_CPU: 3, LINK: 2, QUEUE_WAIT: 1, GAP: 0}

#: full-path head → bucket: every network-plane span family
_HEAD_BUCKETS = {
    "p2p": LINK,
    "relay": LINK,
    "cloud": LINK,
    "feeder": LINK,  # H2D staging: producer fetch AND consumer wait
    # batches riding the multi-process execution plane: the pass is
    # waiting on host CPU burned in pool workers (their GIL, not ours)
    "procpool": HOST_CPU,
}

#: last dotted segment → bucket for the pipeline stages
_SEGMENT_BUCKETS = {
    # device compute
    "hash": DEVICE,        # identify.hash, mesh.shard_hash (via last seg)
    "shard_hash": DEVICE,
    "device": DEVICE,      # thumbnail.device
    "resize": DEVICE,
    # host CPU
    "walk": HOST_CPU,
    "db": HOST_CPU,        # identify.db (SQL linking)
    "decode": HOST_CPU,
    "encode": HOST_CPU,
    "ingest": HOST_CPU,    # sync.ingest (op apply is SQLite + Python)
    "request": HOST_CPU,   # sync.request assembly
    "journal": HOST_CPU,
    "store": HOST_CPU,
    # queueing
    "dispatch": QUEUE_WAIT,  # the synthetic task.dispatch queue-wait span
    "queue": QUEUE_WAIT,
    "admit": QUEUE_WAIT,
}

_REPORT_CACHE_MAX = 16
_PASS_RING = 64


def bucket_of(stage: str) -> str:
    """Classify a span stage path. Unknown stages are orchestration:
    their self-time is the unattributed gap."""
    head = stage.split(".", 1)[0]
    got = _HEAD_BUCKETS.get(head)
    if got is not None:
        return got
    return _SEGMENT_BUCKETS.get(stage.rsplit(".", 1)[-1], GAP)


# --- pass boundary markers (jobs/manager.py) -----------------------------

_passes: collections.deque = collections.deque(maxlen=_PASS_RING)
_passes_lock = threading.Lock()


def mark_pass(job: str, trace_id: str, event: str, **fields: Any) -> None:
    """A job-pass boundary: ``started`` at ingest, ``settled`` when the
    supervisor closes it. ``sdx attrib`` with no trace id resolves "the
    last pass" through these markers instead of guessing from the span
    ring."""
    rec = {"ts": time.time(), "job": job, "trace_id": trace_id,
           "event": event}
    if fields:
        rec.update(fields)
    with _passes_lock:
        _passes.append(rec)


def recent_passes() -> list[dict[str, Any]]:
    with _passes_lock:
        return list(_passes)


def last_pass_trace() -> str | None:
    """The most recently *settled* pass's trace id (falling back to the
    most recently started one when nothing settled yet)."""
    started = None
    with _passes_lock:
        for rec in reversed(_passes):
            if rec["event"] == "settled":
                return rec["trace_id"]
            if started is None:
                started = rec["trace_id"]
    return started


def _pass_settled(trace_id: str) -> bool:
    """True when this trace's pass markers prove the pass is over: at
    least one job settled under it and none started after the last
    settle (chained jobs share one trace — a mid-chain read must not
    freeze a half-pass report in the cache)."""
    with _passes_lock:
        last = None
        for rec in _passes:
            if rec["trace_id"] == trace_id:
                last = rec["event"]
    return last == "settled"


# --- the sweep -----------------------------------------------------------


def _span_intervals(spans: Iterable[dict[str, Any]]) -> list[dict[str, Any]]:
    """Normalize raw span records into sweep intervals with bucket,
    priority, and tree depth (via parent links where present)."""
    by_id: dict[str, dict[str, Any]] = {}
    out: list[dict[str, Any]] = []
    for rec in spans:
        try:
            t0 = float(rec["t0"])
            dur = max(0.0, float(rec.get("seconds", 0.0)))
        except (KeyError, TypeError, ValueError):
            continue
        iv = {
            "stage": str(rec.get("stage", "?")),
            "t0": t0,
            "t1": t0 + dur,
            "span_id": rec.get("span_id"),
            "parent_id": rec.get("parent_id"),
            "node": rec.get("node", "local"),
        }
        iv["bucket"] = bucket_of(iv["stage"])
        out.append(iv)
        if iv["span_id"]:
            by_id[iv["span_id"]] = iv
    for iv in out:
        depth = 0
        cur = iv
        seen = set()
        while cur is not None and cur["parent_id"] in by_id:
            pid = cur["parent_id"]
            if pid in seen:  # defensive: a wire-supplied cycle must not hang
                break
            seen.add(pid)
            depth += 1
            cur = by_id[pid]
        iv["depth"] = depth
    return out


def _sweep(intervals: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Partition the pass window into critical-path segments: between
    consecutive span boundaries the active set is constant; each slice
    goes to the active span with the highest (bucket priority, depth,
    start) — or to nobody (a pure gap)."""
    if not intervals:
        return []
    bounds = sorted({iv["t0"] for iv in intervals}
                    | {iv["t1"] for iv in intervals})
    # event sweep: active set maintained across boundaries
    starts = sorted(intervals, key=lambda iv: iv["t0"])
    ends = sorted(intervals, key=lambda iv: iv["t1"])
    active: dict[int, dict[str, Any]] = {}
    si = ei = 0
    segments: list[dict[str, Any]] = []
    for i in range(len(bounds) - 1):
        t, t2 = bounds[i], bounds[i + 1]
        while si < len(starts) and starts[si]["t0"] <= t:
            active[id(starts[si])] = starts[si]
            si += 1
        while ei < len(ends) and ends[ei]["t1"] <= t:
            active.pop(id(ends[ei]), None)
            ei += 1
        if t2 <= t:
            continue
        owner = None
        if active:
            owner = max(active.values(), key=lambda iv: (
                _PRIORITY[iv["bucket"]], iv["depth"], iv["t0"]))
        seg = {
            "t0": t, "t1": t2, "seconds": t2 - t,
            "stage": owner["stage"] if owner else None,
            "bucket": owner["bucket"] if owner else GAP,
            "node": owner["node"] if owner else None,
        }
        # merge with the previous segment when the owner is unchanged
        if segments and segments[-1]["stage"] == seg["stage"] \
                and segments[-1]["bucket"] == seg["bucket"] \
                and segments[-1]["node"] == seg["node"] \
                and abs(segments[-1]["t1"] - seg["t0"]) < 1e-9:
            segments[-1]["t1"] = seg["t1"]
            segments[-1]["seconds"] += seg["seconds"]
        else:
            segments.append(seg)
    return segments


def report(trace_id: str, spans: list[dict[str, Any]] | None = None,
           *, max_path: int = 64) -> dict[str, Any]:
    """The attribution report for one trace over the given spans
    (default: the local trace ring). Pure computation — remote
    assembly lives in :func:`assemble`."""
    if spans is None:
        spans = _trace.recent(trace_id)
    intervals = _span_intervals(spans)
    segments = _sweep(intervals)
    buckets = {b: 0.0 for b in BUCKETS}
    stages: dict[str, float] = {}
    for seg in segments:
        buckets[seg["bucket"]] += seg["seconds"]
        key = seg["stage"] or "(gap)"
        stages[key] = stages.get(key, 0.0) + seg["seconds"]
    wall = sum(buckets.values())
    nodes: dict[str, int] = {}
    for iv in intervals:
        nodes[iv["node"]] = nodes.get(iv["node"], 0) + 1
    origin = min((iv["t0"] for iv in intervals), default=0.0)
    path = [
        {
            "stage": seg["stage"], "bucket": seg["bucket"],
            "node": seg["node"],
            "offset_s": round(seg["t0"] - origin, 6),
            "seconds": round(seg["seconds"], 6),
        }
        for seg in sorted(segments, key=lambda s: s["seconds"],
                          reverse=True)[:max_path]
    ]
    doc = {
        "trace_id": trace_id,
        "spans": len(intervals),
        "nodes": nodes,
        "wall_seconds": round(wall, 6),
        "buckets": {b: round(v, 6) for b, v in buckets.items()},
        "bucket_fractions": {
            b: round(v / wall, 4) if wall > 0 else 0.0
            for b, v in buckets.items()
        },
        "top_segments": path,
        "top_stages": dict(sorted(
            ((k, round(v, 6)) for k, v in stages.items()),
            key=lambda kv: kv[1], reverse=True)[:16]),
    }
    # the host profiler (telemetry/sampler.py) names the code inside
    # the anonymous buckets: every timeline sample landing in a gap
    # (or host_cpu) critical-path segment votes for its frame group,
    # and the bucket's seconds split proportionally. The report keeps
    # the span-derived buckets authoritative — the decomposition only
    # explains them.
    from . import sampler as _sampler

    for bucket, key in ((GAP, "gap_decomposition"),
                        (HOST_CPU, "host_cpu_decomposition")):
        # LOCAL segments only: the timeline is this process's samples,
        # and voting them into a wall window owned by a REMOTE
        # executor's span would name local code for the peer's time
        # (gap segments have no owner and are always local wall)
        segs = [(s["t0"], s["t1"]) for s in segments
                if s["bucket"] == bucket
                and s["node"] in (None, "local")]
        local_seconds = sum(t1 - t0 for t0, t1 in segs)
        decomp = _sampler.decompose_segments(segs, local_seconds)
        if decomp is not None:
            doc[key] = decomp
    _tm.ATTRIB_REPORTS.inc()
    _tm.ATTRIB_BUCKET_SECONDS.set(buckets[DEVICE], bucket="device")
    _tm.ATTRIB_BUCKET_SECONDS.set(buckets[HOST_CPU], bucket="host_cpu")
    _tm.ATTRIB_BUCKET_SECONDS.set(buckets[LINK], bucket="link")
    _tm.ATTRIB_BUCKET_SECONDS.set(buckets[QUEUE_WAIT], bucket="queue_wait")
    _tm.ATTRIB_BUCKET_SECONDS.set(buckets[GAP], bucket="gap")
    return doc


# --- distributed assembly ------------------------------------------------

_report_cache: "collections.OrderedDict[str, dict[str, Any]]" = \
    collections.OrderedDict()
_cache_lock = threading.Lock()


def cached_report(trace_id: str) -> dict[str, Any] | None:
    with _cache_lock:
        return _report_cache.get(trace_id)


def _cache_store(trace_id: str, doc: dict[str, Any]) -> None:
    with _cache_lock:
        _report_cache[trace_id] = doc
        _report_cache.move_to_end(trace_id)
        while len(_report_cache) > _REPORT_CACHE_MAX:
            _report_cache.popitem(last=False)


async def assemble(node: Any, trace_id: str | None = None, *,
                   remote: bool = True,
                   refresh: bool = False) -> dict[str, Any]:
    """The full distributed report: local spans plus executor-side
    spans pulled from every reachable mesh peer for this trace. Pull
    failures degrade the report to ``partial`` (with per-peer errors)
    — they never block or raise. ``refresh`` bypasses the per-trace
    report cache (a settled pass's report is immutable in practice)."""
    if trace_id is None:
        trace_id = last_pass_trace()
    if trace_id is None:
        return {"error": "no completed pass found — pass a trace_id",
                "passes": recent_passes()[-8:]}
    if not refresh:
        got = cached_report(trace_id)
        if got is not None:
            return got
    spans = [dict(r, node="local") for r in _trace.recent(trace_id)]
    pull_failures: dict[str, str] = {}
    remote_n = 0
    manager = getattr(node, "p2p", None)
    if remote and manager is not None:
        remote_spans, pull_failures = await manager.pull_remote_spans(
            trace_id
        )
        remote_n = len(remote_spans)
        spans.extend(remote_spans)
    doc = report(trace_id, spans)
    doc["remote_spans"] = remote_n
    doc["partial"] = bool(pull_failures)
    if pull_failures:
        doc["pull_failures"] = pull_failures
    doc["passes"] = [
        p for p in recent_passes() if p["trace_id"] == trace_id
    ]
    # cache ONLY immutable answers: a settled pass's complete
    # assembly. A still-running pass (more spans coming) or a partial
    # pull (a peer may come back) must be recomputed on the next read
    # — the serve meta cache still coalesces dashboard bursts.
    if not pull_failures and _pass_settled(trace_id):
        _cache_store(trace_id, doc)
    return doc


def reset() -> None:
    """Test isolation (rides ``telemetry.reset()``): drop the report
    cache and the pass-boundary ring."""
    with _cache_lock:
        _report_cache.clear()
    with _passes_lock:
        _passes.clear()
