"""Distributed trace context — Dapper-style ids over the span layer.

PR 1's spans measure *where* time goes; this module answers *whose*
time it was. Every span now carries ``trace_id``/``span_id``/
``parent_id``, and a small ``TraceContext`` travels across the
boundaries where ``contextvars`` nesting dies:

- task-system dispatch (``tasks/system.py``): a batch executes inside
  the trace of the caller that coalesced it;
- the H2D feeder's producer thread (``parallel/feeder.py``);
- job suspend/resume (the context serializes into job state, so a job
  cold-resumed after a crash continues its original trace);
- the P2P wire (``p2p/protocol.py`` carries it on sync-ingest,
  spacedrop and cloud-relay messages, so a remote node's spans join the
  initiator's trace).

Completed spans land in a bounded ring here; ``export()`` renders it as
Chrome-trace-event JSON (the ``traceEvents`` array format), loadable
directly in Perfetto / ``chrome://tracing``.

Propagation contract: ``current()`` reflects the innermost *active*
span (every ``Span.__enter__`` publishes itself here) or, absent one,
whatever context a boundary installed via ``use()``. A span opening
with no parent span adopts ``current()`` as its parent; with nothing
ambient it mints a fresh root trace.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
from collections import deque
from typing import Any, Iterator

TRACE_RING = 4096  # completed spans retained for export


class TraceContext:
    """An addressable point in a trace: (trace_id, span_id)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_wire(self) -> dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_wire(cls, d: Any) -> "TraceContext | None":
        """Tolerant decode: anything that isn't a dict with both ids is
        treated as 'no context' (the wire field is best-effort)."""
        if not isinstance(d, dict):
            return None
        trace_id, span_id = d.get("trace_id"), d.get("span_id")
        if not isinstance(trace_id, str) or not isinstance(span_id, str):
            return None
        return cls(trace_id, span_id)

    def __repr__(self) -> str:
        return f"<TraceContext {self.trace_id[:8]}…/{self.span_id}>"


def new_trace_id() -> str:
    return os.urandom(16).hex()  # 128-bit, W3C-trace-context sized


def new_span_id() -> str:
    return os.urandom(8).hex()


def new_context() -> TraceContext:
    """A fresh root context (the origin point of a new trace)."""
    return TraceContext(new_trace_id(), new_span_id())


_ambient: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "sd_trace_ctx", default=None
)


def current() -> TraceContext | None:
    """The context new spans (and outbound messages) should join."""
    return _ambient.get()


def wire_current() -> dict[str, str] | None:
    ctx = _ambient.get()
    return ctx.to_wire() if ctx is not None else None


def set_current(ctx: TraceContext | None) -> contextvars.Token:
    """Low-level install (spans, boundary shims). Pair with
    ``reset_current``."""
    return _ambient.set(ctx)


def reset_current(token: contextvars.Token) -> None:
    _ambient.reset(token)


@contextlib.contextmanager
def use(ctx: TraceContext | None) -> Iterator[TraceContext | None]:
    """Run a block under ``ctx``; ``use(None)`` is a no-op so call
    sites don't need to branch on 'did the wire carry a context'."""
    if ctx is None:
        yield None
        return
    token = _ambient.set(ctx)
    try:
        yield ctx
    finally:
        _ambient.reset(token)


# --- the completed-span ring -------------------------------------------


_ring: deque[dict[str, Any]] = deque(maxlen=TRACE_RING)
_ring_lock = threading.Lock()


def record_span(rec: dict[str, Any]) -> None:
    """Append one completed span record. Expected keys: ``stage``,
    ``trace_id``, ``span_id``, ``parent_id``, ``t0`` (epoch seconds),
    ``seconds``, plus optional ``bytes``/``error``/extra args. Spans
    call this on exit; boundary shims (task dispatch) record synthetic
    spans directly."""
    with _ring_lock:
        _ring.append(rec)


def recent(trace_id: str | None = None) -> list[dict[str, Any]]:
    """Most-recent-last completed span records, optionally filtered to
    one trace."""
    with _ring_lock:
        recs = list(_ring)
    if trace_id is not None:
        recs = [r for r in recs if r.get("trace_id") == trace_id]
    return recs


def clear() -> None:
    with _ring_lock:
        _ring.clear()


# --- Chrome-trace-event export -----------------------------------------


def _tid_for(trace_id: str) -> int:
    """Stable per-trace lane so Perfetto groups one trace's spans
    together (31-bit to stay a small positive JSON int)."""
    return int(trace_id[:8], 16) & 0x7FFFFFFF


def export(trace_id: str | None = None) -> dict[str, Any]:
    """The ring as Chrome trace JSON: ``{"traceEvents": [...]}`` with
    complete ("X") events, microsecond timestamps, and the trace/span
    ids in ``args`` — loadable as-is in Perfetto."""
    pid = os.getpid()
    events: list[dict[str, Any]] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": "spacedrive_tpu"},
        }
    ]
    for rec in recent(trace_id):
        args: dict[str, Any] = {
            "trace_id": rec.get("trace_id"),
            "span_id": rec.get("span_id"),
            "parent_id": rec.get("parent_id"),
        }
        if rec.get("bytes"):
            args["bytes"] = rec["bytes"]
        if rec.get("error"):
            args["error"] = rec["error"]
        if rec.get("fields"):
            args.update(rec["fields"])  # span.annotate() scalars
        events.append(
            {
                "name": rec.get("stage", "?"),
                "cat": "span",
                "ph": "X",
                "ts": int(float(rec.get("t0", 0.0)) * 1e6),
                "dur": max(1, int(float(rec.get("seconds", 0.0)) * 1e6)),
                "pid": pid,
                "tid": _tid_for(rec.get("trace_id") or "0" * 8),
                "args": args,
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}
