"""IsolatedFilePathData — the canonical path decomposition stored in the
library DB (behavior parity with
ref:crates/file-path-helper/src/isolated_file_path_data.rs:33-46):

    location_id + materialized_path + name + extension + is_dir

`materialized_path` is the PARENT directory relative to the location
root, always "/"-wrapped (``/a/b/`` for ``<root>/a/b/x.txt``; ``/`` at
the root). `name` excludes the extension for files and is the full name
for directories; the location root row has empty name/extension.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from datetime import datetime, timezone


class FilePathError(ValueError):
    pass


def separate_name_and_extension(filename: str) -> tuple[str, str]:
    """('archive.tar', 'gz') for 'archive.tar.gz'; hidden files like
    '.env' have no extension."""
    stem, dot, ext = filename.rpartition(".")
    if not dot or not stem or not ext:
        return filename, ""
    return stem, ext


def path_is_hidden(path: str | os.PathLike) -> bool:
    """Unix dotfile convention (ref:crates/file-path-helper/src/lib.rs:132)."""
    name = os.path.basename(os.fspath(path).rstrip("/"))
    return name.startswith(".")


@dataclass(frozen=True)
class FilePathMetadata:
    """Filesystem facts recorded per file_path row
    (ref:crates/file-path-helper/src/lib.rs:124-130)."""

    inode: int
    size_in_bytes: int
    created_at: datetime
    modified_at: datetime
    hidden: bool
    # exact stat identity for the index journal (datetime fields above
    # lose sub-ms precision through float timestamps; the journal's
    # "unchanged" verdict must be lossless)
    mtime_ns: int = 0
    dev: int = 0

    @classmethod
    def from_path(cls, path: str | os.PathLike, stat: os.stat_result | None = None) -> "FilePathMetadata":
        st = stat if stat is not None else os.stat(path)
        return cls(
            inode=st.st_ino,
            size_in_bytes=st.st_size,
            created_at=datetime.fromtimestamp(getattr(st, "st_birthtime", st.st_ctime), timezone.utc),
            modified_at=datetime.fromtimestamp(st.st_mtime, timezone.utc),
            hidden=path_is_hidden(path),
            mtime_ns=st.st_mtime_ns,
            dev=st.st_dev,
        )


@dataclass(frozen=True)
class IsolatedFilePathData:
    location_id: int
    materialized_path: str
    is_dir: bool
    name: str
    extension: str
    relative_path: str = field(default="", compare=False)

    @classmethod
    def new(
        cls,
        location_id: int,
        location_path: str | os.PathLike,
        full_path: str | os.PathLike,
        is_dir: bool,
    ) -> "IsolatedFilePathData":
        loc = os.path.normpath(os.fspath(location_path))
        full = os.path.normpath(os.fspath(full_path))
        if full == loc:
            return cls(location_id, "/", is_dir, "", "", "")
        try:
            rel = os.path.relpath(full, loc)
        except ValueError as e:
            raise FilePathError(f"{full!r} not under location {loc!r}") from e
        if rel.startswith(".."):
            raise FilePathError(f"{full!r} not under location {loc!r}")
        rel = rel.replace(os.sep, "/")
        parent, _, filename = rel.rpartition("/")
        materialized = f"/{parent}/" if parent else "/"
        if is_dir:
            name, ext = filename, ""
        else:
            name, ext = separate_name_and_extension(filename)
        return cls(location_id, materialized, is_dir, name, ext, rel)

    @classmethod
    def from_relative_str(
        cls, location_id: int, relative: str, is_dir: bool | None = None
    ) -> "IsolatedFilePathData":
        """Parse a stored relative path; trailing '/' implies a dir."""
        if is_dir is None:
            is_dir = relative.endswith("/")
        rel = relative.strip("/")
        if not rel:
            return cls(location_id, "/", True, "", "", "")
        parent, _, filename = rel.rpartition("/")
        materialized = f"/{parent}/" if parent else "/"
        if is_dir:
            name, ext = filename, ""
        else:
            name, ext = separate_name_and_extension(filename)
        return cls(location_id, materialized, is_dir, name, ext, rel)

    @classmethod
    def from_db_row(
        cls, location_id: int, materialized_path: str, name: str, extension: str, is_dir: bool
    ) -> "IsolatedFilePathData":
        rel = materialized_path[1:] + name
        if not is_dir and extension:
            rel = f"{rel}.{extension}"
        return cls(location_id, materialized_path, is_dir, name, extension, rel)

    @property
    def is_root(self) -> bool:
        return self.is_dir and self.materialized_path == "/" and not self.name

    def full_name(self) -> str:
        if self.extension and not self.is_dir:
            return f"{self.name}.{self.extension}"
        return self.name

    def parent(self) -> "IsolatedFilePathData":
        if self.materialized_path == "/":
            return IsolatedFilePathData(self.location_id, "/", True, "", "", "")
        trimmed = self.materialized_path.strip("/")
        parent_of_parent, _, dir_name = trimmed.rpartition("/")
        materialized = f"/{parent_of_parent}/" if parent_of_parent else "/"
        return IsolatedFilePathData(
            self.location_id, materialized, True, dir_name, "", trimmed
        )

    def materialized_path_for_children(self) -> str | None:
        """What this row's children store as their materialized_path."""
        if not self.is_dir:
            return None
        if self.is_root:
            return "/"
        return f"{self.materialized_path}{self.name}/"

    def join_on(self, location_path: str | os.PathLike) -> str:
        """Absolute filesystem path of this row under `location_path`."""
        return os.path.join(os.fspath(location_path), self.relative_path.replace("/", os.sep))

    def __str__(self) -> str:
        return self.relative_path


def materialized_prefix(sub_path: str | None) -> str:
    """Materialized-path prefix for a location-relative sub_path; root
    ("", "/") is "/" so `LIKE prefix%` covers the whole location."""
    if not sub_path or sub_path.strip("/") == "":
        return "/"
    return f"/{sub_path.strip('/')}/"


def full_path_from_db_row(location_path: str | os.PathLike, row: dict) -> str:
    """Absolute path of a file_path DB row — the one canonical
    reconstruction used by every pipeline."""
    iso = IsolatedFilePathData.from_db_row(
        row.get("location_id", 0),
        row["materialized_path"],
        row["name"],
        row["extension"] or "",
        bool(row.get("is_dir")),
    )
    return iso.join_on(location_path)
