"""File taxonomy + path decomposition (the reference's sd-file-ext and
sd-file-path-helper crates, re-designed as data-driven Python)."""

from .kind import ObjectKind
from .extensions import (
    Extension,
    ExtensionPossibility,
    from_str,
    resolve_conflicting,
    verify_magic_bytes,
)
from .isolated_path import IsolatedFilePathData, FilePathMetadata

__all__ = [
    "ObjectKind",
    "Extension",
    "ExtensionPossibility",
    "from_str",
    "resolve_conflicting",
    "verify_magic_bytes",
    "IsolatedFilePathData",
    "FilePathMetadata",
]
