"""ObjectKind — the object-type taxonomy stored in `object.kind`.

Numbering is wire/DB-stable and must never change (the reference keeps
it in lockstep with its frontend, ref:crates/file-ext/src/kind.rs:7-64).
"""

from __future__ import annotations

import enum


class ObjectKind(enum.IntEnum):
    Unknown = 0          # not identifiable by the indexer
    Document = 1         # known filetype without specific support
    Folder = 2           # virtual filesystem directory
    Text = 3             # human-readable text
    Package = 4          # virtual directory (e.g. macOS bundle)
    Image = 5
    Audio = 6
    Video = 7
    Archive = 8
    Executable = 9
    Alias = 10           # link to another object
    Encrypted = 11       # bytes encrypted by the framework
    Key = 12             # key or certificate
    Link = 13            # opens web pages / apps / spaces
    WebPageArchive = 14
    Widget = 15
    Album = 16
    Collection = 17
    Font = 18
    Mesh = 19            # 3D object
    Code = 20
    Database = 21
    Book = 22
    Config = 23
    Dotfile = 24
    Screenshot = 25
    Label = 26
