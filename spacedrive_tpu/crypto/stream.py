"""Stream AEAD — bounded-memory encryption of arbitrarily large files.

Parity: ref:crates/crypto/src/crypto/stream.rs — `Algorithm::
{XChaCha20Poly1305, Aes256Gcm}` (:8-13) wrapped in the `aead` crate's
STREAM construction (`EncryptorLE31`, :153-168): per-message nonce =
base ‖ u32-LE counter ‖ last-block flag byte, so the base nonce is
(nonce_len − 5) bytes — 19 for XChaCha, 7 for AES-GCM — and truncation
or reordering of the 1 MiB blocks is detected. Block size matches the
reference's `BLOCK_LEN` (1 MiB, crypto/mod.rs).
"""

from __future__ import annotations

import enum
import os
import secrets
from typing import BinaryIO

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # gated: AEADs refuse at construction time below
    AESGCM = None  # type: ignore

    class InvalidTag(Exception):  # type: ignore[no-redef]
        """Placeholder so except-clauses stay valid; never raised."""

from .xchacha import XChaCha20Poly1305

BLOCK_LEN = 1024 * 1024  # ref:crypto/mod.rs BLOCK_LEN
TAG_LEN = 16
KEY_LEN = 32


class CryptoError(Exception):
    pass


class Algorithm(enum.IntEnum):
    """ref:stream.rs:8-13."""

    XCHACHA20_POLY1305 = 0
    AES_256_GCM = 1

    @property
    def nonce_len(self) -> int:
        return 24 if self is Algorithm.XCHACHA20_POLY1305 else 12

    @property
    def stream_nonce_len(self) -> int:
        # base nonce for LE31 STREAM: full nonce minus 4 counter + 1 flag
        return self.nonce_len - 5

    def generate_nonce(self) -> bytes:
        return secrets.token_bytes(self.stream_nonce_len)


class _Stream:
    def __init__(self, key: bytes, base_nonce: bytes, algorithm: Algorithm):
        if len(key) != KEY_LEN:
            raise CryptoError("key must be 32 bytes")
        if len(base_nonce) != algorithm.stream_nonce_len:
            raise CryptoError(
                f"nonce must be {algorithm.stream_nonce_len} bytes for {algorithm.name}"
            )
        self.algorithm = algorithm
        self.base_nonce = base_nonce
        self.counter = 0
        if algorithm is not Algorithm.XCHACHA20_POLY1305 and AESGCM is None:
            raise CryptoError(
                "the `cryptography` package is required for AES-256-GCM")
        self._aead = (
            XChaCha20Poly1305(key)
            if algorithm is Algorithm.XCHACHA20_POLY1305
            else AESGCM(key)
        )

    def _nonce(self, last: bool) -> bytes:
        # LE31: base ‖ counter (u32 LE) ‖ last-block flag
        if self.counter >= 1 << 31:
            raise CryptoError("stream counter overflow")
        n = (
            self.base_nonce
            + self.counter.to_bytes(4, "little")
            + (b"\x01" if last else b"\x00")
        )
        self.counter += 1
        return n


class StreamEncryption(_Stream):
    def encrypt_next(self, plaintext: bytes, aad: bytes = b"", *, last: bool) -> bytes:
        return self._aead.encrypt(self._nonce(last), plaintext, aad or None)

    def encrypt_streams(
        self, reader: BinaryIO, writer: BinaryIO, aad: bytes = b""
    ) -> int:
        """ref:stream.rs `encrypt_streams` — 1 MiB blocks; AAD bound to
        the first block only (header authentication), like the reference."""
        total = 0
        block = reader.read(BLOCK_LEN)
        first = True
        while True:
            nxt = reader.read(BLOCK_LEN)
            ct = self.encrypt_next(block, aad if first else b"", last=not nxt)
            writer.write(ct)
            total += len(block)
            first = False
            if not nxt:
                return total
            block = nxt


class StreamDecryption(_Stream):
    def decrypt_next(self, ciphertext: bytes, aad: bytes = b"", *, last: bool) -> bytes:
        try:
            return self._aead.decrypt(self._nonce(last), ciphertext, aad or None)
        except InvalidTag as e:
            raise CryptoError("decryption failed (wrong key or tampered data)") from e

    def decrypt_streams(
        self, reader: BinaryIO, writer: BinaryIO, aad: bytes = b""
    ) -> int:
        total = 0
        block = reader.read(BLOCK_LEN + TAG_LEN)
        first = True
        while True:
            nxt = reader.read(BLOCK_LEN + TAG_LEN)
            pt = self.decrypt_next(block, aad if first else b"", last=not nxt)
            writer.write(pt)
            total += len(pt)
            first = False
            if not nxt:
                return total
            block = nxt
