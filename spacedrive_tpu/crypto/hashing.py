"""Password hashing / key derivation.

Parity: ref:crates/crypto/src/types.rs:52-53 — `HashingAlgorithm::
{Argon2id(Params), BalloonBlake3(Params)}` with `Params::{Standard,
Hardened, Paranoid}` cost profiles. Argon2id rides `cryptography`'s
OpenSSL binding; Balloon hashing (Boneh–Corrigan-Gibbs–Schechter) is
implemented over the framework's native-C BLAKE3 — the same pairing
the reference gets from the `balloon-hash` + `blake3` crates. Output
is always a 32-byte key from (password, 16-byte salt).
"""

from __future__ import annotations

import enum
import secrets
import struct

try:
    from cryptography.hazmat.primitives.kdf.argon2 import Argon2id
except ImportError:  # gated: Argon2id derivation refuses at use below
    Argon2id = None  # type: ignore

from .. import native
from .stream import KEY_LEN, CryptoError

SALT_LEN = 16  # ref:types.rs SALT_LEN


class Params(enum.IntEnum):
    """Cost profiles (ref:keys/hashing.rs params tables)."""

    STANDARD = 0
    HARDENED = 1
    PARANOID = 2


# Argon2id (memory KiB, iterations, lanes) per profile — the reference's
# keys/hashing.rs ladder (standard ≈ interactive, paranoid ≈ sensitive)
_ARGON2 = {
    Params.STANDARD: (131_072, 8, 4),
    Params.HARDENED: (262_144, 8, 4),
    Params.PARANOID: (524_288, 8, 4),
}

# Balloon (space cost in 64-byte blocks, time cost) per profile
_BALLOON = {
    Params.STANDARD: (131_072, 2),
    Params.HARDENED: (262_144, 2),
    Params.PARANOID: (524_288, 2),
}

_DELTA = 3  # balloon dependency count (standard choice)


class HashingAlgorithm:
    """ref:types.rs `HashingAlgorithm` — (kind, params) pair."""

    ARGON2ID = "Argon2id"
    BALLOON_BLAKE3 = "BalloonBlake3"

    def __init__(self, kind: str, params: Params = Params.STANDARD):
        if kind not in (self.ARGON2ID, self.BALLOON_BLAKE3):
            raise CryptoError(f"unknown hashing algorithm {kind}")
        self.kind = kind
        self.params = Params(params)

    def to_wire(self) -> list:
        return [self.kind, int(self.params)]

    @classmethod
    def from_wire(cls, obj: list) -> "HashingAlgorithm":
        return cls(obj[0], Params(obj[1]))

    def hash_password(
        self, password: bytes, salt: bytes, *, _test_overrides: tuple | None = None
    ) -> bytes:
        if len(salt) != SALT_LEN:
            raise CryptoError(f"salt must be {SALT_LEN} bytes")
        if self.kind == self.ARGON2ID:
            if Argon2id is None:
                raise CryptoError(
                    "the `cryptography` package is required for Argon2id")
            memory, iterations, lanes = _test_overrides or _ARGON2[self.params]
            return Argon2id(
                salt=salt,
                length=KEY_LEN,
                iterations=iterations,
                lanes=lanes,
                memory_cost=memory,
            ).derive(password)
        space, time = _test_overrides or _BALLOON[self.params]
        return balloon_blake3(password, salt, space_cost=space, time_cost=time)


def generate_salt() -> bytes:
    return secrets.token_bytes(SALT_LEN)


def _blake3(data: bytes) -> bytes:
    digest = native.blake3_digest(data)
    if digest is None:  # pragma: no cover - native ext always builds here
        raise CryptoError("native blake3 unavailable")
    return digest


def balloon_blake3(
    password: bytes, salt: bytes, *, space_cost: int, time_cost: int
) -> bytes:
    """Balloon hashing (BCGS16) with BLAKE3 as H; sequential-memory-hard.

    Layout follows the paper's single-buffer variant: expand, then
    `time_cost` rounds of mixing each block with its predecessor and
    `_DELTA` pseudo-random other blocks derived from (counter, salt).
    """
    if space_cost < 1 or time_cost < 1:
        raise CryptoError("balloon params must be >= 1")
    cnt = 0

    def h(*parts: bytes) -> bytes:
        nonlocal cnt
        out = _blake3(struct.pack("<Q", cnt) + b"".join(parts))
        cnt += 1
        return out

    buf = [h(password, salt)]
    for m in range(1, space_cost):
        buf.append(h(buf[m - 1]))
    for t in range(time_cost):
        for m in range(space_cost):
            buf[m] = h(buf[(m - 1) % space_cost], buf[m])
            for i in range(_DELTA):
                idx_block = h(
                    struct.pack("<QQQ", t, m, i), salt
                )
                other = int.from_bytes(idx_block[:8], "little") % space_cost
                buf[m] = h(buf[m], buf[other])
    return buf[-1]
