"""Key manager — mounted keys + encrypted on-disk keystore.

Parity: ref:crates/crypto/src/keys/keymanager.rs — a per-library key
manager holding *mounted* (usable) keys in memory, backed by stored key
entries (key encrypted under the library's master password via a
keyslot-like record), plus the OS-keyring role (ref:keys/keyring) which
here is an encrypted JSON keystore file next to the library. Secrets
are bytearrays zeroized on unmount (best effort — the reference uses
the `zeroize` crate).
"""

from __future__ import annotations

import os
import secrets
import uuid
from dataclasses import dataclass
from typing import Any

import msgpack

from .hashing import HashingAlgorithm, generate_salt
from .stream import KEY_LEN, Algorithm, CryptoError
from .header import _aead_for


@dataclass
class StoredKey:
    """ref:keymanager.rs `StoredKey`."""

    uuid: str
    algorithm: Algorithm
    hashing_algorithm: HashingAlgorithm
    salt: bytes
    nonce: bytes
    encrypted_key: bytes
    memory_only: bool = False
    automount: bool = False

    def to_wire(self) -> dict[str, Any]:
        return {
            "uuid": self.uuid,
            "a": int(self.algorithm),
            "h": self.hashing_algorithm.to_wire(),
            "s": self.salt,
            "n": self.nonce,
            "k": self.encrypted_key,
            "auto": self.automount,
        }

    @classmethod
    def from_wire(cls, obj: dict[str, Any]) -> "StoredKey":
        return cls(
            uuid=obj["uuid"],
            algorithm=Algorithm(obj["a"]),
            hashing_algorithm=HashingAlgorithm.from_wire(obj["h"]),
            salt=obj["s"],
            nonce=obj["n"],
            encrypted_key=obj["k"],
            automount=obj.get("auto", False),
        )


class KeyManager:
    def __init__(
        self,
        keystore_path: str | None = None,
        *,
        algorithm: Algorithm = Algorithm.XCHACHA20_POLY1305,
        _test_overrides: tuple | None = None,
    ):
        self.path = keystore_path
        self.algorithm = algorithm
        self._overrides = _test_overrides
        self.stored: dict[str, StoredKey] = {}
        self._mounted: dict[str, bytearray] = {}
        self._master: bytearray | None = None
        if self.path and os.path.exists(self.path):
            with open(self.path, "rb") as f:
                for obj in msgpack.unpackb(f.read(), raw=False):
                    sk = StoredKey.from_wire(obj)
                    self.stored[sk.uuid] = sk

    # --- master password (unlocks the manager) -------------------------

    def set_master_password(self, password: bytes) -> None:
        self._master = bytearray(password)

    @property
    def unlocked(self) -> bool:
        return self._master is not None

    def _require_master(self) -> bytes:
        if self._master is None:
            raise CryptoError("key manager is locked")
        return bytes(self._master)

    # --- OS keyring (ref:keys/keyring/mod.rs:44-45) --------------------

    _KEYRING_SERVICE = "spacedrive-tpu"

    def remember_master(self, keyring, account: str = "master") -> None:
        """Persist the master password in the OS keyring so the next
        session unlocks without prompting (the reference's keyring
        usage). Call after set_master_password; raises when locked."""
        keyring.set(self._KEYRING_SERVICE, account, self._require_master())

    def unlock_from_keyring(self, keyring, account: str = "master") -> bool:
        """Unlock from a remembered master password; False when the
        keyring has no entry."""
        secret = keyring.get(self._KEYRING_SERVICE, account)
        if secret is None:
            return False
        self.set_master_password(secret)
        return True

    def forget_master(self, keyring, account: str = "master") -> bool:
        return keyring.delete(self._KEYRING_SERVICE, account)

    # --- key CRUD (ref:keymanager.rs add_to_keystore/mount/unmount) ----

    def add_key(
        self,
        key_material: bytes,
        *,
        hashing: HashingAlgorithm | None = None,
        memory_only: bool = False,
        automount: bool = False,
    ) -> str:
        hashing = hashing or HashingAlgorithm(HashingAlgorithm.ARGON2ID)
        salt = generate_salt()
        derived = hashing.hash_password(
            self._require_master(), salt, _test_overrides=self._overrides
        )
        nonce = secrets.token_bytes(self.algorithm.nonce_len)
        enc = _aead_for(self.algorithm, derived).encrypt(nonce, key_material, None)
        sk = StoredKey(
            uuid=str(uuid.uuid4()),
            algorithm=self.algorithm,
            hashing_algorithm=hashing,
            salt=salt,
            nonce=nonce,
            encrypted_key=enc,
            memory_only=memory_only,
            automount=automount,
        )
        self.stored[sk.uuid] = sk
        self._persist()
        return sk.uuid

    def mount(self, key_uuid: str) -> None:
        sk = self.stored.get(key_uuid)
        if sk is None:
            raise CryptoError(f"unknown key {key_uuid}")
        derived = sk.hashing_algorithm.hash_password(
            self._require_master(), sk.salt, _test_overrides=self._overrides
        )
        # constructed OUTSIDE the decrypt try: a crypto-unavailable
        # refusal (gated AEAD) must surface as itself, not be
        # misreported as a wrong password
        aead = _aead_for(sk.algorithm, derived)
        try:
            key = aead.decrypt(sk.nonce, sk.encrypted_key, None)
        except Exception as e:
            raise CryptoError("wrong master password for key") from e
        self._mounted[key_uuid] = bytearray(key)

    def automount(self) -> int:
        n = 0
        for sk in self.stored.values():
            if sk.automount and sk.uuid not in self._mounted:
                self.mount(sk.uuid)
                n += 1
        return n

    def get_key(self, key_uuid: str) -> bytes:
        key = self._mounted.get(key_uuid)
        if key is None:
            raise CryptoError(f"key {key_uuid} not mounted")
        return bytes(key)

    def unmount(self, key_uuid: str) -> None:
        key = self._mounted.pop(key_uuid, None)
        if key is not None:
            for i in range(len(key)):
                key[i] = 0

    def unmount_all(self) -> None:
        for key_uuid in list(self._mounted):
            self.unmount(key_uuid)

    def delete_key(self, key_uuid: str) -> None:
        self.unmount(key_uuid)
        self.stored.pop(key_uuid, None)
        self._persist()

    def mounted_uuids(self) -> list[str]:
        return list(self._mounted)

    def lock(self) -> None:
        """Unmount everything and forget the master password."""
        self.unmount_all()
        if self._master is not None:
            for i in range(len(self._master)):
                self._master[i] = 0
            self._master = None

    def _persist(self) -> None:
        if not self.path:
            return
        data = msgpack.packb(
            [sk.to_wire() for sk in self.stored.values() if not sk.memory_only],
            use_bin_type=True,
        )
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self.path)
