"""XChaCha20-Poly1305 — extended-nonce AEAD.

Parity: ref:crates/crypto/src/crypto/stream.rs:8-13 — the reference's
primary AEAD is XChaCha20-Poly1305 (24-byte nonce) from the `aead`
crate family. `cryptography` ships only the IETF 12-byte-nonce
ChaCha20Poly1305, so this module adds the missing HChaCha20 subkey
step (RFC draft-irtf-cfrg-xchacha-03): subkey = HChaCha20(key,
nonce[0:16]); then IETF ChaCha20-Poly1305 with nonce 0x00000000 ‖
nonce[16:24]. HChaCha20 runs once per message in pure Python (20
rounds over 16 words — microseconds); bulk crypto stays in OpenSSL.
Verified against the RFC test vector (tests/test_crypto.py).
"""

from __future__ import annotations

import struct

try:
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
except ImportError:  # gated: construction refuses below
    ChaCha20Poly1305 = None  # type: ignore

_MASK = 0xFFFFFFFF


def _rotl(v: int, c: int) -> int:
    return ((v << c) | (v >> (32 - c))) & _MASK


def _quarter(state: list[int], a: int, b: int, c: int, d: int) -> None:
    state[a] = (state[a] + state[b]) & _MASK
    state[d] = _rotl(state[d] ^ state[a], 16)
    state[c] = (state[c] + state[d]) & _MASK
    state[b] = _rotl(state[b] ^ state[c], 12)
    state[a] = (state[a] + state[b]) & _MASK
    state[d] = _rotl(state[d] ^ state[a], 8)
    state[c] = (state[c] + state[d]) & _MASK
    state[b] = _rotl(state[b] ^ state[c], 7)


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """HChaCha20(key, 16-byte nonce) -> 32-byte subkey."""
    if len(key) != 32 or len(nonce16) != 16:
        raise ValueError("hchacha20 needs 32-byte key + 16-byte nonce")
    state = [
        0x61707865, 0x3320646E, 0x79622D32, 0x6B206574,
        *struct.unpack("<8I", key),
        *struct.unpack("<4I", nonce16),
    ]
    for _ in range(10):  # 10 double rounds = 20 rounds
        _quarter(state, 0, 4, 8, 12)
        _quarter(state, 1, 5, 9, 13)
        _quarter(state, 2, 6, 10, 14)
        _quarter(state, 3, 7, 11, 15)
        _quarter(state, 0, 5, 10, 15)
        _quarter(state, 1, 6, 11, 12)
        _quarter(state, 2, 7, 8, 13)
        _quarter(state, 3, 4, 9, 14)
    return struct.pack("<8I", *(state[i] for i in (0, 1, 2, 3, 12, 13, 14, 15)))


class XChaCha20Poly1305:
    """Drop-in sibling of cryptography's AEAD classes, 24-byte nonce."""

    NONCE_LEN = 24

    def __init__(self, key: bytes):
        if ChaCha20Poly1305 is None:
            # CryptoError so keys/stream/header handlers see a clean
            # "crypto unavailable" instead of misreading the refusal
            # as a wrong password (lazy import: stream imports us)
            from .stream import CryptoError

            raise CryptoError(
                "the `cryptography` package is required for XChaCha20")
        if len(key) != 32:
            raise ValueError("key must be 32 bytes")
        self._key = key

    def _inner(self, nonce: bytes) -> tuple[ChaCha20Poly1305, bytes]:
        if len(nonce) != self.NONCE_LEN:
            raise ValueError("nonce must be 24 bytes")
        subkey = hchacha20(self._key, nonce[:16])
        return ChaCha20Poly1305(subkey), b"\x00\x00\x00\x00" + nonce[16:]

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.encrypt(n12, data, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.decrypt(n12, data, aad)
