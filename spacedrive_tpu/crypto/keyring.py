"""OS keyring — Secret Service integration via libsecret, with the
encrypted file keystore as the portable fallback.

Parity: ref:crates/crypto/src/keys/keyring/mod.rs:44-45 — the reference
stores library secrets in the OS keyring (Secret Service on Linux,
Keychain on macOS) through the `secret-service` crate. Here the same
desktop integration goes through libsecret's password API over ctypes
(libsecret speaks the Secret Service D-Bus protocol to whatever daemon
— gnome-keyring, KWallet — owns the session). Headless hosts without
libsecret/D-Bus keep the encrypted keystore file (crypto/keys.py), and
`default_keyring()` returns None so callers fall back explicitly.

The ctypes structs mirror libsecret's public ABI (SecretSchema with 32
inline attributes + reserved fields); the binding is exercised in tests
against a stub libsecret built from source, so the call contract is
pinned even on hosts without the real library.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging

logger = logging.getLogger(__name__)

_SECRET_SCHEMA_NONE = 0
_ATTR_STRING = 0
_COLLECTION_DEFAULT = None  # libsecret: NULL = default collection


class _SchemaAttribute(ctypes.Structure):
    _fields_ = [("name", ctypes.c_char_p), ("type", ctypes.c_int)]


class _SecretSchema(ctypes.Structure):
    _fields_ = [
        ("name", ctypes.c_char_p),
        ("flags", ctypes.c_int),
        ("attributes", _SchemaAttribute * 32),
        # libsecret reserves expansion space in the public struct
        ("reserved", ctypes.c_int),
        *[(f"reserved{i}", ctypes.c_void_p) for i in range(1, 8)],
    ]


class KeyringError(Exception):
    pass


class LibsecretKeyring:
    """Secret Service keyring through libsecret's sync password API.

    Secrets are keyed by (service, account) string attributes under the
    one spacedrive schema — the shape the reference's keyring entries
    use (Identifier{application, library_uuid, usage},
    ref:keyring/mod.rs)."""

    def __init__(self, lib_path: str | None = None):
        path = lib_path or ctypes.util.find_library("secret-1")
        if path is None:
            raise KeyringError("libsecret not available")
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            raise KeyringError(f"libsecret load failed: {e}") from e
        V, S = ctypes.c_void_p, ctypes.c_char_p
        lib.secret_password_store_sync.restype = ctypes.c_int
        lib.secret_password_lookup_sync.restype = V  # char* (freed below)
        lib.secret_password_clear_sync.restype = ctypes.c_int
        lib.secret_password_free.argtypes = [V]
        lib.secret_password_free.restype = None
        self._lib = lib

        self._schema = _SecretSchema()
        self._schema.name = b"com.spacedrive.tpu.Secret"
        self._schema.flags = _SECRET_SCHEMA_NONE
        self._schema.attributes[0] = _SchemaAttribute(b"service", _ATTR_STRING)
        self._schema.attributes[1] = _SchemaAttribute(b"account", _ATTR_STRING)
        self._schema.attributes[2] = _SchemaAttribute(None, 0)

    def set(self, service: str, account: str, secret: bytes) -> None:
        ok = self._lib.secret_password_store_sync(
            ctypes.byref(self._schema),
            _COLLECTION_DEFAULT,
            f"spacedrive {service}/{account}".encode(),
            secret.hex().encode(),  # hex: secrets may be binary
            None, None,
            b"service", service.encode(),
            b"account", account.encode(),
            ctypes.c_void_p(None),
        )
        if not ok:
            raise KeyringError("secret store failed")

    def get(self, service: str, account: str) -> bytes | None:
        raw = self._lib.secret_password_lookup_sync(
            ctypes.byref(self._schema), None, None,
            b"service", service.encode(),
            b"account", account.encode(),
            ctypes.c_void_p(None),
        )
        if not raw:
            return None
        try:
            return bytes.fromhex(ctypes.cast(raw, ctypes.c_char_p).value.decode())
        except ValueError as e:
            raise KeyringError(f"corrupt keyring entry: {e}") from e
        finally:
            self._lib.secret_password_free(raw)

    def delete(self, service: str, account: str) -> bool:
        return bool(self._lib.secret_password_clear_sync(
            ctypes.byref(self._schema), None, None,
            b"service", service.encode(),
            b"account", account.encode(),
            ctypes.c_void_p(None),
        ))


def default_keyring() -> LibsecretKeyring | None:
    """The OS keyring when the host has one; None on headless boxes
    (callers keep the encrypted file keystore)."""
    try:
        return LibsecretKeyring()
    except KeyringError:
        return None
