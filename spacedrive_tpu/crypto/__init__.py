"""Encryption stack.

Parity: ref:crates/crypto — stream AEAD (XChaCha20-Poly1305 +
AES-256-GCM, STREAM LE31 construction), Argon2id + Balloon-BLAKE3 key
hashing, encrypted-file header with keyslots/metadata/preview-media,
key manager with encrypted keystore; secure erase lives with the fs
jobs (spacedrive_tpu/object/fs/erase.py).
"""

from .hashing import HashingAlgorithm, Params, balloon_blake3, generate_salt
from .header import FileHeader, Keyslot, decrypt_file, encrypt_file
from .keys import KeyManager, StoredKey
from .stream import (
    BLOCK_LEN,
    KEY_LEN,
    Algorithm,
    CryptoError,
    StreamDecryption,
    StreamEncryption,
)
from .xchacha import XChaCha20Poly1305, hchacha20

__all__ = [
    "Algorithm",
    "BLOCK_LEN",
    "CryptoError",
    "FileHeader",
    "HashingAlgorithm",
    "KEY_LEN",
    "KeyManager",
    "Keyslot",
    "Params",
    "StoredKey",
    "StreamDecryption",
    "StreamEncryption",
    "XChaCha20Poly1305",
    "balloon_blake3",
    "decrypt_file",
    "encrypt_file",
    "generate_salt",
    "hchacha20",
]
