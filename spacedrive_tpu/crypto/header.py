"""Encrypted-file header — keyslots, metadata, preview media.

Parity: ref:crates/crypto/src/header/* — `FileHeader{version,
algorithm, nonce, keyslots[≤2], metadata, preview_media}`
(header/file.rs): each `Keyslot` stores (hashing algorithm, salt,
master-key nonce, encrypted master key) so either of two passwords can
unlock the file; optional `Metadata`/`PreviewMedia` objects are
encrypted under the same master key and authenticated as AAD-free
sections. The header bytes up to the section table are fed to the body
stream as AAD, so swapping headers between files fails decryption —
the same binding the reference gets by passing the header as AAD
(header/file.rs `to_writer`/`from_reader` + stream AAD).
"""

from __future__ import annotations

import io
import os
import secrets
from dataclasses import dataclass, field
from typing import Any, BinaryIO

import msgpack

from .hashing import SALT_LEN, HashingAlgorithm, generate_salt
from .stream import (
    KEY_LEN,
    Algorithm,
    CryptoError,
    StreamDecryption,
    StreamEncryption,
)
from .xchacha import XChaCha20Poly1305

MAGIC = b"sdcrypt\x00"  # 8 bytes (the reference uses a magic+version prefix)
HEADER_VERSION = 1
MAX_KEYSLOTS = 2  # ref:header/keyslot.rs


def _aead_for(algorithm: Algorithm, key: bytes):
    if algorithm is Algorithm.XCHACHA20_POLY1305:
        return XChaCha20Poly1305(key)
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    except ImportError:
        raise CryptoError(
            "the `cryptography` package is required for AES-256-GCM")
    return AESGCM(key)


@dataclass
class Keyslot:
    """ref:header/keyslot.rs `Keyslot`."""

    hashing_algorithm: HashingAlgorithm
    salt: bytes
    nonce: bytes
    encrypted_master_key: bytes  # 32 + 16 tag

    def to_wire(self) -> dict[str, Any]:
        return {
            "h": self.hashing_algorithm.to_wire(),
            "s": self.salt,
            "n": self.nonce,
            "k": self.encrypted_master_key,
        }

    @classmethod
    def from_wire(cls, obj: dict[str, Any]) -> "Keyslot":
        return cls(
            HashingAlgorithm.from_wire(obj["h"]), obj["s"], obj["n"], obj["k"]
        )


@dataclass
class FileHeader:
    algorithm: Algorithm
    nonce: bytes  # the body's STREAM base nonce
    keyslots: list[Keyslot] = field(default_factory=list)
    metadata: bytes | None = None  # encrypted msgpack
    metadata_nonce: bytes | None = None
    preview_media: bytes | None = None  # encrypted bytes (e.g. thumbnail)
    preview_media_nonce: bytes | None = None
    version: int = HEADER_VERSION

    # --- keyslots ------------------------------------------------------

    def add_keyslot(
        self,
        master_key: bytes,
        password: bytes,
        hashing_algorithm: HashingAlgorithm,
        *,
        _test_overrides: tuple | None = None,
    ) -> None:
        """ref:header/file.rs `add_keyslot`."""
        if len(self.keyslots) >= MAX_KEYSLOTS:
            raise CryptoError(f"at most {MAX_KEYSLOTS} keyslots")
        salt = generate_salt()
        derived = hashing_algorithm.hash_password(
            password, salt, _test_overrides=_test_overrides
        )
        nonce = secrets.token_bytes(self.algorithm.nonce_len)
        enc = _aead_for(self.algorithm, derived).encrypt(nonce, master_key, None)
        self.keyslots.append(Keyslot(hashing_algorithm, salt, nonce, enc))

    def decrypt_master_key(
        self, password: bytes, *, _test_overrides: tuple | None = None
    ) -> bytes:
        """Try every keyslot (ref:header/file.rs `decrypt_master_key`)."""
        for slot in self.keyslots:
            derived = slot.hashing_algorithm.hash_password(
                password, slot.salt, _test_overrides=_test_overrides
            )
            try:
                return _aead_for(self.algorithm, derived).decrypt(
                    slot.nonce, slot.encrypted_master_key, None
                )
            except Exception:
                continue
        raise CryptoError("no keyslot matched the provided password")

    # --- optional sections (ref:header/{metadata,preview_media}.rs) ----

    def set_metadata(self, master_key: bytes, obj: Any) -> None:
        nonce = secrets.token_bytes(self.algorithm.nonce_len)
        self.metadata = _aead_for(self.algorithm, master_key).encrypt(
            nonce, msgpack.packb(obj, use_bin_type=True), None
        )
        self.metadata_nonce = nonce

    def get_metadata(self, master_key: bytes) -> Any:
        if self.metadata is None:
            return None
        return msgpack.unpackb(
            _aead_for(self.algorithm, master_key).decrypt(
                self.metadata_nonce, self.metadata, None
            ),
            raw=False,
        )

    def set_preview_media(self, master_key: bytes, media: bytes) -> None:
        nonce = secrets.token_bytes(self.algorithm.nonce_len)
        self.preview_media = _aead_for(self.algorithm, master_key).encrypt(
            nonce, media, None
        )
        self.preview_media_nonce = nonce

    def get_preview_media(self, master_key: bytes) -> bytes | None:
        if self.preview_media is None:
            return None
        return _aead_for(self.algorithm, master_key).decrypt(
            self.preview_media_nonce, self.preview_media, None
        )

    # --- wire ----------------------------------------------------------

    def to_bytes(self) -> bytes:
        body = msgpack.packb(
            {
                "v": self.version,
                "a": int(self.algorithm),
                "n": self.nonce,
                "ks": [k.to_wire() for k in self.keyslots],
                "md": self.metadata,
                "mdn": self.metadata_nonce,
                "pv": self.preview_media,
                "pvn": self.preview_media_nonce,
            },
            use_bin_type=True,
        )
        return MAGIC + len(body).to_bytes(4, "big") + body

    @classmethod
    def from_reader(cls, reader: BinaryIO) -> tuple["FileHeader", bytes]:
        """Returns (header, raw_bytes) — raw bytes double as body AAD."""
        magic = reader.read(len(MAGIC))
        if magic != MAGIC:
            raise CryptoError("not an encrypted file (bad magic)")
        ln = int.from_bytes(reader.read(4), "big")
        if ln > 16 * 1024 * 1024:
            raise CryptoError("oversized header")
        body = reader.read(ln)
        if len(body) != ln:
            raise CryptoError("truncated header")
        obj = msgpack.unpackb(body, raw=False)
        header = cls(
            algorithm=Algorithm(obj["a"]),
            nonce=obj["n"],
            keyslots=[Keyslot.from_wire(k) for k in obj["ks"]],
            metadata=obj.get("md"),
            metadata_nonce=obj.get("mdn"),
            preview_media=obj.get("pv"),
            preview_media_nonce=obj.get("pvn"),
            version=obj.get("v", HEADER_VERSION),
        )
        return header, MAGIC + ln.to_bytes(4, "big") + body


# --- whole-file convenience (ref:crypto examples + fs jobs) --------------


def encrypt_file(
    src: str,
    dst: str,
    password: bytes,
    *,
    algorithm: Algorithm = Algorithm.XCHACHA20_POLY1305,
    hashing: HashingAlgorithm | None = None,
    metadata: Any = None,
    preview_media: bytes | None = None,
    _test_overrides: tuple | None = None,
) -> None:
    hashing = hashing or HashingAlgorithm(HashingAlgorithm.ARGON2ID)
    master_key = secrets.token_bytes(KEY_LEN)
    header = FileHeader(algorithm=algorithm, nonce=algorithm.generate_nonce())
    header.add_keyslot(master_key, password, hashing, _test_overrides=_test_overrides)
    if metadata is not None:
        header.set_metadata(master_key, metadata)
    if preview_media is not None:
        header.set_preview_media(master_key, preview_media)
    raw = header.to_bytes()
    with open(src, "rb") as fin, open(dst, "wb") as fout:
        fout.write(raw)
        StreamEncryption(master_key, header.nonce, algorithm).encrypt_streams(
            fin, fout, aad=raw
        )


def decrypt_file(
    src: str, dst: str, password: bytes, *, _test_overrides: tuple | None = None
) -> Any:
    """Returns the decrypted metadata (if any)."""
    with open(src, "rb") as fin:
        header, raw = FileHeader.from_reader(fin)
        master_key = header.decrypt_master_key(
            password, _test_overrides=_test_overrides
        )
        with open(dst, "wb") as fout:
            StreamDecryption(
                master_key, header.nonce, header.algorithm
            ).decrypt_streams(fin, fout, aad=raw)
    return header.get_metadata(master_key)
