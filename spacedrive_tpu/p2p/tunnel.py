"""Tunnel — library-instance authentication on top of a node stream.

Parity: ref:crates/p2p-tunnel/src/tunnel.rs — wraps an established
(already node-authenticated, already encrypted) stream with a second
handshake proving both ends belong to the same *library*: each side
signs a fresh challenge with its node identity and sends the library
instance it claims; the peer checks the claimed instance exists in its
own library DB. The reference's deeper per-instance re-encryption is
WIP/commented out of its workspace (Cargo.toml:7-8); we match the
shipped surface: authenticate, then pass reads/writes through.
"""

from __future__ import annotations

import os
import uuid
from typing import Any

from .identity import Identity, RemoteIdentity
from .wire import Reader, Writer


class TunnelError(Exception):
    pass


class Tunnel:
    """Authenticated pass-through wrapper (ref:tunnel.rs `Tunnel`)."""

    def __init__(self, stream: Any, remote_instance: uuid.UUID):
        self._stream = stream
        self.remote_instance = remote_instance

    async def write(self, data: bytes) -> None:
        await self._stream.write(data)

    async def read_exact(self, n: int) -> bytes:
        return await self._stream.read_exact(n)

    async def close(self) -> None:
        await self._stream.close()

    @property
    def remote_identity(self) -> RemoteIdentity:
        return self._stream.remote_identity

    @classmethod
    async def initiator(
        cls, stream: Any, identity: Identity, library_id: uuid.UUID,
        instance_uuid: uuid.UUID, known_instances: set[uuid.UUID],
    ) -> "Tunnel":
        w, r = Writer(stream), Reader(stream)
        challenge = os.urandom(32)
        w.uuid(library_id).uuid(instance_uuid).raw(challenge)
        w.raw(identity.sign(challenge + library_id.bytes + instance_uuid.bytes))
        await w.flush()
        remote_instance = await r.uuid()
        their_sig = await r.exact(64)
        if not stream.remote_identity.verify(
            their_sig, challenge + library_id.bytes + remote_instance.bytes
        ):
            raise TunnelError("responder signature invalid")
        if remote_instance not in known_instances:
            raise TunnelError(f"unknown remote instance {remote_instance}")
        return cls(stream, remote_instance)

    @classmethod
    async def responder(
        cls, stream: Any, identity: Identity, library_id: uuid.UUID,
        instance_uuid: uuid.UUID, known_instances: set[uuid.UUID],
    ) -> "Tunnel":
        w, r = Writer(stream), Reader(stream)
        claimed_library = await r.uuid()
        remote_instance = await r.uuid()
        challenge = await r.exact(32)
        their_sig = await r.exact(64)
        if claimed_library != library_id:
            raise TunnelError("library mismatch")
        if not stream.remote_identity.verify(
            their_sig, challenge + library_id.bytes + remote_instance.bytes
        ):
            raise TunnelError("initiator signature invalid")
        if remote_instance not in known_instances:
            raise TunnelError(f"unknown remote instance {remote_instance}")
        w.uuid(instance_uuid)
        w.raw(identity.sign(challenge + library_id.bytes + instance_uuid.bytes))
        await w.flush()
        return cls(stream, remote_instance)
