"""P2P mesh: identity, transport, discovery, protocol, transfer.

Parity: ref:crates/p2p2 (runtime), crates/p2p-block (Spaceblock),
crates/p2p-proto (wire helpers), core/src/p2p (protocol + operations).
The reference rides QUIC on a patched libp2p; here streams are
length-framed asyncio TCP with an ed25519-authenticated X25519 +
ChaCha20-Poly1305 channel (same trust model: identity keypairs, no CA).
"""

from .identity import Identity, RemoteIdentity

__all__ = ["Identity", "RemoteIdentity"]
