"""P2P mesh: identity, transport, discovery, protocol, transfer.

Parity: ref:crates/p2p2 (runtime), crates/p2p-block (Spaceblock),
crates/p2p-proto (wire helpers), core/src/p2p (protocol + operations).
The reference rides QUIC on a patched libp2p; here streams are
length-framed asyncio TCP with an ed25519-authenticated X25519 +
ChaCha20-Poly1305 channel (same trust model: identity keypairs, no CA).
"""

from .block import BlockSize, Range, SpaceblockRequest, SpaceblockRequests, Transfer
from .identity import Identity, RemoteIdentity
from .p2p import P2P, Peer
from .protocol import FileRequest, Header, HeaderType

__all__ = [
    "BlockSize",
    "FileRequest",
    "Header",
    "HeaderType",
    "Identity",
    "P2P",
    "Peer",
    "Range",
    "RemoteIdentity",
    "SpaceblockRequest",
    "SpaceblockRequests",
    "Transfer",
]
