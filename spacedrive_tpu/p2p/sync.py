"""Device-to-device sync exchange over P2P streams.

Parity: ref:core/src/p2p/sync/mod.rs:22-70 — after any local
`write_ops`, the originator opens a stream per library peer with
`Header::Sync(library_id)` as a *new-ops alert*; the responder then
notifies its ingest actor, whose `request_ops` pulls with its
per-instance watermarks (`Vec<(instance, NTP64)>`) and receives an op
batch + has_more flag (the reference's `GetOpsArgs`/`Operations`
messages, msgpack-encoded like its rmp payloads).
"""

from __future__ import annotations

import uuid
from typing import Any

from ..sync.crdt import CRDTOperation
from ..sync.hlc import NTP64
from ..sync.manager import SyncManager
from ..telemetry import trace as _trace
from .identity import RemoteIdentity
from .protocol import Header, HeaderType
from .wire import Reader, Writer


async def alert_new_ops(p2p: Any, identity: RemoteIdentity, library_id: uuid.UUID) -> None:
    """Originator half (ref:p2p/sync/mod.rs originator): fire-and-forget
    notification that this library has new ops."""
    stream = await p2p.new_stream(identity)
    try:
        await Header(
            HeaderType.SYNC, library_id=library_id,
            trace=_trace.wire_current(),
        ).write(stream)
        await Reader(stream).u8()  # 1-byte ack so the write isn't racing close
    finally:
        await stream.close()


async def request_ops_from_peer(
    p2p: Any,
    identity: RemoteIdentity,
    library_id: uuid.UUID,
    timestamps: list[tuple[uuid.UUID, NTP64]],
    count: int,
) -> tuple[list[CRDTOperation], bool]:
    """Responder's pull (the ingest actor's `request_ops` transport):
    send watermarks, receive one op page + has_more."""
    stream = await p2p.new_stream(identity)
    try:
        await Header(
            HeaderType.SYNC_REQUEST, library_id=library_id,
            trace=_trace.wire_current(),
        ).write(stream)
        w = Writer(stream)
        w.msgpack(
            {
                "clocks": [[inst.bytes, int(ts)] for inst, ts in timestamps],
                "count": count,
            }
        )
        await w.flush()
        resp = await Reader(stream).msgpack()
        ops = [CRDTOperation.unpack(raw) for raw in resp["ops"]]
        return ops, bool(resp["has_more"])
    finally:
        await stream.close()


async def respond_sync_request(stream: Any, sync: SyncManager) -> None:
    """Server half of the pull (ref:p2p/sync/mod.rs responder)."""
    req = await Reader(stream).msgpack()
    clocks = [
        (uuid.UUID(bytes=inst), NTP64(ts)) for inst, ts in req.get("clocks", [])
    ]
    count = int(req.get("count", 1000))
    ops = sync.get_ops(count=count, clocks=clocks)
    w = Writer(stream)
    w.msgpack({"ops": [op.pack() for op in ops], "has_more": len(ops) == count})
    await w.flush()
