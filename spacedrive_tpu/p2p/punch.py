"""NAT hole punching — DCUtR-style UDP simultaneous open via the relay.

Parity: the reference punches through NATs for direct WAN paths using
libp2p DCUtR over its relayed connection, falling back to the relay
when punching fails (ref:crates/p2p2/src/quic/transport.rs:212,344
`open_stream_with_addrs` on a patched libp2p). Same shape here:

1. **observe** — each peer sends a datagram to the relay's UDP port
   from the SAME socket it will punch with; the relay echoes the
   source address it saw (STUN's binding-request role). That address
   is the peer's NAT mapping.
2. **exchange** — observed addresses cross through the peers'
   authenticated relay control channels (`{"cmd":"punch"}` routed to
   the target, `punch_ack` routed back). Addresses are only ever
   disclosed to registered, challenge-authenticated identities.
3. **simultaneous open** — both sides spray small probes at each
   other's observed address. Outbound probes open the cone-NAT
   mapping; the first probe/probe-ack that lands proves the path.
4. **secure channel** — the winner runs the ordinary Noise XX
   handshake (`transport.py`) over a reliable UDP stream
   (`udpstream.py`). Identity binding and channel security are
   exactly the TCP path's; a relay that lies about addresses can only
   prevent the direct path, never impersonate (docs/security.md).

Symmetric NATs allocate a different mapping per destination, so the
observed (relay-facing) address is useless to the peer and the probes
never land: punching times out and the caller falls back to the
relayed TCP pipe. The test suite simulates cone and symmetric NATs
with real translating sockets (tests/test_punch.py).
"""

from __future__ import annotations

import asyncio
import json
import secrets

from .udp import UdpEndpoint

OBSERVE_MAGIC = b"SDOB"
PROBE = b"SDPU"
PROBE_ACK = b"SDPA"
PUNCH_TIMEOUT = 3.0
PROBE_INTERVAL = 0.1


class PunchError(ConnectionError):
    pass


async def observe(ep: UdpEndpoint, relay_udp: tuple[str, int],
                  timeout: float = 2.0) -> tuple[tuple[str, int], str]:
    """Learn this socket's public (NAT-mapped) address from the relay's
    UDP echo; returns (address, token). The token names this relay-
    witnessed observation in punch messages — the relay only routes
    addresses it saw itself, so probes cannot be pointed at third
    parties. Retries a few times — a single UDP loss must not kill the
    whole punch attempt."""
    token = secrets.token_hex(8)
    fut: asyncio.Future = asyncio.get_running_loop().create_future()

    def on_dgram(data: bytes, addr: tuple[str, int]) -> None:
        if not data.startswith(OBSERVE_MAGIC):
            return
        try:
            msg = json.loads(data[len(OBSERVE_MAGIC):])
        except ValueError:
            return
        if msg.get("token") == token and not fut.done():
            fut.set_result((msg["addr"][0], int(msg["addr"][1])))

    ep.set_receiver(on_dgram)
    try:
        request = OBSERVE_MAGIC + json.dumps({"token": token}).encode()
        for _ in range(4):
            ep.sendto(request, relay_udp)
            try:
                addr = await asyncio.wait_for(
                    asyncio.shield(fut), timeout / 4
                )
                return addr, token
            except asyncio.TimeoutError:
                continue
        raise PunchError("relay UDP observe timed out")
    finally:
        ep.set_receiver(None)


def observe_reply(token: str, addr: tuple[str, int]) -> bytes:
    """Relay side: the datagram answering an observe request."""
    return OBSERVE_MAGIC + json.dumps(
        {"token": token, "addr": [addr[0], addr[1]]}
    ).encode()


async def simultaneous_open(ep: UdpEndpoint, peer: tuple[str, int],
                            timeout: float = PUNCH_TIMEOUT) -> None:
    """Spray probes at the peer's observed address until traffic flows
    both ways (or raise). Keeps answering probes for a short grace
    period so the slower side also converges."""
    peer = (peer[0], int(peer[1]))
    opened: asyncio.Future = asyncio.get_running_loop().create_future()
    got_ack = False

    def on_dgram(data: bytes, addr: tuple[str, int]) -> None:
        nonlocal got_ack
        if tuple(addr) != peer:
            return
        if data.startswith(PROBE):
            # their probe reached us: our mapping is open their way —
            # ack it so THEY learn the path works
            ep.sendto(PROBE_ACK, peer)
            if not opened.done():
                opened.set_result(None)
        elif data.startswith(PROBE_ACK):
            got_ack = True
            if not opened.done():
                opened.set_result(None)

    ep.set_receiver(on_dgram)
    try:
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            ep.sendto(PROBE, peer)
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise PunchError(f"hole punch to {peer} timed out")
            try:
                await asyncio.wait_for(
                    asyncio.shield(opened), min(PROBE_INTERVAL, remaining)
                )
                break
            except asyncio.TimeoutError:
                continue
        # linger briefly: keep acking probes until the peer has seen
        # evidence too (it stops sending once its future resolves)
        linger = asyncio.get_running_loop().time() + 0.5
        while not got_ack and asyncio.get_running_loop().time() < linger:
            ep.sendto(PROBE, peer)
            await asyncio.sleep(PROBE_INTERVAL / 2)
    finally:
        ep.set_receiver(None)
