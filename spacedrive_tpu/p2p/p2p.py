"""P2P runtime — peer registry, metadata, events, stream dispatch.

Parity: ref:crates/p2p2/src/{p2p.rs,peer.rs,hooks.rs} — `P2P::new(app
name, identity)` owns a peer map keyed by `RemoteIdentity`, a mutable
self-metadata map advertised to the LAN, discovery/connection hooks and
an event stream (`P2P::events`), and dispatches every inbound stream to
the application handler (p2p.rs:23-44). Discovery backends (mdns) and
the listener register themselves onto this object.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable

from ..utils.events import EventBus
from .identity import Identity, RemoteIdentity
from . import transport
from .transport import EncryptedStream, Listener


@dataclass
class Peer:
    """ref:p2p2 `Peer` — identity + discovered metadata/addresses +
    connection state."""

    identity: RemoteIdentity
    metadata: dict[str, str] = field(default_factory=dict)
    addrs: set[tuple[str, int]] = field(default_factory=set)
    discovered_by: set[str] = field(default_factory=set)
    last_seen: float = 0.0
    active_connections: int = 0
    relayed: bool = False  # reachable through the WAN relay (p2p/relay.py)

    @property
    def is_connected(self) -> bool:
        return self.active_connections > 0

    @property
    def is_discovered(self) -> bool:
        return bool(self.addrs) or self.relayed


StreamHandler = Callable[[EncryptedStream], Awaitable[None]]


class P2P:
    """One per node (ref:p2p.rs:23 `P2P`)."""

    def __init__(self, app_name: str, identity: Identity | None = None):
        self.app_name = app_name
        self.identity = identity or Identity()
        self.remote_identity = self.identity.to_remote_identity()
        self.metadata: dict[str, str] = {}
        self.peers: dict[RemoteIdentity, Peer] = {}
        self.events = EventBus()
        self.listener: Listener | None = None
        self._handler: StreamHandler | None = None
        self._discovery: list[Any] = []
        # relayed dialing fallback, set by p2p/relay.py RelayClient
        # (signature: (identity, *, timeout) -> EncryptedStream)
        self.relay_dial: Callable[..., Awaitable[EncryptedStream]] | None = None

    # --- listener ------------------------------------------------------

    async def listen(self, port: int = 0, host: str = "0.0.0.0") -> int:
        """Bind the accept socket; inbound streams go to the registered
        handler (ref:quic/transport.rs listener task)."""
        self.listener = await transport.listen(
            self.identity, self._on_stream, host=host, port=port
        )
        return self.listener.port

    def set_stream_handler(self, handler: StreamHandler) -> None:
        self._handler = handler

    async def _on_stream(self, stream: EncryptedStream) -> None:
        peer = self.touch_peer(stream.remote_identity)
        peer.active_connections += 1
        try:
            # inside the try: a raising event subscriber must not leave
            # active_connections inflated forever (sdlint SD016) — and
            # the Connected/Disconnected pairing survives it
            self.events.emit(("PeerConnected", stream.remote_identity))
            if self._handler is not None:
                await self._handler(stream)
        finally:
            peer.active_connections -= 1
            self.events.emit(("PeerDisconnected", stream.remote_identity))

    # --- registry ------------------------------------------------------

    def touch_peer(self, identity: RemoteIdentity) -> Peer:
        peer = self.peers.get(identity)
        if peer is None:
            peer = Peer(identity=identity)
            self.peers[identity] = peer
        peer.last_seen = time.monotonic()
        return peer

    def discovered(
        self,
        source: str,
        identity: RemoteIdentity,
        addrs: set[tuple[str, int]],
        metadata: dict[str, str],
    ) -> None:
        """A discovery backend saw a peer (ref:hooks.rs discovery hook)."""
        if identity == self.remote_identity:
            return
        peer = self.touch_peer(identity)
        fresh = not peer.is_discovered
        changed = any(peer.metadata.get(k) != v for k, v in metadata.items())
        peer.addrs |= addrs
        peer.metadata.update(metadata)
        peer.discovered_by.add(source)
        if fresh:
            self.events.emit(("PeerDiscovered", identity))
        elif changed:
            # e.g. the peer joined a new library since its last beacon
            self.events.emit(("PeerMetadataChanged", identity))

    def expired(self, source: str, identity: RemoteIdentity) -> None:
        peer = self.peers.get(identity)
        if peer is None:
            return
        peer.discovered_by.discard(source)
        if not peer.discovered_by:
            peer.addrs.clear()
            self.events.emit(("PeerExpired", identity))

    def discovered_peers(self) -> list[Peer]:
        return [p for p in self.peers.values() if p.is_discovered]

    # --- outbound ------------------------------------------------------

    async def new_stream(
        self, identity: RemoteIdentity, timeout: float = 10.0
    ) -> EncryptedStream:
        """Open a fresh authenticated unicast stream to a discovered
        peer: direct LAN addresses first, then the WAN relay fallback
        (ref:p2p2 `Peer::new_stream`; relayed parity with
        quic/transport.rs:212,344)."""
        from ..utils import faults as _faults

        if _faults.hit("p2p.connect") is not None:
            raise ConnectionResetError(
                f"injected connection reset dialing {identity}"
            )
        peer = self.peers.get(identity)
        if peer is None or not peer.is_discovered:
            raise ConnectionError(f"peer {identity} not discovered")

        def adopt(stream: EncryptedStream) -> EncryptedStream:
            peer.active_connections += 1
            orig_close = stream.close

            async def close(_orig=orig_close, _peer=peer):
                _peer.active_connections -= 1
                await _orig()

            stream.close = close  # type: ignore[method-assign]
            return stream

        last_err: Exception | None = None
        for addr in sorted(peer.addrs):
            try:
                return adopt(await transport.connect(
                    addr, self.identity, expect=identity, timeout=timeout
                ))
            except (OSError, transport.HandshakeError, asyncio.TimeoutError) as e:
                last_err = e
        if peer.relayed and self.relay_dial is not None:
            try:
                return adopt(await self.relay_dial(identity, timeout=timeout))
            except (OSError, ConnectionError, transport.HandshakeError,
                    asyncio.TimeoutError) as e:
                last_err = e
        raise ConnectionError(f"all routes failed for {identity}: {last_err}")

    # --- lifecycle -----------------------------------------------------

    def register_discovery(self, backend: Any) -> None:
        self._discovery.append(backend)

    async def shutdown(self) -> None:
        for d in self._discovery:
            await d.shutdown()
        self._discovery.clear()
        if self.listener is not None:
            await self.listener.close()
            self.listener = None
