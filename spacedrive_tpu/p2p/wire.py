"""Async wire encode/decode helpers.

Parity: ref:crates/p2p-proto/src/lib.rs — tiny primitives (uuid, buf,
string) layered on an async stream, plus msgpack frames for structured
payloads (the reference's rmp-serde). All integers big-endian like the
reference's `AsyncWriteExt` usage.
"""

from __future__ import annotations

import struct
import uuid
from typing import Any

import msgpack

MAX_FRAME = 64 * 1024 * 1024  # defensive bound on one framed payload


class Writer:
    """Buffers little writes; flush once per logical message."""

    def __init__(self, stream: Any):
        self._stream = stream
        self._buf = bytearray()

    def u8(self, v: int) -> "Writer":
        self._buf.append(v)
        return self

    def u32(self, v: int) -> "Writer":
        self._buf += struct.pack(">I", v)
        return self

    def u64(self, v: int) -> "Writer":
        self._buf += struct.pack(">Q", v)
        return self

    def uuid(self, v: uuid.UUID) -> "Writer":
        self._buf += v.bytes
        return self

    def string(self, s: str) -> "Writer":
        raw = s.encode()
        return self.u32(len(raw)).raw(raw)

    def buf(self, b: bytes) -> "Writer":
        return self.u32(len(b)).raw(b)

    def raw(self, b: bytes) -> "Writer":
        self._buf += b
        return self

    def msgpack(self, obj: Any) -> "Writer":
        return self.buf(msgpack.packb(obj, use_bin_type=True))

    async def flush(self) -> None:
        await self._stream.write(bytes(self._buf))
        self._buf.clear()


class Reader:
    def __init__(self, stream: Any):
        self._stream = stream

    async def exact(self, n: int) -> bytes:
        return await self._stream.read_exact(n)

    async def u8(self) -> int:
        return (await self.exact(1))[0]

    async def u32(self) -> int:
        return struct.unpack(">I", await self.exact(4))[0]

    async def u64(self) -> int:
        return struct.unpack(">Q", await self.exact(8))[0]

    async def uuid(self) -> uuid.UUID:
        return uuid.UUID(bytes=await self.exact(16))

    async def string(self) -> str:
        return (await self.buf()).decode()

    async def buf(self) -> bytes:
        n = await self.u32()
        if n > MAX_FRAME:
            raise ValueError(f"frame too large: {n}")
        return await self.exact(n)

    async def msgpack(self) -> Any:
        return msgpack.unpackb(await self.buf(), raw=False)
