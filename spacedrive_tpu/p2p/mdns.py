"""LAN discovery — periodic UDP service beacons.

Parity: ref:crates/p2p2/src/mdns.rs — the reference registers a
`_sd._udp.local.` mDNS service via `mdns_sd::ServiceDaemon` whose TXT
records carry the peer metadata, and maps add/remove events into the
P2P registry (mdns.rs:6-53, service expiry included). Python has no
baked-in mDNS stack, so this speaks the same *shape* over a simpler
wire: a JSON beacon datagram `{app, identity, port, metadata}`
multicast every `interval` seconds, with peer expiry after
`expiry` seconds of silence. `beacon_addrs` can be overridden with
unicast addresses (tests use loopback pairs; WAN meshes can seed
static peers the same way).
"""

from __future__ import annotations

import asyncio
import json
import socket
import time
from typing import Any

from .identity import RemoteIdentity
from .p2p import P2P

MULTICAST_GROUP = "239.255.41.41"
MULTICAST_PORT = 41841
SOURCE = "mdns"


class MdnsDiscovery:
    def __init__(
        self,
        p2p: P2P,
        service_port: int,
        *,
        bind_port: int = MULTICAST_PORT,
        beacon_addrs: list[tuple[str, int]] | None = None,
        interval: float = 1.0,
        expiry: float = 5.0,
    ):
        self.p2p = p2p
        self.service_port = service_port
        self.bind_port = bind_port
        self.beacon_addrs = beacon_addrs or [(MULTICAST_GROUP, MULTICAST_PORT)]
        self.interval = interval
        self.expiry = expiry
        self._sock: socket.socket | None = None
        self._tasks: list[asyncio.Task] = []
        self._seen: dict[RemoteIdentity, float] = {}
        self._stopped = False

    async def start(self) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if hasattr(socket, "SO_REUSEPORT"):
            try:
                sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            except OSError:
                pass
        sock.bind(("0.0.0.0", self.bind_port))
        self.bind_port = sock.getsockname()[1]
        try:  # join the multicast group when the env allows it
            mreq = socket.inet_aton(MULTICAST_GROUP) + socket.inet_aton("0.0.0.0")
            sock.setsockopt(socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP, mreq)
            sock.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_LOOP, 1)
        except OSError:
            pass
        sock.setblocking(False)
        self._sock = sock
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._beacon_loop(), name="mdns-beacon"),
            loop.create_task(self._recv_loop(), name="mdns-recv"),
            loop.create_task(self._expiry_loop(), name="mdns-expiry"),
        ]
        self.p2p.register_discovery(self)

    def _payload(self) -> bytes:
        return json.dumps(
            {
                "app": self.p2p.app_name,
                "identity": str(self.p2p.remote_identity),
                "port": self.service_port,
                "metadata": self.p2p.metadata,
            }
        ).encode()

    async def _beacon_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._stopped:
            payload = self._payload()
            for addr in self.beacon_addrs:
                try:
                    await loop.sock_sendto(self._sock, payload, addr)
                except OSError:
                    pass
            await asyncio.sleep(self.interval)

    async def _recv_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._stopped:
            try:
                data, addr = await loop.sock_recvfrom(self._sock, 65535)
                msg = json.loads(data)
                if msg.get("app") != self.p2p.app_name:
                    continue
                identity = RemoteIdentity.from_str(msg["identity"])
                if identity == self.p2p.remote_identity:
                    continue
                self._seen[identity] = time.monotonic()
                self.p2p.discovered(
                    SOURCE,
                    identity,
                    {(addr[0], int(msg["port"]))},
                    dict(msg.get("metadata", {})),
                )
            except (json.JSONDecodeError, KeyError, ValueError):
                continue
            except OSError:
                return

    async def _expiry_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.expiry / 2)
            cutoff = time.monotonic() - self.expiry
            for identity, seen in list(self._seen.items()):
                if seen < cutoff:
                    del self._seen[identity]
                    self.p2p.expired(SOURCE, identity)

    async def shutdown(self) -> None:
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        if self._sock is not None:
            self._sock.close()
