"""Thin asyncio UDP endpoint with an injectable receive path.

The hole-punching stack (`punch.py`, `udpstream.py`) talks to this
interface instead of raw sockets so the test suite can interpose
simulated NATs (address/port translation + inbound filtering) with real
sockets underneath — the same seam libp2p gets from its transport
abstraction (ref:crates/p2p2/src/quic/transport.rs behind libp2p's
`Transport` trait).
"""

from __future__ import annotations

import asyncio
from typing import Callable

Receiver = Callable[[bytes, tuple[str, int]], None]


class UdpEndpoint:
    """One bound UDP socket. `receiver` gets every datagram; `sendto`
    sends from the bound port (so NAT mappings stay stable across
    relay-observe and peer traffic — the whole point of punching)."""

    def __init__(self) -> None:
        self._transport: asyncio.DatagramTransport | None = None
        self._receiver: Receiver | None = None
        self.local_addr: tuple[str, int] | None = None

    async def bind(self, host: str = "0.0.0.0", port: int = 0) -> tuple[str, int]:
        loop = asyncio.get_running_loop()
        outer = self

        class _Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data: bytes, addr: tuple[str, int]):
                if outer._receiver is not None:
                    outer._receiver(data, addr[:2])

        self._transport, _ = await loop.create_datagram_endpoint(
            _Proto, local_addr=(host, port)
        )
        # the default ~208 KiB buffers hold <200 MTU-sized datagrams —
        # one paced burst from a large congestion window; ask for 4 MiB
        # (the kernel clamps to {r,w}mem_max, so this is best-effort)
        sock = self._transport.get_extra_info("socket")
        if sock is not None:
            import socket as _socket

            for opt in (_socket.SO_RCVBUF, _socket.SO_SNDBUF):
                try:
                    sock.setsockopt(_socket.SOL_SOCKET, opt, 4 << 20)
                except OSError:
                    pass
        self.local_addr = self._transport.get_extra_info("sockname")[:2]
        return self.local_addr

    def set_receiver(self, receiver: Receiver | None) -> None:
        self._receiver = receiver

    def sendto(self, data: bytes, addr: tuple[str, int]) -> None:
        if self._transport is not None:
            self._transport.sendto(data, tuple(addr))

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()
            self._transport = None
