"""Application wire protocol — stream headers.

Parity: ref:core/src/p2p/protocol.rs:18-60 — every unicast stream opens
with a one-byte `Header` discriminant: Ping, Spacedrop(SpaceblockRequests),
Sync(library_id), File{library_id, file_path_id, range}, Http. We add
SyncRequest (the pull half the reference routes through the same Sync
stream) and Rspc (remote API, ref:core/src/p2p/operations/rspc.rs).
Round-trip unit tests mirror protocol.rs's own `#[test]`s (§4).
"""

from __future__ import annotations

import enum
import uuid
from dataclasses import dataclass
from typing import Any

from .block import Range, SpaceblockRequests
from .wire import Reader, Writer


class HeaderType(enum.IntEnum):
    PING = 0
    SPACEDROP = 1
    SYNC = 2  # originator announces new ops for a library
    SYNC_REQUEST = 3  # responder pulls ops with watermarks
    FILE = 4
    HTTP = 5
    RSPC = 6
    PAIRING = 7  # library join request (ref: the reference's pairing flow)
    TELEMETRY = 8  # pull the peer's compact telemetry snapshot (federation)
    WORK = 9  # work-stealing shard exchange for a library (p2p/work.py)


@dataclass
class FileRequest:
    """ref:protocol.rs `Header::File` (operations/request_file.rs:29)."""

    library_id: uuid.UUID
    file_path_pub_id: uuid.UUID
    range: Range


@dataclass
class Header:
    type: HeaderType
    library_id: uuid.UUID | None = None  # SYNC / SYNC_REQUEST
    spacedrop: SpaceblockRequests | None = None  # SPACEDROP
    file: FileRequest | None = None  # FILE
    # distributed-trace context (telemetry.trace wire dict) riding the
    # sync and spacedrop openers, so the remote node's spans join the
    # initiator's trace; {} on the wire means "no context". NOTE: this
    # protocol has no version negotiation (SYNC_REQUEST/RSPC/PAIRING
    # were likewise added flag-day) — every peer in a mesh must run the
    # same wire revision; a cross-revision handshake would have to land
    # before any rolling-upgrade story.
    trace: dict | None = None
    # TELEMETRY sub-operation ({} = the default snapshot pull):
    # {"op": "trace_pull", "trace_id": "<hex>"} asks the responder for
    # its completed spans of one distributed trace (critical-path
    # attribution, telemetry/attrib.py) — same flag-day discipline as
    # `trace` above
    telemetry_op: dict | None = None

    async def write(self, stream: Any) -> None:
        w = Writer(stream)
        w.u8(int(self.type))
        if self.type in (HeaderType.SYNC, HeaderType.SYNC_REQUEST,
                         HeaderType.WORK):
            assert self.library_id is not None
            w.uuid(self.library_id)
            w.msgpack(self.trace or {})
        elif self.type == HeaderType.SPACEDROP:
            assert self.spacedrop is not None
            w.msgpack(self.spacedrop.to_wire())
            w.msgpack(self.trace or {})
        elif self.type == HeaderType.FILE:
            assert self.file is not None
            w.uuid(self.file.library_id)
            w.uuid(self.file.file_path_pub_id)
            w.msgpack(self.file.range.to_wire())
        elif self.type == HeaderType.TELEMETRY:
            w.msgpack(self.trace or {})
            w.msgpack(self.telemetry_op or {})
        await w.flush()

    @classmethod
    async def read(cls, stream: Any) -> "Header":
        r = Reader(stream)
        t = HeaderType(await r.u8())
        if t in (HeaderType.SYNC, HeaderType.SYNC_REQUEST, HeaderType.WORK):
            lib_id = await r.uuid()
            return cls(t, library_id=lib_id, trace=(await r.msgpack()) or None)
        if t == HeaderType.SPACEDROP:
            sd = SpaceblockRequests.from_wire(await r.msgpack())
            return cls(t, spacedrop=sd, trace=(await r.msgpack()) or None)
        if t == HeaderType.FILE:
            return cls(
                t,
                file=FileRequest(
                    library_id=await r.uuid(),
                    file_path_pub_id=await r.uuid(),
                    range=Range.from_wire(await r.msgpack()),
                ),
            )
        if t == HeaderType.TELEMETRY:
            return cls(
                t,
                trace=(await r.msgpack()) or None,
                telemetry_op=(await r.msgpack()) or None,
            )
        return cls(t)
