"""Spaceblock — block-based file transfer with progress and cancel.

Parity: ref:crates/p2p-block — a protocol "modelled after SyncThing's
BEP" (src/lib.rs:4-6): `BlockSize` adaptive to file size
(block_size.rs), `SpaceblockRequest{name, size, range}` +
`SpaceblockRequests{id, block_size, requests}` for multi-file sends
(sb_request.rs), and a `Transfer` engine with a progress callback and
cooperative cancellation checked at block boundaries (lib.rs:75-91).
Wire layout per file: blocks in order, each `u64 offset ‖ u32 len ‖
data`, receiver acks each block with one byte (0 = continue,
1 = cancel) — the back-channel the reference gets from QUIC flow
control.
"""

from __future__ import annotations

import asyncio
import os
import uuid
from dataclasses import dataclass, field
from typing import Any, BinaryIO, Callable

from .wire import Reader, Writer

KIB = 1024
MIB = 1024 * KIB


@dataclass(frozen=True)
class BlockSize:
    """ref:block_size.rs — clamped power-of-two block size derived from
    file size (small files ship in one block; huge files use 1MiB)."""

    size: int

    MIN = 16 * KIB
    MAX = 1 * MIB

    @classmethod
    def from_file_size(cls, file_size: int) -> "BlockSize":
        size = cls.MIN
        while size < cls.MAX and size * 256 < file_size:
            size *= 2
        return cls(size)

    @classmethod
    def dangerously_new(cls, size: int) -> "BlockSize":
        # ref:block_size.rs `dangerously_new` — trusts the peer's value
        if size <= 0 or size > cls.MAX:
            raise ValueError(f"invalid block size {size}")
        return cls(size)


@dataclass
class Range:
    """ref:sb_request.rs `Range::{Full, Partial(start..end)}`."""

    start: int = 0
    end: int | None = None  # None = to EOF (Full when start == 0)

    @property
    def is_full(self) -> bool:
        return self.start == 0 and self.end is None

    def to_wire(self) -> Any:
        return None if self.is_full else [self.start, self.end]

    @classmethod
    def from_wire(cls, obj: Any) -> "Range":
        if obj is None:
            return cls()
        return cls(start=int(obj[0]), end=None if obj[1] is None else int(obj[1]))


@dataclass
class SpaceblockRequest:
    name: str
    size: int
    range: Range = field(default_factory=Range)

    def to_wire(self) -> dict[str, Any]:
        return {"name": self.name, "size": self.size, "range": self.range.to_wire()}

    @classmethod
    def from_wire(cls, obj: dict[str, Any]) -> "SpaceblockRequest":
        return cls(
            name=obj["name"], size=int(obj["size"]), range=Range.from_wire(obj["range"])
        )


@dataclass
class SpaceblockRequests:
    id: uuid.UUID
    block_size: BlockSize
    requests: list[SpaceblockRequest]

    @property
    def total_size(self) -> int:
        return sum(r.size for r in self.requests)

    def to_wire(self) -> dict[str, Any]:
        return {
            "id": self.id.bytes,
            "block_size": self.block_size.size,
            "requests": [r.to_wire() for r in self.requests],
        }

    @classmethod
    def from_wire(cls, obj: dict[str, Any]) -> "SpaceblockRequests":
        return cls(
            id=uuid.UUID(bytes=obj["id"]),
            block_size=BlockSize.dangerously_new(int(obj["block_size"])),
            requests=[SpaceblockRequest.from_wire(r) for r in obj["requests"]],
        )


class TransferCancelled(Exception):
    pass


class Transfer:
    """One directional transfer session over an established stream
    (ref:lib.rs:75-91 `Transfer::new(...).send/receive`)."""

    def __init__(
        self,
        requests: SpaceblockRequests,
        on_progress: Callable[[int], None] | None = None,
        cancelled: asyncio.Event | None = None,
    ):
        self.requests = requests
        self.on_progress = on_progress or (lambda _pct: None)
        self.cancelled = cancelled or asyncio.Event()
        self.transferred = 0

    def _progress(self) -> None:
        total = self.requests.total_size or 1
        self.on_progress(min(100, self.transferred * 100 // total))

    def _file_span(self, req: SpaceblockRequest) -> tuple[int, int]:
        start = req.range.start
        end = req.size if req.range.end is None else min(req.range.end, req.size)
        return start, max(end - start, 0)

    async def send(self, stream: Any, files: list[BinaryIO]) -> None:
        """Stream every requested range; abort on receiver cancel byte."""
        if len(files) != len(self.requests.requests):
            raise ValueError("files/requests length mismatch")
        w, r = Writer(stream), Reader(stream)
        bs = self.requests.block_size.size
        for req, fh in zip(self.requests.requests, files):
            start, remaining = self._file_span(req)
            fh.seek(start)
            offset = start
            while remaining > 0:
                if self.cancelled.is_set():
                    raise TransferCancelled()
                # disk reads off the loop: a cold 1MiB block from a slow
                # volume would otherwise stall every other stream
                data = await asyncio.to_thread(fh.read, min(bs, remaining))
                if not data:
                    raise EOFError(f"file {req.name} shorter than advertised")
                w.u64(offset).u32(len(data)).raw(data)
                await w.flush()
                ack = await r.u8()
                if ack == 1:
                    raise TransferCancelled()
                offset += len(data)
                remaining -= len(data)
                self.transferred += len(data)
                self._progress()

    async def receive(self, stream: Any, sinks: list[BinaryIO]) -> None:
        """Receive every requested range, acking each block."""
        if len(sinks) != len(self.requests.requests):
            raise ValueError("sinks/requests length mismatch")
        w, r = Writer(stream), Reader(stream)
        bs = self.requests.block_size.size
        for req, out in zip(self.requests.requests, sinks):
            _start, remaining = self._file_span(req)
            while remaining > 0:
                _offset = await r.u64()
                length = await r.u32()
                # don't trust the sender: a block must be non-empty, within
                # the negotiated block size, and within the advertised span
                if length == 0 or length > bs or length > remaining:
                    w.u8(1)
                    await w.flush()
                    raise ValueError(
                        f"peer sent invalid block length {length} "
                        f"(block_size={bs}, remaining={remaining})"
                    )
                data = await r.exact(length)
                if self.cancelled.is_set():
                    w.u8(1)
                    await w.flush()
                    raise TransferCancelled()
                await asyncio.to_thread(out.write, data)
                w.u8(0)
                await w.flush()
                remaining -= length
                self.transferred += length
                self._progress()
