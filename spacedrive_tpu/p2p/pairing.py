"""Library pairing — join a peer's library over the mesh.

Parity role: the reference's device-pairing flow (its `pairing.rs`
iteration; the shipped tree pairs instances through the cloud's
instance registry instead — crates/cloud-api `library::join`). Here
pairing rides the P2P mesh directly:

  joiner → owner: PAIRING header ‖ {library_id?, joiner instance info}
  owner:  user accept/reject (same pending-decision surface as
          Spacedrop, auto-accept flag for headless nodes)
  owner → joiner: {library config, instance registry}
  both:   register each other's instance rows; the joiner creates a
          local library with the SAME id, runs sync backfill-free and
          pulls the owner's op log through the normal sync exchange
          (alert → watermark pull), converging to the full library.

The data plane stays CRDT sync — pairing only moves identity +
membership, never rows, so a million-file library joins in O(instances)
bytes and then streams in the background.
"""

from __future__ import annotations

import asyncio
import logging
import os
import uuid
from dataclasses import dataclass, field
from typing import Any

from ..db.database import now_iso
from ..sync.hlc import NTP64
from .identity import RemoteIdentity
from .protocol import Header, HeaderType
from .wire import Reader, Writer

logger = logging.getLogger(__name__)

PAIRING_TIMEOUT = 60.0


@dataclass
class PairingRequest:
    """An inbound join offer pending user decision."""

    id: uuid.UUID
    peer: RemoteIdentity
    library_id: uuid.UUID | None  # None = "any library you offer"
    node_name: str
    _decision: asyncio.Future = field(repr=False, default=None)  # type: ignore[assignment]


class PairingManager:
    """Hangs off P2PManager (accept/reject mirror SpacedropManager)."""

    def __init__(self, node: Any, event_bus: Any = None):
        self.node = node
        self.event_bus = event_bus
        self.pending: dict[uuid.UUID, PairingRequest] = {}
        self.auto_accept = False  # headless nodes can opt in

    # --- joiner side ---------------------------------------------------

    async def join(
        self,
        p2p: Any,
        identity: RemoteIdentity,
        library_id: uuid.UUID | None = None,
    ) -> Any:
        """Request membership of a peer's library; returns the local
        Library on success."""
        # fail fast (also checked in _create_joined_library): a doomed
        # request must not reach the owner and raise a consent prompt
        if library_id is not None and self.node.libraries.get(library_id) is not None:
            raise FileExistsError(f"library {library_id} already exists here")
        stream = await p2p.new_stream(identity)
        try:
            await Header(HeaderType.PAIRING).write(stream)
            w, r = Writer(stream), Reader(stream)
            from ..node.library import _platform_int

            my_instance = {
                "node_name": self.node.config.config.name,
                "node_pub_id": self.node.id.bytes,
                "node_platform": _platform_int(),
                "identity": self.node.config.config.identity
                .to_remote_identity()
                .to_bytes(),
            }
            w.msgpack(
                {
                    "library_id": library_id.bytes if library_id else None,
                    "instance": my_instance,
                }
            )
            await w.flush()
            # bounded: the owner's user gets PAIRING_TIMEOUT to decide,
            # plus slack — a dead owner must not pin this API call open
            resp = await asyncio.wait_for(r.msgpack(), PAIRING_TIMEOUT + 15)
            if not resp.get("ok"):
                raise PermissionError(resp.get("error", "pairing rejected"))
            lib_id = uuid.UUID(bytes=resp["library_id"])
            config = resp["config"]
            instances = resp["instances"]

            lib = self._create_joined_library(lib_id, config, instances)
            try:
                # tell the owner our instance pub_id so both sides register
                w.msgpack({"instance_pub_id": lib.sync.instance.bytes})
                await w.flush()
                await self.node._init_library(lib)
                if self.node.p2p is not None:
                    self.node.p2p.register_library(lib)
                    # pull the op log right away (normal sync exchange)
                    ingest = self.node.p2p.ingest_actors.get(lib.id)
                    if ingest is not None:
                        ingest.notify()
            except BaseException:
                # roll the half-joined library back so a retry can succeed
                self.node.libraries.libraries.pop(lib.id, None)
                lib.close()
                for path in self.node.libraries.paths(lib.id):
                    for suffix in ("", "-wal", "-shm"):
                        p = path + suffix
                        if os.path.exists(p):
                            os.remove(p)
                raise
            return lib
        finally:
            await stream.close()

    def _create_joined_library(
        self, lib_id: uuid.UUID, config: dict[str, Any], instances: list[dict]
    ) -> Any:
        from ..node.library import Library, LibraryConfig, _platform_int
        from ..db import LibraryDb
        from ..db.database import new_pub_id

        libraries = self.node.libraries
        if libraries.get(lib_id) is not None:
            raise FileExistsError(f"library {lib_id} already exists here")
        db = LibraryDb(libraries._db_path(lib_id))
        try:
            return self._populate_joined_library(
                libraries, db, lib_id, config, instances
            )
        except BaseException:
            # never leave a half-written DB: a stale file with instance
            # rows makes every retry hit UNIQUE(pub_id)
            db.close()
            for path in libraries.paths(lib_id):
                for suffix in ("", "-wal", "-shm"):
                    if os.path.exists(path + suffix):
                        os.remove(path + suffix)
            raise

    def _populate_joined_library(
        self, libraries, db, lib_id: uuid.UUID, config: dict[str, Any],
        instances: list[dict],
    ) -> Any:
        from ..node.library import Library, LibraryConfig, _platform_int
        from ..db.database import new_pub_id
        instance_pub = new_pub_id()
        instance_id = db.insert(
            "instance",
            pub_id=instance_pub,
            identity=self.node.config.config.identity
            .to_remote_identity()
            .to_bytes(),
            node_id=self.node.id.bytes,
            node_name=self.node.config.config.name,
            node_platform=_platform_int(),
            last_seen=now_iso(),
            date_created=now_iso(),
        )
        for inst in instances:  # the existing membership
            db.insert(
                "instance",
                pub_id=inst["pub_id"],
                identity=inst.get("identity") or b"",
                node_id=inst.get("node_id") or b"",
                node_name=inst.get("node_name") or "",
                node_platform=inst.get("node_platform") or 0,
                last_seen=now_iso(),
                date_created=inst.get("date_created") or now_iso(),
            )
        lib_config = LibraryConfig(
            name=config.get("name", "joined"),
            description=config.get("description", ""),
            instance_id=instance_id,
        )
        from ..node.library import _config_vm

        _config_vm.save(libraries._config_path(lib_id), lib_config.to_dict())
        lib = Library(
            lib_id, lib_config, db, uuid.UUID(bytes=instance_pub),
            node=self.node,
        )
        libraries.libraries[lib_id] = lib
        from ..location.indexer.rules import seed_rules

        seed_rules(db)
        return lib

    # --- owner side ----------------------------------------------------

    async def handle_inbound(self, stream: Any) -> None:
        r, w = Reader(stream), Writer(stream)
        req_body = await r.msgpack()
        lib_id = (
            uuid.UUID(bytes=req_body["library_id"])
            if req_body.get("library_id")
            else None
        )
        # resolve the library BEFORE bothering the user: an unsatisfiable
        # request gets a distinct error, no consent prompt
        if lib_id is not None:
            target = self.node.libraries.get(lib_id)
        elif self.node.libraries.libraries:
            target = next(iter(self.node.libraries.libraries.values()))
        else:
            target = None
        if target is None:
            w.msgpack({"ok": False, "error": "library not found on this node"})
            await w.flush()
            return
        # backfill pre-sync rows NOW, overlapping the user's decision —
        # never inside the reply window (a big library would blow the
        # joiner's read deadline)
        from ..sync.ingest import backfill_operations

        backfill_task = asyncio.ensure_future(
            asyncio.to_thread(backfill_operations, target.sync)
        )
        req = PairingRequest(
            id=uuid.uuid4(),
            peer=stream.remote_identity,
            library_id=lib_id,
            node_name=req_body.get("instance", {}).get("node_name", "?"),
            _decision=asyncio.get_running_loop().create_future(),
        )
        if self.auto_accept:
            req._decision.set_result(True)
        else:
            self.pending[req.id] = req
            if self.event_bus is not None:
                self.event_bus.emit(("PairingRequest", req))
        try:
            accepted = await asyncio.wait_for(req._decision, PAIRING_TIMEOUT)
        except asyncio.TimeoutError:
            accepted = False
        finally:
            self.pending.pop(req.id, None)

        lib = target if accepted else None
        if lib is None:
            backfill_task.cancel()
            w.msgpack({"ok": False, "error": "pairing rejected"})
            await w.flush()
            return
        # rows that predate sync must have ops before the joiner pulls
        await backfill_task
        instances = [
            {
                "pub_id": row["pub_id"],
                "identity": row["identity"],
                "node_id": row["node_id"],
                "node_name": row["node_name"],
                "node_platform": row["node_platform"],
                "date_created": row["date_created"],
            }
            for row in lib.db.find("instance")
        ]
        w.msgpack(
            {
                "ok": True,
                "library_id": lib.id.bytes,
                "config": {
                    "name": lib.config.name,
                    "description": lib.config.description,
                },
                "instances": instances,
            }
        )
        await w.flush()
        # register the joiner's new instance on our side; bounded read —
        # a stalled joiner must not pin this handler forever
        joiner = await asyncio.wait_for(r.msgpack(), PAIRING_TIMEOUT)
        inst = req_body.get("instance", {})
        lib.db.insert(
            "instance",
            pub_id=joiner["instance_pub_id"],
            identity=inst.get("identity") or b"",
            node_id=inst.get("node_pub_id") or b"",
            node_name=inst.get("node_name") or "",
            node_platform=inst.get("node_platform") or 0,
            last_seen=now_iso(),
            date_created=now_iso(),
        )
        lib.sync.timestamps.setdefault(
            uuid.UUID(bytes=joiner["instance_pub_id"]), NTP64(0)
        )
        if self.event_bus is not None:
            self.event_bus.emit(("PairingComplete", req.id, str(lib.id)))

    def accept(self, pairing_id: uuid.UUID) -> bool:
        req = self.pending.get(pairing_id)
        if req is None or req._decision.done():
            return False
        req._decision.set_result(True)
        return True

    def reject(self, pairing_id: uuid.UUID) -> bool:
        req = self.pending.get(pairing_id)
        if req is None or req._decision.done():
            return False
        req._decision.set_result(False)
        return True
