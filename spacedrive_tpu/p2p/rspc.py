"""rspc-over-P2P — drive another node's API across the mesh.

Parity: ref:core/src/p2p/operations/rspc.rs:13 — a `Header::Http`-style
stream that tunnels API requests to a remote node, used by the frontend
to browse *other* devices. Here the frame is msgpack
`{key, arg, library_id}` → `{ok, result | error, code}` over one
authenticated stream per request; query/mutation only (subscriptions
stay local, as in the reference).
"""

from __future__ import annotations

from typing import Any

from ..api.router import RspcError
from ..utils.resilience import PASS, RETRY, ResiliencePolicy, RetryPolicy
from .identity import RemoteIdentity
from .protocol import Header, HeaderType
from .wire import Reader, Writer


class RemoteRspcError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


def _classify(exc: BaseException) -> str:
    """A peer that ANSWERED (refusal, bad procedure) must neither retry
    nor feed the breaker; only transport failures count."""
    if isinstance(exc, (RemoteRspcError, PermissionError, ValueError)):
        return PASS
    return RETRY


#: policy for remote-rspc call sites (queries are idempotent by the
#: responder's own restriction, so a bounded retry is safe)
RSPC_POLICY = ResiliencePolicy(
    "p2p_rspc",
    RetryPolicy(max_attempts=2, base_delay=0.05, max_delay=0.5,
                attempt_timeout=30.0),
    failure_threshold=3,
    reset_timeout=15.0,
    classify=_classify,
)


async def remote_exec(
    p2p: Any,
    identity: RemoteIdentity,
    key: str,
    arg: Any = None,
    library_id: str | None = None,
) -> Any:
    """Run one procedure on a remote node (ref:operations/rspc.rs)."""
    stream = await p2p.new_stream(identity)
    try:
        await Header(HeaderType.RSPC).write(stream)
        w = Writer(stream)
        w.msgpack({"key": key, "arg": arg, "library_id": library_id})
        await w.flush()
        resp = await Reader(stream).msgpack()
        if not resp.get("ok"):
            raise RemoteRspcError(
                int(resp.get("code", 500)), str(resp.get("error", "remote error"))
            )
        return resp.get("result")
    finally:
        await stream.close()


async def respond_rspc(stream: Any, node: Any) -> None:
    """Server half: execute against the local router.

    Authorization: feature-gated (`remoteRspc`, off by default) and
    restricted to QUERIES — a peer identity alone must never reach
    mutations like files.eraseFiles or library.delete (the reference
    scopes its remote rspc to device-browsing reads the same way)."""
    from ..node.config import BackendFeature

    req = await Reader(stream).msgpack()
    w = Writer(stream)
    try:
        if not node.is_feature_enabled(BackendFeature.REMOTE_RSPC):
            raise RspcError(403, "remoteRspc disabled on this node")
        proc = node.router.procedures.get(req["key"])
        if proc is not None and proc.kind != "query":
            raise RspcError(403, "only queries are served over p2p")
        result = await node.router.exec(
            node, req["key"], req.get("arg"), req.get("library_id")
        )
        w.msgpack({"ok": True, "result": _wireable(result)})
    except RspcError as e:
        w.msgpack({"ok": False, "error": e.message, "code": e.code})
    except Exception as e:
        w.msgpack({"ok": False, "error": str(e), "code": 500})
    await w.flush()


def _wireable(obj: Any) -> Any:
    """msgpack-encodable projection (bytes→hex like the HTTP layer)."""
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, dict):
        return {k: _wireable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_wireable(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if hasattr(obj, "to_wire"):
        return _wireable(obj.to_wire())
    return str(obj)
