"""Node/instance identity keypairs.

Parity: ref:crates/p2p2/src/identity.rs — `Identity` (ed25519 signing
keypair, serialized as the 32-byte secret) and `RemoteIdentity` (the
32-byte verifying key, displayed base64/hex). The reference derives its
libp2p PeerId from the same keypair; here the verifying key itself is
the peer address on the mesh.
"""

from __future__ import annotations

import base64

try:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    _HAVE_CRYPTOGRAPHY = True
except ImportError:  # gated: pure-Python RFC 8032 keeps node boot alive
    from ..utils.ed25519_fallback import Ed25519PrivateKey, Ed25519PublicKey

    serialization = None
    _HAVE_CRYPTOGRAPHY = False


class RemoteIdentity:
    """Verifying half of an identity (ref:identity.rs `RemoteIdentity`)."""

    __slots__ = ("_key", "_raw")

    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("RemoteIdentity must be 32 bytes")
        self._raw = bytes(raw)
        self._key = Ed25519PublicKey.from_public_bytes(self._raw)

    def to_bytes(self) -> bytes:
        return self._raw

    def verify(self, signature: bytes, message: bytes) -> bool:
        try:
            self._key.verify(signature, message)
            return True
        except Exception:
            return False

    def __eq__(self, other: object) -> bool:
        return isinstance(other, RemoteIdentity) and other._raw == self._raw

    def __hash__(self) -> int:
        return hash(self._raw)

    def __str__(self) -> str:
        # reference displays RemoteIdentity base64 (identity.rs Display)
        return base64.urlsafe_b64encode(self._raw).decode().rstrip("=")

    def __repr__(self) -> str:
        return f"<RemoteIdentity {str(self)[:12]}…>"

    @classmethod
    def from_str(cls, s: str) -> "RemoteIdentity":
        pad = "=" * (-len(s) % 4)
        return cls(base64.urlsafe_b64decode(s + pad))


class Identity:
    """Signing keypair (ref:identity.rs `Identity`); serialized as the
    32-byte ed25519 seed."""

    __slots__ = ("_key",)

    def __init__(self, key: Ed25519PrivateKey | None = None):
        self._key = key or Ed25519PrivateKey.generate()

    @classmethod
    def from_bytes(cls, seed: bytes) -> "Identity":
        if len(seed) != 32:
            raise ValueError("Identity seed must be 32 bytes")
        return cls(Ed25519PrivateKey.from_private_bytes(seed))

    def to_bytes(self) -> bytes:
        if not _HAVE_CRYPTOGRAPHY:
            return self._key.private_bytes()
        return self._key.private_bytes(
            serialization.Encoding.Raw,
            serialization.PrivateFormat.Raw,
            serialization.NoEncryption(),
        )

    def to_remote_identity(self) -> RemoteIdentity:
        if not _HAVE_CRYPTOGRAPHY:
            return RemoteIdentity(self._key.public_key().public_bytes())
        return RemoteIdentity(
            self._key.public_key().public_bytes(
                serialization.Encoding.Raw, serialization.PublicFormat.Raw
            )
        )

    def sign(self, message: bytes) -> bytes:
        return self._key.sign(message)
