"""P2PManager — wires the P2P runtime into the Node.

Parity: ref:core/src/p2p/manager.rs:49-118 — builds the P2P runtime
from `NodeConfig.p2p` (port, discovery mode), advertises node metadata
(name/os/version, metadata.rs) plus per-library instances
(libraries.rs) over discovery, dispatches inbound streams by `Header`
(protocol.rs), pushes sync alerts to library peers on every local
`write_ops`, and backs each library's ingest actor with peer pulls.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import platform
import uuid
from typing import Any

from ..node.config import BackendFeature, P2PDiscoveryState
from ..sync.ingest import IngestActor
from ..telemetry import span as _span
from ..telemetry import tenants as _tenants
from ..telemetry import trace as _trace
from ..telemetry.events import P2P_EVENTS
from ..telemetry.federation import FederationCache, local_snapshot, snapshot_compatible
from ..utils import faults as _faults
from ..utils.resilience import (
    PASS,
    RETRY,
    BreakerOpen,
    ResiliencePolicy,
    RetryPolicy,
)
from ..utils.tasks import supervise
from .identity import RemoteIdentity
from .mdns import MdnsDiscovery
from .operations import (
    SpacedropManager,
    _wireable_snapshot,
    request_profile,
    request_telemetry,
    request_trace,
    respond_file,
    respond_profile,
    respond_telemetry,
    respond_trace,
)
from .p2p import P2P
from .protocol import Header, HeaderType
from .sync import alert_new_ops, request_ops_from_peer, respond_sync_request
from .wire import Writer

logger = logging.getLogger(__name__)


def _peer_classify(exc: BaseException) -> str:
    """Retry/breaker classification for peer-facing calls: transport
    failures retry and count; an ANSWER we dislike (refusal, version
    mismatch) passes through untouched — a peer that responds is not a
    peer whose breaker should open."""
    if isinstance(exc, (PermissionError, ValueError)):
        return PASS
    return RETRY


# One bounded, jittered retry ladder + per-peer breaker for every
# sync-plane exchange (alerts, op pulls, telemetry pulls): a flapping
# peer costs one fast BreakerOpen per write instead of a fresh dial +
# timeout, and re-arms itself through the breaker's half-open probe.
SYNC_POLICY = ResiliencePolicy(
    "p2p_sync",
    RetryPolicy(max_attempts=2, base_delay=0.05, max_delay=0.5,
                attempt_timeout=30.0),
    failure_threshold=3,
    reset_timeout=15.0,
    classify=_peer_classify,
)


class P2PManager:
    def __init__(self, node: Any, *, beacon_addrs: list[tuple[str, int]] | None = None,
                 bind_host: str = "0.0.0.0"):
        self.node = node
        self.p2p = P2P("spacedrive", node.config.config.identity)
        self.spacedrop = SpacedropManager(self.p2p, node.event_bus)
        self.relay_client = None  # set when p2p.relay is configured
        from .pairing import PairingManager

        self.pairing = PairingManager(node, node.event_bus)
        self.ingest_actors: dict[uuid.UUID, IngestActor] = {}
        # mesh-wide telemetry: freshest snapshot per peer w/ staleness
        # (telemetry/federation.py; read via GET /mesh, telemetry.mesh)
        self.federation = FederationCache()
        # work-stealing shard plane: board (coordinating) + worker
        # (stealing) — see p2p/work.py + location/indexer/mesh.py
        from .work import WorkPlane

        self.work = WorkPlane(node, self)
        self._beacon_addrs = beacon_addrs
        self._bind_host = bind_host
        self._unsubs: list[Any] = []
        # in-flight sync-alert fan-outs: tracked so shutdown can await
        # them — an orphaned alert coroutine cancelled at loop teardown
        # is exactly the kind of half-sent alert production can't afford
        self._alert_tasks: set[asyncio.Task] = set()
        self._shutting_down = False
        self.port: int | None = None

    def _spawn_alert(self, loop: asyncio.AbstractEventLoop,
                     lib_id: uuid.UUID) -> None:
        if self._shutting_down or not loop.is_running():
            return
        supervise(loop.create_task(self._alert_peers(lib_id)),
                  self._alert_tasks, logger, "sync alert fan-out")

    # --- lifecycle -----------------------------------------------------

    async def start(self) -> None:
        cfg = self.node.config.config
        self._loop = asyncio.get_running_loop()
        self.p2p.set_stream_handler(self._handle_stream)
        # a peer appearing with one of our libraries triggers a pull —
        # discovery often lands after the peer's alerts were sent
        self._unsubs.append(self.p2p.events.on(self._on_p2p_event))
        self.port = await self.p2p.listen(cfg.p2p.port, host=self._bind_host)
        self._advertise()
        if cfg.p2p.discovery != P2PDiscoveryState.DISABLED:
            mdns = MdnsDiscovery(
                self.p2p,
                self.port,
                beacon_addrs=self._beacon_addrs,
                bind_port=0 if self._beacon_addrs is not None else 41841,
            )
            await mdns.start()
        if cfg.p2p.relay:
            host, _, port_s = cfg.p2p.relay.rpartition(":")
            if not host or not port_s.isdigit():
                logger.error(
                    "p2p.relay %r is not \"host:port\" (IPv6: \"[::1]:7000\")"
                    " — WAN relay disabled", cfg.p2p.relay,
                )
            else:
                from .relay import RelayClient

                relay = RelayClient(
                    self.p2p, (host.strip("[]"), int(port_s)),
                    self.p2p._on_stream,
                )
                await relay.start()
                self.p2p.register_discovery(relay)
                self.relay_client = relay  # punch telemetry for p2p.state
        for lib in self.node.libraries.libraries.values():
            self.register_library(lib)

    def _advertise(self) -> None:
        """Node metadata for discovery (ref:p2p/metadata.rs) + the
        instances this node exposes per library (ref:p2p/libraries.rs)."""
        cfg = self.node.config.config
        self.p2p.metadata.update(
            {
                "name": cfg.name,
                "operating_system": platform.system().lower(),
                "device_model": platform.machine(),
                "version": "0.1.0",
                "libraries": ",".join(
                    str(lid) for lid in self.node.libraries.libraries
                ),
                # instance → node mapping for remote file serving
                # (ref:custom_uri/mod.rs ServeFrom::Remote resolution)
                "instances": ",".join(
                    str(lib.sync.instance)
                    for lib in self.node.libraries.libraries.values()
                ),
            }
        )

    def register_library(self, lib: Any) -> None:
        """Wire sync for one library: alert peers on local writes; back
        the ingest actor with peer pulls (ref:p2p/sync/mod.rs)."""
        if lib.id in self.ingest_actors:
            return

        async def request_ops(timestamps, count, lib_id=lib.id):
            for peer in self.peers_for_library(lib_id):
                try:
                    # EOFError covers IncompleteReadError: a peer
                    # vanishing mid-SYNC is a failed (retryable) pull,
                    # not an unhandled ingest-tick crash
                    return await SYNC_POLICY.call(
                        str(peer.identity),
                        lambda peer=peer: request_ops_from_peer(
                            self.p2p, peer.identity, lib_id, timestamps,
                            count,
                        ),
                    )
                except BreakerOpen:
                    continue  # fast-failed: try the next peer
                except (ConnectionError, OSError, EOFError,
                        asyncio.TimeoutError) as e:
                    logger.debug("sync pull from %s failed: %s", peer.identity, e)
            return [], False

        def on_applied(lib_id=lib.id, lib=lib):
            # sync-applied ops dirty this library's cached reads: the
            # remote mutation plane can't name query keys, so the whole
            # library tag drops (serve cache read-your-writes, remote
            # half — the local half lives in api.invalidate)
            from ..serve import runtime_for

            serve = runtime_for(self.node)
            if serve is not None:
                serve.invalidate_library(lib_id, source="sync")
            # replicated object_embedding rows fold into the vector
            # index here, so a replica answers search.semantic without
            # ever running the embed stage itself (failure-contained:
            # the hook must never wedge the ingest actor)
            from ..object.search import on_embeddings_applied

            on_embeddings_applied(lib)

        actor = IngestActor(lib.sync, request_ops, on_applied=on_applied)
        self.ingest_actors[lib.id] = actor
        lib.ingest = actor

        def on_event(event, lib_id=lib.id):
            # Created: local write. Ingested: ops arrived from a peer —
            # re-alerting turns any connected subgraph into a relay
            # (hub topologies converge transitively; alerts are
            # idempotent nudges, peers pull by watermark)
            if event in (("SyncMessage", "Created"), ("SyncMessage", "Ingested")):
                loop = getattr(self, "_loop", None)
                if loop is not None and loop.is_running():
                    loop.call_soon_threadsafe(self._spawn_alert, loop, lib_id)

        try:
            self._loop = asyncio.get_running_loop()
        except RuntimeError:
            pass  # set at start(); registration before start is fine
        self._unsubs.append(lib.event_bus.on(on_event))
        self._advertise()

    def _on_p2p_event(self, event: Any) -> None:
        if not (
            isinstance(event, tuple)
            and event
            and event[0] in ("PeerDiscovered", "PeerMetadataChanged")
        ):
            return
        peer = self.p2p.peers.get(event[1])
        if peer is None:
            return
        advertised = set(peer.metadata.get("libraries", "").split(","))
        for lib_id, actor in self.ingest_actors.items():
            if str(lib_id) in advertised:
                actor.notify()

    async def _alert_peers(self, library_id: uuid.UUID) -> None:
        for peer in self.peers_for_library(library_id):
            try:
                await SYNC_POLICY.call(
                    str(peer.identity),
                    lambda peer=peer: alert_new_ops(
                        self.p2p, peer.identity, library_id
                    ),
                )
            except BreakerOpen:
                continue  # alerts are idempotent nudges; skip fast
            except (ConnectionError, OSError, EOFError,
                    asyncio.TimeoutError) as e:
                logger.debug("sync alert to %s failed: %s", peer.identity, e)

    def peers_for_library(self, library_id: uuid.UUID) -> list[Any]:
        lid = str(library_id)
        return [
            p
            for p in self.p2p.discovered_peers()
            if lid in p.metadata.get("libraries", "").split(",")
        ]

    def peer_for_instance(self, instance: uuid.UUID) -> Any | None:
        """The discovered peer advertising a library instance
        (ref:p2p/libraries.rs instance discovery)."""
        needle = str(instance)
        for p in self.p2p.discovered_peers():
            if needle in p.metadata.get("instances", "").split(","):
                return p
        return None

    def _is_library_member(self, remote_identity: Any,
                           library_id: uuid.UUID | None = None) -> bool:
        """True when the identity belongs to an instance of a loaded
        library — i.e. a peer the pairing flow admitted (instance rows
        store ``RemoteIdentity.to_bytes()``). With ``library_id`` the
        check is scoped to THAT library: membership in library X must
        not open library Y's surfaces (the WORK plane hands out work
        and file metadata per library). The instance table is tiny, so
        the scan is cheap per request."""
        if remote_identity is None:
            return False
        try:
            needle = remote_identity.to_bytes()
        except (AttributeError, ValueError):
            return False
        libs = self.node.libraries.libraries
        if library_id is not None:
            lib = libs.get(library_id)
            scan = [lib] if lib is not None else []
        else:
            scan = list(libs.values())
        for lib in scan:
            for row in lib.db.query("SELECT identity FROM instance"):
                if row["identity"] == needle:
                    return True
        return False

    # --- telemetry federation (telemetry/federation.py) ----------------

    async def refresh_federation(self, force: bool = False) -> dict:
        """Pull fresh snapshots from every discovered peer — direct P2P
        first, the cloud relay as fallback for peers we can't reach —
        and return the refreshed mesh view. Pull-through: a peer whose
        cached snapshot is younger than the cache's refresh interval is
        skipped unless ``force``, so a burst of /mesh hits doesn't
        stampede the mesh."""
        due = [
            peer for peer in self.p2p.discovered_peers()
            if force or self.federation.needs_refresh(str(peer.identity))
        ]

        # pulls are independent — run them concurrently so a mesh with
        # several unreachable peers costs ONE telemetry timeout, not N
        # (EOFError covers IncompleteReadError: a peer closing the
        # stream mid-response is a failed pull, not a /mesh 500)
        async def pull(peer: Any) -> tuple[Any, str] | None:
            try:
                snap = await SYNC_POLICY.call(
                    str(peer.identity),
                    lambda peer=peer: request_telemetry(
                        self.p2p, peer.identity
                    ),
                )
                self.federation.store(str(peer.identity), snap,
                                      transport="p2p")
                return None
            except (ConnectionError, OSError, EOFError,
                    asyncio.TimeoutError, ValueError) as e:
                # BreakerOpen is a ConnectionError: a breaker-gated peer
                # still falls through to the relay leg below
                return (peer, str(e))

        results = await asyncio.gather(*(pull(p) for p in due))
        failed = [r for r in results if r is not None]
        # the relay leg costs real HTTP round-trips per cloud-enabled
        # library — run it only when something needs it (unreached
        # peers, relay-tracked peers due a refresh, or an explicit
        # force), not on every dashboard poll
        if failed or force or self.federation.due_relay_peers():
            await self._relay_federation(failed)
        return self.federation.mesh()

    async def _relay_federation(self, failed: list[tuple[Any, str]]) -> None:
        """Cloud-relay fallback: push our own snapshot and pull every
        other instance's through each cloud-enabled library, then mark
        peers that neither route reached as failed."""
        from ..cloud.api import CloudApiError

        clients = {
            lib.id: (lib.cloud_sync.client, lib.sync.instance)
            for lib in self.node.libraries.libraries.values()
            if getattr(lib, "cloud_sync", None) is not None
        }
        recovered: set[str] = set()
        if clients:
            snap = _wireable_snapshot(local_snapshot(self.node))
            for lib_id, (client, inst) in clients.items():
                try:
                    await client.push_telemetry(str(lib_id), str(inst), snap)
                    rows = await client.pull_telemetry(str(lib_id), str(inst))
                except (CloudApiError, OSError, asyncio.TimeoutError) as e:
                    logger.debug("relay federation via %s failed: %s",
                                 lib_id, e)
                    continue
                for row in rows:
                    remote = row.get("snapshot")
                    if not snapshot_compatible(remote):
                        continue
                    try:
                        inst_uuid = uuid.UUID(row["instance_uuid"])
                    except (KeyError, ValueError):
                        continue
                    peer = self.peer_for_instance(inst_uuid)
                    pid = (str(peer.identity) if peer is not None
                           else f"instance:{inst_uuid}")
                    self.federation.store(
                        pid, remote, transport="relay",
                        age_seconds=float(row.get("age_seconds", 0.0)),
                    )
                    recovered.add(pid)
        for peer, err in failed:
            pid = str(peer.identity)
            if pid not in recovered:
                self.federation.record_failure(pid, err)

    async def pull_remote_spans(
        self, trace_id: str,
    ) -> tuple[list[dict], dict[str, str]]:
        """Distributed-trace assembly (telemetry/attrib.py): pull every
        discovered peer's completed spans for ``trace_id``. Pulls run
        concurrently under the sync-plane resilience policy (per-peer
        breakers — a vanished peer costs one fast failure, never a
        blocked report). Returns ``(spans, failures)``: spans are
        tagged with the serving peer's short-hash label, failures map
        that label to the error string (the report's ``partial``
        evidence)."""
        from ..telemetry import metrics as _tm2
        from ..telemetry.peers import peer_label

        async def pull(peer: Any) -> tuple[str, list[dict] | None, str]:
            label = peer_label(str(peer.identity))
            try:
                spans = await SYNC_POLICY.call(
                    str(peer.identity),
                    lambda peer=peer: request_trace(
                        self.p2p, peer.identity, trace_id
                    ),
                )
                return label, spans, ""
            except (BreakerOpen, ConnectionError, OSError, EOFError,
                    asyncio.TimeoutError, PermissionError, ValueError) as e:
                return label, None, f"{type(e).__name__}: {e}"

        results = await asyncio.gather(
            *(pull(p) for p in self.p2p.discovered_peers())
        )
        spans: list[dict] = []
        failures: dict[str, str] = {}
        for label, got, err in results:
            if got is None:
                failures[label] = err[:200]
                _tm2.ATTRIB_PULL_FAILURES.inc()
                continue
            for rec in got:
                rec = dict(rec)
                rec["node"] = label
                spans.append(rec)
        return spans, failures

    async def pull_remote_profiles(
        self,
    ) -> tuple[dict[str, dict], dict[str, str]]:
        """Mesh profile view (``GET /profile?mesh=1`` / ``sdx
        profile``): pull every discovered peer's host-profile document
        concurrently under the sync-plane resilience policy — a
        vanished peer costs one fast recorded failure and a *partial*
        view, never a block (the trace_pull contract). Returns
        ``(profiles-by-peer-label, failures-by-peer-label)``."""
        from ..telemetry.peers import peer_label

        async def pull(peer: Any) -> tuple[str, dict | None, str]:
            label = peer_label(str(peer.identity))
            try:
                doc = await SYNC_POLICY.call(
                    str(peer.identity),
                    lambda peer=peer: request_profile(
                        self.p2p, peer.identity
                    ),
                )
                return label, doc, ""
            except (BreakerOpen, ConnectionError, OSError, EOFError,
                    asyncio.TimeoutError, PermissionError, ValueError) as e:
                return label, None, f"{type(e).__name__}: {e}"

        results = await asyncio.gather(
            *(pull(p) for p in self.p2p.discovered_peers())
        )
        profiles: dict[str, dict] = {}
        failures: dict[str, str] = {}
        for label, doc, err in results:
            if doc is None:
                failures[label] = err[:200]
            else:
                profiles[label] = doc
        return profiles, failures

    # --- inbound dispatch (ref:manager.rs stream handler) --------------

    def _serve_admit(self, key: str):
        """Sync-class admission for inbound P2P serving legs: counted on
        the gate (so operators see replication traffic riding the same
        budgets the read path does) but never queued or shed — the sync
        class is protected by policy. No-op without a serve runtime."""
        from ..serve import SYNC as _SYNC_CLASS, runtime_for

        serve = runtime_for(self.node)
        if serve is None:
            return contextlib.nullcontext()
        return serve.gate.admit(_SYNC_CLASS, key=key)

    async def _handle_stream(self, stream: Any) -> None:
        header = await Header.read(stream)
        P2P_EVENTS.emit(
            "stream_open",
            header=header.type.name,
            peer=str(getattr(stream, "remote_identity", "?")),
        )
        # join the initiator's trace when the header carried one — the
        # responder's spans (and any ingest work they cause) report
        # into the trace of the node that started the operation
        wire_ctx = _trace.TraceContext.from_wire(header.trace)
        with _trace.use(wire_ctx):
            await self._handle_stream_traced(stream, header, wire_ctx)

    async def _handle_stream_traced(
        self, stream: Any, header: Header,
        wire_ctx: "_trace.TraceContext | None",
    ) -> None:
        if header.type == HeaderType.PING:
            w = Writer(stream)
            w.u8(0xAA)
            await w.flush()
        elif header.type == HeaderType.SPACEDROP:
            with _span("p2p.spacedrop_receive"):
                await self.spacedrop.handle_inbound(stream, header.spacedrop)
        elif header.type == HeaderType.SYNC:
            if _faults.hit("p2p.sync_serve") is not None:
                await stream.close()  # peer "vanishes" before the ack
                return
            with _span("p2p.sync_notify"):
                w = Writer(stream)
                w.u8(0x01)
                await w.flush()
                # responder-side tenant accounting: which library's
                # sync traffic this node is serving (hashed label only)
                _tenants.observe("p2p_sync", header.library_id)
                actor = self.ingest_actors.get(header.library_id)
                if actor is not None:
                    actor.notify(trace_ctx=wire_ctx)
        elif header.type == HeaderType.SYNC_REQUEST:
            if _faults.hit("p2p.sync_serve") is not None:
                await stream.close()  # peer "vanishes" mid-exchange
                return
            lib = self.node.libraries.get(header.library_id)
            if lib is not None:
                _tenants.observe("p2p_sync", header.library_id)
                async with self._serve_admit("p2p.sync_serve"):
                    with _span("p2p.sync_serve"):
                        await respond_sync_request(stream, lib.sync)
        elif header.type == HeaderType.FILE:
            if self.node.is_feature_enabled(BackendFeature.FILES_OVER_P2P):
                await respond_file(stream, header.file, self.node.libraries)
            else:
                w = Writer(stream)
                w.u8(0).string("filesOverP2P disabled")
                await w.flush()
        elif header.type == HeaderType.TELEMETRY:
            # served to LIBRARY MEMBERS only: any LAN node can complete
            # a handshake, but the snapshot names libraries, watermarks,
            # and node metadata — the same trust bar the pairing flow
            # sets (FILE and RSPC gate behind features for the same
            # reason; membership is the natural gate for mesh health)
            if self._is_library_member(
                getattr(stream, "remote_identity", None)
            ):
                # TELEMETRY carries no library id — attribute the
                # responder work to the calling instance's identity
                _tenants.observe(
                    "p2p_telemetry",
                    getattr(stream, "remote_identity", None))
                op = (header.telemetry_op or {}).get("op")
                if op == "trace_pull":
                    if _faults.hit("p2p.trace_pull") is not None:
                        await stream.close()  # peer vanishes mid-pull
                        return
                    async with self._serve_admit("p2p.trace_serve"):
                        with _span("p2p.trace_serve"):
                            await respond_trace(
                                stream,
                                (header.telemetry_op or {}).get("trace_id"),
                            )
                elif op == "profile_pull":
                    if _faults.hit("p2p.profile_pull") is not None:
                        await stream.close()  # peer vanishes mid-pull
                        return
                    async with self._serve_admit("p2p.profile_serve"):
                        with _span("p2p.profile_serve"):
                            await respond_profile(stream)
                elif op not in (None, "snapshot"):
                    w = Writer(stream)
                    w.msgpack({"error": f"unknown TELEMETRY op {op!r}"})
                    await w.flush()
                else:
                    async with self._serve_admit("p2p.telemetry_serve"):
                        with _span("p2p.telemetry_serve"):
                            await respond_telemetry(stream, self.node)
            else:
                w = Writer(stream)
                w.msgpack(
                    {"error": "telemetry is served to library members only"}
                )
                await w.flush()
        elif header.type == HeaderType.WORK:
            # same trust bar as TELEMETRY but scoped to the NAMED
            # library: shard payloads carry that library's paths and
            # stat identities, and a claim hands out its work — strictly
            # members of that specific library
            if self._is_library_member(
                getattr(stream, "remote_identity", None),
                library_id=header.library_id,
            ):
                from .work import respond_work

                _tenants.observe("p2p_work", header.library_id)
                async with self._serve_admit("p2p.work_serve"):
                    with _span("p2p.work_serve"):
                        await respond_work(stream, self.node, header)
            else:
                w = Writer(stream)
                w.msgpack(
                    {"error": "the work plane is served to library "
                              "members only"}
                )
                await w.flush()
        elif header.type == HeaderType.RSPC:
            from .rspc import respond_rspc

            await respond_rspc(stream, self.node)
        elif header.type == HeaderType.PAIRING:
            await self.pairing.handle_inbound(stream)
        else:
            logger.warning("unhandled header type %s", header.type)

    async def shutdown(self) -> None:
        self._shutting_down = True
        for unsub in self._unsubs:
            unsub()
        self._unsubs.clear()
        if self._alert_tasks:
            # drain in-flight alerts (don't interrupt a half-sent one);
            # past the grace window they're cancelled. Our own
            # cancellation propagates out of asyncio.wait untouched.
            done, pending = await asyncio.wait(self._alert_tasks, timeout=5.0)
            for task in pending:
                task.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
            for task in done:
                if not task.cancelled() and (exc := task.exception()):
                    logger.warning("sync alert task died: %r", exc)
        self._alert_tasks.clear()
        await self.work.stop()
        for actor in self.ingest_actors.values():
            await actor.stop()
        self.ingest_actors.clear()
        await self.p2p.shutdown()
