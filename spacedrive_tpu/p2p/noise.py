"""Noise Protocol Framework — XX handshake over 25519/ChaChaPoly/SHA256.

Parity: ref:crates/p2p2/Cargo.toml pins a patched libp2p whose secure
channel is libp2p-noise (`Noise_XX_25519_ChaChaPoly_SHA256` plus a
signed identity payload).  This module implements the same, directly
from the public Noise specification (revision 34, noiseprotocol.org):

- ``CipherState`` (spec §5.1): ChaCha20-Poly1305 with the 64-bit
  little-endian counter nonce layout of spec §12.2.
- ``SymmetricState`` (spec §5.2): SHA256 hash chain ``h``, chaining key
  ``ck``, and the two-output HKDF of spec §4.3.
- ``HandshakeState`` (spec §5.3) specialised to the XX pattern
  (spec §7.5):  ``-> e``, ``<- e ee s es``, ``-> s se``.

The state machine is written token-for-token against the spec so it can
be checked against the published cacophony/snow vector corpus — the
test suite (tests/test_noise.py) validates structural spec invariants
(message sizes, hash agreement, HKDF composition) and, when a standard
``vectors.json`` in cacophony format is present at
``tests/data/noise_vectors.json``, replays every
``Noise_XX_25519_ChaChaPoly_SHA256`` vector byte-for-byte.  This build
environment has no network egress so the corpus is not bundled; the
``Vector hook`` below documents the exact expected format.

Identity binding follows the public libp2p-noise spec: each party's
handshake payload carries its ed25519 identity public key and a
signature over ``"noise-libp2p-static-key:" || x25519_static_pub``,
binding the long-lived identity to the Noise static key for this
session.  See docs/security.md for the full security argument.
"""

from __future__ import annotations

import hashlib
import hmac
import struct

try:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

    _HAVE_CRYPTOGRAPHY = True
except ImportError:  # gated: imports stay alive; channels refuse below
    serialization = X25519PrivateKey = X25519PublicKey = None  # type: ignore
    ChaCha20Poly1305 = None  # type: ignore
    _HAVE_CRYPTOGRAPHY = False

PROTOCOL_NAME = b"Noise_XX_25519_ChaChaPoly_SHA256"
DHLEN = 32
TAGLEN = 16
MAX_MESSAGE = 65535  # spec §3: a Noise transport message is <= 65535 bytes
MAX_PLAINTEXT = MAX_MESSAGE - TAGLEN

# libp2p-noise static-key-binding context (public libp2p spec, noise/README.md)
IDENTITY_CONTEXT = b"noise-libp2p-static-key:"


class NoiseError(Exception):
    pass


def require_crypto() -> None:
    """Encrypted channels hard-require the real `cryptography` AEADs —
    no pure-Python degradation for wire security. Raises where a
    handshake would otherwise start."""
    if not _HAVE_CRYPTOGRAPHY:
        raise NoiseError(
            "the `cryptography` package is required for Noise channels"
        )


def _hkdf(chaining_key: bytes, ikm: bytes, n: int) -> tuple[bytes, ...]:
    """Spec §4.3 HKDF: HMAC-SHA256 chain keyed by ck."""
    temp = hmac.new(chaining_key, ikm, hashlib.sha256).digest()
    out1 = hmac.new(temp, b"\x01", hashlib.sha256).digest()
    out2 = hmac.new(temp, out1 + b"\x02", hashlib.sha256).digest()
    if n == 2:
        return out1, out2
    out3 = hmac.new(temp, out2 + b"\x03", hashlib.sha256).digest()
    return out1, out2, out3


def _dh(priv: X25519PrivateKey, pub_raw: bytes) -> bytes:
    # ValueError covers both bad-length keys and the all-zero shared
    # secret rejection; surface both as protocol errors so transports
    # map them to a clean handshake failure.
    try:
        return priv.exchange(X25519PublicKey.from_public_bytes(pub_raw))
    except ValueError as exc:
        raise NoiseError("invalid DH public key") from exc


def _pub_raw(priv: X25519PrivateKey) -> bytes:
    return priv.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )


class CipherState:
    """Spec §5.1 — AEAD key + 64-bit nonce counter."""

    __slots__ = ("_k", "_n", "_aead")

    def __init__(self, k: bytes | None = None):
        self.initialize_key(k)

    def initialize_key(self, k: bytes | None) -> None:
        if k is not None:
            require_crypto()
        self._k = k
        self._aead = ChaCha20Poly1305(k) if k is not None else None
        self._n = 0

    def has_key(self) -> bool:
        return self._k is not None

    def _nonce(self) -> bytes:
        # spec §12.2: 32 zero bits then the counter as 64-bit little-endian
        if self._n >= 2**64 - 1:  # 2^64-1 reserved for rekey
            raise NoiseError("nonce exhausted")
        return struct.pack("<IQ", 0, self._n)

    def encrypt_with_ad(self, ad: bytes, plaintext: bytes) -> bytes:
        if self._aead is None:
            return plaintext
        ct = self._aead.encrypt(self._nonce(), plaintext, ad)
        self._n += 1
        return ct

    def decrypt_with_ad(self, ad: bytes, ciphertext: bytes) -> bytes:
        if self._aead is None:
            return ciphertext
        try:
            pt = self._aead.decrypt(self._nonce(), ciphertext, ad)
        except Exception as exc:  # InvalidTag — nonce NOT advanced (spec §5.1)
            raise NoiseError("decrypt failed") from exc
        self._n += 1
        return pt


class SymmetricState:
    """Spec §5.2 — ck/h chain shared by both handshake roles."""

    __slots__ = ("ck", "h", "cipher")

    def __init__(self, protocol_name: bytes = PROTOCOL_NAME):
        if len(protocol_name) <= 32:
            self.h = protocol_name.ljust(32, b"\x00")
        else:
            self.h = hashlib.sha256(protocol_name).digest()
        self.ck = self.h
        self.cipher = CipherState(None)

    def mix_key(self, ikm: bytes) -> None:
        self.ck, temp_k = _hkdf(self.ck, ikm, 2)
        self.cipher.initialize_key(temp_k)

    def mix_hash(self, data: bytes) -> None:
        self.h = hashlib.sha256(self.h + data).digest()

    def encrypt_and_hash(self, plaintext: bytes) -> bytes:
        ct = self.cipher.encrypt_with_ad(self.h, plaintext)
        self.mix_hash(ct)
        return ct

    def decrypt_and_hash(self, ciphertext: bytes) -> bytes:
        pt = self.cipher.decrypt_with_ad(self.h, ciphertext)
        self.mix_hash(ciphertext)
        return pt

    def split(self) -> tuple[CipherState, CipherState]:
        k1, k2 = _hkdf(self.ck, b"", 2)
        return CipherState(k1), CipherState(k2)


class HandshakeState:
    """Spec §5.3 restricted to the XX pattern (§7.5):

        XX:
          -> e
          <- e, ee, s, es
          -> s, se

    Construct with ``initiator=True/False`` and a static X25519 key;
    drive with alternating write_message()/read_message() calls.  After
    the third message both sides expose ``split()`` and
    ``handshake_hash`` (channel binding, spec §11.2) and
    ``remote_static`` (the peer's Noise static public key).
    """

    _XX = (("e",), ("e", "ee", "s", "es"), ("s", "se"))

    def __init__(
        self,
        initiator: bool,
        s: X25519PrivateKey,
        prologue: bytes = b"",
        e: X25519PrivateKey | None = None,
        protocol_name: bytes = PROTOCOL_NAME,
    ):
        require_crypto()
        self.initiator = initiator
        self.ss = SymmetricState(protocol_name)
        self.ss.mix_hash(prologue)
        self.s = s
        self.e = e  # injectable for vector replay; generated lazily
        self.rs: bytes | None = None
        self.re: bytes | None = None
        self._msg_idx = 0
        self._finished = False

    # --- token helpers ---

    def _mix_dh(self, token: str) -> None:
        # es = DH(initiator e, responder s); se = DH(initiator s, responder e)
        if token == "ee":
            self.ss.mix_key(_dh(self.e, self.re))
        elif token == "es":
            key = _dh(self.e, self.rs) if self.initiator else _dh(self.s, self.re)
            self.ss.mix_key(key)
        elif token == "se":
            key = _dh(self.s, self.re) if self.initiator else _dh(self.e, self.rs)
            self.ss.mix_key(key)
        else:  # pragma: no cover
            raise NoiseError(f"unknown DH token {token}")

    def _my_turn_to_write(self) -> bool:
        return (self._msg_idx % 2 == 0) == self.initiator

    # --- message processing (spec §5.3 WriteMessage/ReadMessage) ---

    def write_message(self, payload: bytes = b"") -> bytes:
        if self._finished or not self._my_turn_to_write():
            raise NoiseError("out-of-order write_message")
        out = bytearray()
        for token in self._XX[self._msg_idx]:
            if token == "e":
                if self.e is None:
                    self.e = X25519PrivateKey.generate()
                e_pub = _pub_raw(self.e)
                out += e_pub
                self.ss.mix_hash(e_pub)
            elif token == "s":
                out += self.ss.encrypt_and_hash(_pub_raw(self.s))
            else:
                self._mix_dh(token)
        out += self.ss.encrypt_and_hash(payload)
        self._advance()
        return bytes(out)

    def read_message(self, message: bytes) -> bytes:
        if self._finished or self._my_turn_to_write():
            raise NoiseError("out-of-order read_message")
        buf = memoryview(message)
        for token in self._XX[self._msg_idx]:
            if token == "e":
                if len(buf) < DHLEN:
                    raise NoiseError("truncated handshake message")
                self.re = bytes(buf[:DHLEN])
                buf = buf[DHLEN:]
                self.ss.mix_hash(self.re)
            elif token == "s":
                n = DHLEN + (TAGLEN if self.ss.cipher.has_key() else 0)
                if len(buf) < n:
                    raise NoiseError("truncated handshake message")
                self.rs = self.ss.decrypt_and_hash(bytes(buf[:n]))
                buf = buf[n:]
            else:
                self._mix_dh(token)
        payload = self.ss.decrypt_and_hash(bytes(buf))
        self._advance()
        return payload

    def _advance(self) -> None:
        self._msg_idx += 1
        if self._msg_idx == len(self._XX):
            self._finished = True

    # --- post-handshake ---

    @property
    def local_static_pub(self) -> bytes:
        return _pub_raw(self.s)

    @property
    def finished(self) -> bool:
        return self._finished

    @property
    def handshake_hash(self) -> bytes:
        if not self._finished:
            raise NoiseError("handshake not finished")
        return self.ss.h

    def split(self) -> tuple[CipherState, CipherState]:
        """Returns (initiator→responder, responder→initiator) ciphers
        regardless of this side's role (spec §5.3: Split() ordering is
        fixed; callers pick send/recv by role)."""
        if not self._finished:
            raise NoiseError("handshake not finished")
        return self.ss.split()


# --- libp2p-noise style identity payload -----------------------------------


def identity_payload(identity, noise_static_pub: bytes) -> bytes:
    """``identity_pub(32) || sig(64)`` where sig covers the libp2p
    static-key-binding context string plus this session's Noise static
    key (public libp2p noise spec)."""
    sig = identity.sign(IDENTITY_CONTEXT + noise_static_pub)
    return identity.to_remote_identity().to_bytes() + sig


def verify_identity_payload(payload: bytes, noise_static_pub: bytes):
    """Returns the authenticated RemoteIdentity or raises NoiseError."""
    from .identity import RemoteIdentity

    if len(payload) != 96:
        raise NoiseError("malformed identity payload")
    ident = RemoteIdentity(payload[:32])
    if not ident.verify(payload[32:], IDENTITY_CONTEXT + noise_static_pub):
        raise NoiseError("identity signature invalid")
    return ident
