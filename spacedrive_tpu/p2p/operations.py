"""P2P operations — ping, Spacedrop, request_file.

Parity: ref:core/src/p2p/operations/{ping.rs,spacedrop.rs,request_file.rs}.
Spacedrop keeps the reference's flow (spacedrop.rs:28-203): sender
opens a stream, writes `Header::Spacedrop(requests)`, then blocks on a
single accept(1)/reject(0) byte driven by the remote user's dialog
(frontend subscribes via the event bus and resolves through
`accept_spacedrop`/`reject_spacedrop`); on accept the Spaceblock
transfer runs. `request_file` streams one file range out of a library
by `file_path` pub_id (request_file.rs:29-102).
"""

from __future__ import annotations

import asyncio
import io
import os
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

from ..telemetry import trace as _trace
from ..utils.resilience import FAIL, PASS, ResiliencePolicy, RetryPolicy
from .block import BlockSize, Range, SpaceblockRequest, SpaceblockRequests, Transfer
from .identity import RemoteIdentity
from .protocol import FileRequest, Header, HeaderType
from .wire import Reader, Writer

SPACEDROP_TIMEOUT = 60.0  # ref:spacedrop.rs user-decision timeout

# Connection-establishment leg only: once the remote user's dialog is
# in play, retrying would re-prompt them — the transfer itself stays
# single-shot. The breaker keeps repeated sends to a gone peer cheap.
SPACEDROP_POLICY = ResiliencePolicy(
    "spacedrop",
    RetryPolicy(max_attempts=3, base_delay=0.1, max_delay=1.0,
                attempt_timeout=15.0),
    failure_threshold=3,
    reset_timeout=15.0,
)

def _file_classify(exc: BaseException) -> str:
    """A peer that ANSWERED — file not found, refusal — is healthy;
    only transport failures may feed the breaker (otherwise three
    honest not-founds would block files the peer DOES have)."""
    if isinstance(exc, (FileNotFoundError, PermissionError, ValueError)):
        return PASS
    return FAIL  # single-shot policy: count it, never re-run the body


# Remote-file streaming stays SINGLE-shot (a retry mid-transfer would
# duplicate bytes already written into the caller's sink) and UNBOUNDED
# in duration (a 10 GB pull over a slow link is legitimate; the old
# direct call had no deadline either) — the policy contributes only the
# per-peer breaker, so an explorer browse against a gone peer
# fast-fails once instead of paying a dial timeout per row.
FILE_POLICY = ResiliencePolicy(
    "p2p_file",
    RetryPolicy(max_attempts=1, base_delay=0.05, max_delay=0.1,
                attempt_timeout=None),
    failure_threshold=3,
    reset_timeout=15.0,
    classify=_file_classify,
)


async def ping(p2p: Any, identity: RemoteIdentity) -> float:
    """Round-trip a Ping header (ref:operations/ping.rs)."""
    import time

    stream = await p2p.new_stream(identity)
    try:
        t0 = time.monotonic()
        await Header(HeaderType.PING).write(stream)
        pong = await Reader(stream).u8()
        if pong != 0xAA:
            raise ValueError("bad pong")
        return time.monotonic() - t0
    finally:
        await stream.close()


@dataclass
class SpacedropRequest:
    """An inbound offer pending user decision (ref:spacedrop.rs:160-203)."""

    id: uuid.UUID
    peer: RemoteIdentity
    files: list[str]
    total_size: int
    _decision: asyncio.Future = field(repr=False, default=None)  # type: ignore[assignment]


class SpacedropManager:
    """Hangs off P2PManager: outbound sends + inbound accept/reject map
    keyed by request id (ref:spacedrop.rs `spacedrop_pairing_reqs`)."""

    def __init__(self, p2p: Any, event_bus: Any = None, save_dir: str | None = None):
        self.p2p = p2p
        self.event_bus = event_bus
        self.save_dir = save_dir or os.path.expanduser("~/Downloads")
        self.pending: dict[uuid.UUID, SpacedropRequest] = {}
        self.progress: dict[uuid.UUID, int] = {}
        self._cancel: dict[uuid.UUID, asyncio.Event] = {}

    # --- outbound (ref:spacedrop.rs:28-110) ---

    async def send(self, identity: RemoteIdentity, paths: list[str]) -> uuid.UUID:
        sizes = [os.path.getsize(p) for p in paths]
        requests = SpaceblockRequests(
            id=uuid.uuid4(),
            block_size=BlockSize.from_file_size(max(sizes, default=0)),
            requests=[
                SpaceblockRequest(name=os.path.basename(p), size=s)
                for p, s in zip(paths, sizes)
            ],
        )
        stream = await SPACEDROP_POLICY.call(
            str(identity), lambda: self.p2p.new_stream(identity)
        )
        cancel = asyncio.Event()
        self._cancel[requests.id] = cancel
        try:
            await Header(
                HeaderType.SPACEDROP, spacedrop=requests,
                trace=_trace.wire_current(),
            ).write(stream)
            decision = await asyncio.wait_for(
                Reader(stream).u8(), SPACEDROP_TIMEOUT
            )
            if decision != 1:
                raise PermissionError("spacedrop rejected by peer")
            transfer = Transfer(
                requests,
                on_progress=lambda pct: self._on_progress(requests.id, pct),
                cancelled=cancel,
            )
            files: list = []
            try:
                # opened inside the try: a failing open midway must not
                # leak the handles already opened
                for p in paths:
                    files.append(await asyncio.to_thread(open, p, "rb"))
                await transfer.send(stream, files)
            finally:
                for f in files:
                    f.close()
            return requests.id
        finally:
            self._cancel.pop(requests.id, None)
            await stream.close()

    def _on_progress(self, drop_id: uuid.UUID, pct: int) -> None:
        self.progress[drop_id] = pct
        if self.event_bus is not None:
            self.event_bus.emit(("SpacedropProgress", drop_id, pct))

    def cancel(self, drop_id: uuid.UUID) -> None:
        ev = self._cancel.get(drop_id)
        if ev is not None:
            ev.set()

    # --- inbound (ref:spacedrop.rs:160-203 `receiver`) ---

    async def handle_inbound(self, stream: Any, requests: SpaceblockRequests) -> None:
        loop = asyncio.get_running_loop()
        req = SpacedropRequest(
            id=requests.id,
            peer=stream.remote_identity,
            files=[r.name for r in requests.requests],
            total_size=requests.total_size,
            _decision=loop.create_future(),
        )
        self.pending[req.id] = req
        if self.event_bus is not None:
            self.event_bus.emit(("SpacedropRequest", req))
        w = Writer(stream)
        try:
            dest = await asyncio.wait_for(req._decision, SPACEDROP_TIMEOUT)
        except asyncio.TimeoutError:
            dest = None
        finally:
            self.pending.pop(req.id, None)
        if dest is None:
            w.u8(0)
            await w.flush()
            return
        w.u8(1)
        await w.flush()
        os.makedirs(dest, exist_ok=True)
        cancel = asyncio.Event()
        self._cancel[req.id] = cancel
        transfer = Transfer(
            requests,
            on_progress=lambda pct: self._on_progress(req.id, pct),
            cancelled=cancel,
        )
        sinks: list = []
        try:
            # opened inside the try: a failing open midway must not leak
            # the handles already opened
            for r in requests.requests:
                sinks.append(await asyncio.to_thread(
                    open, os.path.join(dest, os.path.basename(r.name)), "wb"
                ))
            await transfer.receive(stream, sinks)
        finally:
            self._cancel.pop(req.id, None)
            for s in sinks:
                s.close()

    def accept(self, drop_id: uuid.UUID, dest_dir: str | None = None) -> bool:
        """rspc `p2p.acceptSpacedrop` with a target dir (ref:spacedrop.rs)."""
        req = self.pending.get(drop_id)
        if req is None or req._decision.done():
            return False
        req._decision.set_result(dest_dir or self.save_dir)
        return True

    def reject(self, drop_id: uuid.UUID) -> bool:
        req = self.pending.get(drop_id)
        if req is None or req._decision.done():
            return False
        req._decision.set_result(None)
        return True


TELEMETRY_TIMEOUT = 10.0


async def request_telemetry(p2p: Any, identity: RemoteIdentity) -> dict:
    """Pull a peer's compact telemetry snapshot (the federation wire
    request; see telemetry/federation.py). The responder builds the
    snapshot on its side — nothing secret rides it — and this side
    validates the version before trusting the shape."""
    from ..telemetry.federation import snapshot_compatible
    from ..utils.compat import timeout

    stream = await p2p.new_stream(identity)
    try:
        async with timeout(TELEMETRY_TIMEOUT):
            await Header(
                HeaderType.TELEMETRY, trace=_trace.wire_current()
            ).write(stream)
            snap = await Reader(stream).msgpack()
    finally:
        await stream.close()
    if isinstance(snap, dict) and "v" not in snap and snap.get("error"):
        # the responder refused (e.g. we are not a library member there)
        raise PermissionError(str(snap["error"]))
    if not snapshot_compatible(snap):
        raise ValueError(
            f"peer served an incompatible telemetry snapshot "
            f"(v={snap.get('v') if isinstance(snap, dict) else '?'})"
        )
    return snap


async def respond_telemetry(stream: Any, node: Any) -> None:
    """Server half: serve this node's snapshot. The snapshot is built
    by the owning node (metrics values, health verdicts, ring digests
    — no ring payloads), so nothing needing redaction crosses here."""
    from ..telemetry.federation import local_snapshot

    w = Writer(stream)
    w.msgpack(_wireable_snapshot(local_snapshot(node)))
    await w.flush()


#: spans shipped per trace_pull response — a full trace ring is 4096
#: records; one pass's share is far smaller, and the cap bounds what a
#: member can make us serialize per exchange
TRACE_PULL_MAX_SPANS = 2048


async def request_trace(p2p: Any, identity: RemoteIdentity,
                        trace_id: str) -> list[dict]:
    """Pull a peer's completed spans for one distributed trace (the
    ``trace_pull`` TELEMETRY op — critical-path attribution assembly,
    telemetry/attrib.py). Raises ``PermissionError`` on a membership
    refusal, ``ValueError`` on a malformed response — both PASS through
    the caller's resilience policy without feeding the breaker."""
    from ..utils.compat import timeout

    stream = await p2p.new_stream(identity)
    try:
        async with timeout(TELEMETRY_TIMEOUT):
            await Header(
                HeaderType.TELEMETRY, trace=_trace.wire_current(),
                telemetry_op={"op": "trace_pull", "trace_id": str(trace_id)},
            ).write(stream)
            resp = await Reader(stream).msgpack()
    finally:
        await stream.close()
    if isinstance(resp, dict) and resp.get("error"):
        raise PermissionError(str(resp["error"]))
    if not isinstance(resp, dict) or not isinstance(resp.get("spans"), list):
        raise ValueError("peer served a malformed trace_pull response")
    return [s for s in resp["spans"] if isinstance(s, dict)]


async def respond_trace(stream: Any, trace_id: Any) -> None:
    """Server half of ``trace_pull``: this node's span records for one
    trace id, straight off the trace ring (bounded). Span records carry
    stages, ids, and timings — no payloads, paths, or secrets — so
    nothing needing redaction crosses here."""
    from ..telemetry import trace as _trace_mod

    w = Writer(stream)
    if not isinstance(trace_id, str) or not trace_id:
        w.msgpack({"error": "trace_pull requires a trace_id"})
        await w.flush()
        return
    spans = _trace_mod.recent(trace_id)[-TRACE_PULL_MAX_SPANS:]
    w.msgpack({"spans": _wireable_snapshot(spans)})
    await w.flush()


async def request_profile(p2p: Any, identity: RemoteIdentity) -> dict:
    """Pull a peer's host-profile document + folded collapsed-stack
    text (the ``profile_pull`` TELEMETRY op — ``sdx profile --peer``
    and the mesh-profile view). Raises ``PermissionError`` on a
    membership refusal, ``ValueError`` on a malformed response — both
    PASS through the caller's resilience policy without feeding the
    breaker."""
    from ..utils.compat import timeout

    stream = await p2p.new_stream(identity)
    try:
        async with timeout(TELEMETRY_TIMEOUT):
            await Header(
                HeaderType.TELEMETRY, trace=_trace.wire_current(),
                telemetry_op={"op": "profile_pull"},
            ).write(stream)
            resp = await Reader(stream).msgpack()
    finally:
        await stream.close()
    if isinstance(resp, dict) and resp.get("error"):
        raise PermissionError(str(resp["error"]))
    if not isinstance(resp, dict) or not isinstance(resp.get("profile"),
                                                    dict):
        raise ValueError("peer served a malformed profile_pull response")
    return resp


async def respond_profile(stream: Any) -> None:
    """Server half of ``profile_pull``: this node's profile document
    and bounded folded text. Frame names are ``module:function`` only
    (sampler.fold_stack strips paths), so nothing needing redaction
    crosses here — the same contract trace_pull makes for spans."""
    from ..telemetry import sampler as _sampler

    w = Writer(stream)
    w.msgpack(_wireable_snapshot({
        "profile": _sampler.SAMPLER.profile(),
        "folded": _sampler.SAMPLER.folded(max_bytes=128 * 1024),
    }))
    await w.flush()


def _wireable_snapshot(obj: Any) -> Any:
    """msgpack-encodable projection (floats/str/ints pass, odd leaves
    stringify) — snapshots must never fail to serialize."""
    if isinstance(obj, dict):
        return {str(k): _wireable_snapshot(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_wireable_snapshot(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


async def request_file(
    p2p: Any,
    identity: RemoteIdentity,
    library_id: uuid.UUID,
    file_path_pub_id: uuid.UUID,
    sink: io.RawIOBase | Any,
    range: Range | None = None,
) -> int:
    """Pull one file (range) from a remote library
    (ref:operations/request_file.rs:29-102)."""
    rng = range or Range()
    stream = await p2p.new_stream(identity)
    try:
        await Header(
            HeaderType.FILE,
            file=FileRequest(library_id, file_path_pub_id, rng),
        ).write(stream)
        r = Reader(stream)
        ok = await r.u8()
        if ok != 1:
            err = await r.string()
            raise FileNotFoundError(err)
        size = await r.u64()
        block_size = BlockSize.dangerously_new(await r.u32())
        requests = SpaceblockRequests(
            id=uuid.uuid4(),
            block_size=block_size,
            requests=[SpaceblockRequest(name="file", size=size, range=rng)],
        )
        await Transfer(requests).receive(stream, [sink])
        return size
    finally:
        await stream.close()


async def respond_file(stream: Any, req: FileRequest, libraries: Any) -> None:
    """Server half of `request_file` (ref:request_file.rs receiver)."""
    w = Writer(stream)
    lib = libraries.get(req.library_id)
    row = None
    if lib is not None:
        row = lib.db.find_one("file_path", pub_id=req.file_path_pub_id.bytes)
    path = None
    if row is not None:
        from ..files.isolated_path import full_path_from_db_row

        loc = lib.db.find_one("location", id=row["location_id"])
        if loc is not None:
            path = full_path_from_db_row(loc["path"], row)
    if path is None or not os.path.isfile(path):
        w.u8(0).string("file not found")
        await w.flush()
        return
    size = os.path.getsize(path)
    bs = BlockSize.from_file_size(size)
    w.u8(1).u64(size).u32(bs.size)
    await w.flush()
    requests = SpaceblockRequests(
        id=uuid.uuid4(),
        block_size=bs,
        requests=[SpaceblockRequest(name="file", size=size, range=req.range)],
    )
    fh = await asyncio.to_thread(open, path, "rb")
    try:
        await Transfer(requests).send(stream, [fh])
    finally:
        fh.close()
