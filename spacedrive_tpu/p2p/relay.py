"""WAN P2P via a relay — rendezvous + dumb byte pipe.

Parity: the reference reaches non-LAN peers through relayed libp2p
streams with hole punching layered on top
(ref:crates/p2p2/src/quic/transport.rs:212,344 `Control::
open_stream_with_addrs` over the patched libp2p relay). Here the cloud
relay (cloud/relay.py) doubles as the rendezvous: nodes hold a control
connection (`listen`), dialers ask the relay to splice a fresh TCP pair
(`dial` ↔ `accept`), and from then on the relay copies bytes blindly —
the normal Noise-style handshake (p2p/transport.py) runs END-TO-END
through the pipe, so the relay can neither read nor impersonate
(circuit-v2's trust model).

Control protocol (4-byte BE length + JSON). Registering an identity
requires proving possession of its ed25519 key (challenge signature),
or any client could hijack a victim's relayed reachability and spoof
its metadata:
  node → relay   {"cmd":"listen","identity":b58,"meta":{…}}
  relay → node   {"challenge":hex}
  node → relay   {"sig":hex}                  → {"ok":true}
  node → relay   {"cmd":"query"}              → {"peers":[{identity,meta}]}
  node → relay   {"cmd":"ping"}               → {"ok":true}
  relay → node   {"event":"incoming","conn":tok}
  dialer → relay {"cmd":"dial","target":b58}  → {"ok":true} then raw pipe
  node → relay   {"cmd":"accept","conn":tok}  → {"ok":true} then raw pipe
  any → relay    {"cmd":"stats"}              → {"ok":true,"stats":{…}}

Hole-punch coordination (DCUtR's role, see punch.py) rides the SAME
authenticated listener channels, so observed addresses are only ever
disclosed to registered identities; routing is stateless (the dialer
mints `conn` and both messages carry the routing target):
  A → relay      {"cmd":"punch","conn":tok,"target":B,"token":obs}
  relay → B      {"event":"punch","conn":tok,"from":A,"addr":[h,p]}
  B → relay      {"cmd":"punch_ack","conn":tok,"target":A,"token":obs}
  relay → A      {"event":"punch_addr","conn":tok,"ok":true,"addr":[h,p]}
The relay answers STUN-style observe datagrams on its UDP port
(advertised as `udp_port` in the listen OK) and REMEMBERS each observe
token → source address briefly; punch messages carry the token, and the
relay substitutes the address IT WITNESSED. Peers therefore can only
ever direct each other's probes at a UDP socket the claimant actually
controls — never at an arbitrary third party. Residual disclosure (any
registered identity can learn a peer's NAT mapping by asking) matches
the reference's posture, where libp2p identify/DCUtR exchange observed
addresses with any connected peer; nodes can opt out with punch=False.
`tok` is an unguessable 128-bit token known only to the listener the
incoming event was sent to, so a third party cannot race the accept.

Resource accounting (libp2p circuit-v2's relay limits play this role in
the reference): per-target pipe caps, a global pipe cap, and an optional
per-pipe-direction byte-rate cap enforced in the splice loop, so one
greedy peer can neither hoard all pipes nor saturate the relay's
bandwidth and starve other pipes. Counters ride the `stats` command and
`sdx relay` logs them.
Dialing needs no relay-level auth: the end-to-end handshake pins the
expected identity, so a misrouted pipe just fails to authenticate.
"""

from __future__ import annotations

import asyncio
import json
import logging
import secrets
import struct
import time
from dataclasses import dataclass
from typing import Any, Awaitable, Callable

from ..utils.tasks import supervise
from .identity import Identity, RemoteIdentity
from .transport import EncryptedStream, _client_handshake, _server_handshake

logger = logging.getLogger(__name__)

MAX_FRAME = 64 * 1024
PIPE_CHUNK = 64 * 1024
DIAL_TIMEOUT = 15.0
# protocol contract: clients must send SOMETHING on the control socket
# at least every CONTROL_IDLE_TIMEOUT seconds (their query loop does);
# the server evicts silent listeners as half-open after that
CONTROL_IDLE_TIMEOUT = 120.0
_LISTEN_CONTEXT = b"sd-relay-listen-v1"
# inbound punch-accept caps (client side): concurrent accepts, and
# accepts per source identity per sliding window
PUNCH_ACCEPT_MAX = 4
PUNCH_ACCEPT_PER_SOURCE = 4
PUNCH_ACCEPT_WINDOW = 30.0


async def read_frame(reader: asyncio.StreamReader) -> dict[str, Any]:
    hdr = await reader.readexactly(4)
    (length,) = struct.unpack(">I", hdr)
    if length > MAX_FRAME:
        raise ValueError("oversized control frame")
    return json.loads(await reader.readexactly(length))


def write_frame(writer: asyncio.StreamWriter, msg: dict[str, Any]) -> None:
    data = json.dumps(msg).encode()
    writer.write(struct.pack(">I", len(data)) + data)


class _SlidingWindow:
    """Per-identity sliding-window rate limiter with bounded memory:
    identities whose whole window expired are pruned once the table
    reaches `max_idents`."""

    def __init__(self, limit: int, window: float, max_idents: int = 1024):
        self.limit = limit
        self.window = window
        self.max_idents = max_idents
        self._times: dict[str, list[float]] = {}

    def allow(self, ident: str) -> bool:
        now = time.monotonic()
        recent = [t for t in self._times.get(ident, [])
                  if now - t < self.window]
        if len(recent) >= self.limit:
            self._times[ident] = recent
            return False
        recent.append(now)
        self._times[ident] = recent
        if len(self._times) > self.max_idents:
            # hard cap: keypairs are free to mint, so expiry alone
            # can't bound the table — evict the stalest identities
            # (oldest last-seen) down to the cap
            for stale in sorted(
                    self._times, key=lambda i: self._times[i][-1]
            )[: len(self._times) - self.max_idents]:
                del self._times[stale]
        return True


@dataclass
class RelayLimits:
    """Resource caps for a deployed relay (circuit-v2's role). `None`
    rate = unlimited; pipes caps always apply."""
    max_pipes_per_target: int = 8
    max_pipes_total: int = 256
    pipe_rate_bytes_per_s: int | None = None
    # punch coordination is cheap for the relay but triggers ~5 s of
    # socket-binding observe+probe work at the TARGET — rate-limit it
    # per authenticated source so one keypair can't spray a victim
    punch_per_source_per_minute: int = 12


@dataclass
class RelayStats:
    pipes_opened: int = 0
    pipes_active: int = 0
    pipes_refused_target_cap: int = 0
    pipes_refused_total_cap: int = 0
    bytes_relayed: int = 0
    listener_evictions: int = 0
    punches_refused_rate: int = 0

    def snapshot(self) -> dict[str, int]:
        return {k: int(v) for k, v in self.__dict__.items()}


async def _splice(a_r, a_w, b_r, b_w, stats: RelayStats | None = None,
                  rate: int | None = None) -> None:
    """Copy bytes both ways until either side closes. `rate` caps each
    DIRECTION with a token bucket (burst = 1 s of budget) so one
    saturating pipe cannot monopolize the relay's uplink; accounting
    lands in `stats`."""

    async def pump(r, w):
        allowance = float(rate) if rate else 0.0
        last = time.monotonic()
        try:
            while True:
                chunk = await r.read(PIPE_CHUNK)
                if not chunk:
                    break
                if rate:
                    now = time.monotonic()
                    allowance = min(float(rate), allowance + (now - last) * rate)
                    last = now
                    allowance -= len(chunk)
                    if allowance < 0:
                        await asyncio.sleep(-allowance / rate)
                w.write(chunk)
                await w.drain()
                if stats is not None:
                    stats.bytes_relayed += len(chunk)
        except (ConnectionError, asyncio.CancelledError, OSError):
            pass
        finally:
            try:
                w.close()
            except Exception:
                pass

    await asyncio.gather(pump(a_r, b_w), pump(b_r, a_w))


class RelayServer:
    """The rendezvous half that rides on the cloud relay process."""

    def __init__(self, limits: RelayLimits | None = None) -> None:
        self.limits = limits or RelayLimits()
        self.stats = RelayStats()
        # caps are enforced on RESERVATIONS (made at dial time, before
        # any listener work is queued), not on active splices — else a
        # burst of concurrent dials all passes the check before the
        # first accept lands and the caps do nothing (TOCTOU)
        self._reserved_total = 0
        self._reserved_by_target: dict[str, int] = {}
        self._pipes: set[asyncio.StreamWriter] = set()  # active splice ends
        self._listeners: dict[str, asyncio.StreamWriter] = {}
        self._meta: dict[str, dict[str, Any]] = {}
        # conn ids are unguessable tokens: the accept claim arrives on a
        # fresh TCP connection, so a guessable id would let any client
        # race the legitimate listener and steal the pending pipe
        # (killing the dial — availability, not confidentiality, since
        # the end-to-end handshake still prevents impersonation)
        # conn → (dial reader, dial writer, accepted future, target)
        self._pending: dict[str, tuple[asyncio.StreamReader, asyncio.StreamWriter,
                                       "asyncio.Future[None]", str]] = {}
        self._server: asyncio.base_events.Server | None = None
        self.port: int | None = None
        self._udp: "UdpEndpoint | None" = None
        self.udp_port: int | None = None
        # observe token → (witnessed addr, monotonic time); punch
        # routing resolves addrs from here so they are relay-verified
        self._observed: dict[str, tuple[tuple[str, int], float]] = {}
        # authenticated source identity → recent punch-request times
        # (sliding minute window, see RelayLimits.punch_per_source_per_minute)
        self._punch_rate = _SlidingWindow(
            self.limits.punch_per_source_per_minute, 60.0)

    def _punch_rate_ok(self, ident: str) -> bool:
        if not self._punch_rate.allow(ident):
            self.stats.punches_refused_rate += 1
            return False
        return True

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._handle, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        # STUN-style observe endpoint for hole punching (punch.py)
        from .punch import OBSERVE_MAGIC, observe_reply
        from .udp import UdpEndpoint

        self._udp = UdpEndpoint()
        _, self.udp_port = await self._udp.bind(host, 0)

        def on_dgram(data: bytes, addr: tuple[str, int]) -> None:
            if not data.startswith(OBSERVE_MAGIC):
                return
            try:
                token = json.loads(data[len(OBSERVE_MAGIC):]).get("token")
            except ValueError:
                return
            if isinstance(token, str) and len(token) <= 64 \
                    and self._udp is not None:
                now = time.monotonic()
                if len(self._observed) >= 4096:  # bounded: evict stale
                    self._observed = {
                        t: v for t, v in self._observed.items()
                        if now - v[1] < 60.0
                    }
                if len(self._observed) < 4096:
                    self._observed[token] = (tuple(addr), now)
                self._udp.sendto(observe_reply(token, addr), addr)

        self._udp.set_receiver(on_dgram)
        return self.port

    async def shutdown(self) -> None:
        if self._udp is not None:
            self._udp.close()
            self._udp = None
        # close the control connections FIRST: on Python 3.12+
        # Server.wait_closed() blocks until every connection handler
        # returns, and listener handlers loop until their socket dies
        for w in list(self._listeners.values()):
            w.close()
        self._listeners.clear()
        for _r, w, fut, _t in self._pending.values():
            if not fut.done():
                fut.cancel()
            w.close()
        self._pending.clear()
        # force-close active splices: their handlers must return or
        # (3.12+) Server.wait_closed() below blocks forever
        for w in list(self._pipes):
            w.close()
        self._pipes.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            msg = await asyncio.wait_for(read_frame(reader), 30)
        except Exception:
            writer.close()
            return
        cmd = msg.get("cmd")
        try:
            if cmd == "listen":
                await self._serve_listener(reader, writer, msg)
            elif cmd == "dial":
                await self._serve_dial(reader, writer, msg)
            elif cmd == "accept":
                await self._serve_accept(reader, writer, msg)
            elif cmd == "stats":
                write_frame(writer, {"ok": True, "stats": self.stats.snapshot(),
                                     "listeners": len(self._listeners)})
                await writer.drain()
                writer.close()
            else:
                write_frame(writer, {"ok": False, "error": "unknown cmd"})
                writer.close()
        except (ConnectionError, asyncio.IncompleteReadError, OSError,
                asyncio.TimeoutError):
            writer.close()

    async def _serve_listener(self, reader, writer, msg) -> None:
        ident = msg["identity"]
        # challenge-response: only the holder of the ed25519 key may
        # register (and keep re-registering metadata for) an identity
        try:
            pub = RemoteIdentity.from_str(ident)
        except Exception:
            write_frame(writer, {"ok": False, "error": "bad identity"})
            await writer.drain()
            writer.close()
            return
        nonce = secrets.token_bytes(32)
        write_frame(writer, {"challenge": nonce.hex()})
        await writer.drain()
        answer = await asyncio.wait_for(read_frame(reader), 30)
        sig = bytes.fromhex(answer.get("sig", ""))
        if not pub.verify(sig, _LISTEN_CONTEXT + nonce):
            write_frame(writer, {"ok": False, "error": "auth failed"})
            await writer.drain()
            writer.close()
            return
        old = self._listeners.get(ident)
        if old is not None and old is not writer:
            old.close()  # the authenticated newcomer supersedes
        self._listeners[ident] = writer
        self._meta[ident] = msg.get("meta", {})
        write_frame(writer, {"ok": True, "udp_port": self.udp_port})
        await writer.drain()
        try:
            while True:
                # a control connection silent past the contract window
                # is half-open — evict the ghost listener
                try:
                    req = await asyncio.wait_for(read_frame(reader),
                                                 CONTROL_IDLE_TIMEOUT)
                except asyncio.TimeoutError:
                    self.stats.listener_evictions += 1
                    raise
                c = req.get("cmd")
                if c == "query":
                    write_frame(writer, {"event": "peers", "peers": [
                        {"identity": i, "meta": m}
                        for i, m in self._meta.items() if i != ident
                    ]})
                elif c == "listen":  # metadata refresh
                    self._meta[ident] = req.get("meta", {})
                    write_frame(writer, {"ok": True})
                elif c == "ping":
                    write_frame(writer, {"ok": True})
                elif c == "punch":
                    # `from` is OUR authenticated ident, never claimed;
                    # the addr is the one the relay WITNESSED for the
                    # carried observe token — senders cannot point
                    # probes at third parties
                    if not self._punch_rate_ok(ident):
                        # refused BEFORE consuming the one-shot observe
                        # token or touching the target: an explicit error
                        # so the dialer falls back to the relayed pipe
                        # immediately instead of timing out
                        write_frame(writer, {
                            "event": "punch_addr",
                            "conn": req.get("conn"), "ok": False,
                            "error": "punch rate limited",
                        })
                        await writer.drain()
                        continue
                    addr = self._witnessed(req.get("token"))
                    target_w = self._listeners.get(req.get("target"))
                    if target_w is None or addr is None:
                        write_frame(writer, {
                            "event": "punch_addr",
                            "conn": req.get("conn"), "ok": False,
                            "error": "target not registered"
                                     if addr else "unknown observe token",
                        })
                    else:
                        # a dead TARGET channel must not tear down THIS
                        # (innocent) sender's registration
                        try:
                            write_frame(target_w, {
                                "event": "punch", "conn": req.get("conn"),
                                "from": ident, "addr": list(addr),
                            })
                            await target_w.drain()
                        except (ConnectionError, OSError):
                            write_frame(writer, {
                                "event": "punch_addr",
                                "conn": req.get("conn"), "ok": False,
                                "error": "target unreachable",
                            })
                elif c == "punch_ack":
                    # stateless reply routing: back to the dialer named
                    # in `target` (only registered identities reach here)
                    addr = self._witnessed(req.get("token"))
                    dialer_w = self._listeners.get(req.get("target"))
                    if dialer_w is not None and addr is not None:
                        try:
                            write_frame(dialer_w, {
                                "event": "punch_addr",
                                "conn": req.get("conn"), "ok": True,
                                "addr": list(addr),
                            })
                            await dialer_w.drain()
                        except (ConnectionError, OSError):
                            pass  # dialer died; punch simply won't happen
                await writer.drain()
        finally:
            if self._listeners.get(ident) is writer:
                del self._listeners[ident]
                self._meta.pop(ident, None)
            writer.close()

    async def _serve_dial(self, reader, writer, msg) -> None:
        target = msg.get("target")
        host_w = self._listeners.get(target)
        if host_w is None:
            write_frame(writer, {"ok": False, "error": "target not registered"})
            await writer.drain()
            writer.close()
            return
        # resource caps BEFORE work is queued: reservations are taken
        # HERE (synchronously, no await between check and reserve) so a
        # burst of concurrent dials can't all pass the check before the
        # first accept lands
        if self._reserved_total >= self.limits.max_pipes_total:
            self.stats.pipes_refused_total_cap += 1
            write_frame(writer, {"ok": False, "error": "relay at capacity"})
            await writer.drain()
            writer.close()
            return
        if (self._reserved_by_target.get(target, 0)
                >= self.limits.max_pipes_per_target):
            self.stats.pipes_refused_target_cap += 1
            write_frame(writer, {"ok": False, "error": "target pipe cap"})
            await writer.drain()
            writer.close()
            return
        self._reserve(target)
        conn_id = secrets.token_hex(16)
        accepted: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[conn_id] = (reader, writer, accepted, target)
        try:
            write_frame(host_w, {"event": "incoming", "conn": conn_id})
            await host_w.drain()
            await asyncio.wait_for(accepted, DIAL_TIMEOUT)
        except Exception:
            self._pending.pop(conn_id, None)
            self._release(target)
            write_frame(writer, {"ok": False, "error": "accept timeout"})
            try:
                await writer.drain()
            except Exception:
                pass
            writer.close()
        # on success the accept side owns the splice (and releases the
        # reservation when it ends); nothing more here

    def _witnessed(self, token: Any) -> tuple[str, int] | None:
        """Address this relay saw for an observe token (fresh only)."""
        if not isinstance(token, str):
            return None
        entry = self._observed.pop(token, None)
        if entry is None or time.monotonic() - entry[1] > 60.0:
            return None
        return entry[0]

    def _reserve(self, target: str) -> None:
        self._reserved_total += 1
        self._reserved_by_target[target] = (
            self._reserved_by_target.get(target, 0) + 1
        )

    def _release(self, target: str) -> None:
        self._reserved_total = max(0, self._reserved_total - 1)
        left = self._reserved_by_target.get(target, 1) - 1
        if left <= 0:
            self._reserved_by_target.pop(target, None)
        else:
            self._reserved_by_target[target] = left

    async def _serve_accept(self, reader, writer, msg) -> None:
        entry = self._pending.pop(str(msg.get("conn", "")), None)
        if entry is None:
            write_frame(writer, {"ok": False, "error": "unknown conn"})
            await writer.drain()
            writer.close()
            return
        dial_r, dial_w, accepted, target = entry
        # resolve the future FIRST: the dial side's wait_for may cancel
        # it during any await below, and set_result on a cancelled
        # future raises InvalidStateError
        if accepted.cancelled():
            # the dial path released the reservation when it timed out
            write_frame(writer, {"ok": False, "error": "dial gone"})
            await writer.drain()
            writer.close()
            return
        accepted.set_result(None)
        # from here the reservation is THIS handler's to release
        self.stats.pipes_opened += 1
        self.stats.pipes_active += 1
        try:
            # inside the try (sdlint SD016): any failure past this
            # point — including registering the pipe pair — must run
            # the finally, or pipes_active overcounts forever and the
            # reservation never releases
            self._pipes.update((dial_w, writer))
            write_frame(writer, {"ok": True})
            write_frame(dial_w, {"ok": True})
            await writer.drain()
            await dial_w.drain()
            await _splice(dial_r, dial_w, reader, writer, stats=self.stats,
                          rate=self.limits.pipe_rate_bytes_per_s)
        finally:
            self.stats.pipes_active -= 1
            self._release(target)
            self._pipes.difference_update((dial_w, writer))


class RelayClient:
    """Node-side: keeps a control connection registered on the relay,
    accepts relayed inbound streams, dials relayed outbound streams,
    and feeds relay-discovered peers into the P2P registry."""

    def __init__(self, p2p: Any, relay_addr: tuple[str, int],
                 on_stream: Callable[[EncryptedStream], Awaitable[None]],
                 query_interval: float = 5.0,
                 udp_factory: Callable[[], Any] | None = None,
                 punch: bool = True):
        self.p2p = p2p
        self.addr = relay_addr
        self.identity: Identity = p2p.identity
        self._on_stream = on_stream
        # the server evicts listeners silent past CONTROL_IDLE_TIMEOUT;
        # clamp so a tuned-up interval can't violate the contract
        self._interval = min(query_interval, CONTROL_IDLE_TIMEOUT / 4)
        self._task: asyncio.Task | None = None
        self._accepts: set[asyncio.Task] = set()  # keep strong refs
        self._stopped = asyncio.Event()
        # hole punching (punch.py); udp_factory is the NAT-simulation
        # seam — tests hand in translating endpoints
        self._punch_enabled = punch
        self._udp_factory = udp_factory
        self._relay_udp: tuple[str, int] | None = None
        self._ctrl: asyncio.StreamWriter | None = None
        self._punch_waits: dict[str, asyncio.Future] = {}
        # inbound punch-accept guard: each accept binds a socket and runs
        # up to ~5 s of observe+probe spray, so any registered keypair
        # could otherwise exhaust us with punch events (availability DoS)
        self._punch_active = 0
        self._punch_rate = _SlidingWindow(
            PUNCH_ACCEPT_PER_SOURCE, PUNCH_ACCEPT_WINDOW, max_idents=256)
        # path-selection telemetry (surfaced via p2p.state)
        self.punch_stats = {"attempted": 0, "direct": 0, "fallback": 0,
                            "accepted": 0, "refused": 0}

    async def start(self) -> None:
        self._task = asyncio.create_task(self._run())
        # expose relayed dialing to P2P.new_stream's fallback
        self.p2p.relay_dial = self.dial

    async def shutdown(self) -> None:
        self._stopped.set()
        for t in (self._task, *self._accepts):
            if t is None:
                continue
            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._accepts.clear()
        if getattr(self.p2p, "relay_dial", None) is self.dial:
            self.p2p.relay_dial = None

    # --- control loop ---------------------------------------------------

    async def _run(self) -> None:
        while not self._stopped.is_set():
            try:
                await self._session()
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001 - reconnect loop
                logger.debug("relay session ended: %s", e)
            try:
                await asyncio.wait_for(self._stopped.wait(), 2.0)
            except asyncio.TimeoutError:
                pass

    def _meta(self) -> dict[str, Any]:
        return dict(self.p2p.metadata)

    async def _session(self) -> None:
        reader, writer = await asyncio.open_connection(*self.addr)
        try:
            write_frame(writer, {
                "cmd": "listen",
                "identity": str(self.p2p.remote_identity),
                "meta": self._meta(),
            })
            await writer.drain()
            challenge = await asyncio.wait_for(read_frame(reader), 30)
            if "challenge" not in challenge:
                raise ConnectionError(f"relay refused listen: {challenge}")
            nonce = bytes.fromhex(challenge["challenge"])
            write_frame(writer, {
                "sig": self.identity.sign(_LISTEN_CONTEXT + nonce).hex()
            })
            await writer.drain()
            resp = await asyncio.wait_for(read_frame(reader), 30)
            if not resp.get("ok"):
                raise ConnectionError(f"relay auth failed: {resp}")
            if resp.get("udp_port"):
                self._relay_udp = (self.addr[0], int(resp["udp_port"]))
            self._ctrl = writer

            # dedicated read loop: incoming dials are answered the
            # moment the relay announces them, never a poll-cycle later
            async def reads():
                while True:
                    msg = await read_frame(reader)
                    event = msg.get("event")
                    if event == "incoming":
                        supervise(asyncio.create_task(self._accept(msg["conn"])),
                                  self._accepts, logger, "relayed accept")
                    elif event == "peers":
                        self._ingest_peers(msg.get("peers", []))
                    elif event == "punch":
                        supervise(asyncio.create_task(self._punch_accept(msg)),
                                  self._accepts, logger, "punch accept")
                    elif event == "punch_addr":
                        fut = self._punch_waits.pop(msg.get("conn", ""), None)
                        if fut is not None and not fut.done():
                            fut.set_result(msg)
                    # {"ok":true} replies to refreshes need no action

            read_task = asyncio.create_task(reads())
            try:
                last_meta = self._meta()
                while not self._stopped.is_set():
                    write_frame(writer, {"cmd": "query"})
                    if self._meta() != last_meta:
                        last_meta = self._meta()
                        write_frame(writer, {
                            "cmd": "listen",
                            "identity": str(self.p2p.remote_identity),
                            "meta": last_meta,
                        })
                    await writer.drain()
                    done, _ = await asyncio.wait(
                        [read_task], timeout=self._interval
                    )
                    if done:  # the control socket died → reconnect
                        read_task.result()
                        return
            finally:
                read_task.cancel()
                try:
                    await read_task
                except (asyncio.CancelledError, Exception):
                    pass
        finally:
            self._ctrl = None
            for fut in self._punch_waits.values():
                if not fut.done():
                    fut.cancel()
            self._punch_waits.clear()
            writer.close()

    def _ingest_peers(self, peers: list[dict[str, Any]]) -> None:
        seen = set()
        for entry in peers:
            try:
                ident = RemoteIdentity.from_str(entry["identity"])
            except Exception:
                continue
            seen.add(ident)
            if ident == self.p2p.remote_identity:
                continue
            peer = self.p2p.touch_peer(ident)
            fresh = not peer.is_discovered
            meta = {str(k): str(v) for k, v in (entry.get("meta") or {}).items()}
            changed = any(peer.metadata.get(k) != v for k, v in meta.items())
            peer.metadata.update(meta)
            peer.discovered_by.add("relay")
            peer.relayed = True
            if fresh:
                self.p2p.events.emit(("PeerDiscovered", ident))
            elif changed:
                self.p2p.events.emit(("PeerMetadataChanged", ident))
        for ident, peer in self.p2p.peers.items():
            if peer.relayed and ident not in seen:
                peer.relayed = False
                self.p2p.expired("relay", ident)  # one expiry semantics

    # --- streams --------------------------------------------------------

    async def _accept(self, conn_id: str) -> None:
        """Dial back to the relay, claim the conn, run the SERVER side
        of the Noise handshake through the pipe."""
        try:
            reader, writer = await asyncio.open_connection(*self.addr)
            write_frame(writer, {"cmd": "accept", "conn": conn_id})
            await writer.drain()
            resp = await asyncio.wait_for(read_frame(reader), DIAL_TIMEOUT)
            if not resp.get("ok"):
                writer.close()
                return
            stream = await asyncio.wait_for(
                _server_handshake(reader, writer, self.identity), DIAL_TIMEOUT
            )
        except Exception as e:  # noqa: BLE001 - inbound is best-effort
            logger.debug("relayed accept %s failed: %s", conn_id, e)
            return
        try:
            await self._on_stream(stream)
        finally:
            await stream.close()

    async def dial(self, identity: RemoteIdentity,
                   timeout: float = DIAL_TIMEOUT) -> EncryptedStream:
        """Open a stream to `identity`: try a punched DIRECT UDP path
        first (every byte then bypasses the relay), fall back to the
        relayed TCP pipe — the reference's DCUtR-then-relay order
        (ref:quic/transport.rs:212,344)."""
        if self._punch_enabled and self._relay_udp and self._ctrl:
            # the punch attempt (observe/exchange/open/handshake) runs
            # under the caller's deadline, and the fallback gets only
            # what remains (floored so it always has a fighting chance)
            start = asyncio.get_running_loop().time()
            self.punch_stats["attempted"] += 1
            try:
                stream = await asyncio.wait_for(
                    self.punch_dial(identity, timeout=timeout), timeout
                )
                self.punch_stats["direct"] += 1
                return stream
            except Exception as e:  # noqa: BLE001 - any punch failure → relay
                logger.debug("punch to %s failed (%s); using relay",
                             identity, e)
            self.punch_stats["fallback"] += 1
            timeout = max(
                3.0, timeout - (asyncio.get_running_loop().time() - start)
            )
        return await self.relay_dial_tcp(identity, timeout=timeout)

    async def relay_dial_tcp(self, identity: RemoteIdentity,
                             timeout: float = DIAL_TIMEOUT) -> EncryptedStream:
        """Open a relayed stream to `identity` (CLIENT handshake through
        the spliced pipe)."""
        reader, writer = await asyncio.open_connection(*self.addr)
        try:
            write_frame(writer, {"cmd": "dial", "target": str(identity)})
            await writer.drain()
            resp = await asyncio.wait_for(read_frame(reader), timeout)
            if not resp.get("ok"):
                raise ConnectionError(f"relay dial failed: {resp.get('error')}")
            return await asyncio.wait_for(
                _client_handshake(reader, writer, self.identity, identity),
                timeout,
            )
        except BaseException:
            writer.close()
            raise

    # --- hole punching (punch.py + udpstream.py) ------------------------

    def _make_udp(self):
        if self._udp_factory is not None:
            return self._udp_factory()
        from .udp import UdpEndpoint

        return UdpEndpoint()

    async def punch_dial(self, identity: RemoteIdentity,
                         timeout: float = DIAL_TIMEOUT) -> EncryptedStream:
        """Direct path: observe → exchange via control channel →
        simultaneous open → Noise XX over the reliable UDP stream."""
        from . import punch
        from .udpstream import UdpStream

        ctrl = self._ctrl
        if ctrl is None or self._relay_udp is None:
            raise punch.PunchError("no relay control channel")
        ep = self._make_udp()
        try:
            await ep.bind()
            _my_addr, token = await punch.observe(ep, self._relay_udp)
            conn = secrets.token_hex(8)
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._punch_waits[conn] = fut
            try:
                write_frame(ctrl, {
                    "cmd": "punch", "conn": conn,
                    "target": str(identity), "token": token,
                })
                await ctrl.drain()
                answer = await asyncio.wait_for(fut, punch.PUNCH_TIMEOUT + 2)
            except asyncio.CancelledError:
                if fut.cancelled():
                    # the control channel dropped and cancelled our wait
                    # — a punch failure, not a caller cancellation
                    raise punch.PunchError("control channel lost") from None
                raise
            finally:
                self._punch_waits.pop(conn, None)
            if not answer.get("ok") or not answer.get("addr"):
                raise punch.PunchError(
                    f"peer unreachable for punch: {answer.get('error')}")
            peer_addr = (answer["addr"][0], int(answer["addr"][1]))
            await punch.simultaneous_open(ep, peer_addr)
            stream = UdpStream(ep, peer_addr)
            es = await asyncio.wait_for(
                _client_handshake(stream.reader, stream, self.identity,
                                  identity),
                timeout,
            )
            es.direct = True  # diagnosable path selection
            return es
        except BaseException:
            ep.close()
            raise

    async def _punch_accept(self, msg: dict[str, Any]) -> None:
        """Admission control for an inbound punch event; the actual
        observe/open/handshake work runs in `_punch_accept_inner`."""
        ctrl = self._ctrl
        if ctrl is None or self._relay_udp is None:
            return
        # concurrency cap + per-source sliding window: dropped requests
        # leave the dialer to fall back to the relayed pipe — bounded
        # work here beats availability for a spraying peer. The cap
        # covers only the observe/probe/handshake phase; the slot is
        # released BEFORE the accepted stream is served, so long-lived
        # inbound transfers don't starve new punches.
        src = str(msg.get("from", ""))
        if self._punch_active >= PUNCH_ACCEPT_MAX \
                or not self._punch_rate.allow(src):
            self.punch_stats["refused"] += 1
            logger.debug("punch accept from %s refused (load)", src[:16])
            return
        self._punch_active += 1
        try:
            es = await self._punch_accept_inner(msg, ctrl)
        finally:
            self._punch_active -= 1
        if es is None:
            return
        try:
            await self._on_stream(es)
        finally:
            await es.close()

    async def _punch_accept_inner(self, msg: dict[str, Any],
                                  ctrl) -> "EncryptedStream | None":
        """Answer an admitted punch request: observe, return our
        address, open simultaneously, then run the SERVER side of
        Noise over UDP. Returns the authenticated stream (served by
        the caller, outside the concurrency slot) or None."""
        from . import punch
        from .udpstream import UdpStream

        ep = self._make_udp()
        try:
            await ep.bind()
            _my_addr, token = await punch.observe(ep, self._relay_udp)
            write_frame(ctrl, {
                "cmd": "punch_ack", "conn": msg.get("conn"),
                "target": msg.get("from"), "token": token,
            })
            await ctrl.drain()
            peer_addr = (msg["addr"][0], int(msg["addr"][1]))
            await punch.simultaneous_open(ep, peer_addr)
            stream = UdpStream(ep, peer_addr)
            es = await asyncio.wait_for(
                _server_handshake(stream.reader, stream, self.identity),
                DIAL_TIMEOUT,
            )
            self.punch_stats["accepted"] += 1
            return es
        except Exception as e:  # noqa: BLE001 - inbound is best-effort
            logger.debug("punch accept failed: %s", e)
            ep.close()
            return None
