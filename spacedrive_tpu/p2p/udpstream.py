"""Reliable ordered byte stream over UDP — the punched-path transport.

Parity: the reference's direct WAN paths are QUIC streams over punched
UDP (ref:crates/p2p2/src/quic/transport.rs:212,344). A full QUIC is out
of scope; this is an ARQ with QUIC-class dynamics so bulk Spacedrop
over a punched WAN path is no longer window-capped:

- segments of ≤``MSS`` bytes, 9-byte header ``!BII`` (type, seq, ack)
  — DATA / ACK / FIN / WPROBE;
- ACKs carry a cumulative ack, a **receiver-advertised window** (free
  reassembly+reader buffer, in segments) and up to ``SACK_MAX`` SACK
  ranges from the reorder buffer, so one lost segment never blocks
  the rest of a large flight (selective repeat, not go-back-N);
- a **rate-seeking congestion controller** (`_RateSeekCC`, BBR-
  flavoured) sets the in-flight budget and a token-bucket pacer
  spaces transmissions at 1.25× the measured delivery rate.
  Loss-halving AIMD collapses to ~sqrt(1/p) segments under the 1-2%
  *random* loss real WAN paths show — below even the old fixed
  window — so decrease keys on what congestion actually looks like:
  mass per-round retransmission, repeated RTOs, and delivery-rate
  plateaus (see the class docstring);
- per-ACK fast retransmit of SACK holes (rate-limited per RTT), RTO
  backstop with exponential backoff, give-up after ``MAX_RETRIES``
  (the punched path then falls back to the relay);
- zero-window persist probes (WPROBE) so a receiver that stalls and
  then drains its buffer reopens the stream without waiting for RTO;
- in-order reassembly into an ``asyncio.StreamReader`` + a writer
  facade, so `transport._client_handshake`/`_server_handshake` and
  `EncryptedStream` run over a punched UDP path UNCHANGED — same
  Noise XX, same identity binding, same record framing, just a
  different byte carrier (docs/security.md's argument carries over).

The security posture does not rest on this layer: every byte above it
is AEAD-protected and an attacker who forges/reorders segments can only
cause decrypt failures (= connection teardown), same as TCP injection.

Scope notes: sequence numbers are 32-bit (a single stream tops out at
~4.9 TB — far beyond any Spacedrop session; streams are per-transfer).
"""

from __future__ import annotations

import asyncio
import struct
import time
from collections import deque
from typing import Any

from ..telemetry import metrics as _tm
from ..telemetry.events import P2P_EVENTS
from .udp import UdpEndpoint

_HDR = struct.Struct("!BII")
_RWND = struct.Struct("!I")
_RANGE = struct.Struct("!II")
DATA, ACK, FIN, WPROBE = 1, 2, 3, 4
MSS = 1150          # fits the 1280-byte IPv6 minimum MTU with headroom
RTO_INITIAL = 0.25
RTO_MAX = 2.0
MAX_RETRIES = 8
SACK_MAX = 3        # ranges per ACK
FAST_RETX_BURST = 16  # SACK holes re-sent per ACK, at most once per RTT each

INIT_CWND = 32
MIN_CWND = 4
MAX_CWND = 4096     # segments (~4.7 MB in flight) — the safety ceiling
RECV_WINDOW = 4096  # segments of reassembly + unread-reader budget
ACK_EVERY = 8       # in-order segments per cumulative ACK (delayed-ack)
DELAYED_ACK = 0.02  # partial-batch ACK latency bound
PACE_BURST = 64     # segments per pacing quantum — an un-paced flight
# of thousands of datagrams overflows socket buffers (kernel OR far-end
# queue) in one loop iteration, self-inflicting tail-drop the loss
# recovery then has to crawl out of; QUIC paces for the same reason
BW_ROUNDS = 8       # delivery-rate max-filter length (rounds ≈ RTTs)
PROBE_EVERY = 4     # plateau rounds between gentle re-probe rounds


class UdpStreamError(ConnectionError):
    pass


class _CountingReader(asyncio.StreamReader):
    """StreamReader that counts consumed bytes, so the receive-window
    credit never depends on the CPython-private ``_buffer`` attribute
    (whose absence used to advertise a permanent zero window —
    ADVICE r5). Fed bytes are counted by the stream itself at its
    feed_data call sites (per-segment hot path: no extra Python frame
    here); this class counts only the cold per-read side
    (read/readexactly/readuntil/readline)."""

    def __init__(self) -> None:
        super().__init__()
        self.bytes_read = 0

    def _count(self, data) -> None:
        self.bytes_read += len(data)

    async def read(self, n: int = -1) -> bytes:
        if n < 0:
            # CPython implements read-all as a loop over
            # self.read(self._limit) — those inner calls re-enter this
            # override and count every block, so counting the joined
            # result too would double bytes_read and silently disable
            # flow control for the rest of the connection
            return await super().read(n)
        data = await super().read(n)
        self._count(data)
        return data

    async def readexactly(self, n: int) -> bytes:
        try:
            data = await super().readexactly(n)
        except asyncio.IncompleteReadError as e:
            self._count(e.partial)
            raise
        self._count(data)
        return data

    async def readuntil(self, separator: bytes = b"\n") -> bytes:
        # readline() delegates here via self, so this single override
        # covers both without double counting
        try:
            data = await super().readuntil(separator)
        except asyncio.IncompleteReadError as e:
            self._count(e.partial)  # EOF consumed the partial tail
            raise
        self._count(data)
        return data


class _RateSeekCC:
    """Bandwidth-seeking congestion controller (BBR-flavoured).

    The budget (cwnd) doubles each round while the measured delivery
    rate still grows — random 1–2% WAN loss cannot stop the climb the
    way it collapses loss-halving AIMD (whose equilibrium ~sqrt(1/p)
    segments sits BELOW the old fixed window). Congestion is detected
    as what it actually looks like:

    - the *loss rate* over a round exceeding LOSS_DECREASE (a path in
      collapse drops far more than the random-loss regime) → ×0.7;
    - repeated RTOs (a real stall) → relearn from INIT_CWND;
    - a delivery-rate plateau → growth stops (with a gentle ×1.25
      re-probe every PROBE_EVERY rounds to rediscover capacity).

    The budget never falls below 2×BDP (windowed-max delivery rate ×
    smoothed RTT), so ACK-clock jitter can't starve a healthy path.
    Rounds are delimited by delivery catching up with the flight that
    was outstanding at the previous round edge (≈ one ACK-clock RTT).
    """

    INIT_RATE = 4_000.0  # segs/s pacing floor before a bandwidth sample
    # pacing gain over the measured rate: 2× while delivery is still
    # climbing (the headroom each sample needs to exceed the last —
    # discovery at ×2/sample reaches any capacity in log time), a
    # gentle 1.25× once it plateaus
    GAIN_GROW = 2.0
    GAIN_STEADY = 1.25
    GROWTH = 1.15        # sample-over-sample delivery growth that counts
    LOSS_DECREASE = 0.10  # per-sample retransmit fraction → back off

    def __init__(self) -> None:
        self.cwnd = float(INIT_CWND)
        self.rtt_min: float | None = None
        self.srtt: float | None = None  # fed by the stream's estimator
        self.delivered = 0              # total segments delivered
        self.retransmitted = 0          # total retransmissions (stream-fed)
        self._bw_window: deque[float] = deque(maxlen=BW_ROUNDS)
        self._round_start_time = time.monotonic()
        self._round_start_delivered = 0
        self._round_start_retx = 0
        self._rounds_since_probe = 0
        self._slow_samples = 0  # consecutive non-growing rate samples
        self._cwnd_scale = 1.0  # loss-event backoff multiplier
        # test seam: pin the budget (A/B vs the fixed-window design)
        self.fixed_cwnd: int | None = None

    def _srtt_eff(self) -> float:
        """RTT for the BDP: the SMOOTHED estimate (floored), not the
        minimum — on low-RTT paths scheduling jitter and delayed ACKs
        dominate rtt_min, and a BDP computed from a 0.1 ms minimum
        would starve the pipe between ACK batches."""
        return max(self.srtt or 0.0, self.rtt_min or 0.0, 0.005)

    def pacing_rate(self) -> float:
        """Segments/s to feed the wire: 1.25× the windowed-max measured
        delivery rate. Pacing at the *delivered* rate — not cwnd/RTT —
        is what keeps a flight from overflowing the path's (or
        kernel's) buffers on ANY RTT; the 1.25 headroom is what lets
        the next round's measurement exceed the last."""
        if self.fixed_cwnd is not None:
            # pinned-budget mode: the window must be the binding
            # constraint; pacing only smooths (1.25× headroom)
            return 1.25 * self.fixed_cwnd / self._srtt_eff()
        if not self._bw_window:
            return self.INIT_RATE
        gain = self.GAIN_GROW if self._slow_samples < 2 else self.GAIN_STEADY
        return max(gain * max(self._bw_window), self.INIT_RATE)

    def on_rtt_sample(self, rtt: float) -> None:
        if rtt > 0 and (self.rtt_min is None or rtt < self.rtt_min):
            self.rtt_min = rtt

    def on_delivered(self, n: int, in_flight: int) -> None:
        """n segments newly cum-acked or SACKed. Rate sampling is
        TIME-based — one sample per ~RTT of wall clock — not flight-
        drain based: when the budget briefly overshoots the achievable
        rate the flight balloons, and a drain-defined "round" would
        stretch to many RTTs, throttling the very feedback loop that
        corrects the overshoot. (`in_flight` is unused but kept: it is
        the natural hook for a future inflight-vs-BDP drain signal.)"""
        self.delivered += n
        now = time.monotonic()
        dt = now - self._round_start_time
        # clamp the interval to 100 ms: a queue-inflated srtt would
        # slow the very feedback that corrects the queue
        if dt < min(max(self._srtt_eff(), 0.02), 0.1):
            return
        round_delivered = self.delivered - self._round_start_delivered
        round_retx = self.retransmitted - self._round_start_retx
        self._round_start_time = now
        self._round_start_delivered = self.delivered
        self._round_start_retx = self.retransmitted
        bw = round_delivered / dt  # segs/s
        prev_max = max(self._bw_window) if self._bw_window else 0.0
        self._bw_window.append(bw)
        self._advance(bw, prev_max, round_retx / max(1, round_delivered))

    def _advance(self, bw: float, prev_max: float,
                 loss_rate: float) -> None:
        if self.fixed_cwnd is not None:
            self.cwnd = float(self.fixed_cwnd)
            return
        self._rounds_since_probe += 1
        if loss_rate > self.LOSS_DECREASE:
            # a collapsing path shows mass retransmission, far above
            # the random-loss regime the growth rule tolerates
            self._cwnd_scale = max(0.5, self._cwnd_scale * 0.7)
            self._slow_samples += 1
        elif bw >= self.GROWTH * prev_max:
            # delivery still climbing (compared against the windowed
            # max, so a stale early peak can't freeze growth forever)
            self._slow_samples = 0
            self._cwnd_scale = min(1.0, self._cwnd_scale + 0.1)
        else:
            self._slow_samples += 1
            if self._rounds_since_probe >= PROBE_EVERY:
                self._rounds_since_probe = 0
                self._slow_samples = 1  # probe sample: re-allow growth
            self._cwnd_scale = min(1.0, self._cwnd_scale + 0.1)
        # the budget is DERIVED, not walked: N×BDP against the MINIMUM
        # RTT (srtt includes self-made queue — sizing the flight by it
        # is how standing queues, 6× RTT inflation, and repair latency
        # spirals happen) + headroom so low-RTT paths survive ACK-batch
        # scheduling jitter. While discovering, the multiple is 4: on a
        # lossy path SACK-held repairs stretch the effective RTT past
        # 2× the minimum, and a 2×BDP flight would window-limit
        # delivery below the growth threshold — freezing discovery.
        rtt_floor = max(self.rtt_min or 0.05, 0.001)
        mult = 4 if self._slow_samples < 2 else 2
        bdp = mult * max(self._bw_window) * rtt_floor + 64
        self.cwnd = max(MIN_CWND, min(self._cwnd_scale * bdp, MAX_CWND))

    def on_rto(self, consecutive: int) -> None:
        """Timeout reaction in two stages: a single RTO (often ACK-path
        jitter) halves the budget; repeated ones mean a real stall —
        relearn the path from scratch."""
        if self.fixed_cwnd is not None:
            return
        if consecutive < 2:
            self.cwnd = max(self.cwnd / 2, float(INIT_CWND))
            return
        self.cwnd = float(INIT_CWND)
        self._bw_window.clear()

    def budget(self) -> int:
        if self.fixed_cwnd is not None:
            return self.fixed_cwnd
        return int(self.cwnd)


class UdpStream:
    """One reliable bidirectional stream bound to (endpoint, remote).

    Exposes ``reader`` (a real asyncio.StreamReader) and itself as the
    writer facade (``write``/``drain``/``close``/``wait_closed``/
    ``get_extra_info``) — the exact surface the Noise transport uses.
    """

    def __init__(self, endpoint: UdpEndpoint, remote: tuple[str, int],
                 *, owns_endpoint: bool = True):
        self._ep = endpoint
        self.remote = tuple(remote)
        self._owns = owns_endpoint
        self.reader = _CountingReader()
        self._fed_bytes = 0  # bytes handed to the reader (credit side)
        # sender state
        self._next_seq = 0
        # seq → [dgram, first_tx, last_tx, retx_count]
        self._unacked: dict[int, list] = {}
        self._sacked: set[int] = set()
        self._send_base = 0
        self._window_free = asyncio.Event()
        self._window_free.set()
        self._retries = 0
        self._rto = RTO_INITIAL
        self._srtt: float | None = None
        self._rttvar = 0.0
        self._rtt_probe: tuple[int, float] | None = None  # (seq, sent_at)
        self._timer: asyncio.TimerHandle | None = None
        self._cc = _RateSeekCC()
        self._peer_rwnd = RECV_WINDOW
        self._probe_timer: asyncio.TimerHandle | None = None
        self._probe_ivl = RTO_INITIAL
        # receiver state
        self._recv_next = 0
        self._reorder: dict[int, tuple[int, bytes]] = {}  # seq → (type, payload)
        # received-run index over _reorder: disjoint sorted [start, end)
        # pairs, maintained incrementally (len = holes+1, usually tiny)
        # so SACK construction never sorts the reorder buffer
        self._runs: list[list[int]] = []
        self._ack_pending = 0
        self._ack_timer: asyncio.TimerHandle | None = None
        self._fin_sent = False
        self._fin_acked = asyncio.Event()
        self._closed = False
        self._pending_writes: deque[bytes] = deque()
        self._sender_task: asyncio.Task | None = None
        # close() fires _graceful_close in the background; the handle is
        # retained so the task can't be GC-cancelled mid-FIN (sdlint SD003)
        self._close_task: asyncio.Task | None = None
        self._loop = asyncio.get_running_loop()
        endpoint.set_receiver(self._on_datagram)

    # --- receiver ------------------------------------------------------

    def _unread(self) -> int:
        """Bytes fed to the reader but not yet consumed by the app —
        tracked explicitly (our feed counter minus the reader's read
        counter), never via the CPython-private _buffer attribute. A
        foreign reader without the counter degrades to FULL credit
        (correctness over flow control, the pre-rewrite behavior)
        instead of the permanent zero window the old fallback
        advertised (ADVICE r5)."""
        consumed = getattr(self.reader, "bytes_read", None)
        if consumed is None:
            return 0
        return max(0, self._fed_bytes - consumed)

    def _rwnd(self) -> int:
        """Segments of credit: reassembly slots not taken by the
        reorder buffer or by unread reader bytes."""
        used = len(self._reorder) + self._unread() // MSS
        return max(0, RECV_WINDOW - used)

    def _runs_add(self, seq: int) -> bool:
        """Insert `seq` into the run index; True if it STARTED a new
        run (a fresh loss signal — worth an immediate dup-ACK)."""
        rs = self._runs
        for i, r in enumerate(rs):  # linear: len(rs) = holes+1, tiny
            if seq < r[0] - 1:
                rs.insert(i, [seq, seq + 1])
                return True
            if seq == r[0] - 1:
                r[0] = seq
                return False
            if r[0] <= seq < r[1]:
                return False  # duplicate
            if seq == r[1]:
                r[1] = seq + 1
                if i + 1 < len(rs) and rs[i + 1][0] == r[1]:
                    r[1] = rs[i + 1][1]
                    rs.pop(i + 1)
                return False
        rs.append([seq, seq + 1])
        return True

    def _runs_trim(self) -> None:
        """Drop runs consumed by the in-order frontier."""
        rs = self._runs
        while rs and rs[0][1] <= self._recv_next:
            rs.pop(0)
        if rs and rs[0][0] < self._recv_next:
            rs[0][0] = self._recv_next

    def _send_ack(self) -> None:
        parts = [_HDR.pack(ACK, 0, self._recv_next),
                 _RWND.pack(self._rwnd())]
        for a, b in self._runs[:SACK_MAX]:
            parts.append(_RANGE.pack(a, b))
        self._ep.sendto(b"".join(parts), self.remote)

    def _on_datagram(self, data: bytes, addr: tuple[str, int]) -> None:
        if tuple(addr) != self.remote or len(data) < _HDR.size:
            return  # stray traffic on the punched socket
        typ, seq, ack = _HDR.unpack_from(data)
        payload = data[_HDR.size:]
        if typ == ACK:
            self._on_ack(ack, payload)
            return
        if typ == WPROBE:
            self._ack_now()  # fresh window advertisement
            return
        if typ not in (DATA, FIN):
            return
        duplicate = seq < self._recv_next
        fin_seen = False
        new_run = False
        # the in-order segment is ALWAYS accepted — if only out-of-order
        # segments could fill a capped buffer, a hostile peer that
        # stuffed the reorder buffer would wedge the stream permanently
        if seq == self._recv_next:
            # fast path: no reorder/run bookkeeping for in-order data
            # (and no new_run, or every clean segment would defeat the
            # delayed-ACK batching below)
            self._recv_next += 1
            if typ == FIN:
                fin_seen = True
                self.reader.feed_eof()
            elif payload:
                self._fed_bytes += len(payload)
                self.reader.feed_data(payload)
            while self._recv_next in self._reorder:
                t, p = self._reorder.pop(self._recv_next)
                self._recv_next += 1
                if t == FIN:
                    fin_seen = True
                    self.reader.feed_eof()
                elif p:
                    self._fed_bytes += len(p)
                    self.reader.feed_data(p)
            self._runs_trim()
        elif seq > self._recv_next and len(self._reorder) < 2 * RECV_WINDOW:
            if seq not in self._reorder:
                self._reorder[seq] = (typ, payload)
                new_run = self._runs_add(seq)
        # delayed cumulative ACKs: every ACK_EVERY in-order segments, or
        # within DELAYED_ACK. Immediate ACKs where the sender's clock
        # depends on them: duplicates (its ACK was lost), a NEW hole
        # (fast retransmit), FIN (close latency). While holes exist,
        # decimate to every 4th — per-segment dup-ACK storms were the
        # top line of the transfer profile — the 20 ms timer still
        # bounds repair latency.
        if duplicate or fin_seen or new_run:
            self._ack_now()
        else:
            self._ack_pending += 1
            if self._ack_pending >= (4 if self._runs else ACK_EVERY):
                self._ack_now()
            elif self._ack_timer is None:
                self._ack_timer = self._loop.call_later(
                    DELAYED_ACK, self._ack_now)

    def _ack_now(self) -> None:
        self._ack_pending = 0
        if self._ack_timer is not None:
            self._ack_timer.cancel()
            self._ack_timer = None
        if not self._closed:
            self._send_ack()

    # --- ACK processing ------------------------------------------------

    def _on_ack(self, ack: int, payload: bytes) -> None:
        now = time.monotonic()
        if ack > self._next_seq:
            # a corrupt/forged ACK beyond the flight would desync
            # _send_base forever (cumulative ACKs could never retire
            # segments again) — drop it whole; an honest peer cannot
            # ack what was never sent (ADVICE r5)
            _tm.UDP_BAD_ACKS.inc()
            P2P_EVENTS.emit("bad_ack", remote=str(self.remote), ack=ack)
            return
        if len(payload) >= _RWND.size:
            self._peer_rwnd = _RWND.unpack_from(payload)[0]
            if self._peer_rwnd > 0:
                self._cancel_probe()
        delivered = 0
        rtt_sample: float | None = None
        # seqs are contiguous from send_base, so the cum-acked region is
        # a range — O(newly acked), not O(outstanding), per ACK
        for seq in range(self._send_base, min(ack, self._next_seq)):
            entry = self._unacked.pop(seq, None)
            if entry is None:
                continue
            if seq not in self._sacked:
                delivered += 1
            self._sacked.discard(seq)
            # one timed segment per RTT (RFC 6298 discipline): batch
            # ACKs after hole repair would otherwise feed the ages of
            # long-parked segments into srtt. Karn: a probe that got
            # retransmitted is discarded, never sampled.
            if self._rtt_probe is not None and seq == self._rtt_probe[0]:
                if entry[3] == 0:
                    rtt_sample = now - self._rtt_probe[1]
                self._rtt_probe = None
        if ack > self._send_base:
            self._send_base = min(ack, self._next_seq)
            self._retries = 0
            self._rto_backoff_reset()
        # SACK ranges; the gaps BETWEEN them are the peer's exact hole
        # list, so retransmission never scans the whole flight. Hostile-
        # input bounds: at most SACK_MAX ranges are parsed (honest peers
        # never send more) and every range is clamped to the live
        # [send_base, next_seq) flight — a forged 64 KB ACK packed with
        # huge ranges must not buy millions of loop iterations.
        off = _RWND.size
        holes: list[int] = []
        prev_end = max(ack, self._send_base)
        ranges_seen = 0
        while off + _RANGE.size <= len(payload) and ranges_seen < SACK_MAX:
            a, b = _RANGE.unpack_from(payload, off)
            off += _RANGE.size
            ranges_seen += 1
            a = max(a, self._send_base)
            b = min(b, self._next_seq)
            for seq in range(a, b):
                if seq in self._unacked and seq not in self._sacked:
                    self._sacked.add(seq)
                    delivered += 1
            if len(holes) < 2 * FAST_RETX_BURST and a > prev_end:
                holes.extend(range(prev_end, min(a, prev_end + MAX_CWND)))
            prev_end = max(prev_end, b)
        if rtt_sample is not None:
            self._rtt_update(rtt_sample)
            self._cc.on_rtt_sample(rtt_sample)
            _tm.UDP_ACK_RTT.observe(rtt_sample)
        if delivered:
            self._cc.on_delivered(delivered, self._in_flight())
        if holes:
            self._fast_retransmit(now, holes)
        if self._in_flight() < self._effective_window():
            self._window_free.set()
        self._rearm_timer()
        if self._fin_sent and not self._unacked:
            self._fin_acked.set()
        if self._peer_rwnd == 0 and not self._unacked \
                and (self._pending_writes or not self._fin_sent):
            self._arm_probe()

    def _rtt_update(self, sample: float) -> None:
        if self._srtt is None:
            self._srtt = sample
            self._rttvar = sample / 2
        else:
            self._rttvar = 0.75 * self._rttvar + 0.25 * abs(self._srtt - sample)
            self._srtt = 0.875 * self._srtt + 0.125 * sample
        # 200 ms floor: delayed ACKs (20 ms) + loop scheduling jitter
        # make a tighter floor fire spuriously, and every spurious RTO
        # both burns retransmissions and dents the budget model
        self._rto = min(max(self._srtt + max(4 * self._rttvar, 0.01), 0.2),
                        RTO_MAX)
        self._cc.srtt = self._srtt

    def _rto_backoff_reset(self) -> None:
        if self._srtt is not None:
            self._rto = min(max(self._srtt + max(4 * self._rttvar, 0.01),
                                0.2), RTO_MAX)
        else:
            self._rto = RTO_INITIAL

    def _in_flight(self) -> int:
        return len(self._unacked) - len(self._sacked)

    def _effective_window(self) -> int:
        if self._peer_rwnd <= 0:
            return 0
        return max(1, min(self._cc.budget(), self._peer_rwnd, MAX_CWND))

    def _fast_retransmit(self, now: float, holes: list[int]) -> None:
        """Re-send the peer-reported holes, each at most once per
        (bounded) RTT estimate."""
        # repair gap bounded at 100 ms: gating on raw srtt would let a
        # stall-inflated estimate throttle the very repairs that end
        # the stall (observed: srtt 1.5 s → one repair per 1.5 s), and
        # every 50 ms of repair latency is 50 ms of head-of-line hold
        # on the receiver's reorder buffer
        min_gap = max(0.01, min(self._srtt or RTO_INITIAL, 0.1))
        burst = 0
        for seq in holes:
            if burst >= FAST_RETX_BURST:
                break
            entry = self._unacked.get(seq)
            if entry is None or seq in self._sacked:
                continue
            if now - entry[2] >= min_gap:
                entry[2] = now
                entry[3] += 1
                self._cc.retransmitted += 1
                _tm.UDP_RETRANSMITS.inc()
                self._ep.sendto(entry[0], self.remote)
                burst += 1

    # --- zero-window persist -------------------------------------------

    def _arm_probe(self, rearm: bool = False) -> None:
        if self._probe_timer is not None or self._closed:
            return
        if not rearm:
            # count stall EPISODES, not probe re-arms: one long stall
            # re-arms once per backoff step and must still read as one
            _tm.UDP_RWND_STALLS.inc()
            P2P_EVENTS.emit("rwnd_stall", remote=str(self.remote))
        self._probe_timer = self._loop.call_later(
            self._probe_ivl, self._on_probe_timer)

    def _cancel_probe(self) -> None:
        self._probe_ivl = RTO_INITIAL
        if self._probe_timer is not None:
            self._probe_timer.cancel()
            self._probe_timer = None

    def _on_probe_timer(self) -> None:
        self._probe_timer = None
        if self._closed or self._peer_rwnd > 0:
            return
        self._ep.sendto(_HDR.pack(WPROBE, 0, 0), self.remote)
        self._probe_ivl = min(self._probe_ivl * 2, RTO_MAX)
        self._arm_probe(rearm=True)

    # --- sender --------------------------------------------------------

    def _transmit(self, typ: int, payload: bytes) -> None:
        seq = self._next_seq
        self._next_seq += 1
        dgram = _HDR.pack(typ, seq, 0) + payload
        now = time.monotonic()
        self._unacked[seq] = [dgram, now, now, 0]
        if self._rtt_probe is None:
            self._rtt_probe = (seq, now)
        if self._in_flight() >= self._effective_window():
            self._window_free.clear()
        self._ep.sendto(dgram, self.remote)
        self._rearm_timer()

    def _rearm_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._unacked and not self._closed:
            self._timer = self._loop.call_later(self._rto, self._on_timeout)

    def _on_timeout(self) -> None:
        self._timer = None
        if not self._unacked or self._closed:
            return
        self._retries += 1
        if self._retries > MAX_RETRIES:
            self._fail(UdpStreamError("udp stream: peer unreachable"))
            return
        self._rto = min(self._rto * 2, RTO_MAX)
        self._cc.on_rto(self._retries)
        # episode-level flight-recorder record (per-segment emits would
        # tax the hot path the CC benchmark measures)
        P2P_EVENTS.emit(
            "rto_timeout", remote=str(self.remote),
            retries=self._retries, outstanding=len(self._unacked),
        )
        now = time.monotonic()
        # re-send a burst from the earliest holes — with lossy links
        # (acks drop too) repairing one segment per RTO crawls
        burst = 0
        for seq in range(self._send_base, self._next_seq):
            if burst >= FAST_RETX_BURST * 2:
                break
            entry = self._unacked.get(seq)
            if entry is None or seq in self._sacked:
                continue
            entry[2] = now
            entry[3] += 1
            self._cc.retransmitted += 1
            _tm.UDP_RETRANSMITS.inc()
            self._ep.sendto(entry[0], self.remote)
            burst += 1
        self._rearm_timer()

    def _fail(self, exc: Exception) -> None:
        if self._closed:
            return
        self._closed = True
        P2P_EVENTS.emit("stream_failed", remote=str(self.remote),
                        error=str(exc)[:200])
        self.reader.set_exception(exc)
        self._fin_acked.set()
        # unblock anything parked on a full window (drain/_drain_pending/
        # _graceful_close) — their loops re-check _closed and bail
        self._window_free.set()
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._cancel_probe()
        if self._ack_timer is not None:
            self._ack_timer.cancel()
            self._ack_timer = None
        if self._owns:
            self._ep.close()

    # --- writer facade (what transport.py expects) ---------------------

    def write(self, data: bytes) -> None:
        if self._closed or self._fin_sent:
            raise UdpStreamError("udp stream closed")
        from ..utils import faults as _faults

        spec = _faults.hit("p2p.write")
        if spec is not None:
            if spec.mode == "partial":
                # first segment goes out ON THE WIRE, then the
                # "connection" dies — the peer sees a truncated message,
                # this side an error. Transmitted synchronously: a
                # queued write would be discarded by _fail() below
                # before the sender task ever ran.
                self._transmit(DATA, bytes(memoryview(bytes(data))[:MSS]))
            self._fail(UdpStreamError("injected connection reset"))
            raise UdpStreamError("injected connection reset")
        view = memoryview(bytes(data))
        for off in range(0, max(len(view), 1), MSS):
            self._pending_writes.append(bytes(view[off:off + MSS]))
        self._kick_sender()

    def _kick_sender(self) -> None:
        if self._sender_task is None or self._sender_task.done():
            self._sender_task = self._loop.create_task(self._drain_pending())

    async def _drain_pending(self) -> None:
        # token-bucket pacing: credits accrue at the pacing rate and
        # every transmission spends one. Sleeping a computed interval
        # directly would throttle below the target — the loop oversleeps
        # by its scheduling granularity — but accrued credit absorbs the
        # overshoot, so only the *average* rate is enforced.
        credit = float(PACE_BURST)
        last = self._loop.time()
        while self._pending_writes and not self._closed:
            await self._window_free.wait()
            if self._closed:
                return
            rate = self._cc.pacing_rate()
            now = self._loop.time()
            credit = min(credit + (now - last) * rate, 2.0 * PACE_BURST)
            last = now
            while self._pending_writes and credit >= 1.0 \
                    and self._in_flight() < self._effective_window():
                self._transmit(DATA, self._pending_writes.popleft())
                credit -= 1.0
            if self._in_flight() >= self._effective_window():
                self._window_free.clear()
                if self._peer_rwnd == 0:
                    self._arm_probe()
            elif self._pending_writes and credit < 1.0:
                await asyncio.sleep(max((PACE_BURST - credit) / rate, 0.001))

    async def drain(self) -> None:
        # await the sender task rather than polling _window_free: when
        # the PACER (not the window) is the binding constraint the
        # event stays set and a poll loop would spin a core for the
        # whole paced transmission
        while self._pending_writes and not self._closed:
            task = self._sender_task
            if task is not None and not task.done():
                try:
                    await asyncio.shield(task)
                except Exception:  # noqa: BLE001 - stream failure below
                    pass
            else:
                await asyncio.sleep(0)
        if self._closed and not self._fin_sent:
            raise UdpStreamError("udp stream closed")

    def close(self) -> None:
        if self._closed or self._fin_sent:
            return
        self._fin_sent = True
        self._close_task = self._loop.create_task(self._graceful_close())

    async def _graceful_close(self) -> None:
        try:
            # flush queued writes (paced, same as the sender task),
            # then a reliable FIN
            await self._drain_pending()
            self._transmit(FIN, b"")
            await asyncio.wait_for(self._fin_acked.wait(), 5.0)
        except (asyncio.TimeoutError, Exception):
            pass
        finally:
            self._closed = True
            P2P_EVENTS.emit("stream_closed", remote=str(self.remote),
                            retransmits=self._cc.retransmitted)
            self._fin_acked.set()  # give-up still unblocks wait_closed()
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._cancel_probe()
            if self._ack_timer is not None:
                self._ack_timer.cancel()
                self._ack_timer = None
            if self._owns:
                self._ep.close()

    async def wait_closed(self) -> None:
        if self._fin_sent:
            await self._fin_acked.wait()

    def get_extra_info(self, name: str, default: Any = None) -> Any:
        if name == "peername":
            return self.remote
        if name == "sockname":
            return self._ep.local_addr
        if name == "udpstream_stats":
            # path telemetry for upper layers (Spaceblock block sizing,
            # p2p.state): current budget, rtt estimate, delivered segs
            return {
                "cwnd": self._cc.budget(),
                "srtt": self._srtt,
                "rtt_min": self._cc.rtt_min,
                "delivered_segments": self._cc.delivered,
                "peer_rwnd": self._peer_rwnd,
            }
        return default
