"""Reliable ordered byte stream over UDP — the punched-path transport.

Parity: the reference's direct WAN paths are QUIC streams over punched
UDP (ref:crates/p2p2/src/quic/transport.rs:212,344). A full QUIC is
out of scope; this is the minimal ARQ that gives the Noise channel the
ordered reliable bytes it needs:

- segments of ≤``MSS`` bytes, 9-byte header ``!BII``
  (type, seq, ack) — DATA / ACK / FIN;
- sliding window (``WINDOW`` segments), cumulative ACKs, earliest-
  unacked retransmission with exponential backoff, give-up after
  ``MAX_RETRIES`` (the punched path then falls back to the relay);
- in-order reassembly into an ``asyncio.StreamReader`` + a writer
  facade, so `transport._client_handshake`/`_server_handshake` and
  `EncryptedStream` run over a punched UDP path UNCHANGED — same
  Noise XX, same identity binding, same record framing, just a
  different byte carrier (docs/security.md's argument carries over).

The security posture does not rest on this layer: every byte above it
is AEAD-protected and an attacker who forges/reorders segments can only
cause decrypt failures (= connection teardown), same as TCP injection.

Scope notes: sequence numbers are 32-bit (a single stream tops out at
~4.9 TB — far beyond any Spacedrop session; streams are per-transfer);
there is no receiver-advertised flow-control window — in-flight data is
bounded by the sender window (WINDOW×MSS ≈ 144 KiB) but ACKed data
accumulates in the reader if the application stops consuming, which the
protocol layers above never do (they read in a loop).
"""

from __future__ import annotations

import asyncio
import struct
from collections import deque
from typing import Any

from .udp import UdpEndpoint

_HDR = struct.Struct("!BII")
DATA, ACK, FIN = 1, 2, 3
MSS = 1150          # fits the 1280-byte IPv6 minimum MTU with headroom
WINDOW = 128        # segments in flight (~144 KiB)
RTO_INITIAL = 0.25
RTO_MAX = 2.0
MAX_RETRIES = 8
RETX_BURST = 32     # unacked segments re-sent per timeout
_REORDER_CAP = 4 * WINDOW  # out-of-order buffer bound (hostile peers)


class UdpStreamError(ConnectionError):
    pass


class UdpStream:
    """One reliable bidirectional stream bound to (endpoint, remote).

    Exposes ``reader`` (a real asyncio.StreamReader) and itself as the
    writer facade (``write``/``drain``/``close``/``wait_closed``/
    ``get_extra_info``) — the exact surface the Noise transport uses.
    """

    def __init__(self, endpoint: UdpEndpoint, remote: tuple[str, int],
                 *, owns_endpoint: bool = True):
        self._ep = endpoint
        self.remote = tuple(remote)
        self._owns = owns_endpoint
        self.reader = asyncio.StreamReader()
        # sender state
        self._next_seq = 0
        self._unacked: dict[int, bytes] = {}  # seq → raw datagram
        self._send_base = 0
        self._window_free = asyncio.Event()
        self._window_free.set()
        self._retries = 0
        self._dup_acks = 0
        self._rto = RTO_INITIAL
        self._timer: asyncio.TimerHandle | None = None
        # receiver state
        self._recv_next = 0
        self._reorder: dict[int, tuple[int, bytes]] = {}  # seq → (type, payload)
        self._fin_sent = False
        self._fin_acked = asyncio.Event()
        self._closed = False
        self._pending_writes: deque[bytes] = deque()
        self._sender_task: asyncio.Task | None = None
        self._loop = asyncio.get_running_loop()
        endpoint.set_receiver(self._on_datagram)

    # --- datagram ingress ---------------------------------------------

    def _on_datagram(self, data: bytes, addr: tuple[str, int]) -> None:
        if tuple(addr) != self.remote or len(data) < _HDR.size:
            return  # stray traffic on the punched socket
        typ, seq, ack = _HDR.unpack_from(data)
        payload = data[_HDR.size:]
        if typ == ACK:
            self._on_ack(ack)
            return
        if typ not in (DATA, FIN):
            return
        # the in-order segment is ALWAYS accepted — if only out-of-order
        # segments could fill a capped buffer, a hostile peer that stuffed
        # the reorder buffer would wedge the stream permanently
        if seq == self._recv_next or (
                seq > self._recv_next and len(self._reorder) < _REORDER_CAP):
            self._reorder.setdefault(seq, (typ, payload))
            while self._recv_next in self._reorder:
                t, p = self._reorder.pop(self._recv_next)
                self._recv_next += 1
                if t == FIN:
                    self.reader.feed_eof()
                elif p:
                    self.reader.feed_data(p)
        # cumulative ack (also for duplicates — the ack may have been lost)
        self._ep.sendto(_HDR.pack(ACK, 0, self._recv_next), self.remote)

    def _on_ack(self, ack: int) -> None:
        advanced = False
        for seq in list(self._unacked):
            if seq < ack:
                del self._unacked[seq]
                advanced = True
        if advanced:
            self._send_base = ack
            self._retries = 0
            self._dup_acks = 0
            self._rto = RTO_INITIAL
            if len(self._unacked) < WINDOW:
                self._window_free.set()
            self._rearm_timer()
        elif ack == self._send_base and self._unacked:
            # duplicate cumulative ack: the hole at send_base was lost —
            # fast-retransmit it without waiting out the RTO
            self._dup_acks += 1
            if self._dup_acks >= 3:
                self._dup_acks = 0
                self._ep.sendto(self._unacked[min(self._unacked)], self.remote)
        if self._fin_sent and not self._unacked:
            self._fin_acked.set()

    # --- sender --------------------------------------------------------

    def _transmit(self, typ: int, payload: bytes) -> None:
        seq = self._next_seq
        self._next_seq += 1
        dgram = _HDR.pack(typ, seq, 0) + payload
        self._unacked[seq] = dgram
        if len(self._unacked) >= WINDOW:
            self._window_free.clear()
        self._ep.sendto(dgram, self.remote)
        self._rearm_timer()

    def _rearm_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._unacked and not self._closed:
            self._timer = self._loop.call_later(self._rto, self._on_timeout)

    def _on_timeout(self) -> None:
        self._timer = None
        if not self._unacked or self._closed:
            return
        self._retries += 1
        if self._retries > MAX_RETRIES:
            self._fail(UdpStreamError("udp stream: peer unreachable"))
            return
        self._rto = min(self._rto * 2, RTO_MAX)
        # go-back-N: re-send a burst from the earliest hole — with lossy
        # links (acks drop too) repairing one segment per RTO crawls
        for seq in sorted(self._unacked)[:RETX_BURST]:
            self._ep.sendto(self._unacked[seq], self.remote)
        self._rearm_timer()

    def _fail(self, exc: Exception) -> None:
        if self._closed:
            return
        self._closed = True
        self.reader.set_exception(exc)
        self._fin_acked.set()
        # unblock anything parked on a full window (drain/_drain_pending/
        # _graceful_close) — their loops re-check _closed and bail
        self._window_free.set()
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._owns:
            self._ep.close()

    # --- writer facade (what transport.py expects) ---------------------

    def write(self, data: bytes) -> None:
        if self._closed or self._fin_sent:
            raise UdpStreamError("udp stream closed")
        view = memoryview(bytes(data))
        for off in range(0, max(len(view), 1), MSS):
            self._pending_writes.append(bytes(view[off:off + MSS]))
        self._kick_sender()

    def _kick_sender(self) -> None:
        if self._sender_task is None or self._sender_task.done():
            self._sender_task = self._loop.create_task(self._drain_pending())

    async def _drain_pending(self) -> None:
        while self._pending_writes and not self._closed:
            await self._window_free.wait()
            if self._closed:
                return
            if self._pending_writes:
                self._transmit(DATA, self._pending_writes.popleft())

    async def drain(self) -> None:
        while self._pending_writes and not self._closed:
            await asyncio.sleep(0)
            await self._window_free.wait()
        if self._closed and not self._fin_sent:
            raise UdpStreamError("udp stream closed")

    def close(self) -> None:
        if self._closed or self._fin_sent:
            return
        self._fin_sent = True
        self._loop.create_task(self._graceful_close())

    async def _graceful_close(self) -> None:
        try:
            # flush queued writes, then a reliable FIN
            while self._pending_writes and not self._closed:
                await self._window_free.wait()
                if self._pending_writes:
                    self._transmit(DATA, self._pending_writes.popleft())
            self._transmit(FIN, b"")
            await asyncio.wait_for(self._fin_acked.wait(), 5.0)
        except (asyncio.TimeoutError, Exception):
            pass
        finally:
            self._closed = True
            self._fin_acked.set()  # give-up still unblocks wait_closed()
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            if self._owns:
                self._ep.close()

    async def wait_closed(self) -> None:
        if self._fin_sent:
            await self._fin_acked.wait()

    def get_extra_info(self, name: str, default: Any = None) -> Any:
        if name == "peername":
            return self.remote
        if name == "sockname":
            return self._ep.local_addr
        return default
