"""Encrypted authenticated stream transport (Noise XX).

Parity: ref:crates/p2p2/src/quic/transport.rs + stream.rs — the
reference runs a patched libp2p whose secure channel is libp2p-noise
(`Noise_XX_25519_ChaChaPoly_SHA256` + a signed identity payload) under
protocol `/sdp2p/1`, and hands out `UnicastStream`s.  Here each unicast
stream is one asyncio TCP connection secured by the same construction:

  → clear:  protocol magic `/sdp2p/1` (also the Noise prologue)
  → msg1:   XX `e`
  ← msg2:   XX `e, ee, s, es`   payload: ident_pub ‖ sig(ctx ‖ s_pub)
  → msg3:   XX `s, se`          payload: ident_pub ‖ sig(ctx ‖ s_pub)

The Noise state machine lives in `noise.py` (written against the public
spec, rev 34); each side's ed25519 identity is bound to its session
X25519 static by the libp2p-noise signed payload.  Transport-phase
records are Noise transport messages (≤64 KiB) framed with a 2-byte BE
length, keys from Split(), counter nonces per spec §5.1.  Security
argument and threat model: docs/security.md.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Awaitable, Callable

try:
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
    )
except ImportError:  # gated: noise.require_crypto() refuses at handshake
    X25519PrivateKey = None  # type: ignore

from . import noise
from .identity import Identity, RemoteIdentity
from .noise import CipherState, HandshakeState, NoiseError

PROTOCOL = b"/sdp2p/1"  # ref:quic/transport.rs:33
MAX_RECORD = noise.MAX_PLAINTEXT  # plaintext bytes per encrypted record


class HandshakeError(Exception):
    pass


async def _send_msg(writer: asyncio.StreamWriter, msg: bytes) -> None:
    writer.write(struct.pack(">H", len(msg)) + msg)
    await writer.drain()


async def _recv_msg(reader: asyncio.StreamReader) -> bytes:
    (length,) = struct.unpack(">H", await reader.readexactly(2))
    return await reader.readexactly(length)


class EncryptedStream:
    """One bidirectional encrypted stream (ref:stream.rs `UnicastStream`)
    in the Noise transport phase."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        send: CipherState,
        recv: CipherState,
        remote_identity: RemoteIdentity,
    ):
        self._reader = reader
        self._writer = writer
        self._send = send
        self._recv = recv
        self._recv_buf = bytearray()
        self.remote_identity = remote_identity
        self._closed = False

    # --- raw byte API (wire.Reader/Writer plug in here) ---

    async def write(self, data: bytes) -> None:
        view = memoryview(data)
        for off in range(0, max(len(view), 1), MAX_RECORD):
            chunk = bytes(view[off : off + MAX_RECORD])
            ct = self._send.encrypt_with_ad(b"", chunk)
            self._writer.write(struct.pack(">H", len(ct)) + ct)
        await self._writer.drain()

    async def read_exact(self, n: int) -> bytes:
        while len(self._recv_buf) < n:
            ct = await _recv_msg(self._reader)
            try:
                self._recv_buf += self._recv.decrypt_with_ad(b"", ct)
            except NoiseError as exc:
                raise ValueError("record decrypt failed") from exc
        out = bytes(self._recv_buf[:n])
        del self._recv_buf[:n]
        return out

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._writer.close()
            await self._writer.wait_closed()
        except Exception:
            pass

    @property
    def peer_addr(self) -> tuple[str, int] | None:
        try:
            return self._writer.get_extra_info("peername")[:2]
        except Exception:
            return None


def _split_for_role(hs: HandshakeState) -> tuple[CipherState, CipherState]:
    """(send, recv) cipher states for this side's role."""
    c_i2r, c_r2i = hs.split()
    return (c_i2r, c_r2i) if hs.initiator else (c_r2i, c_i2r)


async def _client_handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    identity: Identity,
    expect: RemoteIdentity | None,
) -> EncryptedStream:
    noise.require_crypto()
    static = X25519PrivateKey.generate()
    hs = HandshakeState(initiator=True, s=static, prologue=PROTOCOL)
    try:
        writer.write(PROTOCOL)
        await _send_msg(writer, hs.write_message(b""))

        payload = hs.read_message(await _recv_msg(reader))
        srv_ident = noise.verify_identity_payload(payload, hs.rs)
        if expect is not None and srv_ident != expect:
            raise HandshakeError(f"unexpected peer identity {srv_ident}")

        my_payload = noise.identity_payload(identity, hs.local_static_pub)
        await _send_msg(writer, hs.write_message(my_payload))
    except NoiseError as exc:
        raise HandshakeError(str(exc)) from exc

    send, recv = _split_for_role(hs)
    return EncryptedStream(reader, writer, send, recv, srv_ident)


async def _server_handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    identity: Identity,
) -> EncryptedStream:
    magic = await reader.readexactly(len(PROTOCOL))
    if magic != PROTOCOL:
        raise HandshakeError("bad protocol magic")
    noise.require_crypto()
    static = X25519PrivateKey.generate()
    hs = HandshakeState(initiator=False, s=static, prologue=PROTOCOL)
    try:
        hs.read_message(await _recv_msg(reader))

        my_payload = noise.identity_payload(identity, hs.local_static_pub)
        await _send_msg(writer, hs.write_message(my_payload))

        payload = hs.read_message(await _recv_msg(reader))
        cli_ident = noise.verify_identity_payload(payload, hs.rs)
    except NoiseError as exc:
        raise HandshakeError(str(exc)) from exc

    send, recv = _split_for_role(hs)
    return EncryptedStream(reader, writer, send, recv, cli_ident)


class Listener:
    """Bound accept socket handing each authenticated stream to
    `on_stream` (ref:transport.rs incoming-stream task)."""

    def __init__(self, server: asyncio.base_events.Server, port: int):
        self._server = server
        self.port = port

    async def close(self) -> None:
        self._server.close()
        await self._server.wait_closed()


async def listen(
    identity: Identity,
    on_stream: Callable[[EncryptedStream], Awaitable[None]],
    host: str = "0.0.0.0",
    port: int = 0,
) -> Listener:
    async def handle(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            stream = await _server_handshake(reader, writer, identity)
        except (HandshakeError, asyncio.IncompleteReadError, OSError):
            writer.close()
            return
        try:
            await on_stream(stream)
        finally:
            await stream.close()

    server = await asyncio.start_server(handle, host, port)
    bound = server.sockets[0].getsockname()[1]
    return Listener(server, bound)


async def connect(
    addr: tuple[str, int],
    identity: Identity,
    expect: RemoteIdentity | None = None,
    timeout: float = 10.0,
) -> EncryptedStream:
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(addr[0], addr[1]), timeout
    )
    try:
        return await asyncio.wait_for(
            _client_handshake(reader, writer, identity, expect), timeout
        )
    except BaseException:
        writer.close()
        raise
