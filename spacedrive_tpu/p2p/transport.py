"""Encrypted authenticated stream transport.

Parity: ref:crates/p2p2/src/quic/transport.rs + stream.rs — the
reference runs QUIC (TLS with identity-derived certs) on a patched
libp2p, protocol `/sdp2p/1`, and hands out `UnicastStream`s. Here each
unicast stream is one asyncio TCP connection secured by a Noise-style
handshake:

  client → server: eph X25519 pub ‖ ed25519 identity pub
  server → client: eph X25519 pub ‖ identity pub ‖ sig(transcript)
  client → server: sig(transcript)

Both sides HKDF the X25519 shared secret into two ChaCha20-Poly1305
directional keys; records are 4-byte-BE-length framed ciphertexts with
64-bit counter nonces. Mutual identity authentication matches the
reference's trust model (raw keypairs, no CA); the ephemeral DH gives
forward secrecy like QUIC's TLS handshake.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Awaitable, Callable

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF

from .identity import Identity, RemoteIdentity

PROTOCOL = b"/sdp2p/1"  # ref:quic/transport.rs:33
MAX_RECORD = 1 << 20  # plaintext bytes per encrypted record


class HandshakeError(Exception):
    pass


def _derive_keys(shared: bytes, transcript: bytes) -> tuple[bytes, bytes]:
    okm = HKDF(
        algorithm=hashes.SHA256(), length=64, salt=transcript, info=PROTOCOL
    ).derive(shared)
    return okm[:32], okm[32:]


class EncryptedStream:
    """One bidirectional encrypted stream (ref:stream.rs `UnicastStream`)."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        send_key: bytes,
        recv_key: bytes,
        remote_identity: RemoteIdentity,
    ):
        self._reader = reader
        self._writer = writer
        self._send = ChaCha20Poly1305(send_key)
        self._recv = ChaCha20Poly1305(recv_key)
        self._send_ctr = 0
        self._recv_ctr = 0
        self._recv_buf = bytearray()
        self.remote_identity = remote_identity
        self._closed = False

    # --- raw byte API (wire.Reader/Writer plug in here) ---

    async def write(self, data: bytes) -> None:
        view = memoryview(data)
        for off in range(0, max(len(view), 1), MAX_RECORD):
            chunk = bytes(view[off : off + MAX_RECORD])
            nonce = struct.pack(">IQ", 0, self._send_ctr)
            self._send_ctr += 1
            ct = self._send.encrypt(nonce, chunk, None)
            self._writer.write(struct.pack(">I", len(ct)) + ct)
        await self._writer.drain()

    async def read_exact(self, n: int) -> bytes:
        while len(self._recv_buf) < n:
            hdr = await self._reader.readexactly(4)
            (length,) = struct.unpack(">I", hdr)
            if length > MAX_RECORD + 16:
                raise ValueError("oversized record")
            ct = await self._reader.readexactly(length)
            nonce = struct.pack(">IQ", 0, self._recv_ctr)
            self._recv_ctr += 1
            self._recv_buf += self._recv.decrypt(nonce, ct, None)
        out = bytes(self._recv_buf[:n])
        del self._recv_buf[:n]
        return out

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._writer.close()
            await self._writer.wait_closed()
        except Exception:
            pass

    @property
    def peer_addr(self) -> tuple[str, int] | None:
        try:
            return self._writer.get_extra_info("peername")[:2]
        except Exception:
            return None


async def _client_handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    identity: Identity,
    expect: RemoteIdentity | None,
) -> EncryptedStream:
    eph = X25519PrivateKey.generate()
    eph_pub = eph.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )
    my_ident = identity.to_remote_identity().to_bytes()
    writer.write(PROTOCOL + eph_pub + my_ident)
    await writer.drain()

    srv = await reader.readexactly(32 + 32 + 64)
    srv_eph, srv_ident_raw, srv_sig = srv[:32], srv[32:64], srv[64:]
    srv_ident = RemoteIdentity(srv_ident_raw)
    transcript = PROTOCOL + eph_pub + my_ident + srv_eph + srv_ident_raw
    if not srv_ident.verify(srv_sig, transcript + b"server"):
        raise HandshakeError("server signature invalid")
    if expect is not None and srv_ident != expect:
        raise HandshakeError(f"unexpected peer identity {srv_ident}")

    writer.write(identity.sign(transcript + b"client"))
    await writer.drain()

    shared = eph.exchange(X25519PublicKey.from_public_bytes(srv_eph))
    c2s, s2c = _derive_keys(shared, transcript)
    return EncryptedStream(reader, writer, c2s, s2c, srv_ident)


async def _server_handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    identity: Identity,
) -> EncryptedStream:
    hello = await reader.readexactly(len(PROTOCOL) + 32 + 32)
    if hello[: len(PROTOCOL)] != PROTOCOL:
        raise HandshakeError("bad protocol magic")
    cli_eph = hello[len(PROTOCOL) : len(PROTOCOL) + 32]
    cli_ident_raw = hello[len(PROTOCOL) + 32 :]
    cli_ident = RemoteIdentity(cli_ident_raw)

    eph = X25519PrivateKey.generate()
    eph_pub = eph.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )
    my_ident = identity.to_remote_identity().to_bytes()
    transcript = PROTOCOL + cli_eph + cli_ident_raw + eph_pub + my_ident
    writer.write(eph_pub + my_ident + identity.sign(transcript + b"server"))
    await writer.drain()

    cli_sig = await reader.readexactly(64)
    if not cli_ident.verify(cli_sig, transcript + b"client"):
        raise HandshakeError("client signature invalid")

    shared = eph.exchange(X25519PublicKey.from_public_bytes(cli_eph))
    c2s, s2c = _derive_keys(shared, transcript)
    return EncryptedStream(reader, writer, s2c, c2s, cli_ident)


class Listener:
    """Bound accept socket handing each authenticated stream to
    `on_stream` (ref:transport.rs incoming-stream task)."""

    def __init__(self, server: asyncio.base_events.Server, port: int):
        self._server = server
        self.port = port

    async def close(self) -> None:
        self._server.close()
        await self._server.wait_closed()


async def listen(
    identity: Identity,
    on_stream: Callable[[EncryptedStream], Awaitable[None]],
    host: str = "0.0.0.0",
    port: int = 0,
) -> Listener:
    async def handle(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            stream = await _server_handshake(reader, writer, identity)
        except (HandshakeError, asyncio.IncompleteReadError, OSError):
            writer.close()
            return
        try:
            await on_stream(stream)
        finally:
            await stream.close()

    server = await asyncio.start_server(handle, host, port)
    bound = server.sockets[0].getsockname()[1]
    return Listener(server, bound)


async def connect(
    addr: tuple[str, int],
    identity: Identity,
    expect: RemoteIdentity | None = None,
    timeout: float = 10.0,
) -> EncryptedStream:
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(addr[0], addr[1]), timeout
    )
    try:
        return await asyncio.wait_for(
            _client_handshake(reader, writer, identity, expect), timeout
        )
    except BaseException:
        writer.close()
        raise
