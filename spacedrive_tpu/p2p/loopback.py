"""In-process duplex P2P transport — two REAL nodes, one process.

The two-node test/bench harness: builds real ``Node``s sharing one
library and links their ``P2PManager``s over an in-process duplex
that drives the real wire protocol (``Header`` SYNC / SYNC_REQUEST /
TELEMETRY / WORK, msgpack frames) without the encrypted socket layer,
so it runs in dep-less CI containers where ``cryptography`` is absent.
Extracted from tests/test_mesh_observability.py so the mesh-parallel
index tests and ``bench_e2e.py``'s ``config_mesh`` drive the SAME
loopback instead of three drifting copies.

Note: both nodes live in one process and therefore share the global
metrics registry and flight-recorder rings — per-peer series stay
distinguishable because every label is the instance's ``peer_label``
short-hash.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
from typing import Any

logger = logging.getLogger(__name__)


class Pipe:
    """One direction of a duplex stream: an awaitable byte buffer."""

    def __init__(self):
        self._buf = bytearray()
        self._event = asyncio.Event()

    async def write(self, data: bytes) -> None:
        self._buf += data
        self._event.set()

    async def read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            self._event.clear()
            await self._event.wait()
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


class DuplexEnd:
    """One side of the duplex: reads one pipe, writes the other, and
    carries the remote's identity the way a real stream would."""

    def __init__(self, rd: Pipe, wr: Pipe, remote_identity: Any):
        self._rd, self._wr = rd, wr
        self.remote_identity = remote_identity

    async def write(self, data: bytes) -> None:
        await self._wr.write(data)

    async def read_exact(self, n: int) -> bytes:
        return await self._rd.read_exact(n)

    async def close(self) -> None:
        pass


def fake_transport(src_mgr: Any, dst_mgr: Any, server_tasks: set):
    """A ``new_stream`` replacement: in-process duplex whose server end
    is dispatched through the destination manager's REAL stream handler
    (the full Header protocol, minus socket encryption)."""

    async def new_stream(identity, timeout: float = 10.0):
        assert identity == dst_mgr.p2p.remote_identity
        c2s, s2c = Pipe(), Pipe()
        client = DuplexEnd(s2c, c2s, dst_mgr.p2p.remote_identity)
        server = DuplexEnd(c2s, s2c, src_mgr.p2p.remote_identity)
        task = asyncio.ensure_future(dst_mgr._handle_stream(server))
        server_tasks.add(task)

        def _reap(t: asyncio.Task) -> None:
            server_tasks.discard(t)
            if not t.cancelled() and t.exception() is not None:
                # a responder racing node shutdown (library DB already
                # closed) is harness teardown, not a test failure —
                # keep it off the unraisable-exception channel
                logger.debug("loopback server task died: %r", t.exception())

        task.add_done_callback(_reap)
        return client

    return new_stream


async def make_mesh_pair(base_dir: str | os.PathLike,
                         names: tuple[str, str] = ("alpha", "beta")):
    """Two Nodes sharing one library, P2PManagers linked in-process.

    Returns ``(node_a, node_b, lib_a, lib_b, server_tasks)`` — the
    library is created on ``node_a`` and shared to ``node_b`` by file
    move (the pairing outcome), with each instance row carrying the
    owning node's ``RemoteIdentity`` bytes so the TELEMETRY/WORK
    library-membership gates admit both sides.
    """
    from ..node import Node
    from .manager import P2PManager

    nodes = []
    for name in names:
        n = Node(os.path.join(os.fspath(base_dir), name), use_device=False,
                 with_labeler=False)
        n.config.config.p2p.enabled = False
        n.config.config.name = name
        await n.start()
        nodes.append(n)
    a, b = nodes

    lib_a = await a.create_library("shared")
    # share the library id with the second node (pairing, by file move)
    b.libraries.libraries.clear()
    lib_b_local = b.libraries.create("shared")
    old = lib_b_local.id
    for suffix in (".sdlibrary", ".db"):
        shutil.move(
            os.path.join(b.libraries.dir, f"{old}{suffix}"),
            os.path.join(b.libraries.dir, f"{lib_a.id}{suffix}"),
        )
    for s in ("-wal", "-shm"):
        p = os.path.join(b.libraries.dir, f"{old}.db{s}")
        if os.path.exists(p):
            shutil.move(p, os.path.join(b.libraries.dir, f"{lib_a.id}.db{s}"))
    lib_b_local.close()
    b.libraries.libraries.clear()
    lib_b = b.libraries._load(lib_a.id)
    await b._init_library(lib_b)
    for src, dst, src_node in ((lib_a, lib_b, a), (lib_b, lib_a, b)):
        inst = src.db.find_one("instance", pub_id=src.instance_uuid.bytes)
        dst.db.insert(
            "instance",
            pub_id=inst["pub_id"],
            # what the pairing flow stores: the owning node's
            # RemoteIdentity bytes — the TELEMETRY/WORK responders'
            # library-membership gates key off this
            identity=src_node.config.config.identity
            .to_remote_identity().to_bytes(),
            node_id=inst["node_id"], node_name=inst["node_name"],
            node_platform=inst["node_platform"], last_seen=inst["last_seen"],
            date_created=inst["date_created"],
        )

    a.p2p = P2PManager(a)
    b.p2p = P2PManager(b)
    server_tasks: set = set()
    a.p2p.p2p.new_stream = fake_transport(a.p2p, b.p2p, server_tasks)
    b.p2p.p2p.new_stream = fake_transport(b.p2p, a.p2p, server_tasks)
    a.p2p.register_library(lib_a)
    b.p2p.register_library(lib_b)
    # mutual "discovery" with library/instance metadata (what mdns
    # beacons would have advertised)
    for me, other, other_lib in ((a, b, lib_b), (b, a, lib_a)):
        me.p2p.p2p.discovered(
            "test",
            other.p2p.p2p.remote_identity,
            {("127.0.0.1", 1)},
            {
                "name": other.config.config.name,
                "libraries": str(other_lib.id),
                "instances": str(other_lib.sync.instance),
            },
        )
    return a, b, lib_a, lib_b, server_tasks
