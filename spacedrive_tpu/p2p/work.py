"""Mesh work-stealing — shard dispatch for one location's index work
across library peers.

The reference's task system is explicitly work-stealing
(ref:crates/task-system, PAPER.md §L3); this module scales the same
idea past one host: the coordinating node splits a location's
identify work into **journal-keyed shards** (file-path key + stat
identity, so a peer's own index-journal hits still count), publishes
them on a :class:`WorkBoard`, and idle library peers pull shards over
a new ``WORK`` wire header — the inverted (pull) form of stealing,
which is the only form that works when the thief is across a network
hop.

Safety model (the part that makes re-stealing free):

- **leases, not assignments** — a claim grants shards for a bounded
  lease sized from the peer's observed throughput and its federated
  ``/mesh`` health verdict (slow or degraded peers get fewer shards
  and shorter leases; unhealthy or stale peers get none). A lease
  that expires returns the shard to the steal pool; nothing waits on
  a dead peer.
- **idempotent execution** — shard results (cas_id assignments,
  object links, journal vouches) merge through the existing HLC/LWW
  sync path like any other op, and object pub_ids are derived
  deterministically from ``(library, cas_id)``
  (``location/indexer/mesh.py``), so a twice-executed shard — lease
  expiry, claim race, peer death after sync but before its
  ``complete`` — converges to the same rows instead of corrupting.
- **resilience** — every peer-facing leg (announce, claim, complete)
  rides :data:`WORK_POLICY` with a per-peer breaker, so a flapping
  peer costs one fast ``BreakerOpen`` instead of a retry ladder.

Wire ops (msgpack body after ``Header(WORK, library_id)``, served to
library members only — same trust bar as TELEMETRY):

- ``announce``  coordinator → peer: a session has work; the peer
  starts a claim loop against the announcer.
- ``claim``     peer → coordinator: lease up to ``max_shards``;
  reports the claimer's observed files/s for lease sizing.
- ``complete``  peer → coordinator: shard results (idempotent; a
  duplicate completion is counted and absorbed).
- ``status``    board introspection (tests, ``/mesh`` drill-down).

Fault points: ``p2p.steal`` (``vanish`` at arg ``lease`` = claiming
worker dies mid-lease; ``race`` at arg ``claim`` = a shard is
double-leased) — see docs/robustness.md.
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

from ..telemetry import metrics as _tm
from ..telemetry import trace as _trace
from ..telemetry.events import WORK_EVENTS
from ..telemetry.peers import peer_label
from ..utils import faults as _faults
from ..utils.resilience import (
    PASS,
    RETRY,
    BreakerOpen,
    ResiliencePolicy,
    RetryPolicy,
)
from .protocol import Header, HeaderType
from .wire import Reader, Writer

logger = logging.getLogger(__name__)

WORK_TIMEOUT = 30.0          # one wire exchange
CLAIM_POLL_S = 0.2           # worker poll while the board is drained
DEFAULT_FILES_PER_S = 50.0   # lease sizing before any throughput is observed
LEASE_SLACK = 4.0            # lease = slack × estimated shard wall-clock
LEASE_MIN_S = 5.0
LEASE_MAX_S = 120.0
MAX_SHARDS_PER_CLAIM = 4
WORKER_MAX_FAILURES = 5      # consecutive wire failures before giving up

#: shard states
AVAILABLE, LEASED, DONE = "available", "leased", "done"


def _peer_classify(exc: BaseException) -> str:
    """Transport failures retry and count toward the breaker; an answer
    we dislike (refusal, malformed body) passes through untouched."""
    if isinstance(exc, (PermissionError, ValueError)):
        return PASS
    return RETRY


#: One bounded, jittered retry ladder + per-peer breaker for every
#: work-plane exchange. Mirrors manager.SYNC_POLICY but with its own
#: breaker namespace: a peer whose sync plane is sick may still be a
#: fine steal target (and vice versa).
WORK_POLICY = ResiliencePolicy(
    "p2p_work",
    RetryPolicy(max_attempts=2, base_delay=0.05, max_delay=0.5,
                attempt_timeout=WORK_TIMEOUT),
    failure_threshold=3,
    reset_timeout=15.0,
    classify=_peer_classify,
)


# --- the board (coordinator side) -----------------------------------------


@dataclass
class WorkShard:
    """One leased unit: a batch of journal-keyed file entries, typed by
    the pipeline stage that executes it (``parallel/scheduler.py`` is
    the stage vocabulary — identify.hash, thumb, media.extract, phash,
    embed). Pre-continuum shards carried no stage; the default keeps
    old wire bodies and tests meaning what they always meant."""

    id: str
    entries: list[dict]  # {pub_id, mat, name, ext, ...} (stage-shaped)
    stage: str = "identify.hash"
    state: str = AVAILABLE
    assignee: str | None = None
    lease_deadline: float = 0.0
    grants: int = 0
    # every peer this shard was EVER leased to: a complete from anyone
    # else is rejected (a member may only report work it was granted)
    granted_to: set = field(default_factory=set)

    def to_wire(self) -> dict:
        return {"id": self.id, "stage": self.stage, "entries": self.entries}


@dataclass
class WorkSession:
    """One location's distributed pass."""

    id: str
    library_id: uuid.UUID
    location_pub: str  # location pub_id hex (peers resolve their local row)
    shards: dict[str, WorkShard] = field(default_factory=dict)
    #: per-session lease clamp override (tests/bench use short leases)
    lease_max_s: float = LEASE_MAX_S
    created_at: float = field(default_factory=time.time)
    completed_by: dict[str, str] = field(default_factory=dict)  # shard -> peer

    def pending(self) -> int:
        return sum(1 for s in self.shards.values() if s.state != DONE)

    def all_done(self) -> bool:
        return self.pending() == 0


class WorkBoard:
    """Session registry + lease bookkeeping on the coordinating node.

    Single-threaded by construction (all calls run on the node's event
    loop: the responder coroutines and the coordinator's local loop),
    so state transitions need no lock — the async boundary IS the
    serialization point.
    """

    def __init__(self) -> None:
        self.sessions: dict[str, WorkSession] = {}

    def publish(self, session: WorkSession) -> None:
        self.sessions[session.id] = session
        by_stage: dict[str, int] = {}
        for sh in session.shards.values():
            by_stage[sh.stage] = by_stage.get(sh.stage, 0) + 1
        for st, n in by_stage.items():
            # inline bounded conditional pins the stage label domain at
            # the emit site (SD007): the scheduler registry is the
            # entire vocabulary
            _tm.WORK_SHARDS.inc(
                n, result="published",
                stage="identify.hash" if st == "identify.hash" else (
                    "thumb" if st == "thumb" else (
                        "media.extract" if st == "media.extract" else (
                            "phash" if st == "phash" else (
                                "embed" if st == "embed" else "other")))),
            )
        WORK_EVENTS.emit(
            "publish", session=session.id, shards=len(session.shards),
            stages=sorted(by_stage), library=str(session.library_id),
        )

    def get(self, session_id: str) -> WorkSession | None:
        return self.sessions.get(session_id)

    def expire_leases(self, session_id: str) -> int:
        """Return expired-lease shards to the steal pool."""
        session = self.sessions.get(session_id)
        if session is None:
            return 0
        now = time.monotonic()
        n = 0
        expired_by_stage: dict[str, int] = {}
        for shard in session.shards.values():
            if shard.assignee == "local":
                # the coordinator's own in-flight execution: "peer
                # death" is meaningless here (if the coordinator dies
                # the session dies), and expiring it under load just
                # buys a duplicate execution
                continue
            if shard.state == LEASED and now >= shard.lease_deadline:
                shard.state = AVAILABLE
                WORK_EVENTS.emit(
                    "lease_expired", session=session_id, shard=shard.id,
                    peer=peer_label(shard.assignee or "?"),
                )
                shard.assignee = None
                expired_by_stage[shard.stage] = (
                    expired_by_stage.get(shard.stage, 0) + 1)
                n += 1
        for st, cnt in expired_by_stage.items():
            _tm.WORK_SHARDS.inc(
                cnt, result="expired",
                stage="identify.hash" if st == "identify.hash" else (
                    "thumb" if st == "thumb" else (
                        "media.extract" if st == "media.extract" else (
                            "phash" if st == "phash" else (
                                "embed" if st == "embed" else "other")))),
            )
        return n

    def claim(
        self,
        session_id: str | None,
        peer_id: str,
        *,
        library_id: uuid.UUID | None = None,
        max_shards: int = 1,
        files_per_s: float = 0.0,
        rates: dict | None = None,
        verdict: str = "unknown",
        local: bool = False,
    ) -> tuple[WorkSession | None, list[WorkShard], float]:
        """Lease up to ``max_shards`` to ``peer_id``. With no session id
        the NEWEST open session FOR ``library_id`` that still has an
        available shard is used (idle peers steal without knowing
        session ids — and a newer fully-leased session must not mask an
        older session's unclaimed shards). A claimer is scoped to the
        library its WORK header named — membership in library X must
        never lease (or even reveal) library Y's shards. ``rates`` is
        the claimer's per-stage files/s self-report: grants prefer the
        stages the claimer is fastest at, and each stage's lease
        contribution is sized from its own rate (heterogeneous-fleet
        scheduling); ``files_per_s`` stays as the stage-blind fallback.
        Returns ``(session, shards, lease_seconds)`` — an empty grant
        with a session means "drained or gated", with ``None`` "no work
        at all"."""
        session = None
        if session_id is not None:
            session = self.sessions.get(session_id)
            if session is not None and library_id is not None \
                    and session.library_id != library_id:
                return None, [], 0.0
            if session is not None:
                self.expire_leases(session.id)
        else:
            open_sessions = sorted(
                (
                    s for s in self.sessions.values()
                    if not s.all_done()
                    and (library_id is None or s.library_id == library_id)
                ),
                key=lambda s: s.created_at, reverse=True,
            )
            for cand in open_sessions:
                # expire before inspecting: a lapsed lease IS an
                # available shard for the next claimer
                self.expire_leases(cand.id)
                if any(sh.state == AVAILABLE
                       for sh in cand.shards.values()):
                    session = cand
                    break
            else:
                # everything in flight: poll against the newest open
                # session (matches the historical behavior when no
                # shard is available anywhere)
                session = open_sessions[0] if open_sessions else None
        if session is None:
            return None, [], 0.0
        if not local:
            # health-gated stealing: a peer the federated mesh view
            # calls unhealthy (or whose snapshot went stale — silence
            # is a symptom) gets nothing; a degraded peer gets one
            # small shard so it can prove itself without hoarding
            if verdict == "unhealthy":
                _tm.WORK_SHARDS.inc(result="refused", stage="any")
                WORK_EVENTS.emit(
                    "claim_refused", session=session.id,
                    peer=peer_label(peer_id), verdict=verdict,
                )
                return session, [], 0.0
            if verdict == "degraded":
                max_shards = 1
        avail = [
            sh for sh in session.shards.values() if sh.state == AVAILABLE
        ]
        if rates:
            # stable sort: the claimer's fastest stages first, board
            # insertion order breaking ties — a CPU-rich peer drains
            # the decode/encode stages, a chip-rich peer the device
            # stages, and rate-less stages keep publish order
            avail.sort(key=lambda sh: -float(rates.get(sh.stage) or 0.0))
        grant: list[WorkShard] = avail[:max(1, max_shards)]
        spec = _faults.hit("p2p.steal", arg="claim")
        if spec is not None and spec.mode == "race":
            # double-lease an already-leased shard: the chaos proof
            # that a raced (twice-executed) shard merges idempotently
            for shard in session.shards.values():
                if shard.state == LEASED and shard.assignee != peer_id:
                    grant.append(shard)
                    break
        from ..parallel import scheduler as _scheduler

        by_stage: dict[str, int] = {}
        for sh in grant:
            by_stage[sh.stage] = by_stage.get(sh.stage, 0) + len(sh.entries)
        n_files = sum(by_stage.values())
        # per-stage lease sizing: each stage's contribution is sized
        # from the claimer's rate FOR THAT STAGE (then the Controller's
        # per-stage target, then the static default — inside
        # lease_seconds_for); contributions sum because the claimer
        # executes the grant serially, and the session clamp still caps
        # the total. A single-stage grant reproduces the pre-continuum
        # lease law bit-for-bit.
        stage_leases: dict[str, float] = {}
        for st, files_st in by_stage.items():
            rate_st = float((rates or {}).get(st) or 0.0)
            if rate_st <= 0:
                rate_st = files_per_s
            stage_leases[st] = _scheduler.lease_seconds_for(
                st, files_st, rate_st, session.lease_max_s)
        lease_s = (
            min(sum(stage_leases.values()), session.lease_max_s)
            if stage_leases
            # empty grant: the historical floor (callers only read this
            # when shards were granted, but the reply shape is stable)
            else min(LEASE_MIN_S, session.lease_max_s)
        )
        if verdict == "degraded":
            lease_s = LEASE_MIN_S
        deadline = time.monotonic() + lease_s
        for shard in grant:
            shard.state = LEASED
            shard.assignee = peer_id
            shard.lease_deadline = deadline
            shard.grants += 1
            shard.granted_to.add(peer_id)
            if not local:
                st = shard.stage
                _tm.WORK_STEALS.inc(
                    peer=peer_label(peer_id),
                    stage="identify.hash" if st == "identify.hash" else (
                        "thumb" if st == "thumb" else (
                            "media.extract" if st == "media.extract" else (
                                "phash" if st == "phash" else (
                                    "embed" if st == "embed"
                                    else "other")))),
                )
        if grant:
            for st, stage_lease in stage_leases.items():
                _tm.WORK_LEASE_SECONDS.observe(
                    stage_lease,
                    stage="identify.hash" if st == "identify.hash" else (
                        "thumb" if st == "thumb" else (
                            "media.extract" if st == "media.extract" else (
                                "phash" if st == "phash" else (
                                    "embed" if st == "embed"
                                    else "other")))),
                )
            WORK_EVENTS.emit(
                "lease", session=session.id, peer=peer_label(peer_id),
                shards=len(grant), files=n_files,
                stages=sorted(by_stage),
                lease_s=round(lease_s, 2), local=local,
            )
        return session, grant, lease_s

    def complete(self, session_id: str, shard_id: str, peer_id: str,
                 *, library_id: uuid.UUID | None = None,
                 local: bool = False) -> str:
        """Mark a shard done. Returns ``completed`` for the first
        completion, ``duplicate`` for a re-stolen/raced repeat (the
        caller already merged idempotently), ``unknown`` otherwise —
        including completes scoped to the wrong library or from a peer
        this shard was never granted to (a member may only report work
        it was leased)."""
        session = self.sessions.get(session_id)
        if session is None:
            return "unknown"
        if library_id is not None and session.library_id != library_id:
            return "unknown"
        shard = session.shards.get(shard_id)
        if shard is None:
            return "unknown"
        if not local and peer_id not in shard.granted_to:
            return "unknown"
        st = shard.stage
        if shard.state == DONE:
            _tm.WORK_SHARDS.inc(
                result="duplicate",
                stage="identify.hash" if st == "identify.hash" else (
                    "thumb" if st == "thumb" else (
                        "media.extract" if st == "media.extract" else (
                            "phash" if st == "phash" else (
                                "embed" if st == "embed" else "other")))),
            )
            WORK_EVENTS.emit(
                "duplicate_complete", session=session_id, shard=shard_id,
                peer=peer_label(peer_id),
            )
            return "duplicate"
        shard.state = DONE
        shard.assignee = peer_id
        session.completed_by[shard_id] = peer_id
        _tm.WORK_SHARDS.inc(
            result="completed_local" if local else "completed_remote",
            stage="identify.hash" if st == "identify.hash" else (
                "thumb" if st == "thumb" else (
                    "media.extract" if st == "media.extract" else (
                        "phash" if st == "phash" else (
                            "embed" if st == "embed" else "other")))),
        )
        WORK_EVENTS.emit(
            "complete", session=session_id, shard=shard_id,
            peer=peer_label(peer_id), local=local,
        )
        return "completed"

    def retire(self, session_id: str) -> None:
        """Drop a finished (or abandoned) session: the shard entry
        lists hold per-file metadata for the whole location — a
        long-running coordinator must not accumulate one copy per
        pass. Workers seeing the session gone read ``done`` and stop;
        any in-flight results still arrive through sync."""
        session = self.sessions.pop(session_id, None)
        if session is not None:
            WORK_EVENTS.emit(
                "retire", session=session_id,
                shards=len(session.shards), done=session.all_done(),
            )

    def status(self, session_id: str) -> dict[str, Any] | None:
        session = self.sessions.get(session_id)
        if session is None:
            return None
        by_state: dict[str, int] = {}
        for s in session.shards.values():
            by_state[s.state] = by_state.get(s.state, 0) + 1
        return {
            "session": session.id,
            "library_id": str(session.library_id),
            "location_pub": session.location_pub,
            "shards": len(session.shards),
            "by_state": by_state,
            "done": session.all_done(),
        }


# --- wire halves ----------------------------------------------------------


async def request_work(
    p2p: Any, identity: Any, library_id: uuid.UUID, body: dict,
    timeout: float = WORK_TIMEOUT,
) -> dict:
    """One WORK exchange. Raises ``PermissionError`` on a refusal
    (membership gate), ``ValueError`` on a malformed response — both
    PASS through the policy without feeding the breaker."""
    from ..utils.compat import timeout as _timeout

    stream = await p2p.new_stream(identity)
    try:
        async with _timeout(timeout):
            await Header(
                HeaderType.WORK, library_id=library_id,
                trace=_trace.wire_current(),
            ).write(stream)
            w = Writer(stream)
            w.msgpack(body)
            await w.flush()
            resp = await Reader(stream).msgpack()
    finally:
        await stream.close()
    if isinstance(resp, dict) and resp.get("error"):
        raise PermissionError(str(resp["error"]))
    if not isinstance(resp, dict):
        raise ValueError("malformed WORK response")
    return resp


async def respond_work(stream: Any, node: Any, header: Any) -> None:
    """Server half, dispatched by the manager AFTER the library-member
    gate. ``claim``/``complete`` run against this node's board;
    ``announce`` starts this node's worker loop against the announcer."""
    body = await Reader(stream).msgpack()
    w = Writer(stream)
    if not isinstance(body, dict):
        w.msgpack({"error": "malformed WORK request"})
        await w.flush()
        return
    op = body.get("op")
    peer_id = str(getattr(stream, "remote_identity", "?"))
    plane: "WorkPlane | None" = getattr(node.p2p, "work", None)
    if plane is None:
        w.msgpack({"error": "work plane not running"})
        await w.flush()
        return

    if op == "claim":
        verdict = plane.peer_verdict(peer_id)
        # wire fields are untrusted: a non-numeric ask must get the
        # structured error reply (PASS through the caller's policy),
        # not a responder crash that reads as a transport failure and
        # feeds the healthy coordinator's breaker
        try:
            max_shards = int(body.get("max_shards", 1))
            files_per_s = float(body.get("files_per_s", 0.0))
        except (TypeError, ValueError):
            w.msgpack({"error": "malformed WORK claim fields"})
            await w.flush()
            return
        # the per-stage rate report is advisory (grant preference +
        # lease sizing): a malformed one degrades to the stage-blind
        # scalar instead of erroring the claim
        raw_rates = body.get("rates")
        rates: dict[str, float] = {}
        if isinstance(raw_rates, dict):
            for k, v in raw_rates.items():
                try:
                    rates[str(k)] = float(v)
                except (TypeError, ValueError):
                    continue
        session, shards, lease_s = plane.board.claim(
            body.get("session"), peer_id,
            # scope to the header's library (the one the membership
            # gate verified) and clamp the ask server-side: one slow
            # peer must not hoard a whole session under a single lease
            library_id=header.library_id,
            max_shards=min(max_shards, MAX_SHARDS_PER_CLAIM),
            files_per_s=files_per_s,
            rates=rates or None,
            verdict=verdict,
        )
        w.msgpack({
            "ok": True,
            "session": session.id if session else None,
            "location_pub": session.location_pub if session else None,
            "shards": [s.to_wire() for s in shards],
            "lease_s": lease_s,
            "done": session.all_done() if session else True,
        })
    elif op == "complete":
        # stage BEFORE complete: the shard's stage routes the merge,
        # and the board row is the trusted source (never the wire body)
        session = plane.board.get(str(body.get("session")))
        shard_row = (
            session.shards.get(str(body.get("shard")))
            if session is not None else None
        )
        stage_id = shard_row.stage if shard_row is not None \
            else "identify.hash"
        outcome = plane.board.complete(
            str(body.get("session")), str(body.get("shard")), peer_id,
            library_id=header.library_id,
        )
        applied = 0
        if outcome in ("completed", "duplicate"):
            # merge the shipped results locally (idempotent): the
            # coordinator gets cas rows / webp bytes / vectors +
            # journal vouches even when the peer's own sync ops are
            # still in flight — and a duplicate completion re-applies
            # to the same state
            from ..location.indexer.stages import apply_stage_results

            if session is not None:
                applied = apply_stage_results(
                    node, session, stage_id, body.get("results") or []
                )
        w.msgpack({"ok": True, "outcome": outcome, "applied": applied})
    elif op == "announce":
        session_id = str(body.get("session"))
        plane.worker.on_announce(
            getattr(stream, "remote_identity", None), header.library_id,
            session_id,
        )
        w.msgpack({"ok": True})
    elif op == "status":
        session = plane.board.get(str(body.get("session")))
        if session is not None and session.library_id != header.library_id:
            session = None  # cross-library probe reads as "no session"
        w.msgpack({"ok": True, "status": (
            plane.board.status(session.id) if session is not None else None
        )})
    else:
        w.msgpack({"error": f"unknown WORK op {op!r}"})
    await w.flush()


# --- the worker (stealing side) -------------------------------------------


class MeshWorker:
    """Per-node claim loop: on an announce, steal shards from the
    coordinator until its board reports done. Execution happens against
    this node's own library replica; results additionally ship back in
    ``complete`` so the coordinator can merge without waiting on sync."""

    def __init__(self, node: Any, manager: Any):
        self.node = node
        self.manager = manager
        self._loops: dict[str, asyncio.Task] = {}  # session id -> loop
        self.executed_shards = 0
        self.executed_files = 0
        self._stopped = False

    def on_announce(self, coordinator: Any, library_id: uuid.UUID,
                    session_id: str) -> None:
        if self._stopped or coordinator is None:
            return
        # prune finished loops (a long-lived node steals from many
        # sessions over its lifetime — done tasks must not accumulate)
        for sid in [s for s, t in self._loops.items() if t.done()]:
            del self._loops[sid]
        if session_id in self._loops:
            return
        task = asyncio.get_running_loop().create_task(
            self._work_loop(coordinator, library_id, session_id),
            name=f"mesh-worker-{session_id[:8]}",
        )
        self._loops[session_id] = task

    def observed_files_per_s(self) -> float:
        """This node's stage-blind throughput self-report (the legacy
        claim-sizing scalar, kept as the fallback for stages missing
        from the per-stage report): the identify EWMA the scheduler
        keeps, falling back to the autotune-observed identify rate
        before any shard ran here."""
        from ..parallel import scheduler as _scheduler

        return _scheduler.observed_files_per_s(_scheduler.STAGE_IDENTIFY)

    def rates_report(self) -> dict[str, float]:
        """Per-stage files/s self-report shipped with every claim (the
        continuum's heterogeneous-fleet input): the scheduler's EWMAs
        for every stage that has executed anything here."""
        from ..parallel import scheduler as _scheduler

        out: dict[str, float] = {}
        for stage_id in _scheduler.STAGES:
            rate = _scheduler.observed_files_per_s(stage_id)
            if rate > 0:
                out[stage_id] = round(rate, 3)
        return out

    async def stop(self) -> None:
        self._stopped = True
        loops = [t for t in self._loops.values() if not t.done()]
        for t in loops:
            t.cancel()
        if loops:
            await asyncio.gather(*loops, return_exceptions=True)
        self._loops.clear()

    async def _work_loop(self, coordinator: Any, library_id: uuid.UUID,
                         session_id: str) -> None:
        from ..location.indexer.stages import execute_stage_shard

        lib = self.node.libraries.get(library_id)
        if lib is None:
            return
        p2p = self.manager.p2p
        pid = str(coordinator)
        failures = 0
        while not self._stopped:
            try:
                resp = await WORK_POLICY.call(
                    pid,
                    lambda: request_work(p2p, coordinator, library_id, {
                        "op": "claim",
                        "session": session_id,
                        "max_shards": MAX_SHARDS_PER_CLAIM,
                        "files_per_s": self.observed_files_per_s(),
                        "rates": self.rates_report(),
                    }),
                )
                failures = 0
            except (BreakerOpen, ConnectionError, OSError, EOFError,
                    asyncio.TimeoutError, PermissionError, ValueError) as e:
                failures += 1
                logger.debug("work claim from %s failed: %s", pid, e)
                if failures >= WORKER_MAX_FAILURES:
                    return
                await asyncio.sleep(CLAIM_POLL_S)
                continue
            shards = resp.get("shards") or []
            if not shards:
                if resp.get("done"):
                    return
                await asyncio.sleep(CLAIM_POLL_S)
                continue
            spec = _faults.hit("p2p.steal", arg="lease")
            if spec is not None and spec.mode == "vanish":
                # the claiming peer dies mid-lease: shards stay leased
                # until the coordinator's deadline re-pools them
                WORK_EVENTS.emit("worker_vanish", session=session_id,
                                 shards=len(shards))
                return
            location_pub = resp.get("location_pub")
            for shard in shards:
                stage_id = str(shard.get("stage") or "identify.hash")
                try:
                    # execute_stage_shard feeds scheduler.RATES — the
                    # per-stage EWMA the next claim's report rides
                    results = await execute_stage_shard(
                        self.node, lib, location_pub, stage_id,
                        shard["entries"],
                    )
                except Exception:  # noqa: BLE001 - a bad shard must not kill the loop
                    logger.exception("shard %s execution failed", shard["id"])
                    continue
                self.executed_shards += 1
                self.executed_files += len(shard["entries"])
                try:
                    await WORK_POLICY.call(
                        pid,
                        lambda shard=shard, results=results: request_work(
                            p2p, coordinator, library_id, {
                                "op": "complete",
                                "session": session_id,
                                "shard": shard["id"],
                                "results": results,
                            }),
                    )
                except (BreakerOpen, ConnectionError, OSError, EOFError,
                        asyncio.TimeoutError, PermissionError,
                        ValueError) as e:
                    # the work itself is durable (our sync ops carry
                    # it); a lost complete only costs the coordinator a
                    # re-steal of an already-converged shard
                    logger.debug("work complete to %s failed: %s", pid, e)


class WorkPlane:
    """The per-node work-stealing surface hung off P2PManager: the
    board (when coordinating) + the worker (when stealing)."""

    def __init__(self, node: Any, manager: Any):
        self.node = node
        self.manager = manager
        self.board = WorkBoard()
        self.worker = MeshWorker(node, manager)

    def peer_verdict(self, peer_id: str) -> str:
        """The federated mesh verdict for a claiming peer: ``unknown``
        when we hold no (fresh) snapshot — never a blocker for a mesh
        that has not exchanged telemetry yet — and ``unhealthy`` when
        the snapshot says so or went stale."""
        federation = getattr(self.manager, "federation", None)
        if federation is None:
            return "unknown"
        entry = federation.mesh()["peers"].get(str(peer_id))
        if entry is None:
            return "unknown"
        return str(entry.get("verdict", "unknown"))

    async def announce(self, session: WorkSession) -> int:
        """Tell every library peer the session has work; returns how
        many peers acknowledged. Announces run CONCURRENTLY — they are
        independent, and the coordinator must not stall its own pass
        behind one hung peer's retry ladder (the per-peer breaker makes
        the fan-out safe)."""
        manager = self.manager

        async def one(peer: Any) -> bool:
            pid = str(peer.identity)
            try:
                await WORK_POLICY.call(
                    pid,
                    lambda: request_work(
                        manager.p2p, peer.identity, session.library_id, {
                            "op": "announce",
                            "session": session.id,
                        }),
                )
                return True
            except (BreakerOpen, ConnectionError, OSError, EOFError,
                    asyncio.TimeoutError, PermissionError, ValueError) as e:
                logger.debug("work announce to %s failed: %s", pid, e)
                return False

        results = await asyncio.gather(
            *(one(p) for p in manager.peers_for_library(session.library_id))
        )
        return sum(results)

    async def stop(self) -> None:
        await self.worker.stop()
