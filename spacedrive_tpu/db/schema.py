"""Library-database DDL, mirroring the reference's Prisma schema
(ref:core/prisma/schema.prisma:19-554) table for table.

Storage conventions:
- `pub_id`: 16-byte UUID BLOB (globally unique, sync identity).
- datetimes: ISO-8601 TEXT in UTC.
- u64 (inode, sizes): 8-byte little-endian BLOB where the reference
  uses Bytes (SQLite has no u64), plain INTEGER elsewhere.
- `file_path.name/extension` collate NOCASE (ref:schema.prisma:156).
Versioning via PRAGMA user_version + ordered migration list.
"""

from __future__ import annotations

SCHEMA: list[str] = [
    # --- sync infrastructure -------------------------------------------------
    """
    CREATE TABLE crdt_operation (
        id          BLOB PRIMARY KEY,
        timestamp   INTEGER NOT NULL,
        model       TEXT NOT NULL,
        record_id   BLOB NOT NULL,
        kind        TEXT NOT NULL,
        data        BLOB NOT NULL,
        instance_id INTEGER NOT NULL REFERENCES instance(id)
    )
    """,
    "CREATE INDEX idx_crdt_instance_ts ON crdt_operation(instance_id, timestamp)",
    """
    CREATE TABLE cloud_crdt_operation (
        id          BLOB PRIMARY KEY,
        timestamp   INTEGER NOT NULL,
        model       TEXT NOT NULL,
        record_id   BLOB NOT NULL,
        kind        TEXT NOT NULL,
        data        BLOB NOT NULL,
        instance_id INTEGER NOT NULL REFERENCES instance(id)
    )
    """,
    # --- identity ------------------------------------------------------------
    """
    CREATE TABLE node (
        id           INTEGER PRIMARY KEY AUTOINCREMENT,
        pub_id       BLOB NOT NULL UNIQUE,
        name         TEXT NOT NULL,
        platform     INTEGER NOT NULL,
        date_created TEXT NOT NULL,
        identity     BLOB
    )
    """,
    """
    CREATE TABLE instance (
        id            INTEGER PRIMARY KEY AUTOINCREMENT,
        pub_id        BLOB NOT NULL UNIQUE,
        identity      BLOB NOT NULL,
        node_id       BLOB NOT NULL,
        node_name     TEXT NOT NULL,
        node_platform INTEGER NOT NULL,
        last_seen     TEXT NOT NULL,
        date_created  TEXT NOT NULL,
        timestamp     INTEGER
    )
    """,
    """
    CREATE TABLE statistics (
        id                   INTEGER PRIMARY KEY AUTOINCREMENT,
        date_captured        TEXT NOT NULL DEFAULT (datetime('now')),
        total_object_count   INTEGER NOT NULL DEFAULT 0,
        library_db_size      TEXT NOT NULL DEFAULT '0',
        total_bytes_used     TEXT NOT NULL DEFAULT '0',
        total_bytes_capacity TEXT NOT NULL DEFAULT '0',
        total_unique_bytes   TEXT NOT NULL DEFAULT '0',
        total_bytes_free     TEXT NOT NULL DEFAULT '0',
        preview_media_bytes  TEXT NOT NULL DEFAULT '0'
    )
    """,
    """
    CREATE TABLE volume (
        id                    INTEGER PRIMARY KEY AUTOINCREMENT,
        name                  TEXT NOT NULL,
        mount_point           TEXT NOT NULL,
        total_bytes_capacity  TEXT NOT NULL DEFAULT '0',
        total_bytes_available TEXT NOT NULL DEFAULT '0',
        disk_type             TEXT,
        filesystem            TEXT,
        is_system             INTEGER NOT NULL DEFAULT 0,
        date_modified         TEXT NOT NULL DEFAULT (datetime('now')),
        UNIQUE (mount_point, name)
    )
    """,
    # --- the VDFS core -------------------------------------------------------
    """
    CREATE TABLE location (
        id                     INTEGER PRIMARY KEY AUTOINCREMENT,
        pub_id                 BLOB NOT NULL UNIQUE,
        name                   TEXT,
        path                   TEXT,
        total_capacity         INTEGER,
        available_capacity     INTEGER,
        size_in_bytes          BLOB,
        is_archived            INTEGER,
        generate_preview_media INTEGER,
        sync_preview_media     INTEGER,
        hidden                 INTEGER,
        date_created           TEXT,
        instance_id            INTEGER REFERENCES instance(id) ON DELETE SET NULL
    )
    """,
    """
    CREATE TABLE file_path (
        id                  INTEGER PRIMARY KEY AUTOINCREMENT,
        pub_id              BLOB NOT NULL UNIQUE,
        is_dir              INTEGER,
        cas_id              TEXT,
        integrity_checksum  TEXT,
        location_id         INTEGER REFERENCES location(id) ON DELETE SET NULL,
        materialized_path   TEXT,
        name                TEXT COLLATE NOCASE,
        extension           TEXT COLLATE NOCASE,
        hidden              INTEGER,
        size_in_bytes       TEXT,
        size_in_bytes_bytes BLOB,
        inode               BLOB,
        object_id           INTEGER REFERENCES object(id) ON DELETE SET NULL,
        key_id              INTEGER,
        date_created        TEXT,
        date_modified       TEXT,
        date_indexed        TEXT,
        UNIQUE (location_id, materialized_path, name, extension),
        UNIQUE (location_id, inode)
    )
    """,
    "CREATE INDEX idx_file_path_location ON file_path(location_id)",
    "CREATE INDEX idx_file_path_materialized ON file_path(location_id, materialized_path)",
    "CREATE INDEX idx_file_path_cas ON file_path(cas_id)",
    "CREATE INDEX idx_file_path_object ON file_path(object_id)",
    """
    CREATE TABLE object (
        id            INTEGER PRIMARY KEY AUTOINCREMENT,
        pub_id        BLOB NOT NULL UNIQUE,
        kind          INTEGER,
        key_id        INTEGER,
        hidden        INTEGER,
        favorite      INTEGER,
        important     INTEGER,
        note          TEXT,
        date_created  TEXT,
        date_accessed TEXT
    )
    """,
    """
    CREATE TABLE media_data (
        id             INTEGER PRIMARY KEY AUTOINCREMENT,
        resolution     BLOB,
        media_date     BLOB,
        media_location BLOB,
        camera_data    BLOB,
        artist         TEXT,
        description    TEXT,
        copyright      TEXT,
        exif_version   TEXT,
        epoch_time     INTEGER,
        object_id      INTEGER NOT NULL UNIQUE REFERENCES object(id) ON DELETE CASCADE
    )
    """,
    # --- organisation --------------------------------------------------------
    """
    CREATE TABLE tag (
        id            INTEGER PRIMARY KEY AUTOINCREMENT,
        pub_id        BLOB NOT NULL UNIQUE,
        name          TEXT,
        color         TEXT,
        is_hidden     INTEGER,
        date_created  TEXT,
        date_modified TEXT
    )
    """,
    """
    CREATE TABLE tag_on_object (
        tag_id       INTEGER NOT NULL REFERENCES tag(id) ON DELETE RESTRICT,
        object_id    INTEGER NOT NULL REFERENCES object(id) ON DELETE RESTRICT,
        date_created TEXT,
        PRIMARY KEY (tag_id, object_id)
    )
    """,
    """
    CREATE TABLE label (
        id            INTEGER PRIMARY KEY AUTOINCREMENT,
        name          TEXT NOT NULL UNIQUE,
        date_created  TEXT,
        date_modified TEXT
    )
    """,
    """
    CREATE TABLE label_on_object (
        label_id     INTEGER NOT NULL REFERENCES label(id) ON DELETE RESTRICT,
        object_id    INTEGER NOT NULL REFERENCES object(id) ON DELETE RESTRICT,
        date_created TEXT NOT NULL DEFAULT (datetime('now')),
        PRIMARY KEY (label_id, object_id)
    )
    """,
    """
    CREATE TABLE space (
        id            INTEGER PRIMARY KEY AUTOINCREMENT,
        pub_id        BLOB NOT NULL UNIQUE,
        name          TEXT,
        description   TEXT,
        date_created  TEXT,
        date_modified TEXT
    )
    """,
    """
    CREATE TABLE object_in_space (
        space_id  INTEGER NOT NULL REFERENCES space(id) ON DELETE RESTRICT,
        object_id INTEGER NOT NULL REFERENCES object(id) ON DELETE RESTRICT,
        PRIMARY KEY (space_id, object_id)
    )
    """,
    """
    CREATE TABLE album (
        id            INTEGER PRIMARY KEY,
        pub_id        BLOB NOT NULL UNIQUE,
        name          TEXT,
        is_hidden     INTEGER,
        date_created  TEXT,
        date_modified TEXT
    )
    """,
    """
    CREATE TABLE object_in_album (
        album_id     INTEGER NOT NULL REFERENCES album(id),
        object_id    INTEGER NOT NULL REFERENCES object(id),
        date_created TEXT,
        PRIMARY KEY (album_id, object_id)
    )
    """,
    # --- execution -----------------------------------------------------------
    """
    CREATE TABLE job (
        id                        BLOB PRIMARY KEY,
        name                      TEXT,
        action                    TEXT,
        status                    INTEGER,
        errors_text               TEXT,
        data                      BLOB,
        metadata                  BLOB,
        parent_id                 BLOB REFERENCES job(id) ON DELETE SET NULL,
        task_count                INTEGER,
        completed_task_count      INTEGER,
        date_estimated_completion TEXT,
        date_created              TEXT,
        date_started              TEXT,
        date_completed            TEXT
    )
    """,
    # --- indexer rules -------------------------------------------------------
    """
    CREATE TABLE indexer_rule (
        id             INTEGER PRIMARY KEY AUTOINCREMENT,
        pub_id         BLOB NOT NULL UNIQUE,
        name           TEXT,
        "default"      INTEGER,
        rules_per_kind BLOB,
        date_created   TEXT,
        date_modified  TEXT
    )
    """,
    """
    CREATE TABLE indexer_rule_in_location (
        location_id     INTEGER NOT NULL REFERENCES location(id) ON DELETE RESTRICT,
        indexer_rule_id INTEGER NOT NULL REFERENCES indexer_rule(id) ON DELETE RESTRICT,
        PRIMARY KEY (location_id, indexer_rule_id)
    )
    """,
    # --- misc ----------------------------------------------------------------
    """
    CREATE TABLE preference (
        key   TEXT PRIMARY KEY,
        value BLOB
    )
    """,
    """
    CREATE TABLE notification (
        id         INTEGER PRIMARY KEY AUTOINCREMENT,
        read       INTEGER NOT NULL DEFAULT 0,
        data       BLOB NOT NULL,
        expires_at TEXT
    )
    """,
    """
    CREATE TABLE saved_search (
        id            INTEGER PRIMARY KEY AUTOINCREMENT,
        pub_id        BLOB NOT NULL UNIQUE,
        search        TEXT,
        filters       TEXT,
        name          TEXT,
        icon          TEXT,
        description   TEXT,
        date_created  TEXT,
        date_modified TEXT
    )
    """,
]

# Ordered migrations: MIGRATIONS[v] upgrades user_version v -> v+1.
# Version 0 is an empty database.
MIGRATIONS: list[list[str]] = [
    SCHEMA,
    # v1 -> v2: 64-bit perceptual hash for near-duplicate detection
    # (device-computed, ops/phash_jax.py; no reference counterpart —
    # spacedrive dedups by exact cas_id only)
    ["ALTER TABLE object ADD COLUMN phash BLOB"],
    # v2 -> v3: persistent index journal (location/indexer/journal.py) —
    # per-path stat identity (inode/dev/mtime_ns/size as u64 LE blobs)
    # vouching for derived results (cas_id column for SQL joins; the
    # msgpack payload carries thumb/media/phash vouches and the
    # dirty-range chunk cache). `stale=1` marks watcher-invalidated
    # entries whose chunk cache is still useful for dirty-range rehash.
    [
        """
        CREATE TABLE index_journal (
            location_id       INTEGER NOT NULL REFERENCES location(id)
                              ON DELETE CASCADE,
            materialized_path TEXT NOT NULL,
            name              TEXT COLLATE NOCASE NOT NULL,
            extension         TEXT COLLATE NOCASE NOT NULL,
            inode             BLOB,
            dev               BLOB,
            mtime_ns          BLOB,
            size              BLOB,
            cas_id            TEXT,
            payload           BLOB,
            stale             INTEGER NOT NULL DEFAULT 0,
            date_vouched      TEXT,
            PRIMARY KEY (location_id, materialized_path, name, extension)
        )
        """,
        "CREATE INDEX idx_index_journal_cas ON index_journal(cas_id)",
    ],
    # v3 -> v4: LWW-order lookup index. sync/ingest.py's
    # is_operation_old and the delete re-apply path both filter by
    # (model, record_id) with a timestamp comparison; without this
    # index EVERY ingested op scans the whole op log for its record —
    # O(ops²) ingest that the mesh work plane's result merging (ISSUE 9:
    # thousands of cas/object ops converging through sync) turned from
    # slow into prohibitive.
    [
        "CREATE INDEX idx_crdt_model_record_ts ON "
        "crdt_operation(model, record_id, timestamp)",
    ],
    # v4 -> v5: per-object semantic embedding (models/embedder.py) —
    # the vector column is the EMBED_DIM f32 LE blob the search index
    # memmaps; identity rides the object FK like media_data, so the
    # row replicates through the CRDT plane with `object.pub_id` as
    # its sync id (db/sync_registry.py).
    [
        """
        CREATE TABLE object_embedding (
            id              INTEGER PRIMARY KEY AUTOINCREMENT,
            object_id       INTEGER NOT NULL UNIQUE REFERENCES object(id)
                            ON DELETE CASCADE,
            vector          BLOB,
            dim             INTEGER,
            model           TEXT,
            date_calculated TEXT
        )
        """,
    ],
]

# The version every migrated database reports via PRAGMA user_version.
SCHEMA_VERSION = len(MIGRATIONS)
