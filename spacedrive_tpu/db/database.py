"""LibraryDb — thread-safe SQLite access for one library.

The reference connects one SQLite file per library through a typed
Prisma client (ref:core/src/library/manager/mod.rs library load). Here:
WAL-mode sqlite3 with a single writer lock, dict rows, tiny typed
helpers (insert/update/upsert), and explicit transactions — everything
the job/sync layers need, with no ORM in the way.
"""

from __future__ import annotations

import contextlib
import datetime as _dt
import os
import sqlite3
import threading
import uuid
from typing import Any, Iterable, Iterator, Sequence

from .schema import MIGRATIONS


def dict_row(cursor: sqlite3.Cursor, row: tuple) -> dict[str, Any]:
    return {d[0]: row[i] for i, d in enumerate(cursor.description)}


def now_iso() -> str:
    return _dt.datetime.now(_dt.timezone.utc).isoformat(timespec="milliseconds")


def new_pub_id() -> bytes:
    """16-byte UUIDv4 — the sync identity of shared rows."""
    return uuid.uuid4().bytes


def escape_like(s: str) -> str:
    r"""Escape LIKE wildcards in user-derived path fragments; pair with
    ``LIKE ? ESCAPE '\'`` so a directory named ``50% off`` can't match
    unrelated rows."""
    return s.replace("\\", "\\\\").replace("%", "\\%").replace("_", "\\_")


def u64_blob(value: int) -> bytes:
    """u64 -> 8-byte LE BLOB (inode / size columns; SQLite lacks u64,
    same workaround as ref:core/prisma/schema.prisma:164)."""
    return int(value).to_bytes(8, "little")


def blob_u64(blob: bytes | None) -> int | None:
    return None if blob is None else int.from_bytes(blob, "little")


class LibraryDb:
    """One library database. All writes hold the writer lock; reads use
    the same connection (SQLite serializes internally under WAL)."""

    def __init__(self, path: str | os.PathLike | None, *, memory: bool = False):
        self.path = ":memory:" if memory or path is None else os.fspath(path)
        if self.path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(self.path)) or ".", exist_ok=True)
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.row_factory = dict_row
        self._lock = threading.RLock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA foreign_keys=ON")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._migrate()

    # --- lifecycle -----------------------------------------------------------

    def _migrate(self) -> None:
        version = self._conn.execute("PRAGMA user_version").fetchone()["user_version"]
        while version < len(MIGRATIONS):
            with self._conn:
                for stmt in MIGRATIONS[version]:
                    self._conn.execute(stmt)
                version += 1
                self._conn.execute(f"PRAGMA user_version={version}")

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    # --- core access ---------------------------------------------------------

    @contextlib.contextmanager
    def transaction(self) -> Iterator[sqlite3.Connection]:
        """Exclusive write transaction (the sync layer's atomicity
        guarantee: domain rows + crdt_operation rows in one tx,
        ref:core/crates/sync/src/manager.rs:70-93)."""
        with self._lock:
            with self._conn:
                yield self._conn

    def execute(self, sql: str, params: Sequence | dict = ()) -> sqlite3.Cursor:
        with self._lock:
            with self._conn:
                return self._conn.execute(sql, params)

    def executemany(self, sql: str, seq: Iterable[Sequence]) -> None:
        with self._lock:
            with self._conn:
                self._conn.executemany(sql, seq)

    @staticmethod
    def _maybe_slow() -> None:
        """`db.slow` fault point: one `is None` check in production; an
        armed `stall` spec sleeps delay_s per read — the deterministic
        stand-in for a slow/contended disk that the serve layer's
        overload chaos tests (and bench_serve.py's throttled arm) put
        under the whole read surface."""
        from ..utils import faults as _faults

        spec = _faults.hit("db.slow")
        if spec is not None:
            import time

            time.sleep(spec.delay_s)

    def query(self, sql: str, params: Sequence | dict = ()) -> list[dict[str, Any]]:
        self._maybe_slow()
        with self._lock:
            return self._conn.execute(sql, params).fetchall()

    def query_one(self, sql: str, params: Sequence | dict = ()) -> dict[str, Any] | None:
        self._maybe_slow()
        with self._lock:
            return self._conn.execute(sql, params).fetchone()

    # --- typed helpers -------------------------------------------------------

    @staticmethod
    def _quote(col: str) -> str:
        return f'"{col}"'

    def insert(self, table: str, **cols: Any) -> int:
        names = ", ".join(self._quote(c) for c in cols)
        ph = ", ".join("?" for _ in cols)
        cur = self.execute(
            f"INSERT INTO {table} ({names}) VALUES ({ph})", tuple(cols.values())
        )
        return cur.lastrowid

    def insert_many(self, table: str, columns: Sequence[str], rows: Iterable[Sequence]) -> None:
        names = ", ".join(self._quote(c) for c in columns)
        ph = ", ".join("?" for _ in columns)
        self.executemany(f"INSERT INTO {table} ({names}) VALUES ({ph})", rows)

    def update(self, table: str, where: dict[str, Any], **cols: Any) -> int:
        sets = ", ".join(f"{self._quote(c)}=?" for c in cols)
        conds = " AND ".join(f"{self._quote(c)}=?" for c in where)
        cur = self.execute(
            f"UPDATE {table} SET {sets} WHERE {conds}",
            tuple(cols.values()) + tuple(where.values()),
        )
        return cur.rowcount

    def upsert(self, table: str, key_cols: dict[str, Any], **cols: Any) -> None:
        all_cols = {**key_cols, **cols}
        names = ", ".join(self._quote(c) for c in all_cols)
        ph = ", ".join("?" for _ in all_cols)
        keys = ", ".join(self._quote(c) for c in key_cols)
        sets = ", ".join(f"{self._quote(c)}=excluded.{self._quote(c)}" for c in cols) or \
            f"{next(iter(key_cols))}={next(iter(key_cols))}"
        self.execute(
            f"INSERT INTO {table} ({names}) VALUES ({ph}) "
            f"ON CONFLICT ({keys}) DO UPDATE SET {sets}",
            tuple(all_cols.values()),
        )

    def delete(self, table: str, **where: Any) -> int:
        conds = " AND ".join(f"{self._quote(c)}=?" for c in where)
        cur = self.execute(f"DELETE FROM {table} WHERE {conds}", tuple(where.values()))
        return cur.rowcount

    def find(self, table: str, **where: Any) -> list[dict[str, Any]]:
        if not where:
            return self.query(f"SELECT * FROM {table}")
        conds = " AND ".join(f"{self._quote(c)}=?" for c in where)
        return self.query(f"SELECT * FROM {table} WHERE {conds}", tuple(where.values()))

    def find_one(self, table: str, **where: Any) -> dict[str, Any] | None:
        rows = self.find(table, **where)
        return rows[0] if rows else None

    def count(self, table: str, where_sql: str = "", params: Sequence = ()) -> int:
        sql = f"SELECT COUNT(*) AS n FROM {table}"
        if where_sql:
            sql += f" WHERE {where_sql}"
        return self.query_one(sql, params)["n"]
