"""Sync-model registry — which tables sync, and how.

The reference derives this from schema doc-attributes (`/// @local`,
`/// @shared(id: …)`, `/// @relation(item, group)`) via its
`sync-generator` crate (ref:crates/sync-generator/src/lib.rs:22-36).
Here the registry is explicit data; the sync manager (spacedrive_tpu/
sync/) uses it to build and apply CRDT operations.

Sync kinds (ref:docs/developers/architecture/sync.mdx):
- LOCAL:    never leaves the device (instance, volume, cloud op cache).
- SHARED:   one instance owns writes at a time; LWW per field.
  `id_field` names the column whose value is the record's global sync
  id (usually pub_id; `name` for label, `key` for preference; media_data
  uses its object's pub_id — `id_ref` points through the FK).
- RELATION: link rows identified by (item, group) sync-id pair.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class SyncKind(enum.Enum):
    LOCAL = "local"
    SHARED = "shared"
    RELATION = "relation"


@dataclass(frozen=True)
class ForeignRef:
    """A column that stores a local integer FK but syncs as the target
    row's global sync id (e.g. file_path.object_id syncs as the object's
    pub_id)."""

    column: str          # local column, e.g. "object_id"
    table: str           # target table, e.g. "object"
    target_id_field: str = "pub_id"


@dataclass(frozen=True)
class SyncModel:
    name: str                      # table name; also CRDTOperation.model
    kind: SyncKind
    id_field: str | None = None    # SHARED: column carrying the sync id
    id_ref: ForeignRef | None = None  # SHARED with FK-derived identity (media_data)
    item: ForeignRef | None = None    # RELATION: the item side
    group: ForeignRef | None = None   # RELATION: the group side
    foreign_refs: tuple[ForeignRef, ...] = field(default=())  # synced FK columns
    local_fields: tuple[str, ...] = field(default=())  # @local fields, not synced


SYNC_MODELS: dict[str, SyncModel] = {
    m.name: m
    for m in [
        SyncModel("instance", SyncKind.LOCAL, id_field="pub_id"),
        SyncModel("volume", SyncKind.LOCAL),
        SyncModel("cloud_crdt_operation", SyncKind.LOCAL, id_field="id"),
        SyncModel(
            "location", SyncKind.SHARED, id_field="pub_id",
            local_fields=("instance_id",),  # client-side cache (ref:schema.prisma:126)
        ),
        SyncModel(
            "file_path", SyncKind.SHARED, id_field="pub_id",
            foreign_refs=(
                ForeignRef("location_id", "location"),
                ForeignRef("object_id", "object"),
            ),
        ),
        SyncModel("object", SyncKind.SHARED, id_field="pub_id"),
        SyncModel(
            "media_data", SyncKind.SHARED,
            id_ref=ForeignRef("object_id", "object"),
        ),
        SyncModel(
            "object_embedding", SyncKind.SHARED,
            id_ref=ForeignRef("object_id", "object"),
        ),
        SyncModel("tag", SyncKind.SHARED, id_field="pub_id"),
        SyncModel("label", SyncKind.SHARED, id_field="name"),
        SyncModel("preference", SyncKind.SHARED, id_field="key"),
        SyncModel("saved_search", SyncKind.SHARED, id_field="pub_id"),
        SyncModel(
            "tag_on_object", SyncKind.RELATION,
            item=ForeignRef("object_id", "object"),
            group=ForeignRef("tag_id", "tag"),
        ),
        SyncModel(
            "label_on_object", SyncKind.RELATION,
            item=ForeignRef("object_id", "object"),
            group=ForeignRef("label_id", "label", target_id_field="name"),
        ),
    ]
}


def model_sync_kind(table: str) -> SyncKind | None:
    """None for tables with no sync annotation (purely device-local
    bookkeeping like job/statistics/notification)."""
    m = SYNC_MODELS.get(table)
    return m.kind if m else None
