"""Data layer — per-library SQLite database.

Parity: the reference's Prisma schema (ref:core/prisma/schema.prisma)
and generated client. One SQLite file per library; typed access helpers
and the sync-model registry (the reference generates these with
`prisma-client-rust` + `sync-generator`; here they are explicit,
readable tables).
"""

from .database import LibraryDb, dict_row
from .schema import SCHEMA_VERSION
from .sync_registry import SyncKind, SYNC_MODELS, model_sync_kind

__all__ = [
    "LibraryDb",
    "dict_row",
    "SCHEMA_VERSION",
    "SyncKind",
    "SYNC_MODELS",
    "model_sync_kind",
]
