"""NodeConfig — the per-device JSON config with versioned migrations.

Parity: ref:core/src/node/config.rs:56-124 — `NodeConfig{id, name,
identity, p2p: {port, discovery}, features, preferences,
image_labeler_version}` stored as `node.json` in the data dir, loaded
through a `VersionManager` (config.rs:171) that applies sequential
migrations. The identity keypair lives in the config exactly as the
reference stores its ed25519 keypair.
"""

from __future__ import annotations

import os
import platform
import threading
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any

from ..p2p.identity import Identity
from ..utils.version_manager import VersionManager

NODE_CONFIG_VERSION = 2

_vm = VersionManager(NODE_CONFIG_VERSION)


@_vm.register(1)
def _v1_to_v2(data: dict[str, Any]) -> dict[str, Any]:
    # v2 added the features list (ref: config.rs migrations add/remove keys)
    data.setdefault("features", [])
    return data


class BackendFeature(str, Enum):
    """Runtime-toggleable features (ref:core/src/api/mod.rs:66-81)."""

    FILES_OVER_P2P = "filesOverP2P"
    CLOUD_SYNC = "cloudSync"
    REMOTE_RSPC = "remoteRspc"  # serve queries to mesh peers (off by default)


class P2PDiscoveryState(str, Enum):
    """ref:core/src/node/config.rs `P2PDiscoveryState`."""

    EVERYONE = "everyone"
    CONTACTS_ONLY = "contactsOnly"
    DISABLED = "disabled"


@dataclass
class NodeConfigP2P:
    """ref:config.rs p2p block: enabled flag, fixed port (0 = random),
    discovery mode."""

    enabled: bool = True
    port: int = 0
    discovery: P2PDiscoveryState = P2PDiscoveryState.EVERYONE
    relay: str | None = None  # "host:port" WAN relay rendezvous (optional)

    def to_dict(self) -> dict[str, Any]:
        return {
            "enabled": self.enabled,
            "port": self.port,
            "discovery": self.discovery.value,
            "relay": self.relay,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "NodeConfigP2P":
        return cls(
            enabled=bool(d.get("enabled", True)),
            port=int(d.get("port", 0)),
            discovery=P2PDiscoveryState(d.get("discovery", "everyone")),
            relay=d.get("relay") or None,
        )


@dataclass
class NodeConfig:
    id: uuid.UUID = field(default_factory=uuid.uuid4)
    name: str = field(default_factory=platform.node)
    identity: Identity = field(default_factory=Identity)
    p2p: NodeConfigP2P = field(default_factory=NodeConfigP2P)
    features: list[BackendFeature] = field(default_factory=list)
    preferences: dict[str, Any] = field(default_factory=dict)
    image_labeler_version: str | None = None
    version: int = NODE_CONFIG_VERSION

    def to_dict(self) -> dict[str, Any]:
        return {
            "version": self.version,
            "id": str(self.id),
            "name": self.name,
            "identity": self.identity.to_bytes().hex(),
            "p2p": self.p2p.to_dict(),
            "features": [f.value for f in self.features],
            "preferences": self.preferences,
            "image_labeler_version": self.image_labeler_version,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "NodeConfig":
        return cls(
            id=uuid.UUID(d["id"]) if "id" in d else uuid.uuid4(),
            name=d.get("name") or platform.node(),
            identity=(
                Identity.from_bytes(bytes.fromhex(d["identity"]))
                if d.get("identity")
                else Identity()
            ),
            p2p=NodeConfigP2P.from_dict(d.get("p2p", {})),
            features=[BackendFeature(f) for f in d.get("features", [])],
            preferences=d.get("preferences", {}),
            image_labeler_version=d.get("image_labeler_version"),
            version=d.get("version", NODE_CONFIG_VERSION),
        )


class ConfigManager:
    """Load-or-init + atomic persist of `node.json`
    (ref:core/src/node/config.rs:293 `config::Manager::new`)."""

    FILENAME = "node.json"

    def __init__(self, data_dir: str | os.PathLike):
        self.path = os.path.join(os.fspath(data_dir), self.FILENAME)
        self._lock = threading.Lock()
        if os.path.exists(self.path):
            data = _vm.load(self.path)
            self.config = NodeConfig.from_dict(data)
            # persist any defaults from_dict filled in (a migrated file
            # missing `identity` must not mint a new keypair every boot)
            if self.config.to_dict() != data:
                self.save()
        else:
            self.config = NodeConfig()
            self.save()

    def save(self) -> None:
        with self._lock:
            _vm.save(self.path, self.config.to_dict())

    def update(self, **fields: Any) -> NodeConfig:
        """Mutate-and-persist (ref:config.rs `Manager::write`)."""
        for k, v in fields.items():
            if not hasattr(self.config, k):
                raise AttributeError(f"NodeConfig has no field {k!r}")
            setattr(self.config, k, v)
        self.save()
        return self.config
