"""Library — one synced database + its services.

Parity: ref:core/src/library/ — `Library{id, config, db, sync,
instance_uuid, event_bus}` (library.rs:29-54) and the `Libraries`
manager loading `libraries/*.sdlibrary` configs next to per-library
SQLite files (manager/mod.rs:62-130), creating the local Instance row
on create, wiring the sync manager, and cold-resuming jobs.
"""

from __future__ import annotations

import os
import platform
import uuid
from dataclasses import dataclass, field
from typing import Any

from ..db import LibraryDb
from ..db.database import new_pub_id, now_iso
from ..sync.manager import SyncManager
from ..utils.events import EventBus
from ..utils.version_manager import VersionManager

LIBRARY_CONFIG_VERSION = 1

_config_vm = VersionManager(LIBRARY_CONFIG_VERSION)


@dataclass
class LibraryConfig:
    """Per-library JSON config (ref:core/src/library/config.rs)."""

    name: str
    description: str = ""
    instance_id: int = 0  # local DB id of this device's Instance row
    version: int = LIBRARY_CONFIG_VERSION

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "description": self.description,
            "instance_id": self.instance_id,
            "version": self.version,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "LibraryConfig":
        return cls(
            name=d.get("name", ""),
            description=d.get("description", ""),
            instance_id=d.get("instance_id", 0),
            version=d.get("version", LIBRARY_CONFIG_VERSION),
        )


class Library:
    def __init__(
        self,
        lib_id: uuid.UUID,
        config: LibraryConfig,
        db: LibraryDb,
        instance_uuid: uuid.UUID,
        event_bus: EventBus | None = None,
        node: Any = None,
    ):
        self.id = lib_id
        self.config = config
        self.db = db
        self.instance_uuid = instance_uuid
        self.event_bus = event_bus or EventBus()
        self.node = node
        self.sync = SyncManager(db, instance_uuid, self.event_bus)

    @property
    def name(self) -> str:
        return self.config.name

    def close(self) -> None:
        self.db.close()

    def __repr__(self) -> str:
        return f"<Library {self.name!r} {str(self.id)[:8]}>"


class Libraries:
    """Loads/creates libraries under `<data_dir>/libraries/`
    (ref:core/src/library/manager/mod.rs)."""

    def __init__(self, data_dir: str | os.PathLike, node: Any = None):
        self.dir = os.path.join(os.fspath(data_dir), "libraries")
        os.makedirs(self.dir, exist_ok=True)
        self.node = node
        self.libraries: dict[uuid.UUID, Library] = {}

    # --- lifecycle ---

    def load_all(self) -> list[Library]:
        for fname in sorted(os.listdir(self.dir)):
            if fname.endswith(".sdlibrary"):
                lib_id = uuid.UUID(fname[: -len(".sdlibrary")])
                if lib_id not in self.libraries:
                    self._load(lib_id)
        return list(self.libraries.values())

    def _config_path(self, lib_id: uuid.UUID) -> str:
        return os.path.join(self.dir, f"{lib_id}.sdlibrary")

    def _db_path(self, lib_id: uuid.UUID) -> str:
        return os.path.join(self.dir, f"{lib_id}.db")

    def _load(self, lib_id: uuid.UUID) -> Library:
        data = _config_vm.load(self._config_path(lib_id))
        config = LibraryConfig.from_dict(data)
        db = LibraryDb(self._db_path(lib_id))
        inst = db.find_one("instance", id=config.instance_id)
        if inst is None:
            raise ValueError(f"library {lib_id} missing local instance row")
        lib = Library(lib_id, config, db, uuid.UUID(bytes=inst["pub_id"]), node=self.node)
        self.libraries[lib_id] = lib
        return lib

    def create(self, name: str, description: str = "",
               node_pub_id: bytes | None = None, node_name: str | None = None) -> Library:
        lib_id = uuid.uuid4()
        db = LibraryDb(self._db_path(lib_id))
        instance_pub = new_pub_id()
        instance_id = db.insert(
            "instance",
            pub_id=instance_pub,
            identity=new_pub_id(),  # replaced by real keypair when p2p enabled
            node_id=node_pub_id or new_pub_id(),
            node_name=node_name or platform.node(),
            node_platform=_platform_int(),
            last_seen=now_iso(),
            date_created=now_iso(),
        )
        config = LibraryConfig(name=name, description=description, instance_id=instance_id)
        data = config.to_dict()
        _config_vm.save(self._config_path(lib_id), data)
        lib = Library(lib_id, config, db, uuid.UUID(bytes=instance_pub), node=self.node)
        self.libraries[lib_id] = lib

        from ..location.indexer.rules import seed_rules

        seed_rules(db)
        return lib

    def get(self, lib_id: uuid.UUID) -> Library | None:
        return self.libraries.get(lib_id)

    def save_config(self, lib: Library) -> None:
        """Persist a library's (possibly edited) config file."""
        _config_vm.save(self._config_path(lib.id), lib.config.to_dict())

    def paths(self, lib_id: uuid.UUID) -> tuple[str, str]:
        """(config_path, db_path) on disk — the backup/restore surface."""
        return self._config_path(lib_id), self._db_path(lib_id)

    def load(self, lib_id: uuid.UUID) -> Library:
        """(Re)load one library from disk (restore path)."""
        return self._load(lib_id)

    def delete(self, lib_id: uuid.UUID) -> None:
        lib = self.libraries.pop(lib_id, None)
        if lib is not None:
            lib.close()
        for path in (self._config_path(lib_id), self._db_path(lib_id)):
            if os.path.exists(path):
                os.remove(path)


def _platform_int() -> int:
    """Platform enum (ref:core/src/node/mod.rs Platform)."""
    return {"Windows": 2, "Darwin": 3, "Linux": 4}.get(platform.system(), 0)
