"""Node runtime: root object, config, libraries.

Parity: ref:core/src/{lib.rs,node/,library/}.
"""

from .library import Library, Libraries, LibraryConfig

__all__ = ["Library", "Libraries", "LibraryConfig"]
