"""Node runtime: root object, config, libraries, volumes, preferences.

Parity: ref:core/src/{lib.rs,node/,library/,volume/,preferences/,
notifications.rs}.
"""

from .actors import Actors
from .config import BackendFeature, ConfigManager, NodeConfig, P2PDiscoveryState
from .library import Library, Libraries, LibraryConfig
from .node import Node
from .notifications import Notification, NotificationId, Notifications
from .preferences import clear_preference, read_preferences, write_preferences
from .statistics import get_statistics, update_statistics
from .volumes import Volume, get_volumes, save_volumes

__all__ = [
    "Actors",
    "BackendFeature",
    "ConfigManager",
    "Library",
    "Libraries",
    "LibraryConfig",
    "Node",
    "NodeConfig",
    "Notification",
    "NotificationId",
    "Notifications",
    "P2PDiscoveryState",
    "Volume",
    "clear_preference",
    "get_statistics",
    "get_volumes",
    "read_preferences",
    "save_volumes",
    "update_statistics",
    "write_preferences",
]
