"""Notifications — node- and library-scoped user notifications.

Parity: ref:core/src/notifications.rs + `Node::emit_notification`
(ref:core/src/lib.rs:258-278): library-scoped notifications persist to
the library `notification` table then push a `Notification{id, data}`
onto the node-wide channel; node-scoped ones are in-memory with a
monotonic counter. `data` carries kind/title/content like the
reference's `NotificationData`.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass
from typing import Any

import msgpack

from ..db.database import LibraryDb
from ..utils.events import EventBus


@dataclass(frozen=True)
class NotificationId:
    """ref:notifications.rs `NotificationId::{Node(u32), Library(Uuid, u32)}`."""

    library_id: str | None
    local_id: int


@dataclass
class Notification:
    id: NotificationId
    data: dict[str, Any]
    read: bool = False
    expires_at: str | None = None


class Notifications:
    def __init__(self, event_bus: EventBus | None = None):
        self.event_bus = event_bus or EventBus()
        self._node_counter = itertools.count(1)
        self._node_notifications: list[Notification] = []
        self._lock = threading.Lock()

    def emit_node(self, data: dict[str, Any]) -> Notification:
        """Node-scoped, in-memory (ref:lib.rs:258-266)."""
        n = Notification(NotificationId(None, next(self._node_counter)), data)
        with self._lock:
            self._node_notifications.append(n)
        self.event_bus.emit(("notification", n))
        return n

    def emit_library(
        self,
        db: LibraryDb,
        library_id: str,
        data: dict[str, Any],
        expires_at: str | None = None,
    ) -> Notification:
        """Library-scoped, persisted (ref:lib.rs:267-278)."""
        row_id = db.insert(
            "notification", data=msgpack.packb(data), expires_at=expires_at
        )
        n = Notification(NotificationId(library_id, row_id), data, expires_at=expires_at)
        self.event_bus.emit(("notification", n))
        return n

    def list_node(self) -> list[Notification]:
        with self._lock:
            return list(self._node_notifications)

    @staticmethod
    def list_library(db: LibraryDb, library_id: str) -> list[Notification]:
        return [
            Notification(
                NotificationId(library_id, row["id"]),
                msgpack.unpackb(row["data"]),
                read=bool(row["read"]),
                expires_at=row["expires_at"],
            )
            for row in db.query("SELECT * FROM notification ORDER BY id")
        ]

    @staticmethod
    def mark_read(db: LibraryDb, local_id: int) -> None:
        db.update("notification", {"id": local_id}, read=1)
