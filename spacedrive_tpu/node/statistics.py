"""Library statistics snapshot.

Parity: ref:core/src/library/statistics.rs `update_statistics` +
`Statistics` model (ref:core/prisma/schema.prisma:80-93): total object
count, library DB size, total bytes used (sum of file sizes), volume
capacity/free across mounted volumes, preview-media (thumbnail dir)
bytes. Stored as a single latest row in the `statistics` table; big
byte counts are TEXT columns like the reference (u64-as-string).
"""

from __future__ import annotations

import os
from typing import Any

from ..db.database import LibraryDb, blob_u64
from .volumes import get_volumes


def _dir_size(path: str | None) -> int:
    if not path or not os.path.isdir(path):
        return 0
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def update_statistics(
    db: LibraryDb, thumbnails_dir: str | None = None
) -> dict[str, Any]:
    total_objects = db.count("object")
    # one table scan for both totals; unique bytes = one size per distinct
    # cas_id, aggregated in Python (sizes are LE blobs SQLite can't order)
    total_bytes_used = 0
    by_cas: dict[str, int] = {}
    for r in db.query("SELECT cas_id, size_in_bytes_bytes FROM file_path"):
        size = blob_u64(r["size_in_bytes_bytes"]) or 0
        total_bytes_used += size
        if r["cas_id"] is not None:
            by_cas.setdefault(r["cas_id"], size)
    total_unique_bytes = sum(by_cas.values())

    capacity = 0
    free = 0
    for v in get_volumes():
        capacity += v.total_bytes_capacity
        free += v.total_bytes_available

    db_size = 0
    if db.path != ":memory:":
        for suffix in ("", "-wal", "-shm"):
            try:
                db_size += os.path.getsize(db.path + suffix)
            except OSError:
                pass

    stats = {
        "total_object_count": total_objects,
        "library_db_size": str(db_size),
        "total_bytes_used": str(total_bytes_used),
        "total_bytes_capacity": str(capacity),
        "total_unique_bytes": str(total_unique_bytes),
        "total_bytes_free": str(free),
        "preview_media_bytes": str(_dir_size(thumbnails_dir)),
    }
    existing = db.query_one("SELECT id FROM statistics ORDER BY id DESC LIMIT 1")
    if existing:
        db.update("statistics", {"id": existing["id"]}, **stats)
    else:
        db.insert("statistics", **stats)
    return stats


def get_statistics(db: LibraryDb) -> dict[str, Any] | None:
    return db.query_one("SELECT * FROM statistics ORDER BY id DESC LIMIT 1")
