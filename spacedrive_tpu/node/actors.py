"""Named-actor registry.

Parity: ref:crates/actors/src/lib.rs — `Actors::declare(name, factory)`
registers a named async actor that can be started/stopped/restarted at
runtime, with an invalidation broadcast so UIs can re-query actor state
(lib.rs:20-38). Used per-library by the sync ingest and cloud-sync
actors. Here actors are asyncio tasks created from a factory coroutine
function; `stop` cancels, `start` re-creates.
"""

from __future__ import annotations

import asyncio
from typing import Any, Awaitable, Callable

ActorFactory = Callable[[], Awaitable[Any]]


class Actors:
    def __init__(self) -> None:
        self._factories: dict[str, ActorFactory] = {}
        self._tasks: dict[str, asyncio.Task] = {}
        self.invalidate = asyncio.Event()

    def declare(self, name: str, factory: ActorFactory, *, autostart: bool = False) -> None:
        """Register a named actor (ref:lib.rs:20-38). `autostart` mirrors
        the reference's immediate `start` after declare in sync setup."""
        self._factories[name] = factory
        if autostart:
            self.start(name)

    def start(self, name: str) -> bool:
        if name not in self._factories:
            return False
        task = self._tasks.get(name)
        # a just-cancelled task isn't done() until the loop runs; treat it
        # as stopped so restart() can hand the name to a replacement
        if task is not None and not task.done() and not task.cancelling():
            return False
        self._tasks[name] = asyncio.get_running_loop().create_task(
            self._factories[name](), name=f"actor:{name}"
        )
        self._notify()
        return True

    def stop(self, name: str) -> bool:
        task = self._tasks.get(name)
        if task is None or task.done():
            return False
        task.cancel()
        self._notify()
        return True

    def restart(self, name: str) -> bool:
        self.stop(name)
        return self.start(name)

    def is_running(self, name: str) -> bool:
        task = self._tasks.get(name)
        return task is not None and not task.done()

    def states(self) -> dict[str, bool]:
        """name -> running? for every declared actor (UI listing)."""
        return {name: self.is_running(name) for name in self._factories}

    async def shutdown(self) -> None:
        for task in self._tasks.values():
            if not task.done():
                task.cancel()
        for task in self._tasks.values():
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()

    def _notify(self) -> None:
        self.invalidate.set()
        self.invalidate = asyncio.Event()
