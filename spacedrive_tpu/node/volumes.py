"""OS volume / mounted-disk enumeration.

Parity: ref:core/src/volume/mod.rs — `Volume{name, mount_points,
total_capacity, available_capacity, disk_type, file_system,
is_root_filesystem}` gathered via `sysinfo` (mod.rs:109,249), persisted
into the library `volume` table keyed (mount_point, name). Here:
/proc/mounts + `shutil.disk_usage` on Linux, `psutil`-free; other
platforms fall back to the root filesystem only. Pseudo-filesystems are
filtered the way the reference skips zero-capacity disks.
"""

from __future__ import annotations

import os
import platform
import shutil
from dataclasses import dataclass, field
from typing import Any

from ..db.database import LibraryDb, now_iso

_PSEUDO_FS = {
    "proc", "sysfs", "devtmpfs", "devpts", "tmpfs", "cgroup", "cgroup2",
    "overlay", "squashfs", "securityfs", "debugfs", "tracefs", "ramfs",
    "pstore", "bpf", "autofs", "mqueue", "hugetlbfs", "fusectl",
    "configfs", "binfmt_misc", "nsfs", "rpc_pipefs", "efivarfs",
}


@dataclass
class Volume:
    name: str
    mount_point: str
    total_bytes_capacity: int = 0
    total_bytes_available: int = 0
    disk_type: str = "Unknown"  # SSD | HDD | Unknown (ref:volume/mod.rs DiskType)
    filesystem: str | None = None
    is_system: bool = False

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "mount_point": self.mount_point,
            "total_bytes_capacity": self.total_bytes_capacity,
            "total_bytes_available": self.total_bytes_available,
            "disk_type": self.disk_type,
            "filesystem": self.filesystem,
            "is_system": self.is_system,
        }


def _disk_type(device: str) -> str:
    """SSD/HDD via /sys rotational flag (sysinfo does the same probe)."""
    base = os.path.basename(device).rstrip("0123456789")
    if base.startswith("nvme"):
        return "SSD"
    rot = f"/sys/block/{base}/queue/rotational"
    try:
        with open(rot) as f:
            return "HDD" if f.read().strip() == "1" else "SSD"
    except OSError:
        return "Unknown"


def get_volumes() -> list[Volume]:
    """Enumerate real mounted volumes (ref:volume/mod.rs:109 `get_volumes`)."""
    vols: list[Volume] = []
    seen: set[str] = set()
    if platform.system() == "Linux" and os.path.exists("/proc/mounts"):
        with open("/proc/mounts") as f:
            lines = f.readlines()
        for line in lines:
            parts = line.split()
            if len(parts) < 3:
                continue
            device, mount, fstype = parts[0], parts[1], parts[2]
            # /proc/mounts octal-escapes UTF-8 bytes (\040 space etc.);
            # unicode_escape yields Latin-1 codepoints, so re-encode
            mount = (
                mount.encode("latin-1")
                .decode("unicode_escape")
                .encode("latin-1")
                .decode("utf-8", "surrogateescape")
            )
            if fstype in _PSEUDO_FS or mount in seen:
                continue
            try:
                usage = shutil.disk_usage(mount)
            except OSError:
                continue
            if usage.total == 0:
                continue  # ref skips zero-capacity disks
            seen.add(mount)
            vols.append(
                Volume(
                    name=os.path.basename(device) or device,
                    mount_point=mount,
                    total_bytes_capacity=usage.total,
                    total_bytes_available=usage.free,
                    disk_type=_disk_type(device),
                    filesystem=fstype,
                    is_system=(mount == "/"),
                )
            )
    if not vols:  # non-Linux fallback: root filesystem only
        usage = shutil.disk_usage(os.path.abspath(os.sep))
        vols.append(
            Volume(
                name="Root",
                mount_point=os.path.abspath(os.sep),
                total_bytes_capacity=usage.total,
                total_bytes_available=usage.free,
                is_system=True,
            )
        )
    return vols


def save_volumes(db: LibraryDb, vols: list[Volume] | None = None) -> int:
    """Upsert volumes into the library DB (ref:volume/mod.rs
    `save_volume` keyed on (mount_point, name))."""
    vols = vols if vols is not None else get_volumes()
    for v in vols:
        db.upsert(
            "volume",
            {"mount_point": v.mount_point, "name": v.name},
            total_bytes_capacity=str(v.total_bytes_capacity),
            total_bytes_available=str(v.total_bytes_available),
            disk_type=v.disk_type,
            filesystem=v.filesystem,
            is_system=int(v.is_system),
            date_modified=now_iso(),
        )
    return len(vols)
